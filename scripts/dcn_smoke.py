"""CI DCN smoke (PR 15): a REAL 2-process jax.distributed CPU cluster
— 2 OS processes x 2 virtual devices each, gloo collectives — runs
the shared ``parallel.dcn_worker`` tasks and this parent pins them
bit-exact against its own 1-process x 4-device twin:

- ``sims``      all three sims stepwise + donated-fused (the kafka
                parity leg rides here), seed-replay inside the worker;
- ``certify``   one certified crash+loss broadcast nemesis on the
                structured words-major path;
- ``takeover``  the HOST-loss drill: one DCN host's entire node block
                crashes for a window, the survivors' flood stalls and
                re-converges after restart;
- ``pipelined`` (PR 20) the ``sims`` body under ``GG_DCN_PIPELINE=1``:
                the cluster compiles the double-buffered half-block
                DCN circuits and every digest must STILL equal the
                synchronous flat twin's — latency hiding with zero
                semantic drift, proven bit-exact;
- ``stale``     (PR 20) counter allreduce crash+loss at ``stale:4``
                vs its sync twin, certified by
                ``check_staleness_bound`` with a REAL nonzero
                convergence delay — compared against a 1-process
                ``pick_mesh_2d`` twin (staleness needs the hierarchy,
                so the flat parity mesh refuses it).

Parent-side staleness legs (PR 20) ride after the parity sweep: the
falsifiability plant (the same stale:4 run certified against a
claimed k=1 bound MUST fail naming the violating round) and the
flight-recorder loop (a stale run failed by an impossible recovery
budget writes a bundle whose ``runner_kw`` records the DCN mode, and
``replay_bundle(..., mesh=pick_mesh_2d())`` reproduces the same
failure under the same mode).  Artifacts land in
``artifacts/dcn_smoke/``.

Every compared number is a replicated ledger scalar or an on-device
position-weighted checksum, so rank-vs-rank and cluster-vs-twin
equality is bit-exactness.  One retry with a fresh coordinator port
absorbs the rare gloo startup flake.  Exits nonzero on any mismatch
or failed certification.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gossip_glomers_tpu.parallel.mesh import (  # noqa: E402
    force_virtual_devices)

# the single-process twin matches the cluster's GLOBAL device count:
# 2 procs x 2 devices = one 4-way virtual mesh here
force_virtual_devices(4)

from gossip_glomers_tpu.parallel.dcn_worker import (  # noqa: E402
    run_tasks)
from gossip_glomers_tpu.parallel.mesh import (  # noqa: E402
    pick_mesh, pick_mesh_2d)
from gossip_glomers_tpu.utils.compile_cache import (  # noqa: E402
    enable_compile_cache)

TASKS = "sims,certify,takeover,pipelined,stale"
# tasks the FLAT 1x4 twin can replay (pipelined mode is a structural
# no-op on one host, which is exactly the bit-exactness claim); the
# stale task needs the hierarchy and gets its own pick_mesh_2d twin
FLAT_TASKS = ("sims", "certify", "takeover", "pipelined")
N_PROCS, LOCAL_DEVICES = 2, 2
ART_DIR = os.path.join(REPO, "artifacts", "dcn_smoke")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_cluster(tmp: str, timeout: float = 480.0):
    last_diag = ""
    for attempt in range(2):
        out = os.path.join(tmp, f"out{attempt}.json")
        env = dict(os.environ)
        # this parent forced a 4-device split; the workers must see a
        # clean slate so GG_LOCAL_DEVICES=2 applies
        env.pop("XLA_FLAGS", None)
        env.update(JAX_PLATFORMS="cpu",
                   GG_COORDINATOR=f"127.0.0.1:{_free_port()}",
                   GG_NUM_PROCS=str(N_PROCS),
                   GG_LOCAL_DEVICES=str(LOCAL_DEVICES),
                   GG_DCN_TASKS=TASKS, GG_DCN_OUT=out)
        procs, logs = [], []
        for rank in range(N_PROCS):
            log = open(os.path.join(tmp, f"log{attempt}.{rank}"),
                       "w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "gossip_glomers_tpu.parallel.dcn_worker"],
                cwd=REPO, env=dict(env, GG_PROC_ID=str(rank)),
                stdout=log, stderr=subprocess.STDOUT))
        deadline = time.monotonic() + timeout
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(
                    timeout=max(1.0, deadline - time.monotonic())))
            except subprocess.TimeoutExpired:
                rcs.append(None)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        if all(rc == 0 for rc in rcs):
            reports = []
            for rank in range(N_PROCS):
                with open(f"{out}.{rank}") as fh:
                    reports.append(json.load(fh))
            for log in logs:
                log.close()
            return reports
        diag = []
        for rank, log in enumerate(logs):
            log.seek(0)
            diag.append(f"-- rank {rank} rc={rcs[rank]} --\n"
                        + log.read()[-3000:])
            log.close()
        last_diag = "\n".join(diag)
    print(f"dcn-smoke: cluster failed twice\n{last_diag}",
          file=sys.stderr)
    return None


def _stale_legs(stale_report: dict) -> tuple[int, dict]:
    """The PR-20 parent-side staleness legs on the ``pick_mesh_2d``
    hierarchy: (1) falsifiability — the cluster's REAL stale:4 run,
    re-certified against a claimed k=1 bound, must FAIL naming its
    violating round; (2) the flight-recorder loop — a stale run
    failed by an impossible recovery budget (the sync twin passes the
    same budget, so staleness IS the failure) writes a bundle whose
    ``runner_kw`` records the DCN mode, and the replay on a fresh
    hierarchical mesh reproduces the same verdict."""
    from gossip_glomers_tpu.harness.checkers import (
        check_staleness_bound)
    from gossip_glomers_tpu.harness.nemesis import run_counter_nemesis
    from gossip_glomers_tpu.harness.observe import (
        load_bundle, replay_bundle)
    from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec

    rc = 0
    legs: dict = {}

    # -- planted k-violation: the stale:4 run's observed delay is
    # real (>= 1 round), so a claimed stale:1 bound must be violated
    ok, details = check_staleness_bound(
        stale_k=1,
        sync_converged_round=stale_report["sync_round"],
        stale_converged_round=stale_report["stale_round"],
        lost_writes=[])
    planted_ok = (not ok
                  and details.get("violating_round")
                  == stale_report["stale_round"])
    legs["planted_k_violation"] = {
        "ok": planted_ok, "claimed_k": 1,
        "violating_round": details.get("violating_round"),
        "bound_round": details["bound_round"]}
    print(f"dcn-smoke stale-plant "
          f"{'falsified-ok' if planted_ok else 'FAIL'} "
          f"(claimed k=1, violating round "
          f"{details.get('violating_round')})")
    if not planted_ok:
        rc = 1

    # -- flight-recorder loop: same seeded spec as the stale task,
    # recovery budget 1 — the sync run converges AT the clear round
    # and passes; the stale:4 run needs 2 more rounds and fails,
    # writing the bundle with dcn_mode in runner_kw
    hier = pick_mesh_2d(hosts=N_PROCS)
    spec = NemesisSpec(n_nodes=16, seed=3, crash=((1, 4, (2, 11)),),
                       loss_rate=0.2, loss_until=5)
    sync = run_counter_nemesis(spec, mode="allreduce", mesh=hier,
                               max_recovery_rounds=1, dcn_mode="sync")
    failed = run_counter_nemesis(spec, mode="allreduce", mesh=hier,
                                 max_recovery_rounds=1,
                                 dcn_mode="stale:4",
                                 observe_dir=ART_DIR)
    bundle_path = failed.get("flight_bundle")
    leg_ok = bool(sync["ok"]) and not failed["ok"] \
        and bundle_path is not None
    replayed = mode_ok = None
    if bundle_path:
        mode_ok = (load_bundle(bundle_path)["runner_kw"]
                   .get("dcn_mode") == "stale:4")
        replayed = replay_bundle(bundle_path, mesh=hier)
        leg_ok = (leg_ok and mode_ok and not replayed["ok"]
                  and replayed["converged_round"]
                  == failed["converged_round"])
    legs["flight_replay"] = {
        "ok": bool(leg_ok),
        "sync_ok_same_budget": bool(sync["ok"]),
        "bundle": bundle_path,
        "bundle_records_mode": mode_ok,
        "failed_converged_round": failed["converged_round"],
        "replay_converged_round": (None if replayed is None
                                   else replayed["converged_round"])}
    print(f"dcn-smoke stale-replay "
          f"{'replayed-ok' if leg_ok else 'FAIL'} "
          f"(bundle {os.path.basename(bundle_path or '<none>')})")
    if not leg_ok:
        rc = 1
    return rc, legs


def main() -> int:
    enable_compile_cache()
    os.makedirs(ART_DIR, exist_ok=True)
    with tempfile.TemporaryDirectory() as tmp:
        reports = _spawn_cluster(tmp)
    if reports is None:
        return 1
    r0, r1 = reports
    rc = 0
    if r0["tasks"] != r1["tasks"]:
        print("dcn-smoke: FAIL rank 0 and rank 1 reports differ",
              file=sys.stderr)
        rc = 1
    if r0["mesh_shape"] != [N_PROCS, LOCAL_DEVICES]:
        print(f"dcn-smoke: FAIL mesh shape {r0['mesh_shape']}",
              file=sys.stderr)
        rc = 1

    flat = json.loads(json.dumps(
        run_tasks(list(FLAT_TASKS), pick_mesh())))
    # the stale twin folds THIS process's 4 virtual devices into the
    # same 2x2 global hierarchy the cluster runs
    flat["stale"] = json.loads(json.dumps(run_tasks(
        ["stale"], pick_mesh_2d(hosts=N_PROCS))["stale"]))
    for task in TASKS.split(","):
        same = flat[task] == r0["tasks"][task]
        print(f"dcn-smoke {task:9s} "
              f"{'parity-ok' if same else 'PARITY-FAIL'}")
        if not same:
            print(json.dumps({"cluster": r0["tasks"][task],
                              "twin": flat[task]}, indent=1,
                             sort_keys=True)[:4000], file=sys.stderr)
            rc = 1

    cert = r0["tasks"]["certify"]
    take = r0["tasks"]["takeover"]
    stale = r0["tasks"]["stale"]
    if not cert["ok"]:
        print(f"dcn-smoke: FAIL certify {cert}", file=sys.stderr)
        rc = 1
    if not take["converged"]:
        print(f"dcn-smoke: FAIL takeover {take}", file=sys.stderr)
        rc = 1
    # the certified stale run must show a REAL bounded lag: within
    # k=4 of the sync twin but not free (the spec is seeded so the
    # last drained deltas wait for a refresh round)
    if not (stale["ok"] and stale["delay_rounds"] is not None
            and 1 <= stale["delay_rounds"] <= 4):
        print(f"dcn-smoke: FAIL stale certification {stale}",
              file=sys.stderr)
        rc = 1

    stale_rc, stale_legs = _stale_legs(stale)
    rc = rc or stale_rc

    with open(os.path.join(ART_DIR, "dcn_smoke_report.json"),
              "w") as fh:
        json.dump({"ok": rc == 0, "tasks": r0["tasks"],
                   "mesh_shape": r0["mesh_shape"],
                   "stale_legs": stale_legs},
                  fh, indent=1, sort_keys=True)
        fh.write("\n")
    if rc == 0:
        print("dcn-smoke: 2-proc cluster == 1-proc twin (bit-exact, "
              "sync AND pipelined); certified nemesis ok (round "
              f"{cert['converged_round']}), host-loss takeover "
              f"converged in {take['rounds']} rounds; stale:4 "
              f"certified with delay {stale['delay_rounds']} <= 4, "
              "k-violation falsified, failing bundle replayed "
              "mode-faithfully")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
