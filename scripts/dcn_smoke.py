"""CI DCN smoke (PR 15): a REAL 2-process jax.distributed CPU cluster
— 2 OS processes x 2 virtual devices each, gloo collectives — runs
the shared ``parallel.dcn_worker`` tasks and this parent pins them
bit-exact against its own 1-process x 4-device twin:

- ``sims``      all three sims stepwise + donated-fused (the kafka
                parity leg rides here), seed-replay inside the worker;
- ``certify``   one certified crash+loss broadcast nemesis on the
                structured words-major path;
- ``takeover``  the HOST-loss drill: one DCN host's entire node block
                crashes for a window, the survivors' flood stalls and
                re-converges after restart.

Every compared number is a replicated ledger scalar or an on-device
position-weighted checksum, so rank-vs-rank and cluster-vs-twin
equality is bit-exactness.  One retry with a fresh coordinator port
absorbs the rare gloo startup flake.  Exits nonzero on any mismatch
or failed certification.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gossip_glomers_tpu.parallel.mesh import (  # noqa: E402
    force_virtual_devices)

# the single-process twin matches the cluster's GLOBAL device count:
# 2 procs x 2 devices = one 4-way virtual mesh here
force_virtual_devices(4)

from gossip_glomers_tpu.parallel.dcn_worker import (  # noqa: E402
    run_tasks)
from gossip_glomers_tpu.parallel.mesh import pick_mesh  # noqa: E402
from gossip_glomers_tpu.utils.compile_cache import (  # noqa: E402
    enable_compile_cache)

TASKS = "sims,certify,takeover"
N_PROCS, LOCAL_DEVICES = 2, 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_cluster(tmp: str, timeout: float = 480.0):
    last_diag = ""
    for attempt in range(2):
        out = os.path.join(tmp, f"out{attempt}.json")
        env = dict(os.environ)
        # this parent forced a 4-device split; the workers must see a
        # clean slate so GG_LOCAL_DEVICES=2 applies
        env.pop("XLA_FLAGS", None)
        env.update(JAX_PLATFORMS="cpu",
                   GG_COORDINATOR=f"127.0.0.1:{_free_port()}",
                   GG_NUM_PROCS=str(N_PROCS),
                   GG_LOCAL_DEVICES=str(LOCAL_DEVICES),
                   GG_DCN_TASKS=TASKS, GG_DCN_OUT=out)
        procs, logs = [], []
        for rank in range(N_PROCS):
            log = open(os.path.join(tmp, f"log{attempt}.{rank}"),
                       "w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "gossip_glomers_tpu.parallel.dcn_worker"],
                cwd=REPO, env=dict(env, GG_PROC_ID=str(rank)),
                stdout=log, stderr=subprocess.STDOUT))
        deadline = time.monotonic() + timeout
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(
                    timeout=max(1.0, deadline - time.monotonic())))
            except subprocess.TimeoutExpired:
                rcs.append(None)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        if all(rc == 0 for rc in rcs):
            reports = []
            for rank in range(N_PROCS):
                with open(f"{out}.{rank}") as fh:
                    reports.append(json.load(fh))
            for log in logs:
                log.close()
            return reports
        diag = []
        for rank, log in enumerate(logs):
            log.seek(0)
            diag.append(f"-- rank {rank} rc={rcs[rank]} --\n"
                        + log.read()[-3000:])
            log.close()
        last_diag = "\n".join(diag)
    print(f"dcn-smoke: cluster failed twice\n{last_diag}",
          file=sys.stderr)
    return None


def main() -> int:
    enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        reports = _spawn_cluster(tmp)
    if reports is None:
        return 1
    r0, r1 = reports
    rc = 0
    if r0["tasks"] != r1["tasks"]:
        print("dcn-smoke: FAIL rank 0 and rank 1 reports differ",
              file=sys.stderr)
        rc = 1
    if r0["mesh_shape"] != [N_PROCS, LOCAL_DEVICES]:
        print(f"dcn-smoke: FAIL mesh shape {r0['mesh_shape']}",
              file=sys.stderr)
        rc = 1

    flat = json.loads(json.dumps(
        run_tasks(TASKS.split(","), pick_mesh())))
    for task in TASKS.split(","):
        same = flat[task] == r0["tasks"][task]
        print(f"dcn-smoke {task:9s} "
              f"{'parity-ok' if same else 'PARITY-FAIL'}")
        if not same:
            print(json.dumps({"cluster": r0["tasks"][task],
                              "twin": flat[task]}, indent=1,
                             sort_keys=True)[:4000], file=sys.stderr)
            rc = 1

    cert = r0["tasks"]["certify"]
    take = r0["tasks"]["takeover"]
    if not cert["ok"]:
        print(f"dcn-smoke: FAIL certify {cert}", file=sys.stderr)
        rc = 1
    if not take["converged"]:
        print(f"dcn-smoke: FAIL takeover {take}", file=sys.stderr)
        rc = 1
    if rc == 0:
        print("dcn-smoke: 2-proc cluster == 1-proc twin (bit-exact); "
              f"certified nemesis ok (round "
              f"{cert['converged_round']}), host-loss takeover "
              f"converged in {take['rounds']} rounds")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
