#!/usr/bin/env python
"""CI fuzz smoke (PR 10): a small seeded fault-space fuzz run with a
planted failing seed, end to end through the auto-shrinker.

Asserts the whole scenario-axis pipeline:

- >= 64 scenarios certified in compiled batch dispatches on the 8-way
  virtual CPU mesh (scenario-sharded — tpu_sim/scenario.py), one
  PLANTED provably-failing cell among them;
- the planted failure is detected by the batched recovery certifier
  (named by scenario index), reproduced sequentially, and auto-shrunk
  (harness/fuzz.py): the shrunk repro's flight bundle is WRITTEN,
  schema-valid (observe.load_bundle), strictly SMALLER than the
  original cell (fuzz.scenario_weight), every retained fault
  component is load-bearing, and ``replay_bundle`` reproduces the
  SAME checker failure from the bundle's JSON alone with a faithful
  (divergence-free) record;
- artifacts land in ``artifacts/fuzz_smoke/`` (uploaded by CI).

Exit nonzero on any failed assertion.
"""

from __future__ import annotations

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import jax                                                  # noqa: E402
import numpy as np                                          # noqa: E402
from jax.sharding import Mesh                               # noqa: E402

from gossip_glomers_tpu.harness import fuzz as FZ           # noqa: E402
from gossip_glomers_tpu.harness import observe              # noqa: E402

OUT = pathlib.Path(__file__).resolve().parent.parent \
    / "artifacts" / "fuzz_smoke"


def main() -> int:
    OUT.mkdir(parents=True, exist_ok=True)
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
    res = FZ.fuzz_run(
        "broadcast", 64, n_nodes=24, batch_size=32, horizon=8,
        max_recovery_rounds=48, seed=7, mesh=mesh,
        plant_failure=True, max_shrinks=3, observe_dir=str(OUT))
    print(f"fuzz: {res['n_certified_ok']}/{res['n_scenarios']} "
          f"certified ({res['n_distinct']} distinct), "
          f"{res['n_failing']} failing, "
          f"{res['scenarios_per_sec']}/s")
    ok = True

    def check(cond: bool, msg: str) -> None:
        nonlocal ok
        print(("ok  " if cond else "FAIL") + f" {msg}")
        ok = ok and cond

    check(res["n_scenarios"] >= 64, ">= 64 scenarios dispatched")
    check(res["n_distinct"] >= 64, "all scenario cells distinct")
    check(res["n_failing"] >= 1, "the planted failing seed failed")
    planted = next(
        (s for s in res["shrinks"]
         if s["original"]["spec"]["seed"] == 424242), None)
    check(planted is not None, "planted seed reached the shrinker")
    if planted is None:
        return 1
    check(planted["weight_after"] < planted["weight_before"],
          f"shrunk repro is smaller "
          f"({planted['weight_before']} -> "
          f"{planted['weight_after']})")
    check(bool(planted["moves_accepted"]),
          "shrinker accepted at least one reduction")
    check(planted["all_components_load_bearing"],
          "every retained fault component is load-bearing")
    bundle_path = planted["bundle"]
    check(bundle_path is not None and
          pathlib.Path(bundle_path).exists(),
          f"shrunk flight bundle written ({bundle_path})")
    bundle = observe.load_bundle(bundle_path)   # schema-valid or raises
    check(bundle["workload"] == "broadcast",
          "bundle schema valid (load_bundle)")
    check(planted["replay_same_failure"],
          "shrunk bundle replays to the SAME failure from JSON alone")
    # an independent replay from the file (not the shrinker's cached
    # verdict): same failure signature, faithful record
    replay = observe.replay_bundle(bundle_path)
    sig = FZ.failure_signature(replay)
    check(sig is not None, "independent replay still fails")
    check(replay.get("first_divergence_round") is None,
          "independent replay is divergence-free")
    (OUT / "fuzz_smoke_report.json").write_text(json.dumps(
        {k: v for k, v in res.items() if k != "rows"},
        indent=1, default=str) + "\n")
    print("fuzz smoke", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
