#!/bin/sh
# Full-suite smoke gate: the whole test suite on the virtual 8-device
# CPU mesh, stop at first failure.  Runs against the STAGED snapshot
# (a temp checkout of the index), not the working tree, so a partially
# staged commit cannot land red (VERDICT r2 item 1).  Installed as a
# symlink at .git/hooks/pre-commit by scripts/install-hooks.sh.
# Bypass for WIP commits: GG_SKIP_SMOKE=1 or git commit --no-verify.
set -e
if [ "${GG_SKIP_SMOKE:-0}" = "1" ]; then
    echo "smoke: skipped (GG_SKIP_SMOKE=1)"
    exit 0
fi
cd "$(git rev-parse --show-toplevel)"
tmp=$(mktemp -d)
trap 'rm -rf "$tmp"' EXIT
git checkout-index -a --prefix="$tmp/"
cd "$tmp"
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m pytest tests/ -x -q
