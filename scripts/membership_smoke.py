#!/usr/bin/env python
"""CI membership smoke (PR 17): dynamic membership end to end on CPU,
seconds — the budget-safe slice the tier-1 gate runs on every push:

1. one certified join+leave churn campaign per stateful sim
   (``run_*_nemesis``): joiners enter empty and catch up through the
   workload's own anti-entropy, leavers drain first (the fuzzer's
   drain-margin convention) — bounded recovery, zero lost acked
   writes;
2. one certified elastic RESIZE campaign per stateful sim
   (``harness.membership.run_resize_campaign``): checkpoint at the
   boundary (the fault spec rides the meta), restore into a
   larger/smaller padded node axis, certify across the boundary —
   broadcast/counter pinned bit-exact against their straight-through
   twins, the broadcast grow also verifying the KV re-homing diff
   against the host routing twin;
3. planted-failure probe: a counter leave WITHOUT the drain margin
   MUST fail naming the lost delta shortfall, and its flight bundle
   must replay to the same verdict from its JSON alone
   (first-divergence None — a checker that cannot fail certifies
   nothing);
4. a membership-churn fuzz slice with coverage-steered sampling
   (``fuzz_run(membership_axis=True, adapt=True)``): the behavioral
   signature's churn bucket must populate the coverage map with
   distinct churn cells.

Exits nonzero on any failure.  Output dir: ``GG_OBSERVE_DIR``
(default ``artifacts/membership_smoke``).
"""

from __future__ import annotations

import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import jax                                                    # noqa: E402
import numpy as np                                            # noqa: E402
from jax.sharding import Mesh                                 # noqa: E402

from gossip_glomers_tpu.harness import fuzz as FZ             # noqa: E402
from gossip_glomers_tpu.harness import membership as HM       # noqa: E402
from gossip_glomers_tpu.harness import nemesis as NM          # noqa: E402
from gossip_glomers_tpu.harness import observe                # noqa: E402
from gossip_glomers_tpu.tpu_sim import telemetry as TM        # noqa: E402
from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec     # noqa: E402


def main() -> int:
    out = pathlib.Path(os.environ.get("GG_OBSERVE_DIR",
                                      "artifacts/membership_smoke"))
    out.mkdir(parents=True, exist_ok=True)
    failed = []
    report = {}

    # 1. certified join+leave churn at fixed capacity, per sim.  The
    # leave rounds carry the drain margin (clear + n + 2): a leave is
    # permanent, so anti-entropy must replicate the row's uniquely
    # held acked state first.
    n = 12
    churn = {
        "broadcast": NemesisSpec(
            n_nodes=n, seed=3, crash=((2, 6, (1, 2)),),
            join=((4, (9, 10, 11)),), leave=((20, (5,)),)),
        "counter": NemesisSpec(
            n_nodes=n, seed=5, crash=((4, 8, (1,)),),
            join=((6, (10, 11)),), leave=((22, (5,)),)),
        "kafka": NemesisSpec(
            n_nodes=n, seed=7, crash=((2, 6, (1,)),),
            join=((4, (10, 11)),), leave=((20, (5,)),)),
    }
    runners = {
        "broadcast": lambda sp: NM.run_broadcast_nemesis(
            sp, n_values=24, max_recovery_rounds=48),
        "counter": lambda sp: NM.run_counter_nemesis(
            sp, max_recovery_rounds=48),
        "kafka": lambda sp: NM.run_kafka_nemesis(
            sp, n_keys=4, max_recovery_rounds=48),
    }
    for wl, sp in churn.items():
        res = runners[wl](sp)
        print(f"membership-smoke churn-{wl:9s} "
              f"{'ok' if res['ok'] else 'FAIL'}  "
              f"converged={res['converged_round']} "
              f"recovery={res['recovery_rounds']} "
              f"lost={res['lost_writes']}")
        report[f"churn_{wl}"] = {
            "ok": bool(res["ok"]), "spec": sp.to_meta(),
            "converged_round": res["converged_round"],
            "recovery_rounds": res["recovery_rounds"],
            "lost_writes": res["lost_writes"]}
        if not res["ok"]:
            failed.append((f"churn-{wl}", res["lost_writes"]))

    # 2. certified elastic resize per sim: broadcast grows 8 -> 12
    # with the re-homing diff verified, counter shrinks 12 -> 8,
    # kafka grows 8 -> 12 (certified-only — module docstring); every
    # campaign's crash window CROSSES the resize boundary.
    resizes = {
        "broadcast": dict(
            spec=NemesisSpec(n_nodes=8, seed=3,
                             crash=((4, 9, (1, 2)),)),
            n_to=12, resize_round=6, kv_keys=128),
        "counter": dict(
            spec=NemesisSpec(n_nodes=12, seed=5,
                             crash=((16, 21, (1,)),),
                             leave=((16, (8, 9, 10, 11)),)),
            n_to=8, resize_round=18),
        "kafka": dict(
            spec=NemesisSpec(n_nodes=8, seed=7,
                             crash=((4, 9, (1, 2)),)),
            n_to=12, resize_round=6),
    }
    for wl, kw in resizes.items():
        sp = kw.pop("spec")
        res = HM.run_resize_campaign(
            wl, sp, kw.pop("n_to"), kw.pop("resize_round"),
            max_recovery_rounds=48, **kw)
        twin = res["twin"]["bit_exact"]
        rh = res.get("rehoming")
        print(f"membership-smoke resize-{wl:8s} "
              f"{'ok' if res['ok'] else 'FAIL'}  "
              f"{res['n_from']}->{res['n_to']}@{res['resize_round']} "
              f"twin={twin} "
              f"rehomed={rh['n_moved'] if rh else '-'}")
        report[f"resize_{wl}"] = {
            k: res[k] for k in
            ("ok", "n_from", "n_to", "resize_round",
             "converged_round", "recovery_rounds", "lost_writes")}
        report[f"resize_{wl}"]["twin_bit_exact"] = twin
        if rh:
            report[f"resize_{wl}"]["rehoming"] = {
                "n_moved": rh["n_moved"], "ok": rh["ok"]}
        if not res["ok"]:
            failed.append((f"resize-{wl}", res["lost_writes"]))

    # 3. planted failure: a counter leave WITHOUT the drain margin
    # loses the leavers' acked unflushed deltas — must fail naming
    # the shortfall, and the flight bundle must replay to the same
    # verdict from its JSON alone
    bad_spec = NemesisSpec(n_nodes=12, seed=5, crash=((4, 9, (1,)),),
                           leave=((3, (8, 9, 10, 11)),))
    tel = TM.TelemetrySpec("counter",
                           rounds=bad_spec.clear_round + 48)
    bad = NM.run_counter_nemesis(bad_spec, max_recovery_rounds=48,
                                 telemetry=tel,
                                 observe_dir=str(out))
    named = (not bad["ok"] and bad["lost_writes"]
             and "flight_bundle" in bad)
    faithful = False
    if named:
        replay = observe.replay_bundle(bad["flight_bundle"])
        faithful = (not replay["ok"]
                    and replay["first_divergence_round"] is None
                    and replay["lost_writes"] == bad["lost_writes"])
    print(f"membership-smoke planted-leave "
          f"{'ok' if named and faithful else 'FAIL'}  "
          f"lost={bad['lost_writes']} replay_faithful={faithful}")
    report["planted_leave"] = {
        "spec": bad_spec.to_meta(), "named": bool(named),
        "lost_writes": bad["lost_writes"],
        "replay_faithful": bool(faithful)}
    if not (named and faithful):
        failed.append(("planted-leave", bad.get("lost_writes")))

    # 4. membership-churn fuzz slice with coverage-steered sampling:
    # the signature's churn bucket must separate churn shapes in the
    # coverage map
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("nodes",))
    fz = FZ.fuzz_run("broadcast", 32, n_nodes=12, batch_size=16,
                     horizon=6, max_recovery_rounds=32, seed=8,
                     mesh=mesh, delay_axis="off",
                     membership_axis=True, adapt=True, shrink=False,
                     observe_dir=str(out))
    churn_buckets = {r["signature"][4]
                     for r in fz["rows"] if "signature" in r}
    print(f"membership-smoke fuzz-32      "
          f"{'ok' if fz['n_failing'] == 0 else 'FAIL'}  "
          f"certified={fz['n_certified_ok']}/{fz['n_scenarios']} "
          f"churn_buckets={sorted(churn_buckets)}")
    report["fuzz"] = {
        "n_scenarios": fz["n_scenarios"],
        "n_certified_ok": fz["n_certified_ok"],
        "n_failing": fz["n_failing"],
        "churn_buckets": sorted(int(b) for b in churn_buckets)}
    if fz["n_failing"] or len(churn_buckets) < 2:
        failed.append(("fuzz", fz["failing"] or churn_buckets))

    observe.write_json_atomic(str(out / "membership_report.json"),
                              report)
    if failed:
        print(f"membership-smoke FAILED: {failed}")
        return 1
    print("membership-smoke all ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
