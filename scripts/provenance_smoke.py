#!/usr/bin/env python
"""CI provenance smoke (PR 9): one certified crash+loss run per
stateful sim on the PROVENANCE-ON observed drivers, end to end on
CPU, seconds — the budget-safe slice the tier-1 gate runs on every
push:

1. each run records the causal stamps next to the state
   (tpu_sim/provenance.py) and ``checkers.check_provenance``
   certifies them against the fault model itself (the host
   re-evaluates the liveness/loss coins of every claimed edge);
2. the broadcast dissemination-tree artifact is WRITTEN and
   schema-validated (``observe.validate_tree``) and the timeline
   carries the causal flow arrows — the artifact directory is
   uploaded as a CI build artifact;
3. falsifiability probe: a forged parent on a dead edge must FAIL
   the checker (a checker that cannot fail certifies nothing);
4. the first-divergence hook: a forced failure's flight bundle
   replays with ``first_divergence_round`` None, and a tampered
   record fires.

Exits nonzero on any failure.  Output dir: ``GG_OBSERVE_DIR``
(default ``artifacts/provenance_smoke``).
"""

from __future__ import annotations

import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import numpy as np                                            # noqa: E402

from gossip_glomers_tpu.harness import nemesis as NM          # noqa: E402
from gossip_glomers_tpu.harness import observe                # noqa: E402
from gossip_glomers_tpu.harness.checkers import check_provenance  # noqa: E402
from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec     # noqa: E402

N = 16
# certified crash+loss scenarios (counter's crash window opens after
# the acked deltas drained — amnesia before the flush is a REAL loss)
SPECS = {
    "broadcast": NemesisSpec(n_nodes=N, seed=5,
                             crash=((2, 5, (1, 8)),),
                             loss_rate=0.15, loss_until=8),
    "counter": NemesisSpec(n_nodes=N, seed=3,
                           crash=((12, 16, (1,)),),
                           loss_rate=0.1, loss_until=6),
    "kafka": NemesisSpec(n_nodes=N, seed=5, crash=((2, 5, (1, 8)),),
                         loss_rate=0.15, loss_until=8),
}
RUNNERS = {"broadcast": NM.run_broadcast_nemesis,
           "counter": NM.run_counter_nemesis,
           "kafka": NM.run_kafka_nemesis}


def main() -> int:
    out = pathlib.Path(os.environ.get("GG_OBSERVE_DIR",
                                      "artifacts/provenance_smoke"))
    out.mkdir(parents=True, exist_ok=True)
    failed = []

    for kind in ("broadcast", "counter", "kafka"):
        res = RUNNERS[kind](SPECS[kind], provenance=True,
                            telemetry=True, observe_dir=str(out))
        p = res.get("provenance", {})
        chk = p.get("check", {})
        print(f"provenance-smoke {kind:10s} "
              f"{'ok' if res['ok'] else 'FAIL'}  "
              f"converged={res['converged_round']} "
              f"check={ {k: v for k, v in chk.items() if k != 'problems'} }")
        if not res["ok"]:
            failed.append((kind, chk.get("problems",
                                         res["n_lost_writes"])))
            continue
        if kind == "broadcast":
            tree = p["tree"]
            observe.validate_tree(tree)
            tpath = observe.write_json_atomic(
                str(out / "dissemination_tree_broadcast.json"), tree)
            tl = observe.run_timeline(res)
            observe.validate_timeline(tl)
            flows = sum(1 for e in tl["traceEvents"]
                        if e["ph"] == "s")
            if not flows:
                failed.append((kind, "timeline has no flow events"))
            observe.write_json_atomic(
                str(out / "timeline_broadcast_flows.json"), tl)
            print(f"  tree={os.path.basename(tpath)} "
                  f"edges={tree['n_tree_edges']} "
                  f"critical_path={tree['critical_path']['span_rounds']}"
                  f"r/{tree['critical_path']['hops']}h flows={flows}")

    # falsifiability probe: forged parent on a dead edge fails loudly
    spec = NemesisSpec(n_nodes=3, seed=1, crash=((2, 20, (1,)),))
    nbrs = np.array([[1, -1], [0, 2], [1, -1]], np.int32)
    forged = {"arrival": np.array([[0], [2], [5]], np.int32),
              "parent": np.array([[-1], [0], [1]], np.int32)}
    ok_f, det_f = check_provenance(
        "broadcast", forged, spec=spec, nbrs=nbrs,
        received=np.ones((3, 1), bool), msgs_total=100)
    print(f"provenance-smoke falsifiable "
          f"{'ok' if not ok_f else 'FAIL'}  "
          f"problems={len(det_f['problems'])}")
    if ok_f:
        failed.append(("falsifiability",
                       "forged dead-edge parent passed"))

    # first-divergence hook: forced failure -> bundle -> faithful
    # replay reports None; a tampered stamp fires
    spec_k = NemesisSpec(n_nodes=8, seed=3, crash=((2, 6, (1, 5)),),
                         loss_rate=0.2, loss_until=8)
    bad = NM.run_kafka_nemesis(spec_k, provenance=True,
                               telemetry=True, observe_dir=str(out),
                               max_recovery_rounds=0)
    if bad["ok"] or "flight_bundle" not in bad:
        failed.append(("divergence", "forced failure wrote no "
                       "bundle"))
    else:
        replay = observe.replay_bundle(bad["flight_bundle"])
        faithful = replay["first_divergence_round"] is None
        bundle = observe.load_bundle(bad["flight_bundle"])
        tampered = {k: [list(r) for r in v]
                    for k, v in bundle["provenance"].items()}
        fired = None
        for row in tampered["alloc_round"]:
            for i, r in enumerate(row):
                if r >= 1 and fired is None:
                    row[i] = r + 7
                    fired = r
        replay2 = observe.replay_bundle(
            dict(bundle, provenance=tampered))
        hit = replay2["first_divergence_round"] == fired
        print(f"provenance-smoke divergence "
              f"{'ok' if faithful and hit else 'FAIL'}  "
              f"faithful={replay['first_divergence_round']} "
              f"tampered={replay2['first_divergence_round']}=={fired}")
        if not (faithful and hit):
            failed.append(("divergence", (faithful, hit)))

    if failed:
        print(f"provenance-smoke: {len(failed)} leg(s) failed: "
              f"{failed}", file=sys.stderr)
        return 1
    print("provenance-smoke: all legs ok, artifacts in", out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
