#!/bin/sh
# Install the repo's git hooks (currently: pre-commit = scripts/smoke.sh).
# Symlinked, so later edits to scripts/smoke.sh take effect immediately.
set -e
cd "$(git rev-parse --show-toplevel)"
chmod +x scripts/smoke.sh
ln -sf ../../scripts/smoke.sh .git/hooks/pre-commit
echo "installed pre-commit smoke hook (symlink)"
