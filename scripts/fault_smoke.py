"""CI fault-matrix smoke: one CRASH and one LOSS scenario per stateful
sim, on CPU, seconds-not-minutes — the budget-safe slice of
benchmarks/fault_sweep.py the tier-1 gate runs on every push.

Exits nonzero if any scenario fails recovery certification (bounded
convergence after faults clear, zero lost acknowledged writes).
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

try:
    from gossip_glomers_tpu.harness import nemesis
except ImportError:  # bare checkout (no pip install -e .)
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from gossip_glomers_tpu.harness import nemesis
from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec  # noqa: E402
from gossip_glomers_tpu.tpu_sim.traffic import TrafficSpec  # noqa: E402

N = 8
CRASH = NemesisSpec(n_nodes=N, seed=3, crash=((12, 16, (1, 5)),))
LOSS = NemesisSpec(n_nodes=N, seed=4, loss_rate=0.2, loss_until=10)
# crash+loss WHILE open-loop client traffic flows (PR 7): the serving
# certifier must drain every acked op after the plan clears — zero
# lost, bounded drain, latency keys in the verdict
CRASH_LOSS = NemesisSpec(n_nodes=N, seed=5, crash=((6, 10, (2, 6)),),
                         loss_rate=0.15, loss_until=16)
TRAFFIC = TrafficSpec(n_nodes=N, n_clients=8, ops_per_client=8,
                      until=20, rate=0.3, seed=1)

SCENARIOS = [
    ("broadcast/crash", nemesis.run_broadcast_nemesis, CRASH, {}),
    ("broadcast/loss", nemesis.run_broadcast_nemesis, LOSS, {}),
    # the words-major structured path under the SAME plans (PR 3):
    # certifies the gather-free nemesis decomposition on every push
    ("broadcast/s-crash", nemesis.run_broadcast_nemesis, CRASH,
     {"structured": True, "topology": "tree"}),
    ("broadcast/s-loss", nemesis.run_broadcast_nemesis, LOSS,
     {"structured": True}),
    ("counter/crash", nemesis.run_counter_nemesis, CRASH, {}),
    ("counter/loss", nemesis.run_counter_nemesis, LOSS, {}),
    ("kafka/crash", nemesis.run_kafka_nemesis, CRASH, {}),
    ("kafka/loss", nemesis.run_kafka_nemesis, LOSS, {}),
    # crash+loss under open-loop serving load, one per sim (PR 7)
    ("broadcast/load", nemesis.run_broadcast_nemesis, CRASH_LOSS,
     {"traffic": TRAFFIC}),
    ("counter/load", nemesis.run_counter_nemesis, CRASH_LOSS,
     {"traffic": TRAFFIC}),
    ("kafka/load", nemesis.run_kafka_nemesis, CRASH_LOSS,
     {"traffic": TRAFFIC}),
]


def main() -> int:
    failed = []
    for name, run, spec, kw in SCENARIOS:
        res = run(spec, **kw)
        status = "ok" if res["ok"] else "FAIL"
        lat = (f" p99={res['lat_p99']}" if "lat_p99" in res else "")
        print(f"fault-smoke {name:16s} {status}  "
              f"recovery={res['recovery_rounds']} "
              f"lost={res['n_lost_writes']} msgs={res['msgs_total']}"
              f"{lat}")
        if not res["ok"]:
            failed.append((name, res))
    if failed:
        print(f"fault-smoke: {len(failed)} scenario(s) failed",
              file=sys.stderr)
        return 1
    print("fault-smoke: all scenarios certified")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
