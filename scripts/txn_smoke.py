#!/usr/bin/env python
"""CI txn smoke (PR 14): the txn-rw-register workload end to end on
CPU, seconds — the budget-safe slice the tier-1 gate runs on every
push:

1. one certified crash+loss campaign (``run_txn_nemesis``): bounded
   recovery, zero lost acked commits, serializable device-recorded
   history (``check_txn_serializable`` over the per-op version/value
   stamps + commit-round provenance);
2. a fuzzed 64-scenario crash+loss campaign certified in ONE batched
   dispatch on the 8-way virtual mesh (``scenario.run_txn_batch``) —
   the acceptance-criterion shape;
3. planted-anomaly probe: ``kv_amnesia=True`` owner wipes MUST fail
   the serializability check with named lost updates, the failure's
   flight bundle replays to the same verdict from its JSON alone with
   bit-faithful per-transaction stamps (first-divergence None), and a
   hand-planted write-skew history fails the checker naming both
   transaction ids (a checker that cannot fail certifies nothing).

Exits nonzero on any failure.  Output dir: ``GG_OBSERVE_DIR``
(default ``artifacts/txn_smoke``).
"""

from __future__ import annotations

import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import jax                                                    # noqa: E402
import numpy as np                                            # noqa: E402
from jax.sharding import Mesh                                 # noqa: E402

from gossip_glomers_tpu.harness import fuzz as FZ             # noqa: E402
from gossip_glomers_tpu.harness import observe                # noqa: E402
from gossip_glomers_tpu.harness import txn as HTX             # noqa: E402
from gossip_glomers_tpu.harness.checkers import (             # noqa: E402
    check_txn_serializable)
from gossip_glomers_tpu.tpu_sim import kvstore as KV          # noqa: E402
from gossip_glomers_tpu.tpu_sim import scenario as SC         # noqa: E402
from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec     # noqa: E402


def main() -> int:
    out = pathlib.Path(os.environ.get("GG_OBSERVE_DIR",
                                      "artifacts/txn_smoke"))
    out.mkdir(parents=True, exist_ok=True)
    failed = []

    # 1. certified crash+loss campaign
    spec = NemesisSpec(n_nodes=8, seed=3, crash=((3, 6, (4,)),),
                       loss_rate=0.2, loss_until=6)
    res = HTX.run_txn_nemesis(spec, n_keys=8, until=12,
                              max_recovery_rounds=48,
                              observe_dir=str(out))
    print(f"txn-smoke nemesis    {'ok' if res['ok'] else 'FAIL'}  "
          f"converged={res['converged_round']} "
          f"committed={res['n_committed']}/{res['n_txns']} "
          f"by_kind={res['serializability']['by_kind']}")
    if not res["ok"]:
        failed.append(("nemesis", res["serializability"]["problems"]))

    # 2. 64 fuzzed crash+loss scenarios, ONE batched dispatch, 8-way
    # virtual mesh — the acceptance-criterion shape
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("nodes",))
    scs = FZ.sample_scenarios("txn", 64, n_nodes=16, seed=3,
                              horizon=8)
    batch = SC.ScenarioBatch(
        workload="txn", scenarios=tuple(scs),
        runner_kw=dict(n_keys=8, txns_per_node=4, ops_per_txn=2,
                       rate=0.5, until=16),
        max_recovery_rounds=48)
    bres = SC.run_txn_batch(batch, mesh=mesh)
    n_comm = sum(r["n_committed"] for r in bres["scenarios"])
    n_lost = sum(r["n_lost_writes"] for r in bres["scenarios"])
    print(f"txn-smoke batch-64   {'ok' if bres['ok'] else 'FAIL'}  "
          f"scenarios={len(bres['scenarios'])} committed={n_comm} "
          f"lost_acked={n_lost}")
    if not bres["ok"] or n_lost:
        failed.append(("batch-64", bres["failing"]))
    observe.write_json_atomic(
        str(out / "txn_batch64_report.json"),
        {"n_scenarios": len(bres["scenarios"]),
         "n_committed": n_comm, "n_lost_acked": n_lost,
         "ok": bool(bres["ok"]),
         "rows": [{k: row[k] for k in
                   ("ok", "converged_round", "recovery_rounds",
                    "msgs_total", "n_committed", "serializable")}
                  for row in bres["scenarios"]]})

    # 3a. planted anomaly: kv_amnesia owner wipes fail loudly with
    # named lost updates, and the bundle replays to the same verdict
    owners = KV.host_owner_of(np.arange(8, dtype=np.int32), 8, 0)
    aspec = NemesisSpec(n_nodes=8, seed=3,
                        crash=((3, 6, (int(owners[0]),)),))
    bad = HTX.run_txn_nemesis(aspec, n_keys=8, until=12,
                              max_recovery_rounds=48,
                              kv_amnesia=True, observe_dir=str(out))
    lost = [p for p in bad["serializability"]["problems"]
            if p["kind"] in ("lost-update", "lost-acked-commit")]
    named = bool(lost) and all(p["txns"] for p in lost)
    if bad["ok"] or not named or "flight_bundle" not in bad:
        print("txn-smoke amnesia    FAIL  wipe did not fail loudly")
        failed.append(("amnesia", bad["serializability"]["by_kind"]))
    else:
        replay = observe.replay_bundle(bad["flight_bundle"])
        faithful = (not replay["ok"]
                    and replay["first_divergence_round"] is None
                    and replay["serializability"]["by_kind"]
                    == bad["serializability"]["by_kind"])
        print(f"txn-smoke amnesia    {'ok' if faithful else 'FAIL'}  "
              f"by_kind={bad['serializability']['by_kind']} "
              f"first_txns={lost[0]['txns']} "
              f"divergence={replay['first_divergence_round']}")
        if not faithful:
            failed.append(("amnesia-replay",
                           replay["first_divergence_round"]))

    # 3b. planted history: classic write skew must fail naming ids
    skew = [
        {"id": 1, "status": "committed", "commit_round": 2,
         "ops": [{"kind": "r", "key": 0, "ver": 0, "val": 0},
                 {"kind": "w", "key": 1, "ver": 1, "val": 5}]},
        {"id": 2, "status": "committed", "commit_round": 2,
         "ops": [{"kind": "r", "key": 1, "ver": 0, "val": 0},
                 {"kind": "w", "key": 0, "ver": 1, "val": 6}]},
    ]
    ok_s, det_s = check_txn_serializable(skew)
    cyc = [p for p in det_s["problems"] if p["kind"] == "write-cycle"]
    hit = not ok_s and cyc and cyc[0]["txns"] == [1, 2]
    print(f"txn-smoke falsifiable {'ok' if hit else 'FAIL'}  "
          f"by_kind={det_s['by_kind']}")
    if not hit:
        failed.append(("falsifiability", det_s["by_kind"]))

    if failed:
        print(f"txn-smoke: {len(failed)} leg(s) failed: {failed}",
              file=sys.stderr)
        return 1
    print("txn-smoke: all legs ok, artifacts in", out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
