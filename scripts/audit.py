#!/usr/bin/env python
"""Run the program-contract auditor (tpu_sim/audit.py) — the CI audit
leg and the ``AUDIT_PR*.json`` artifact writer.

Audits every registered driver contract on the CPU 8-way virtual mesh
(the same SPMD partitioner and collectives as real chips — what the
tier-1 suite runs on) and runs the determinism lint over the package.
Exit status is nonzero on ANY failed contract or lint finding, so a
refactor that re-grows an all-gather, silently drops a donation,
sneaks a host callback into a round, breaks the analytic memory
formula, or lands a nondeterminism source in traced code fails the
push — not the next hand-run benchmark.

Usage: ``python scripts/audit.py [--out AUDIT.json]``
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import jax                                                  # noqa: E402
import numpy as np                                          # noqa: E402
from jax.sharding import Mesh                               # noqa: E402

from gossip_glomers_tpu.tpu_sim import audit                # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="write the report JSON here (e.g. "
                         "AUDIT_PR6.json)")
    args = ap.parse_args()

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("nodes",))
    report = audit.run_audit(mesh)
    findings = audit.lint_paths(REPO / "gossip_glomers_tpu")
    report["determinism_lint"] = {
        "ok": not findings,
        "n_findings": len(findings),
        "findings": [f.as_dict() for f in findings],
    }
    report["ok"] = report["ok"] and not findings
    report["mesh"] = {"backend": jax.default_backend(),
                      "n_devices": 8, "axis": "nodes"}

    for row in report["contracts"]:
        cs = row["checks"]
        cen = cs["collectives"]["counts"]
        mem = cs["memory"]
        extra = (f" mem-ratio {mem['ratio']}"
                 if mem.get("checked") else "")
        print(f"[{'ok' if row['ok'] else 'FAIL'}] {row['name']}: "
              f"collectives {cen or '{}'}"
              f" aliases {cs['donation']['entries']}{extra}")
        if not row["ok"]:
            print(json.dumps(cs, indent=2))
    lint = report["determinism_lint"]
    print(f"[{'ok' if lint['ok'] else 'FAIL'}] determinism lint: "
          f"{lint['n_findings']} findings")
    for f in findings:
        print(f"  {f.path}:{f.line} [{f.rule}] {f.msg}")

    if args.out:
        out = pathlib.Path(args.out)
        out.write_text(json.dumps(report, indent=1) + "\n")
        print(f"wrote {out}")
    print("audit", "OK" if report["ok"] else "FAILED",
          f"({report['n_contracts']} contracts)")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
