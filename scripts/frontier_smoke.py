#!/usr/bin/env python
"""CI frontier smoke (PR 13): a small serving-frontier cartography
run end to end through the coverage observatory and the SLO flight
recorder.

Asserts the whole (load x fault x topology) pipeline:

- a 16-cell frontier grid certified in scenario-sharded batch
  dispatches on the 8-way virtual CPU mesh, bit-exact per-cell
  latency/throughput surfaces with behavioral signatures recorded
  on-device (tpu_sim/scenario.py, harness/frontier.py);
- the frontier report is schema-valid (observe.validate_frontier),
  its coverage map is consistent, and the Perfetto timeline renders
  + validates;
- a PLANTED SLO violation (p99 bound below the achievable floor on
  the loss+crash row) fails loudly naming its grid coordinates, its
  flight bundle is WRITTEN with the TrafficSpec + NemesisSpec + grid
  coords, and ``replay_bundle`` reproduces the SAME check_slo
  failure from the bundle's JSON alone with a divergence-free
  record;
- artifacts land in ``artifacts/frontier_smoke/`` (uploaded by CI).

Exit nonzero on any failed assertion.
"""

from __future__ import annotations

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

import jax                                                  # noqa: E402
import numpy as np                                          # noqa: E402
from jax.sharding import Mesh                               # noqa: E402

from gossip_glomers_tpu.harness import frontier as FR       # noqa: E402
from gossip_glomers_tpu.harness import observe              # noqa: E402
from gossip_glomers_tpu.harness.checkers import check_slo   # noqa: E402

OUT = pathlib.Path(__file__).resolve().parent.parent \
    / "artifacts" / "frontier_smoke"


def main() -> int:
    OUT.mkdir(parents=True, exist_ok=True)
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))
    cells = FR.frontier_grid(
        "broadcast", n_nodes=16,
        rates=(0.2, 0.4, 0.6, 0.8),
        fault_levels=(None, {"n_crash_windows": 1,
                             "loss_rate": 0.15}),
        topologies=("grid", "tree"), until=10, seed=3)
    rep = FR.run_frontier(
        "broadcast", cells, mesh=mesh, batch_size=8,
        slo={"p99_max_rounds": 1, "min_completed": 1},
        max_recovery_rounds=24, drain_every=4,
        observe_dir=str(OUT))
    print(f"frontier: {rep['n_cells']} cells in "
          f"{rep['n_batches']} batches "
          f"({'pipelined' if rep['pipelined'] else 'sync'}), "
          f"{rep['cells_per_sec']}/s, "
          f"{rep['coverage']['n_distinct']} distinct behaviors, "
          f"{len(rep['failing'])} SLO-failing")
    ok = True

    def check(cond: bool, msg: str) -> None:
        nonlocal ok
        print(("ok  " if cond else "FAIL") + f" {msg}")
        ok = ok and cond

    check(rep["n_cells"] == 16, "16-cell grid dispatched")
    observe.validate_frontier(rep)   # schema-valid or raises
    check(True, "frontier report schema valid (validate_frontier)")
    check(all(c["ok"] for c in rep["cells"]),
          "every cell passed the serving certifier "
          "(drain/conservation)")
    check(rep["coverage"]["n_seen"] == 16,
          "one behavioral signature recorded per cell")
    check(rep["coverage"]["n_distinct"] >= 2,
          "the surface exercises >= 2 distinct behaviors")
    tl = FR.frontier_timeline(rep)
    observe.validate_timeline(tl)
    check(any(ev.get("name") == "coverage/distinct_behaviors"
              for ev in tl["traceEvents"]),
          "Perfetto timeline renders coverage counters")
    (OUT / "frontier_timeline.json").write_text(
        json.dumps(tl) + "\n")

    # the planted p99 SLO (1 round) is below the achievable floor,
    # so cells fail loudly naming their grid coordinates
    check(len(rep["failing"]) >= 1, "planted SLO violation detected")
    check(any("p99 latency" in p for p in rep["problems"]),
          "violation names the broken bound")
    check(any(p.startswith("cell(") for p in rep["problems"]),
          "violation names the cell's grid coordinates")
    check(len(rep["bundles"]) == len(rep["failing"]),
          "one flight bundle per SLO-failing cell")
    b = rep["bundles"][0]
    bundle = observe.load_bundle(b["path"])
    check(bundle["kind"] == "serving"
          and bundle["failure"]["checker"] == "check_slo"
          and bundle["failure"]["grid_coords"] == b["coords"],
          f"bundle carries traffic+fault+coords ({b['path']})")
    replay = observe.replay_bundle(b["path"])
    ok_r, det_r = check_slo(replay, **bundle["failure"]["slo"],
                            coords=bundle["failure"]["grid_coords"])
    check(not ok_r, "independent replay fails the SAME check_slo")
    check(replay.get("first_divergence_round") is None,
          "independent replay is divergence-free")

    (OUT / "frontier_smoke_report.json").write_text(json.dumps(
        {k: v for k, v in rep.items() if k != "cells"},
        indent=1, default=str) + "\n")
    print("frontier smoke", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
