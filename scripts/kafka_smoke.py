#!/usr/bin/env python
"""CI smoke for the PR-4 kafka scale paths (seconds on CPU):

- **4-device sharded-kafka parity**: a toy KafkaSim on a 4-device
  virtual CPU mesh (a DIFFERENT shard count than the 8-way mesh the
  tier-1 suite runs on — shard-count bugs in the prefix-scan/reduce_or
  decomposition would alias at one fixed count) must be bit-identical
  to single-device, fault-free (union replication) AND under a
  crash/loss plan (faulted origin-union), and the fault-free sharded
  step HLO must contain no all-gather — the blocked psum-of-OR
  replication contract.
- **kafka mesh-takeover smoke**: benchmarks/mesh_takeover.py kafka
  mode at a small shape (subprocess: its own 8-device virtual mesh)
  must allocate every send and report ok.
- **blocked-union bit-exactness leg (PR 5)**: the streaming
  destination-slab union (union_block) must be bit-identical to the
  materialized union_nem on the 4-device mesh, and the BLOCKED sharded
  step HLO must contain no all-gather (the per-send metadata rides a
  ring ppermute instead of the materialized path's widen).
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(4)

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
from jax.sharding import Mesh                               # noqa: E402

from gossip_glomers_tpu.harness import nemesis              # noqa: E402
from gossip_glomers_tpu.tpu_sim import audit                # noqa: E402
from gossip_glomers_tpu.tpu_sim import faults as F          # noqa: E402
from gossip_glomers_tpu.tpu_sim.kafka import KafkaSim       # noqa: E402


def _assert_gather_free(prog, args, what: str) -> None:
    """The no-all-gather HLO gate via the PR-6 contract checkers (the
    same census/boundary walk the registered contracts run — here at
    the smoke's 4-device shard count)."""
    hlo = prog.lower(*args).compile().as_text()
    census = audit.collective_census(hlo)
    assert census.get("all-gather", 0) == 0, \
        f"{what} regained an all-gather: {census}"
    assert census.get("collective-permute", 0) >= 1, \
        f"{what} lost its ppermute circuit: {census}"
    host = audit.host_boundary_violations(hlo)
    assert not host, f"{what} crossed the host boundary: {host}"


def parity_4dev() -> None:
    n, k, cap, s, r = 8, 6, 32, 2, 5
    rng = np.random.default_rng(0)
    sks = rng.integers(-1, k, (r, n, s)).astype(np.int32)
    svs = rng.integers(0, 1000, (r, n, s)).astype(np.int32)
    crs = np.where(rng.random((r, n, k)) < 0.25,
                   rng.integers(1, 5, (r, n, k)), -1).astype(np.int32)
    mesh = Mesh(np.array(jax.devices()[:4]), ("nodes",))
    ref = KafkaSim(n, k, capacity=cap, max_sends=s)
    shd = KafkaSim(n, k, capacity=cap, max_sends=s, mesh=mesh)
    s1 = ref.run_rounds(ref.init_state(), sks, svs, crs)
    s2 = shd.run_rounds(shd.init_state(), sks, svs, crs)
    for a, b, name in zip(s1, s2, s1._fields):
        assert (np.asarray(a) == np.asarray(b)).all(), \
            f"fault-free 4-dev mismatch: {name}"
    prog = shd._step_prog("union")
    args = [jnp.full((n, s), -1, jnp.int32),
            jnp.zeros((n, s), jnp.int32),
            jnp.full((n, k), -1, jnp.int32), shd.kv_sched]
    _assert_gather_free(prog, [shd.init_state()] + args,
                        "4-dev sharded kafka union step")
    spec = F.NemesisSpec(n_nodes=n, seed=5, crash=((2, 4, (1,)),),
                         loss_rate=0.2, loss_until=6)
    fs, fv, fc = nemesis.stage_kafka_ops(spec, 6, n_keys=k,
                                         max_sends=s)
    f_ref = KafkaSim(n, k, capacity=cap, max_sends=s,
                     fault_plan=spec.compile())
    f_shd = KafkaSim(n, k, capacity=cap, max_sends=s,
                     fault_plan=spec.compile(), mesh=mesh)
    assert f_shd._repl_mode(None) == "union_nem"
    t1 = f_ref.run_rounds(f_ref.init_state(), fs, fv, fc)
    t2 = f_shd.run_rounds(f_shd.init_state(), fs, fv, fc)
    for a, b, name in zip(t1, t2, t1._fields):
        assert (np.asarray(a) == np.asarray(b)).all(), \
            f"faulted 4-dev mismatch: {name}"
    # blocked-union leg (PR 5): streaming slabs bit-exact with the
    # materialized union_nem above, and the blocked sharded step HLO
    # stays all-gather-free (ring-ppermute metadata circuit)
    b_shd = KafkaSim(n, k, capacity=cap, max_sends=s,
                     fault_plan=spec.compile(), mesh=mesh,
                     union_block=1)
    assert b_shd._ub == 1
    t3 = b_shd.run_rounds(b_shd.init_state(), fs, fv, fc)
    for a, b, name in zip(t1, t3, t1._fields):
        assert (np.asarray(a) == np.asarray(b)).all(), \
            f"blocked 4-dev mismatch: {name}"
    bprog = b_shd._step_prog("union_nem")
    bargs = [jnp.full((n, s), -1, jnp.int32),
             jnp.zeros((n, s), jnp.int32),
             jnp.full((n, k), -1, jnp.int32), b_shd.kv_sched,
             b_shd.fault_plan]
    _assert_gather_free(bprog, [b_shd.init_state()] + bargs,
                        "4-dev blocked sharded union_nem step")
    print("kafka 4-device sharded parity OK (union + union_nem + "
          "blocked union, no all-gather)")


def takeover_smoke() -> None:
    from benchmarks.takeover_subprocess import run_takeover_subprocess

    res = run_takeover_subprocess(
        {"GG_TAKEOVER_WORKLOAD": "kafka", "GG_TAKEOVER_NODES": "4096",
         "GG_TAKEOVER_ROUNDS": "2"}, timeout=600)
    assert res["ok"], res
    print(f"kafka mesh-takeover smoke OK "
          f"({res['wall_s_virtual_mesh']}s, "
          f"{res['n_devices']}-way virtual mesh)")


if __name__ == "__main__":
    parity_4dev()
    takeover_smoke()
