#!/usr/bin/env bash
# Tier-1 CI gate: the exact verify command from ROADMAP.md, on CPU.
#
# Runs the full non-slow test suite over the 8-device virtual CPU mesh
# (tests/conftest.py forces XLA's host-platform device splitting — same
# SPMD partitioner and collectives as real chips).  Exits nonzero on
# any failure; prints DOTS_PASSED for the driver's pass-count check.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
# Fault-matrix smoke: one crash + one loss nemesis scenario per sim,
# plus the words-major STRUCTURED-path crash/loss scenarios (the same
# plans through structured.make_nemesis), plus one crash+loss-UNDER-
# LOAD scenario per sim (PR 7: open-loop traffic flowing through the
# fault windows, serving certifier — zero lost acked ops, bounded
# drain, latency keys).  (CPU, seconds.)
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python scripts/fault_smoke.py || rc=1
# Kafka scale smoke (PR 4 + PR 5): 4-device sharded-kafka parity
# (union + faulted origin-union + the BLOCKED streaming union, with
# no all-gather in either sharded step HLO — the blocked step's
# metadata rides a ring ppermute) + the kafka mesh-takeover at a
# small shape on the 8-way virtual mesh.
# (CPU, seconds.)  Outer budget > the smoke's inner 600 s subprocess
# timeout so a wedged takeover surfaces its diagnostic dict instead
# of a bare SIGTERM.
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python scripts/kafka_smoke.py || rc=1
# Telemetry smoke (PR 8): one certified crash+loss+traffic run per
# sim on the TELEMETRY-ON observed drivers — manifest + Perfetto
# timeline written and schema-validated (uploaded as a CI artifact),
# and the flight recorder exercised via a deliberately failing
# latency bound whose bundle must replay to the same failure from
# its own JSON.  (CPU, seconds.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/telemetry_smoke.py || rc=1
# Provenance smoke (PR 9): one certified crash+loss run per sim on
# the PROVENANCE-ON observed drivers — check_provenance certifies the
# causal stamps against the fault model's own coins, the broadcast
# dissemination-tree artifact + flow-event timeline are written and
# schema-validated (uploaded as a CI artifact), a forged dead-edge
# parent must FAIL, and the flight-bundle replay must report the
# first-divergence round (None faithful / the tampered round).
# (CPU, seconds.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/provenance_smoke.py || rc=1
# Fuzz smoke (PR 10): a seeded 64-scenario fault-space fuzz run on
# the scenario-axis batched drivers (8-way virtual mesh, scenario-
# sharded, one compiled program per batch) with one PLANTED failing
# seed — asserts the batched certifier names the failure, the
# auto-shrinker reduces it to a strictly smaller minimal repro whose
# every retained component is load-bearing, and the shrunk flight
# bundle replays to the same failure from its JSON alone.  Artifacts
# uploaded.  (CPU, a few minutes: each shrink replays candidate specs
# sequentially.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/fuzz_smoke.py || rc=1
# Frontier smoke (PR 13): a 16-cell (load x fault x topology)
# serving-frontier grid certified in scenario-sharded batch
# dispatches on the 8-way virtual mesh — per-cell SLO surfaces with
# on-device behavioral signatures, schema-valid frontier report +
# coverage map + Perfetto timeline, and a PLANTED p99 SLO violation
# that fails naming its grid coordinates, writes a flight bundle
# (TrafficSpec + NemesisSpec + coords), and replays to the same
# check_slo failure from the bundle's JSON alone.  (CPU, seconds.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/frontier_smoke.py || rc=1
# Txn smoke (PR 14): one certified crash+loss txn-rw-register
# campaign on the device-native sharded KV (wound-or-die commits,
# serializable device-recorded history), a fuzzed 64-scenario
# crash+loss campaign certified in ONE batched dispatch on the 8-way
# virtual mesh with zero lost acked commits, and the planted-anomaly
# probes: kv_amnesia owner wipes MUST fail with named lost updates
# and a bundle that replays to the same verdict, and a hand-planted
# write-skew history MUST fail the checker naming both transaction
# ids.  (CPU, seconds.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/txn_smoke.py || rc=1
# DCN smoke (PR 15 + PR 20): a REAL 2-process jax.distributed CPU
# cluster (gloo, 2 virtual devices per process) runs the shared
# dcn_worker tasks — all three sims stepwise + donated-fused, one
# certified crash+loss structured broadcast, the host-loss takeover
# drill, the sims re-run under GG_DCN_PIPELINE=1 (the double-buffered
# half-block DCN circuits must stay bit-exact vs the flat twin), and
# a stale:4 counter campaign certified by check_staleness_bound
# against its sync twin — then the parent pins every digest bit-exact
# against its own 1-process twin, falsifies a planted k=1 staleness
# claim, and replays a failing stale run's flight bundle
# mode-faithfully (artifacts/dcn_smoke/).  (CPU, ~1 min warm.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/dcn_smoke.py || rc=1
# Membership smoke (PR 17): one certified join+leave churn campaign
# per sim (joiners catch up empty, leavers drain first), one
# certified elastic RESIZE per sim (checkpoint-restore into a
# larger/smaller padded node axis, crash windows crossing the
# boundary, broadcast/counter pinned bit-exact vs their straight-
# through twins, KV re-homing diff verified against the host routing
# twin), a planted drain-margin-free leave that MUST fail naming the
# lost delta shortfall with a bundle that replays to the same
# verdict, and a coverage-steered membership-churn fuzz slice whose
# signature churn buckets must populate.  (CPU, seconds.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/membership_smoke.py || rc=1
# Program-contract audit (PR 6): every registered driver contract
# (collective census, donation alias table, host boundary, memory
# band) on the CPU 8-way virtual mesh, plus the AST determinism lint
# over the package — the static gates behind the HLO/donation/memory
# guarantees.  (CPU, ~2 min.)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/audit.py || rc=1
# Standard-lint leg (the pinned [tool.ruff] config in pyproject.toml);
# the custom determinism lint above never depends on it.
if command -v ruff >/dev/null 2>&1; then
    ruff check gossip_glomers_tpu tests scripts benchmarks || rc=1
fi
exit $rc
