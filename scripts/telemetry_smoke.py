#!/usr/bin/env python
"""CI telemetry smoke (PR 8): one certified crash+loss+traffic run per
stateful sim on the TELEMETRY-ON observed drivers, with the full
observability pipeline exercised end to end on CPU, seconds — the
budget-safe slice the tier-1 gate runs on every push:

1. each run's manifest (program fingerprint + compiled memory + cost
   analysis + the workload's telemetry-on contract verdict) and
   Perfetto timeline are WRITTEN and schema-validated — the manifest
   directory is uploaded as a CI build artifact;
2. the flight recorder is exercised via a deliberately failing per-op
   latency bound: the bundle must be written atomically and
   ``observe.replay_bundle`` must reproduce the SAME failure from the
   bundle's own JSON alone.

Exits nonzero on any failure.  Output dir: ``GG_OBSERVE_DIR``
(default ``artifacts/telemetry_smoke``).
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gossip_glomers_tpu.parallel.mesh import force_virtual_devices  # noqa: E402

force_virtual_devices(8)

from gossip_glomers_tpu.harness import observe, serving  # noqa: E402
from gossip_glomers_tpu.tpu_sim import audit             # noqa: E402
from gossip_glomers_tpu.tpu_sim import telemetry as TM   # noqa: E402
from gossip_glomers_tpu.tpu_sim.engine import program_record  # noqa: E402
from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec     # noqa: E402
from gossip_glomers_tpu.tpu_sim.traffic import TrafficSpec    # noqa: E402

N = 8
SPEC = NemesisSpec(n_nodes=N, seed=5, crash=((6, 10, (2, 6)),),
                   loss_rate=0.15, loss_until=16)
TRAFFIC = TrafficSpec(n_nodes=N, n_clients=8, ops_per_client=8,
                      until=20, rate=0.3, seed=1)
# the same certified crash+loss-under-load scenarios the fault smoke
# runs (grid broadcast: the sole-copy amnesia race of a tree root is
# a real loss, not a telemetry bug)
SIM_KW = {"broadcast": {}, "counter": {}, "kafka": {}}
CONTRACT = {"broadcast": "broadcast/observed-run-halo-wm-nem",
            "counter": "counter/observed-run",
            "kafka": "kafka/observed-run-union-nem"}


def main() -> int:
    out = pathlib.Path(os.environ.get("GG_OBSERVE_DIR",
                                      "artifacts/telemetry_smoke"))
    out.mkdir(parents=True, exist_ok=True)
    import jax
    import numpy as np
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("nodes",))
    contracts = {c.name: c for c in TM.audit_contracts()}
    failed = []

    for kind in ("broadcast", "counter", "kafka"):
        res = serving.run_serving(
            kind, TRAFFIC, nemesis=SPEC, telemetry=True,
            observe_dir=str(out), sim_kw=SIM_KW[kind])
        rec = len(res.get("telemetry", {}).get("series",
                                               {}).get("_round", ()))
        print(f"telemetry-smoke {kind:10s} "
              f"{'ok' if res['ok'] else 'FAIL'}  "
              f"rounds={rec} completed={res['completed']} "
              f"lost={res['n_lost_writes']} p99={res['lat_p99']}")
        if not res["ok"]:
            failed.append((kind, res.get("telemetry", {}).get(
                "check", res["n_lost_writes"])))
            continue
        # the manifest: the EXACT observed driver's fingerprint +
        # memory + cost, and this workload's telemetry-on contract
        # verdict (all-gather census / donation / memory band) — the
        # TelemetrySpec is lifted from the run itself so the recorded
        # program IS the one run_serving executed (same ring shape)
        sim, _ = serving.make_serving_sim(kind, TRAFFIC, nemesis=SPEC,
                                          **SIM_KW[kind])
        tsp = TM.TelemetrySpec.from_meta(res["telemetry"]["spec"])
        prog, args = sim.audit_traffic_program(TRAFFIC, tel_spec=tsp)
        programs = {"observed-traffic-run": program_record(prog,
                                                           *args)}
        verdict = audit.audit_contract(contracts[CONTRACT[kind]],
                                       mesh)
        manifest = observe.run_manifest(res, programs=programs,
                                        contracts=[verdict])
        observe.validate_manifest(manifest)
        mpath = observe.write_json_atomic(
            str(out / f"manifest_{kind}.json"), manifest)
        timeline = observe.run_timeline(res)
        observe.validate_timeline(timeline)
        tpath = observe.write_json_atomic(
            str(out / f"timeline_{kind}.json"), timeline)
        if not verdict["ok"]:
            failed.append((kind, f"contract {verdict['name']}"))
        print(f"  manifest={os.path.basename(mpath)} "
              f"fingerprint={programs['observed-traffic-run']['fingerprint']} "
              f"contract={'ok' if verdict['ok'] else 'FAIL'} "
              f"timeline_events={len(timeline['traceEvents'])}")

    # flight recorder: a deliberately failing latency bound must
    # produce a bundle that replays to the same failure
    bad = serving.run_serving(
        "counter", TRAFFIC, nemesis=SPEC, telemetry=True,
        observe_dir=str(out), latency_bound={"p99_max_rounds": 0.0})
    if bad["ok"] or "flight_bundle" not in bad:
        failed.append(("flight-recorder", "failing bound did not "
                       "produce a bundle"))
    else:
        bundle_path = bad["flight_bundle"]
        replay = observe.replay_bundle(bundle_path)
        same = (not replay["ok"]
                and replay["lat_p99"] == bad["lat_p99"]
                and bool(replay["latency_bound"]["problems"]))
        print(f"telemetry-smoke flight-rec "
              f"{'ok' if same else 'FAIL'}  "
              f"bundle={os.path.basename(bundle_path)} "
              f"replay_p99={replay['lat_p99']}=={bad['lat_p99']}")
        if not same:
            failed.append(("flight-recorder", "replay diverged"))
        with open(bundle_path) as fp:
            json.load(fp)        # bundle is complete, valid JSON

    if failed:
        print(f"telemetry-smoke: {len(failed)} leg(s) failed: "
              f"{failed}", file=sys.stderr)
        return 1
    print("telemetry-smoke: all legs ok, artifacts in", out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
