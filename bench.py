#!/usr/bin/env python
"""Flagship benchmark: 1M-node tree broadcast to convergence on TPU.

BASELINE.json north star: simulate a 1M-node tree-topology broadcast to
convergence in < 10 s (target set for a v5e-8; this runs on however many
chips are visible).  The Go reference tops out at 25 OS processes under
Maelstrom; here every node is a row of a device-sharded bitset array and
one jitted round == one network hop.

Prints exactly one JSON line:
  {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": ratio}
vs_baseline = baseline_target_seconds / measured  (>1 means faster than
the 10 s target).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_NODES = 1 << 20            # 1,048,576
N_VALUES = 32                # one bitset word; injected round-robin
BRANCHING = 4
BASELINE_TARGET_S = 10.0     # BASELINE.json: "<10 s on a v5e-8"


def main() -> None:
    import jax

    from gossip_glomers_tpu.parallel.mesh import pick_mesh
    from gossip_glomers_tpu.parallel.topology import tree, to_padded_neighbors
    from gossip_glomers_tpu.tpu_sim.broadcast import BroadcastSim, make_inject

    devices = jax.devices()
    mesh = pick_mesh()

    from gossip_glomers_tpu.tpu_sim.structured import (
        make_exchange, make_sharded_exchange, make_sharded_sync_diff,
        make_sync_diff)

    nbrs = to_padded_neighbors(tree(N_NODES, branching=BRANCHING))
    inject = make_inject(N_NODES, N_VALUES)
    sharded = sharded_diff = None
    if mesh is not None:
        # halo path: parent/child slice ppermutes, O(block) ICI traffic
        # per round — no all_gather, no redundant full-axis compute
        sharded = make_sharded_exchange("tree", N_NODES, mesh.size,
                                        branching=BRANCHING)
        sharded_diff = make_sharded_sync_diff("tree", N_NODES, mesh.size,
                                              branching=BRANCHING)
    # timed sim: server ledger OFF — its sync diff runs every round
    # under jit (where-masked, not cond-skipped) and would inflate the
    # headline number; a separate untimed accounted run below reports
    # the Maelstrom-comparable srv_msgs for the same deterministic
    # schedule
    sim = BroadcastSim(nbrs, n_values=N_VALUES, sync_every=64, mesh=mesh,
                       exchange=make_exchange("tree", N_NODES,
                                              branching=BRANCHING),
                       sharded_exchange=sharded,
                       srv_ledger=False)
    sim_acct = BroadcastSim(nbrs, n_values=N_VALUES, sync_every=64,
                            mesh=mesh,
                            exchange=make_exchange("tree", N_NODES,
                                                   branching=BRANCHING),
                            sharded_exchange=sharded,
                            sync_diff=make_sync_diff("tree", N_NODES,
                                                     branching=BRANCHING),
                            sharded_sync_diff=sharded_diff)

    # Warmup: compile the fused runner and run one full convergence.
    state, rounds = sim.run_fused(inject)
    jax.block_until_ready(state.received)

    # Timed region: the whole-convergence device program, start to
    # observed completion.  Workload staging (host->device upload of the
    # injected values) happens before the clock, mirroring how the
    # reference's Maelstrom timings exclude process startup.
    state0, target = sim.stage(inject)
    jax.block_until_ready(state0.received)
    t0 = time.perf_counter()
    state = sim.run_staged(state0, target)
    jax.block_until_ready(state.received)
    elapsed = time.perf_counter() - t0
    rounds = int(state.t)

    assert sim.converged(state, target), "benchmark run did not converge"

    # untimed accounted run: same schedule, server ledger on
    state_a, rounds_a = sim_acct.run_fused(inject)
    assert rounds_a == rounds, (rounds_a, rounds)
    srv_msgs = sim_acct.server_msgs(state_a)

    print(json.dumps({
        "metric": "1M-node tree broadcast time-to-convergence",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_TARGET_S / elapsed, 2),
        "rounds": rounds,
        "msgs": int(state.msgs),
        # Maelstrom-comparable accounting: server messages (broadcast +
        # ack + anti-entropy reads/pushes) per broadcast op
        "srv_msgs": srv_msgs,
        "srv_msgs_per_op": round(srv_msgs / N_VALUES, 1),
        "n_devices": len(devices),
    }))


if __name__ == "__main__":
    main()
