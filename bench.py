#!/usr/bin/env python
"""Flagship benchmark: 1M-node tree broadcast to convergence on TPU.

BASELINE.json north star: simulate a 1M-node tree-topology broadcast to
convergence in < 10 s (target set for a v5e-8; this runs on however many
chips are visible).  The Go reference tops out at 25 OS processes under
Maelstrom; here every node is a row of a device-sharded bitset array and
one jitted round == one network hop.

Timing methodology lives in gossip_glomers_tpu/tpu_sim/timing.py
(fused whole-convergence device program, staged inputs, median of 3).

Prints exactly one JSON line:
  {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": ratio}
vs_baseline = baseline_target_seconds / measured  (>1 means faster than
the 10 s target).  Extra keys: Maelstrom-comparable server-message
accounting for the same run, and the W=128 words-axis regime (4,096
values -> 128 uint32 bitset words per node) on tree and circulant
topologies — the many-values case the words-major layout exists for.
"""

from __future__ import annotations

import json
import sys

N_NODES = 1 << 20            # 1,048,576
N_VALUES = 32                # one bitset word; injected round-robin
BRANCHING = 4
BASELINE_TARGET_S = 10.0     # BASELINE.json: "<10 s on a v5e-8"
W128_VALUES = 4096           # words-axis regime: 128 uint32 words


def main() -> None:
    import jax

    from gossip_glomers_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()

    from gossip_glomers_tpu.tpu_sim.broadcast import make_inject
    from gossip_glomers_tpu.tpu_sim.timing import (bench_structured,
                                                   format_words_regime,
                                                   structured_sim,
                                                   words_axis_entries)

    devices = jax.devices()
    inject = make_inject(N_NODES, N_VALUES)

    # One session-clean two-phase schedule over all three benchmarks
    # (the headline plus the shared words_axis_entries, whose traffic
    # model is defined once in timing.py): every timed sample runs
    # before any finish/validation/accounting program — see timing.py's
    # module docstring for the tunnel-session rationale.  The w128
    # entries are best-effort extras: if the combined run fails for any
    # reason (theirs or a transient), the headline is re-measured alone
    # so the driver never loses its line; only a headline-alone failure
    # is fatal.
    head_entry = ("w1_tree", "tree", N_VALUES, {"branching": BRANCHING},
                  BRANCHING + 1)
    try:
        entries = [head_entry,
                   *words_axis_entries(N_NODES, W128_VALUES,
                                       branching=BRANCHING)]
        res = bench_structured(N_NODES, entries)
    except AssertionError:
        raise   # TimedRun.finish correctness validations (e.g. "fixed
        #         runner diverged from run()") are real bugs — same
        #         policy as the accounted-run block below
    except Exception as e:                         # noqa: BLE001
        print(f"combined benchmark run failed ({e!r}); "
              "retrying headline alone", file=sys.stderr)
        res = bench_structured(N_NODES, [head_entry])
        w128 = {"error": f"not measured: combined run failed: {e!r}"}
    else:
        try:   # formatting must never discard the measurement
            w128 = format_words_regime(res, W128_VALUES)
        except Exception as e:                     # noqa: BLE001
            print(f"w128 formatting failed: {e!r}", file=sys.stderr)
            w128 = {"error": f"formatting failed: {e!r}"}
    head = res["w1_tree"]
    elapsed, rounds, state = (head["wall_s"], head["rounds"],
                              head["_state"])

    out = {
        "metric": "1M-node tree broadcast time-to-convergence",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_TARGET_S / elapsed, 2),
        "rounds": rounds,
        "msgs": int(state.msgs),
        "w1_ms_per_round": round(elapsed / rounds * 1e3, 3),
        "w128": w128,
        "n_devices": len(devices),
    }

    # Untimed accounted run: server ledger ON (its sync diff runs every
    # round under jit and would inflate timed numbers) — reports the
    # Maelstrom-comparable srv_msgs for the same deterministic
    # schedule, and independently re-derives the convergence round
    # count through the data-dependent while runner as validation.
    # Best-effort for the same reason as above.
    try:
        sim_acct = structured_sim("tree", N_NODES, N_VALUES,
                                  branching=BRANCHING, srv_ledger=True)
        state_a, rounds_a = sim_acct.run_fused(inject)
        assert rounds_a == rounds, (rounds_a, rounds)
        assert int(state_a.msgs) == int(state.msgs), "ledger mismatch"
        srv_msgs = sim_acct.server_msgs(state_a)
        # Maelstrom-comparable accounting: server messages (broadcast +
        # ack + anti-entropy reads/pushes) per broadcast op
        out["srv_msgs"] = srv_msgs
        out["srv_msgs_per_op"] = round(srv_msgs / N_VALUES, 1)
    except AssertionError:
        raise   # a ledger/rounds validation failure is a real bug —
        #         it must crash the benchmark, not become a JSON field
    except Exception as e:                         # noqa: BLE001
        print(f"accounted run failed: {e!r}", file=sys.stderr)
        out["srv_msgs_error"] = repr(e)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
