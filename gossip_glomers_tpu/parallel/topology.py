"""Topology builders: adjacency lists for clusters of N nodes.

The reference gets its topology from Maelstrom's harness-supplied map
(consumed at broadcast/broadcast.go:36-48); the topologies themselves
(grid default, ``--topology tree4``, etc.) live in the external harness.
These builders provide the same families natively, as integer adjacency
lists usable both by the virtual-clock harness (via ``to_name_map``) and
by the vectorized tpu_sim backend (via ``to_padded_neighbors``).

The reference README notes tree was its best-performing broadcast
topology (README.md:19).
"""

from __future__ import annotations

import math

import numpy as np


def tree(n: int, branching: int = 4) -> list[list[int]]:
    """k-ary tree (Maelstrom's ``tree4`` shape for k=4): node i's parent
    is (i-1)//k; neighbors are parent + children."""
    adj: list[list[int]] = [[] for _ in range(n)]
    for i in range(1, n):
        parent = (i - 1) // branching
        adj[i].append(parent)
        adj[parent].append(i)
    return adj


def grid_cols(n: int) -> int:
    """Column count of the n-node grid — shared by the adjacency builder
    and the structured exchange so they can never disagree."""
    return max(1, math.isqrt(n - 1) + 1) if n > 1 else 1


def grid(n: int, cols: int | None = None) -> list[list[int]]:
    """2D grid (Maelstrom's default broadcast topology): ceil(sqrt(n))
    columns by default, neighbors up/down/left/right."""
    cols = cols or grid_cols(n)
    adj: list[list[int]] = [[] for _ in range(n)]
    for i in range(n):
        r, c = divmod(i, cols)
        for dr, dc in ((-1, 0), (1, 0), (0, -1), (0, 1)):
            rr, cc = r + dr, c + dc
            j = rr * cols + cc
            if rr >= 0 and cc >= 0 and cc < cols and 0 <= j < n:
                adj[i].append(j)
    return adj


def ring(n: int) -> list[list[int]]:
    if n == 1:
        return [[]]
    if n == 2:
        return [[1], [0]]
    return [[(i - 1) % n, (i + 1) % n] for i in range(n)]


def line(n: int) -> list[list[int]]:
    adj: list[list[int]] = [[] for _ in range(n)]
    for i in range(n - 1):
        adj[i].append(i + 1)
        adj[i + 1].append(i)
    return adj


def full(n: int) -> list[list[int]]:
    return [[j for j in range(n) if j != i] for i in range(n)]


def circulant(n: int, strides: list[int]) -> np.ndarray:
    """Circulant graph: node i's neighbors are i ± s (mod n) for each
    stride s.  With a few random-ish strides this is an expander with
    the same O(log n) diameter as a random-regular graph — but its
    neighbor map is pure rotations, so the tpu_sim structured exchange
    delivers it with contiguous rolls instead of a random gather (the
    TPU-native choice for the 1M-node epidemic benchmark,
    BASELINE.json config 4).

    Returns an (n, 2*len(strides)) int32 padded-neighbor array
    compatible with the gather path (for cross-checking).
    """
    cols = []
    for s in strides:
        s = s % n
        idx = np.arange(n, dtype=np.int64)
        cols.append((idx + s) % n)
        cols.append((idx - s) % n)
    return np.stack(cols, axis=1).astype(np.int32)


def expander_strides(n: int, degree: int = 8, seed: int = 0) -> list[int]:
    """Pseudo-random distinct strides in [1, n//2) for a circulant
    expander of the given (even) degree."""
    rng = np.random.default_rng(seed)
    # Distinct useful strides live in [1, n//2] (larger ones alias via
    # i-s ≡ i+(n-s)); clamp so small n can't make the sampling loop
    # unsatisfiable (e.g. n=8, degree=8 has only 4 strides).  For even
    # n the stride exactly n/2 maps i+s and i-s to the SAME node — one
    # edge, not two — which would both lose effective degree and make
    # the per-edge message ledger double-count that edge, so it is
    # sampled only as a last resort when no other distinct stride
    # remains.
    half = max(1, n // 2)
    pair_max = half - 1 if (n % 2 == 0 and half > 1) else half
    want = min(max(1, degree // 2), half)
    strides: set[int] = {1}
    while len(strides) < want and len(strides) < pair_max:
        strides.add(int(rng.integers(2, pair_max + 1)))
    if len(strides) < want:
        strides.add(half)  # sole remaining distinct stride (even n)
    return sorted(strides)


def random_regular(n: int, degree: int, seed: int = 0) -> np.ndarray:
    """Directed random graph with out-degree exactly ``degree``, built
    from ``degree`` seeded derangement-ish permutations (each permutation
    contributes in-degree exactly 1 per node).  O(n·degree) memory, fully
    vectorized — the construction the 1M-node epidemic benchmark uses
    (BASELINE.json config 4).

    Returns an (n, degree) int32 array of neighbor indices.
    """
    rng = np.random.default_rng(seed)
    cols = []
    for _ in range(degree):
        perm = rng.permutation(n)
        # Avoid self-loops while keeping perm a permutation (in-degree
        # exactly 1 per node): cycle the targets of fixed points among
        # themselves.  A single fixed point swaps with its successor.
        fixed = np.flatnonzero(perm == np.arange(n))
        if len(fixed) == 1 and n > 1:
            j = (fixed[0] + 1) % n
            perm[[fixed[0], j]] = perm[[j, fixed[0]]]
        elif len(fixed) > 1:
            perm[fixed] = np.roll(perm[fixed], 1)
        cols.append(perm)
    return np.stack(cols, axis=1).astype(np.int32)


def to_name_map(adj: list[list[int]],
                prefix: str = "n") -> dict[str, list[str]]:
    """Adjacency list → Maelstrom-style topology map of node names."""
    return {f"{prefix}{i}": [f"{prefix}{j}" for j in nbrs]
            for i, nbrs in enumerate(adj)}


def to_padded_neighbors(adj: list[list[int]],
                        fill: int = -1) -> np.ndarray:
    """Adjacency list → (n, max_degree) int32 array padded with ``fill``
    (static shapes for jit; survey §7 "dynamic shapes" hard part)."""
    n = len(adj)
    deg = max((len(a) for a in adj), default=0)
    out = np.full((n, max(deg, 1)), fill, dtype=np.int32)
    for i, nbrs in enumerate(adj):
        out[i, :len(nbrs)] = nbrs
    return out
