"""Multi-process DCN worker — the subprocess body behind
``tests/test_dcn.py`` and ``scripts/dcn_smoke.py``.

Each CI "host" is one of these processes: it joins the
``jax.distributed`` cluster from the ``GG_*`` env contract
(``parallel.mesh.DIST_ENV``), builds the hierarchical
``("hosts", "nodes")`` mesh with :func:`pick_mesh_2d`, runs the task
list from ``GG_DCN_TASKS`` (comma-separated), and writes one JSON
digest file per process to ``GG_DCN_OUT`` (suffix ``.<rank>``).

Every reported number is either a replicated ledger scalar or a
position-weighted uint32 checksum reduced ON DEVICE to a replicated
scalar — so all ranks compute identical files (asserted by the
spawner), and the single-process twin can reproduce them bit-for-bit
on the same global mesh shape without any cross-process state fetch.

Tasks:

- ``sims``      broadcast (grid) + counter (cas) + kafka digests,
                stepwise AND donated-fused, plus a second counter
                replay under the same seed (seed-replay determinism
                across host counts).
- ``batch``     a 64-scenario counter fault campaign in ONE
                host-sharded dispatch — per-scenario verdict rows.
- ``certify``   one certified crash+loss broadcast nemesis run
                (structured words-major path, ledger-calibrated).
- ``takeover``  a HOST-loss smoke: every node shard owned by process
                1 crashed over a window via FaultPlan liveness, the
                survivors' flood re-converges after restart.
- ``roundtime`` measured per-round wall time of the structured tree
                flood at a serving-scale shape — the ICI-vs-DCN
                cost-model anchor (timing is per-rank and NOT part of
                the bit-exact surface; the state digest still is).
                ``GG_DCN_RT_N`` / ``GG_DCN_RT_NV`` override the shape
                (the PR-20 benchmark's w=128 leg).
- ``pipelined`` the ``sims`` body re-run under ``GG_DCN_PIPELINE=1``
                (PR 20): the cluster compiles the double-buffered
                half-block DCN circuits and every digest must still
                equal the synchronous flat twin's bit-for-bit.
- ``stale``     counter allreduce crash+loss at ``stale:2`` vs its
                sync twin, certified by ``check_staleness_bound``
                (PR 20).  Needs the hierarchical mesh — the smoke's
                twin runs THIS task on ``pick_mesh_2d``, not the flat
                parity mesh.

``GG_DCN_TIME=1`` adds per-task ``wall_s`` to each report (for the
throughput benchmark; timing differs across ranks, so the parity
spawners leave it unset).  Run as
``python -m gossip_glomers_tpu.parallel.dcn_worker``.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _digest_fn(jnp):
    """(device array) -> replicated uint32 checksum, jitted by the
    caller: 4-byte leaves are bitcast (bit-exact), narrower ones
    widen losslessly through int32.  Position-weighted so shard-order
    swaps cannot cancel."""
    import jax

    def digest(x):
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.int32)
        if x.dtype.itemsize < 4:
            x = x.astype(jnp.int32)
        words = jax.lax.bitcast_convert_type(x, jnp.uint32)
        flat = words.reshape(-1)
        w = (jnp.arange(flat.shape[0], dtype=jnp.uint32)
             * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9))
        return jnp.sum(flat * w, dtype=jnp.uint32)

    return digest


def state_digest(state) -> dict:
    """Checksum every array leaf of a (possibly cross-process sharded)
    state pytree into replicated host ints, field-keyed."""
    import jax
    import jax.numpy as jnp

    digest = jax.jit(_digest_fn(jnp))
    out = {}
    for name in state._fields:
        value = getattr(state, name)
        if value is None:
            continue
        out[name] = int(digest(value))
    return out


def _task_sims(mesh) -> dict:
    import numpy as np

    from ..tpu_sim.broadcast import BroadcastSim, make_inject
    from ..tpu_sim.counter import CounterSim
    from ..tpu_sim.kafka import KafkaSim
    from .topology import grid, to_padded_neighbors

    res = {}
    n, nv = 16, 16
    nbrs = to_padded_neighbors(grid(n))
    inject = make_inject(n, nv)
    bres = {}
    for runner in ("run", "run_fused"):
        sim = BroadcastSim(nbrs, n_values=nv, mesh=mesh)
        state, rounds = getattr(sim, runner)(inject)
        bres[runner] = {"rounds": int(rounds),
                        "msgs": int(state.msgs),
                        "state": state_digest(state)}
    res["broadcast"] = bres

    nc = 8
    deltas = np.arange(1, nc + 1, dtype=np.int32)
    cres = {}
    for runner in ("run", "run_fused", "replay"):
        sim = CounterSim(nc, mode="cas", seed=7, mesh=mesh)
        state = getattr(sim, "run" if runner == "replay"
                        else runner)(sim.add(sim.init_state(),
                                             deltas), 12)
        cres[runner] = {"msgs": int(state.msgs),
                        "state": state_digest(state)}
    # seed-replay determinism INSIDE this host count; the spawner
    # asserts it ACROSS host counts too
    if cres["run"] != cres["replay"]:                # pragma: no cover
        raise AssertionError("counter seed replay diverged in-process")
    res["counter"] = cres

    rng = np.random.default_rng(0)
    sim = KafkaSim(nc, 4, capacity=32, mesh=mesh)
    state = sim.init_state()
    for _ in range(6):
        send_key = rng.integers(-1, 4,
                                size=(nc, sim.max_sends)).astype(
                                    np.int32)
        send_val = rng.integers(0, 100,
                                size=(nc, sim.max_sends)).astype(
                                    np.int32)
        state = sim.step(state, send_key, send_val)
    res["kafka"] = {"msgs": int(state.msgs),
                    "state": state_digest(state)}
    return res


def _task_batch(mesh) -> dict:
    from ..tpu_sim import scenario as SC
    from ..tpu_sim.faults import random_spec

    from ..tpu_sim.faults import NemesisSpec

    n, s_count = 16, 64
    specs = []
    for s in range(s_count):
        sp = random_spec(n, seed=s, horizon=8,
                         n_crash_windows=1 + (s % 2), loss_rate=0.1)
        # crash after the cas drain so amnesia cannot kill an
        # undrained delta (the acked-write-survives regime — the
        # verdict rows must all certify ok on every host count)
        meta = sp.to_meta()
        meta["crash"] = [[a + n + 2, b + n + 2, ns]
                         for a, b, ns in meta["crash"]]
        meta["loss_until"] += n + 2
        specs.append(NemesisSpec.from_meta(meta))
    batch = SC.ScenarioBatch(
        workload="counter",
        scenarios=tuple(SC.Scenario(spec=sp) for sp in specs),
        runner_kw={"mode": "cas", "poll_every": 2},
        max_recovery_rounds=32)
    res = SC.run_scenario_batch(batch, mesh=mesh)
    rows = [{k: row[k] for k in
             ("scenario", "ok", "converged_round", "msgs_total", "kv")}
            for row in res["scenarios"]]
    return {"ok": bool(res["ok"]), "n_scenarios": res["n_scenarios"],
            "failing": list(res["failing"]), "scenarios": rows}


def _task_certify(mesh) -> dict:
    from ..harness.nemesis import run_broadcast_nemesis
    from ..tpu_sim.faults import NemesisSpec

    spec = NemesisSpec(n_nodes=16, seed=5, crash=((2, 4, (3, 9)),),
                       loss_rate=0.15, loss_until=5)
    res = run_broadcast_nemesis(spec, topology="tree", n_values=16,
                                structured=True, mesh=mesh)
    return {"ok": bool(res["ok"]),
            "converged_round": int(res["converged_round"]),
            "msgs_total": int(res["msgs_total"])}


def _task_takeover(mesh) -> dict:
    """Host loss: crash EVERY node row owned by one DCN host for a
    window; the flood must stall on the survivors and re-converge
    after the host restarts (FaultPlan liveness is per-node, so a
    host death is just the block of its rows).  The lost block is the
    SECOND host's rows under the hosts-major (2, ...) layout — a
    constant, so the 1x8 twin runs the identical spec and the digests
    stay comparable."""
    import numpy as np

    from ..tpu_sim.broadcast import BroadcastSim
    from ..tpu_sim.faults import NemesisSpec
    from .topology import grid, to_padded_neighbors

    n, nv = 16, 16
    lost_host = tuple(range(n // 2, n))
    spec = NemesisSpec(n_nodes=n, seed=3,
                       crash=((1, 6, lost_host),))
    sim = BroadcastSim(to_padded_neighbors(grid(n)), n_values=nv,
                       mesh=mesh, fault_plan=spec.compile())
    # every value starts on the SURVIVING host (node 0): the dead
    # host's amnesia wipe must lose nothing, only delay delivery
    inject = np.zeros((n, 1), np.uint32)
    inject[0, 0] = np.uint32((1 << nv) - 1)
    state, rounds = sim.run(inject)
    reads = sim.read(state)
    converged = all(r == list(range(nv)) for r in reads)
    return {"rounds": int(rounds), "msgs": int(state.msgs),
            "lost_rows": list(lost_host), "converged": converged,
            "state": state_digest(state)}


def _task_roundtime(mesh) -> dict:
    """Measured per-round wall time of the structured (words-major)
    tree flood — pure ppermute halo exchanges, ledger off, fixed
    round count known in closed form.  On a hierarchical mesh every
    exchange decomposes intra-ICI first with one per-host block move
    over DCN, so this number IS the recorded cost-model anchor."""
    import jax

    from ..tpu_sim import structured as S
    from ..tpu_sim.broadcast import BroadcastSim, make_inject
    from ..tpu_sim.engine import node_axes, node_shards
    from ..tpu_sim.timing import discover_rounds
    from .topology import to_padded_neighbors, tree

    n = int(os.environ.get("GG_DCN_RT_N") or 65536)
    nv = int(os.environ.get("GG_DCN_RT_NV") or 32)
    sharded = None
    if mesh is not None:
        sharded = S.make_sharded_exchange(
            "tree", n, node_shards(mesh), axis_name=node_axes(mesh))
    sim = BroadcastSim(to_padded_neighbors(tree(n)), n_values=nv,
                       sync_every=1 << 20, srv_ledger=False,
                       mesh=mesh,
                       exchange=S.make_exchange("tree", n),
                       sharded_exchange=sharded)
    rounds = discover_rounds("tree", n, nv)
    state0, _ = sim.stage(make_inject(n, nv))
    jax.block_until_ready(state0.received)
    out = sim.run_staged_fixed(state0, rounds)      # compile + warm
    jax.block_until_ready(out.received)
    t0 = time.perf_counter()
    out = sim.run_staged_fixed(state0, rounds)
    jax.block_until_ready(out.received)
    dt = time.perf_counter() - t0
    return {"n": n, "nv": nv, "rounds": rounds,
            "us_per_round": round(dt / rounds * 1e6, 1),
            "state": state_digest(out)}


def _task_pipelined(mesh) -> dict:
    """The ``sims`` parity body with DCN round pipelining ON (PR 20):
    the env contract is pinned in-process so every sim constructor
    resolves the pipelined mode and the cluster compiles the
    double-buffered half-block DCN circuits.  Integer operands make
    pipelining bit-exact, and on the 1-host flat twin the mode is a
    structural no-op — so cluster-vs-twin digest equality IS the
    latency-hiding-without-semantic-drift claim."""
    old = os.environ.get("GG_DCN_PIPELINE")
    os.environ["GG_DCN_PIPELINE"] = "1"
    try:
        return _task_sims(mesh)
    finally:
        if old is None:
            os.environ.pop("GG_DCN_PIPELINE", None)
        else:
            os.environ["GG_DCN_PIPELINE"] = old


def _task_stale(mesh) -> dict:
    """Bounded staleness on a REAL cluster (PR 20): the counter
    allreduce crash+loss campaign runs once synchronous and once at
    ``stale:4`` — cross-host partials ride the staleness carry, lag
    at most 4 rounds (this seeded spec lands a REAL nonzero delay:
    the last drained deltas wait for a refresh round), and every
    acked delta still lands — certified by ``check_staleness_bound``
    against the sync twin.  Every reported number is a replicated
    scalar, so rank-vs-rank and cluster-vs-``pick_mesh_2d``-twin
    equality is bit-exactness."""
    from ..harness.checkers import check_staleness_bound
    from ..harness.nemesis import run_counter_nemesis
    from ..tpu_sim.faults import NemesisSpec

    spec = NemesisSpec(n_nodes=16, seed=3, crash=((1, 4, (2, 11)),),
                       loss_rate=0.2, loss_until=5)
    runs = {}
    for label, dcn in (("sync", "sync"), ("stale", "stale:4")):
        runs[label] = run_counter_nemesis(
            spec, mode="allreduce", mesh=mesh,
            max_recovery_rounds=32, dcn_mode=dcn)
    ok, details = check_staleness_bound(
        stale_k=4,
        sync_converged_round=runs["sync"]["converged_round"],
        stale_converged_round=runs["stale"]["converged_round"],
        lost_writes=runs["stale"]["lost_writes"],
        recovery=(runs["stale"]["ok"],
                  {"converged_round": runs["stale"]["converged_round"],
                   "kv": int(runs["stale"]["kv"])}))
    return {"ok": bool(ok),
            "sync_round": runs["sync"]["converged_round"],
            "stale_round": runs["stale"]["converged_round"],
            "delay_rounds": details["delay_rounds"],
            "bound_round": details["bound_round"],
            "kv": int(runs["stale"]["kv"]),
            "acked_sum": int(runs["stale"]["acked_sum"])}


TASKS = {"sims": _task_sims, "batch": _task_batch,
         "certify": _task_certify, "takeover": _task_takeover,
         "roundtime": _task_roundtime, "pipelined": _task_pipelined,
         "stale": _task_stale}


def run_tasks(tasks, mesh) -> dict:
    timed = bool(os.environ.get("GG_DCN_TIME"))
    out = {}
    for name in tasks:
        t0 = time.perf_counter()
        res = TASKS[name](mesh)
        if timed:
            res = dict(res, wall_s=round(time.perf_counter() - t0, 3))
        out[name] = res
    return out


def spawn_local_cluster(tasks: str, out_dir: str, *, n_procs: int = 2,
                        local_devices: int = 4, timeout: float = 600.0,
                        timed: bool = False, attempts: int = 2):
    """Host-side spawner: run this module as ``n_procs`` real OS
    processes forming one local gloo cluster and return the parsed
    per-rank reports (or raise with the tail of every rank log).  A
    retry with a fresh coordinator port absorbs the rare gloo startup
    flake.  The parent's ``XLA_FLAGS`` is dropped so each worker's
    ``GG_LOCAL_DEVICES`` split applies."""
    import socket
    import subprocess
    import tempfile

    last_diag = ""
    for attempt in range(attempts):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        out = os.path.join(tempfile.mkdtemp(dir=out_dir),
                           "report.json")
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update(JAX_PLATFORMS="cpu",
                   GG_COORDINATOR=f"127.0.0.1:{port}",
                   GG_NUM_PROCS=str(n_procs),
                   GG_LOCAL_DEVICES=str(local_devices),
                   GG_DCN_TASKS=tasks, GG_DCN_OUT=out)
        if timed:
            env["GG_DCN_TIME"] = "1"
        else:
            env.pop("GG_DCN_TIME", None)
        procs, logs = [], []
        for rank in range(n_procs):
            log = open(f"{out}.log.{rank}", "w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "gossip_glomers_tpu.parallel.dcn_worker"],
                env=dict(env, GG_PROC_ID=str(rank)),
                stdout=log, stderr=subprocess.STDOUT))
        deadline = time.monotonic() + timeout
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(
                    timeout=max(1.0, deadline - time.monotonic())))
            except subprocess.TimeoutExpired:
                rcs.append(None)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        if all(rc == 0 for rc in rcs):
            reports = []
            for rank in range(n_procs):
                with open(f"{out}.{rank}") as fh:
                    reports.append(json.load(fh))
            for log in logs:
                log.close()
            return reports
        diag = []
        for rank, log in enumerate(logs):
            log.seek(0)
            diag.append(f"-- rank {rank} rc={rcs[rank]} --\n"
                        + log.read()[-3000:])
            log.close()
        last_diag = "\n".join(diag)
    raise RuntimeError(
        f"dcn cluster failed {attempts}x:\n{last_diag}")


def main(argv=None) -> int:
    # join the cluster BEFORE anything touches the backend — the env
    # contract is parallel.mesh.DIST_ENV
    from .mesh import (force_virtual_devices, init_distributed,
                       pick_mesh_2d)

    distributed = init_distributed()
    if not distributed:
        # single-process run (GG_NUM_PROCS absent or 1): the device
        # split still applies, so a 1-host twin can match a cluster's
        # per-host device count exactly
        raw = os.environ.get("GG_LOCAL_DEVICES")
        if raw:
            force_virtual_devices(int(raw))
    import jax

    from ..utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    # NOTE the gloo transport pairs same-clique collectives in POSTING
    # order with no tags, and parallel computations always dispatch
    # asynchronously on the CPU client (jax_cpu_enable_async_dispatch
    # governs non-parallel programs only — flipping it does NOT
    # serialize these).  The one host-thread collective that used to
    # race the in-flight round programs — device_put's hidden
    # multi-host assert_equal broadcast — is gone: sims place host
    # data via parallel.mesh.shard_put, which builds the addressable
    # shards collective-free.

    tasks = [t for t in os.environ.get("GG_DCN_TASKS",
                                       "sims").split(",") if t]
    out_path = os.environ.get("GG_DCN_OUT")
    mesh = pick_mesh_2d()
    report = {
        "process_id": int(jax.process_index()),
        "n_processes": int(jax.process_count()),
        "n_devices": int(jax.device_count()),
        "local_devices": int(jax.local_device_count()),
        "mesh_shape": (None if mesh is None
                       else [int(s) for s in mesh.devices.shape]),
        "tasks": run_tasks(tasks, mesh),
    }
    payload = json.dumps(report, indent=1, sort_keys=True) + "\n"
    if out_path:
        with open(f"{out_path}.{jax.process_index()}", "w") as fh:
            fh.write(payload)
    else:
        sys.stdout.write(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
