"""Device-mesh selection shared by the benchmarks and entry points.

The node axis must divide evenly across the mesh, so the benchmarks use
the largest power-of-two prefix of the visible devices (ICI-contiguous
on real TPU slices), optionally capped by the simulated node count.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh


def pick_mesh(max_axis: int | None = None,
              axis_name: str = "nodes") -> Mesh | None:
    """1-D mesh over the largest power-of-two device prefix, or None on
    a single device.  ``max_axis`` caps the mesh size (e.g. at the node
    count so every shard holds at least one row)."""
    import jax

    devices = jax.devices()
    if len(devices) <= 1:
        return None
    n_dev = 1 << (len(devices).bit_length() - 1)
    if max_axis is not None:
        while n_dev > max_axis:
            n_dev >>= 1
    if n_dev <= 1:
        return None
    return Mesh(np.array(devices[:n_dev]), (axis_name,))
