"""Device-mesh selection shared by the benchmarks and entry points.

The node axis must divide evenly across the mesh, so the benchmarks use
the largest power-of-two prefix of the visible devices (ICI-contiguous
on real TPU slices), optionally capped by the simulated node count.
"""

from __future__ import annotations

import os

import numpy as np
from jax.sharding import Mesh


def force_virtual_devices(n: int = 8) -> None:
    """Point this process at an ``n``-device virtual CPU mesh (XLA's
    host-platform device splitting — same SPMD partitioner and
    collectives as ``n`` real chips, one host core executing all
    shards).  MUST run before the JAX backend initializes (the flags
    are read lazily at first device query, so pre-backend-init is
    enough even if jax is already imported); shared by the mesh
    benchmarks (mesh_takeover.py, bench_pr1.py) and mirrored by
    tests/conftest.py."""
    import sys

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        # a sitecustomize on TPU images registers the TPU plugin and
        # forces the platform at interpreter start; config.update
        # after import wins over it (see tests/conftest.py)
        sys.modules["jax"].config.update("jax_platforms", "cpu")


def pick_mesh(max_axis: int | None = None,
              axis_name: str = "nodes") -> Mesh | None:
    """1-D mesh over the largest power-of-two device prefix, or None on
    a single device.  ``max_axis`` caps the mesh size (e.g. at the node
    count so every shard holds at least one row)."""
    import jax

    devices = jax.devices()
    if len(devices) <= 1:
        return None
    n_dev = 1 << (len(devices).bit_length() - 1)
    if max_axis is not None:
        while n_dev > max_axis:
            n_dev >>= 1
    if n_dev <= 1:
        return None
    return Mesh(np.array(devices[:n_dev]), (axis_name,))
