"""Device-mesh selection shared by the benchmarks and entry points.

The node axis must divide evenly across the mesh, so the benchmarks use
the largest power-of-two prefix of the visible devices (ICI-contiguous
on real TPU slices), optionally capped by the simulated node count.

Multi-process (PR 15): :func:`init_distributed` stands up the
``jax.distributed`` runtime from env vars (multi-host TPU pods and the
multi-process CPU clusters CI spawns), and :func:`pick_mesh_2d` builds
the hierarchical ``("hosts", "nodes")`` mesh — DCN axis outermost, one
row per process, the per-host ICI axis innermost — that
``engine.collectives`` compiles into two-level exchange circuits.
:func:`pick_mesh` stays the 1-D degenerate case.
"""

from __future__ import annotations

import os

import numpy as np
from jax.sharding import Mesh

#: env vars read by :func:`init_distributed` (the CI spawn contract —
#: scripts/dcn_smoke.py and tests/test_dcn.py export exactly these):
#:
#: - ``GG_COORDINATOR``  host:port of process 0's coordinator service
#: - ``GG_NUM_PROCS``    total process count (absent/1 -> single-process)
#: - ``GG_PROC_ID``      this process's rank in [0, GG_NUM_PROCS)
#: - ``GG_LOCAL_DEVICES``  per-PROCESS virtual CPU device count handed
#:   to :func:`force_virtual_devices` (the global mesh then has
#:   ``GG_NUM_PROCS x GG_LOCAL_DEVICES`` devices); ignored on real TPU
#:   backends, which enumerate their own local chips
DIST_ENV = ("GG_COORDINATOR", "GG_NUM_PROCS", "GG_PROC_ID",
            "GG_LOCAL_DEVICES")


def _backend_initialized() -> bool:
    """Whether this process's JAX backend already spun up (device query
    ran) — past that point the virtual-device flags are dead letters."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:                                # pragma: no cover
        return False


def init_distributed(*, coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     local_devices: int | None = None) -> bool:
    """Idempotent ``jax.distributed.initialize`` wrapper, env-driven
    (``DIST_ENV``) with keyword overrides.  Returns True when the
    distributed runtime was (newly) initialized, False for the
    single-process no-op paths — callers can branch on it but never
    need to.

    On CPU the gloo collectives backend is selected and
    ``local_devices`` (or ``GG_LOCAL_DEVICES``) routes through
    :func:`force_virtual_devices`, which MUST precede backend init —
    if the backend already spun up this raises instead of silently
    handing every process the same un-split device, which would
    deadlock the coordinator barrier three stack frames later.  On TPU
    pods the runtime reads its own cluster env and ``local_devices``
    is ignored.
    """
    import jax

    if num_processes is None:
        num_processes = int(os.environ.get("GG_NUM_PROCS", "1") or 1)
    if num_processes <= 1:
        return False
    state = getattr(jax.distributed, "global_state", None)
    if state is not None and getattr(state, "client", None) is not None:
        return False                                 # already up
    if coordinator_address is None:
        coordinator_address = os.environ.get("GG_COORDINATOR")
    if process_id is None:
        process_id = int(os.environ.get("GG_PROC_ID", "0") or 0)
    if local_devices is None:
        raw = os.environ.get("GG_LOCAL_DEVICES")
        local_devices = int(raw) if raw else None
    platform = os.environ.get("JAX_PLATFORMS", "")
    if local_devices is not None and "tpu" not in platform:
        if _backend_initialized():
            raise RuntimeError(
                "init_distributed(local_devices=...) must run before "
                "the JAX backend initializes (a device query already "
                "ran); the virtual-device split cannot be applied now "
                "— move init_distributed to process start, before any "
                "jax.devices()/jit call")
        force_virtual_devices(local_devices)
    if "tpu" not in platform:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if coordinator_address is None:
        raise ValueError(
            "init_distributed: GG_NUM_PROCS > 1 but no coordinator "
            "address (set GG_COORDINATOR=host:port or pass "
            "coordinator_address=)")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def force_virtual_devices(n: int = 8) -> None:
    """Point this process at an ``n``-device virtual CPU mesh (XLA's
    host-platform device splitting — same SPMD partitioner and
    collectives as ``n`` real chips, one host core executing all
    shards).  MUST run before the JAX backend initializes (the flags
    are read lazily at first device query, so pre-backend-init is
    enough even if jax is already imported); shared by the mesh
    benchmarks (mesh_takeover.py, bench_pr1.py) and mirrored by
    tests/conftest.py."""
    import sys

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        # a sitecustomize on TPU images registers the TPU plugin and
        # forces the platform at interpreter start; config.update
        # after import wins over it (see tests/conftest.py)
        sys.modules["jax"].config.update("jax_platforms", "cpu")


def pick_mesh(max_axis: int | None = None,
              axis_name: str = "nodes") -> Mesh | None:
    """1-D mesh over the largest power-of-two device prefix, or None on
    a single device.  ``max_axis`` caps the mesh size (e.g. at the node
    count so every shard holds at least one row)."""
    import jax

    devices = jax.devices()
    if len(devices) <= 1:
        return None
    n_dev = 1 << (len(devices).bit_length() - 1)
    if max_axis is not None:
        while n_dev > max_axis:
            n_dev >>= 1
    if n_dev <= 1:
        return None
    return Mesh(np.array(devices[:n_dev]), (axis_name,))


def pick_mesh_2d(hosts: int | None = None, max_axis: int | None = None,
                 axis_names: tuple = ("hosts", "nodes")) -> Mesh | None:
    """Hierarchical 2-D mesh: the DCN axis (``hosts``, one row per
    process) OUTERMOST, the per-host ICI axis innermost — the layout
    ``engine.collectives`` reads to run its ppermute circuits intra-ICI
    first and exchange one per-host partial over DCN (O(log hosts)
    block moves).

    ``hosts`` defaults to ``jax.process_count()``; pass it explicitly
    to fold a single process's virtual devices into a simulated
    hierarchy (the single-process twin the parity tests pin against
    the real multi-process run).  Rows follow process ownership when
    ``hosts`` matches the process count, so the inner axis is always
    process-local (ICI-contiguous on real slices).  ``max_axis`` caps
    the TOTAL node-shard count (hosts x per-host), shrinking the inner
    axis first.  None on a single device, uneven host split, or a cap
    below the host count — same contract as :func:`pick_mesh`.
    """
    import jax

    devices = jax.devices()
    if hosts is None:
        hosts = max(int(jax.process_count()), 1)
    if hosts < 1 or len(devices) % hosts != 0:
        return None
    if hosts > 1 and int(jax.process_count()) == hosts:
        rows = [[d for d in devices if d.process_index == p]
                for p in range(hosts)]
        per = min(len(r) for r in rows)
        if per == 0:
            return None
    else:
        per = len(devices) // hosts
        rows = [list(devices[h * per:(h + 1) * per])
                for h in range(hosts)]
    per = 1 << (per.bit_length() - 1)
    if max_axis is not None:
        while hosts * per > max_axis and per > 1:
            per >>= 1
        if hosts * per > max_axis:
            return None
    if hosts * per <= 1:
        return None
    return Mesh(np.array([r[:per] for r in rows]), axis_names)


def shard_put(x, sharding=None):
    """``jax.device_put`` of host data WITHOUT the hidden multi-host
    collective.

    On a multi-process backend, ``device_put`` of an uncommitted array
    onto a non-fully-addressable sharding first runs
    ``multihost_utils.assert_equal`` — a full-clique broadcast posted
    from the HOST thread.  Parallel computations are always dispatched
    asynchronously on the CPU client (the ``jax_cpu_enable_async_
    dispatch`` knob applies to non-parallel programs only), so that
    assert broadcast races whatever program collectives are still being
    posted by the executor threads; the gloo transport pairs same-clique
    ops in posting order with no tags, and a cross-paired pair of
    different sizes aborts the run with a preamble-size mismatch
    (observed nondeterministically on the 2-process CI cluster once the
    PR-20 pipelined rows widened the in-flight window).

    SPMD host code passes the same value on every process by
    construction, so the assert buys nothing here: build the
    addressable shards directly via ``make_array_from_callback`` — the
    same committed result, zero collectives.  Single-process (or a
    fully-addressable sharding, or traced values) defers to plain
    ``device_put`` — tier-1 behavior is bit-identical."""
    import jax

    if (sharding is None or int(jax.process_count()) == 1
            or getattr(sharding, "is_fully_addressable", True)
            or isinstance(x, jax.core.Tracer)):
        return (jax.device_put(x) if sharding is None
                else jax.device_put(x, sharding))
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])
