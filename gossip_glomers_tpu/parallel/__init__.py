"""Mesh/sharding/collectives + topology builders for the tpu_sim backend."""

from .topology import (
    full,
    grid,
    line,
    random_regular,
    ring,
    to_name_map,
    to_padded_neighbors,
    tree,
)

__all__ = [
    "tree",
    "grid",
    "ring",
    "line",
    "full",
    "random_regular",
    "to_name_map",
    "to_padded_neighbors",
]
