"""gossip_glomers_tpu — a TPU-native distributed-systems framework.

A from-scratch reimplementation of the capabilities of the Gossip Glomers
reference solutions (Go + Maelstrom) as a TPU-first framework:

- ``protocol``  — Maelstrom wire format: message envelope, body schemas,
  RPC error vocabulary (Layer 3 of the reference).
- ``runtime``   — a Maelstrom-compatible per-process node runtime speaking
  line-delimited JSON over stdio, plus seq/lin KV clients (Layer 1).
- ``models``    — the five challenge node programs (echo, unique-ids,
  broadcast, counter, kafka) written as *pure* handlers
  ``(state, msg) -> (state, effects)`` shared by every backend (Layer 2).
- ``harness``   — an in-repo Maelstrom equivalent: deterministic simulated
  network with latency/partition fault injection, seq-kv/lin-kv service
  nodes, workload generators and correctness checkers (Layer 0).
- ``ops`` / ``parallel`` / ``sim`` — the ``tpu_sim`` backend: every node is
  a row of a device-sharded state array; gossip fan-out, CRDT merges and
  offset allocation become batched JAX kernels (`shard_map` over a
  `jax.sharding.Mesh`, XLA collectives over ICI).

Reference: dshebib/gossip-glomers-distributed-systems (studied, not copied);
citations throughout use ``<file>:<line>`` relative to that repo.
"""

__version__ = "0.1.0"
