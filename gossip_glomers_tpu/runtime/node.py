"""Node runtime: transport-independent core + stdio (Maelstrom) runtime.

Reimplements the surface of the Maelstrom Go client's ``Node`` (surveyed
from node.go symbols embedded in the reference's checked-in binaries;
survey §2b): ``Handle``, ``Run``, ``Send``, ``Reply``, ``RPC``,
``SyncRPC``, ``ID``, ``NodeIDs``, automatic ``init``/``init_ok``
bookkeeping, and reply→callback correlation via ``in_reply_to``.

Design difference from the reference (deliberate, TPU-first): the core is
**event-driven**.  Handlers never block; long-running behavior (the
broadcast anti-entropy timer, the counter's flush loop, kafka's CAS retry
loops) is expressed as timers + RPC continuations.  That makes the exact
same challenge programs runnable on three backends: this threaded stdio
runtime, the deterministic virtual-clock harness, and (in batched form)
the ``tpu_sim`` vectorized backend.
"""

from __future__ import annotations

import json
import random
import sys
import threading
from typing import Callable

from ..protocol import Message, RPCError, TIMEOUT, decode_line, encode_line

Handler = Callable[[Message], None]
ReplyCallback = Callable[[Message], None]


class NodeCore:
    """Transport-independent node logic.

    Subclasses implement ``_transmit(msg)`` (put a message on the wire),
    ``schedule(delay, fn)`` (run ``fn`` after ``delay`` seconds of this
    runtime's notion of time) and ``now()``; everything else is shared.
    """

    def __init__(self) -> None:
        self.node_id: str = ""
        self.node_ids: list[str] = []
        self._handlers: dict[str, Handler] = {}
        self._callbacks: dict[int, ReplyCallback] = {}
        self._next_msg_id = 0
        self._lock = threading.Lock()
        # Programs guard read-modify-write state sections with this (the
        # role the reference's RWMutex/channel plays: broadcast.go:13-16,
        # add.go:39).  Uncontended on the single-threaded harness runtime.
        self.state_lock = threading.RLock()
        self.rng = random.Random(0)

    # -- registration -----------------------------------------------------

    def handle(self, type_: str, fn: Handler) -> None:
        """Register ``fn`` for messages whose body type is ``type_``
        (reference: Node.Handle, used at e.g. broadcast/main.go:22-40)."""
        if type_ in self._handlers:
            raise ValueError(f"duplicate handler for {type_!r}")
        self._handlers[type_] = fn

    # -- identity ---------------------------------------------------------

    def id(self) -> str:
        return self.node_id

    def get_node_ids(self) -> list[str]:
        return list(self.node_ids)

    # -- outbound ---------------------------------------------------------

    def _alloc_msg_id(self) -> int:
        with self._lock:
            self._next_msg_id += 1
            return self._next_msg_id

    def send(self, dest: str, body: dict) -> None:
        """Fire-and-forget send; no msg_id, no reply expected
        (reference: Node.Send, e.g. broadcast/broadcast.go:55)."""
        self._transmit(Message(self.node_id, dest, dict(body)))

    def reply(self, req: Message, body: dict) -> None:
        """Reply to ``req``: same body plus ``in_reply_to`` = request
        msg_id (reference: Node.Reply, e.g. echo/main.go:19)."""
        out = dict(body)
        if req.msg_id is not None:
            out["in_reply_to"] = req.msg_id
        self._transmit(Message(self.node_id, req.src, out))

    def with_backoff(self, attempt: Callable[[Callable[[], bool]], None],
                     *, retries: int = 5, base: float = 0.05,
                     factor: float = 2.0, cap: float = 1.0,
                     jitter: float = 0.5) -> None:
        """Jittered-exponential-backoff retry driver for event-driven
        RPC loops (the analogue of the reference's jittered CAS retry
        sleep, add.go:56-58, generalized).

        Calls ``attempt(retry)`` immediately; inside its continuation,
        calling ``retry()`` schedules the NEXT attempt after
        ``min(cap, base * factor**k) * (1 ± jitter)`` seconds of this
        runtime's clock and returns True — or returns False once
        ``retries`` re-attempts are exhausted, so the caller can fail
        over instead of hammering a dead service on the synthetic
        code-0 timeout (the immediate-retry loops this replaces).
        Jitter draws from ``self.rng`` — seeded runtimes (GG_RNG_SEED,
        the virtual-clock harness) replay the exact delays."""
        tries = [0]

        def retry() -> bool:
            k = tries[0]
            if k >= retries:
                return False
            tries[0] = k + 1
            delay = min(cap, base * (factor ** k))
            delay *= 1.0 + self.rng.uniform(-jitter, jitter)
            self.schedule(delay, lambda: attempt(retry))
            return True

        attempt(retry)

    def rpc(self, dest: str, body: dict, callback: ReplyCallback,
            timeout: float | None = None) -> int:
        """Async request: assign a msg_id, register ``callback`` for the
        reply (reference: Node.RPC, broadcast/broadcast.go:120).

        If ``timeout`` is given and no reply arrives in time, the callback
        fires once with a synthetic ``error`` body, code 0 (timeout) — the
        analogue of a Go context deadline on SyncRPC.
        """
        msg_id = self._alloc_msg_id()
        out = dict(body)
        out["msg_id"] = msg_id
        with self._lock:
            self._callbacks[msg_id] = callback
        self._transmit(Message(self.node_id, dest, out))
        if timeout is not None:
            def _expire() -> None:
                with self._lock:
                    cb = self._callbacks.pop(msg_id, None)
                if cb is not None:
                    err = RPCError(TIMEOUT, "rpc timeout").to_body(msg_id)
                    cb(Message(dest, self.node_id, err))
            self.schedule(timeout, _expire)
        return msg_id

    # -- inbound ----------------------------------------------------------

    def deliver(self, msg: Message) -> None:
        """Dispatch one inbound message (reference: the per-message work of
        Node.Run — handleInitMessage / handleCallback / handleMessage)."""
        body = msg.body
        irt = msg.in_reply_to
        if irt is not None:
            with self._lock:
                cb = self._callbacks.pop(irt, None)
            if cb is None:
                self.log(f"Ignoring reply to {irt} with no callback")
                return
            cb(msg)
            return
        if msg.type == "init":
            self._handle_init(msg)
            return
        handler = self._handlers.get(msg.type)
        if handler is None:
            # The reference client treats this as fatal: Run() returns
            # "No handler for %s" and every main() exits via log.Fatal.
            self.on_unhandled(msg)
            return
        handler(msg)

    def on_unhandled(self, msg: Message) -> None:
        self.log(f"No handler for {json.dumps(msg.to_json())}")

    def _handle_init(self, msg: Message) -> None:
        self.node_id = msg.body.get("node_id", "")
        self.node_ids = list(msg.body.get("node_ids", []))
        user_init = self._handlers.get("init")
        if user_init is not None:
            user_init(msg)
        self.log(f"Node {self.node_id} initialized")
        self.reply(msg, {"type": "init_ok"})

    # -- to be provided by the runtime ------------------------------------

    def _transmit(self, msg: Message) -> None:
        raise NotImplementedError

    def schedule(self, delay: float, fn: Callable[[], None]) -> None:
        raise NotImplementedError

    def now(self) -> float:
        raise NotImplementedError

    def log(self, text: str) -> None:
        raise NotImplementedError


class StdioNode(NodeCore):
    """Per-process runtime over stdin/stdout, Maelstrom-compatible.

    Matches the Go client's process model: one handler invocation per
    thread (Go: goroutine per message), stdout serialized by a lock,
    diagnostics to stderr (reference log strings: "Node %s initialized",
    "Sent %s", "Received %s").
    """

    def __init__(self, in_stream=None, out_stream=None, err_stream=None):
        super().__init__()
        self._in = in_stream if in_stream is not None else sys.stdin
        self._out = out_stream if out_stream is not None else sys.stdout
        self._err = err_stream if err_stream is not None else sys.stderr
        self._out_lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        # GG_RNG_SEED pins all timer jitter for deterministic parity
        # runs (the stdio analogue of GODEBUG=randautoseed=0 pinning a
        # Go binary's global math/rand).
        import os
        seed = os.environ.get("GG_RNG_SEED")
        self.rng = random.Random(int(seed)) if seed else random.Random()

    def _transmit(self, msg: Message) -> None:
        line = encode_line(msg)
        with self._out_lock:
            self._out.write(line)
            self._out.flush()
        self.log(f"Sent {line.strip()}")

    def schedule(self, delay: float, fn: Callable[[], None]) -> None:
        t = threading.Timer(delay, fn)
        t.daemon = True
        t.start()

    def now(self) -> float:
        import time
        return time.monotonic()

    def log(self, text: str) -> None:
        print(text, file=self._err, flush=True)

    def sync_rpc(self, dest: str, body: dict,
                 timeout: float = 1.0) -> Message:
        """Blocking RPC with deadline (reference: Node.SyncRPC — used by
        the Go KV client).  Only valid on the threaded runtime; raises the
        reply's RPCError if the reply is an error body."""
        done = threading.Event()
        box: list[Message] = []

        def _cb(reply: Message) -> None:
            box.append(reply)
            done.set()

        self.rpc(dest, body, _cb, timeout=timeout)
        done.wait(timeout + 1.0)
        if not box:
            raise RPCError(TIMEOUT, "sync rpc timeout")
        reply = box[0]
        if reply.type == "error":
            raise RPCError.from_body(reply.body)
        return reply

    def on_unhandled(self, msg: Message) -> None:
        # Parity with the Go client: a message with no registered handler
        # kills the node (Run returns "No handler for %s"; every reference
        # main() exits via log.Fatal on a Run error).
        self.log(f"No handler for {json.dumps(msg.to_json())}")
        import os
        os._exit(1)

    def run(self) -> None:
        """Blocking event loop: read line-JSON from stdin, dispatch each
        message on its own thread (reference: Node.Run)."""
        for line in self._in:
            line = line.strip()
            if not line:
                continue
            self.log(f"Received {line}")
            try:
                msg = decode_line(line)
            except ValueError as exc:
                # Go's Run returns the unmarshal error -> log.Fatal
                self.log(f"fatal: malformed message: {exc}")
                raise SystemExit(1)
            t = threading.Thread(target=self.deliver, args=(msg,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
            self._threads = [th for th in self._threads if th.is_alive()]
        for th in self._threads:
            th.join(timeout=2.0)
