"""Clients for Maelstrom's ``seq-kv`` / ``lin-kv`` service nodes.

Mirrors the Go client's ``KV`` (kv.go, surveyed from binaries; survey §2b):
``NewSeqKV``/``NewLinKV`` construct a client addressing the harness-provided
service over the normal message transport; ops are ``read``/``write``/``cas``
bodies with keys ``key``, ``value``, ``from``, ``to``,
``create_if_not_exists``.

``AsyncKV`` is the event-driven client the challenge programs use —
continuation-passing, so it runs identically on the threaded stdio runtime
and the deterministic virtual-clock harness.  ``KV`` is the blocking
API-parity wrapper (stdio runtime only), matching the reference call shape
``kv.ReadInt(ctx, key)`` / ``kv.CompareAndSwap(ctx, key, from, to,
create)`` used at counter/add.go:99,76 and kafka/logmap.go:121,159,272.
"""

from __future__ import annotations

from typing import Any, Callable

from ..protocol import Message, RPCError, TIMEOUT

SEQ_KV = "seq-kv"
LIN_KV = "lin-kv"
LWW_KV = "lww-kv"

# callback(value, error): exactly one of the two is non-None
# (value may be None for ops with no result, when error is None).
KVCallback = Callable[[Any, RPCError | None], None]


class AsyncKV:
    """Continuation-passing KV client over ``node.rpc``.

    ``retries`` > 0 makes every op transparently re-issue on the
    synthetic code-0 TIMEOUT error, spaced by the node's jittered
    exponential backoff (``node.with_backoff`` — replacing the
    immediate re-fire the kafka CAS / counter flush loops used to do);
    the callback then sees either the first definitive reply or the
    final timeout.  Non-timeout errors (CAS precondition, missing key)
    are protocol answers, never retried here."""

    def __init__(self, node, service: str = SEQ_KV,
                 timeout: float = 1.0, retries: int = 0,
                 backoff_base: float = 0.05,
                 backoff_cap: float = 1.0) -> None:
        self.node = node
        self.service = service
        self.timeout = timeout
        self.retries = retries
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap

    def _call(self, body: dict, cb: KVCallback, result_key: str | None,
              timeout: float | None = None) -> None:
        op_timeout = self.timeout if timeout is None else timeout

        def attempt(retry) -> None:
            def _on_reply(reply: Message) -> None:
                if reply.type == "error":
                    err = RPCError.from_body(reply.body)
                    if err.code == TIMEOUT and retry():
                        return          # re-issued after backoff
                    cb(None, err)
                else:
                    value = (reply.body.get(result_key)
                             if result_key else None)
                    cb(value, None)

            self.node.rpc(self.service, dict(body), _on_reply,
                          timeout=op_timeout)

        if self.retries > 0:
            self.node.with_backoff(attempt, retries=self.retries,
                                   base=self.backoff_base,
                                   cap=self.backoff_cap)
        else:
            attempt(lambda: False)

    def read(self, key: str, cb: KVCallback,
             timeout: float | None = None) -> None:
        self._call({"type": "read", "key": key}, cb, "value", timeout)

    def write(self, key: str, value: Any, cb: KVCallback,
              timeout: float | None = None) -> None:
        self._call({"type": "write", "key": key, "value": value}, cb, None,
                   timeout)

    def cas(self, key: str, from_: Any, to: Any, cb: KVCallback,
            create_if_not_exists: bool = False,
            timeout: float | None = None) -> None:
        self._call({"type": "cas", "key": key, "from": from_, "to": to,
                    "create_if_not_exists": create_if_not_exists}, cb, None,
                   timeout)


class KV:
    """Blocking KV client (stdio runtime only; wraps ``node.sync_rpc``)."""

    def __init__(self, node, service: str = SEQ_KV,
                 timeout: float = 1.0) -> None:
        self.node = node
        self.service = service
        self.timeout = timeout

    def read(self, key: str, timeout: float | None = None) -> Any:
        reply = self.node.sync_rpc(
            self.service, {"type": "read", "key": key},
            timeout=timeout or self.timeout)
        return reply.body.get("value")

    def read_int(self, key: str, timeout: float | None = None) -> int:
        return int(self.read(key, timeout=timeout))

    def write(self, key: str, value: Any,
              timeout: float | None = None) -> None:
        self.node.sync_rpc(self.service,
                           {"type": "write", "key": key, "value": value},
                           timeout=timeout or self.timeout)

    def compare_and_swap(self, key: str, from_: Any, to: Any,
                         create_if_not_exists: bool = False,
                         timeout: float | None = None) -> None:
        self.node.sync_rpc(
            self.service,
            {"type": "cas", "key": key, "from": from_, "to": to,
             "create_if_not_exists": create_if_not_exists},
            timeout=timeout or self.timeout)


def new_seq_kv(node, timeout: float = 1.0) -> AsyncKV:
    """Reference: maelstrom.NewSeqKV(n), counter/main.go:21."""
    return AsyncKV(node, SEQ_KV, timeout)


def new_lin_kv(node, timeout: float = 1.0) -> AsyncKV:
    """Reference: maelstrom.NewLinKV(n), kafka/main.go:17."""
    return AsyncKV(node, LIN_KV, timeout)
