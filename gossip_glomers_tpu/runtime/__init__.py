"""Node runtime layer (Layer 1 parity with the Maelstrom Go client).

``NodeCore`` holds the transport-independent logic: handler registry,
msg-id allocation, RPC reply correlation, ``init`` bookkeeping.  Two
concrete runtimes exist:

- ``StdioNode`` (here) — a real per-process runtime speaking line-delimited
  JSON over stdin/stdout, drop-in compatible with the external Maelstrom
  harness.
- ``harness.network.SimNodeRuntime`` — the same surface on a deterministic
  virtual clock inside the in-repo harness.
"""

from .kv import KV, AsyncKV
from .node import NodeCore, StdioNode

__all__ = ["NodeCore", "StdioNode", "KV", "AsyncKV"]
