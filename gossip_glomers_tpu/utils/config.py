"""Typed configuration for every tuning constant in the framework.

The reference has no config system — all tuning is compile-time constants
scattered through the Go sources (survey §5).  Each of those constants
defines parity-relevant behavior, so they are lifted here verbatim as
defaults, with citations, and everything is overridable.

All durations are in **seconds** (floats).  The simulated-time harness
interprets them on its virtual clock, so they are cheap no matter how
large.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class BroadcastConfig:
    """Broadcast-node tuning (reference: broadcast/main.go, broadcast.go)."""

    # Anti-entropy timer: sleep 2 s + uniform(0, 1 s) jitter between
    # SyncBroadcast rounds (broadcast/main.go:45-48).
    sync_interval: float = 2.0
    sync_jitter: float = 1.0
    # Declared but never used in the reference (broadcast/broadcast.go:11);
    # kept so a config dump is a superset of the reference's constants.
    cleanup_size: int = 512


@dataclass
class CounterConfig:
    """Counter-node tuning (reference: counter/add.go, counter/main.go)."""

    kv_key: str = "value"            # add.go:13 (KV_VAL_KEY)
    flush_interval: float = 0.200    # long wait between flushes, add.go:62
    retry_min: float = 0.025         # short CAS retry floor, add.go:56-58
    retry_max: float = 0.075         # 25 + rand(51) ms ceiling, add.go:56-58
    kv_op_timeout: float = 1.0       # updateKV context timeout, add.go:69
    poll_interval: float = 0.700     # background KV poll, counter/main.go:53
    poll_timeout: float = 0.500      # poll context timeout, counter/main.go:54
    # AsyncKV transport retries (jittered exponential backoff on the
    # synthetic code-0 TIMEOUT, runtime/kv.py).  The reference has no
    # transport-level retry on the counter (a timed-out flush just waits
    # for the next flush tick), so the default stays 0 to preserve
    # ledger-calibration parity (test_ledger_calibration.py); raise it
    # for lossy-network runs where the flush/poll loops should re-issue
    # instead of skipping a beat.
    kv_retries: int = 0
    kv_backoff_base: float = 0.05    # first retry delay (NodeCore.with_backoff)
    kv_backoff_cap: float = 1.0      # exponential backoff ceiling


@dataclass
class KafkaConfig:
    """Kafka-node tuning (reference: kafka/logmap.go:15-20 and call sites)."""

    default_offset: int = 1          # first offset for a fresh key, logmap.go:16
    offset_inc: int = 1              # logmap.go:17
    kv_timeout: float = 1.0          # defaultKVTimeout (seconds), logmap.go:18
    kv_retries: int = 10             # defaultKVRetries, logmap.go:19
    cas_timeout: float = 5.0         # 5*defaultKVTimeout on CAS paths,
                                     # logmap.go:135,256
    # AsyncKV TRANSPORT retries (distinct from kv_retries, the
    # reference's CAS-conflict attempt budget): jittered-backoff
    # re-issue of timed-out KV ops (runtime/kv.py).  Default 0 — the
    # reference's loops already retry timeouts at the protocol level
    # (logmap.go:177-181), and 0 preserves ledger-calibration parity.
    kv_transport_retries: int = 0
    kv_backoff_base: float = 0.05    # first retry delay (NodeCore.with_backoff)
    kv_backoff_cap: float = 1.0      # exponential backoff ceiling


@dataclass
class NetConfig:
    """Simulated-network behavior (the harness side; reference: external
    Maelstrom — latency/partition knobs per README.md:16-18)."""

    latency: float = 0.0             # fixed per-hop delivery latency
    latency_jitter: float = 0.0     # uniform extra latency
    rpc_timeout: float = 1.0        # default SyncRPC deadline (client lib)
    seed: int = 0                   # all randomness is seeded


@dataclass
class SimConfig:
    """tpu_sim backend shape/scale parameters (no reference equivalent —
    the vectorized backend is new; survey §7)."""

    n_nodes: int = 25
    msg_capacity: int = 128          # bitset width: max distinct broadcast msgs
    degree: int = 3                  # for random-regular topologies
    max_rounds: int = 64
    seed: int = 0


@dataclass
class Config:
    broadcast: BroadcastConfig = field(default_factory=BroadcastConfig)
    counter: CounterConfig = field(default_factory=CounterConfig)
    kafka: KafkaConfig = field(default_factory=KafkaConfig)
    net: NetConfig = field(default_factory=NetConfig)
    sim: SimConfig = field(default_factory=SimConfig)
