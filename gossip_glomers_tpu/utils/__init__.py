"""Utilities: typed configuration, tracing/stats ledger."""

from .config import (
    BroadcastConfig,
    CounterConfig,
    KafkaConfig,
    NetConfig,
    SimConfig,
)

__all__ = [
    "BroadcastConfig",
    "CounterConfig",
    "KafkaConfig",
    "NetConfig",
    "SimConfig",
]
