"""Shared persistent-compilation-cache setup.

One policy for every entry point that compiles XLA programs (the test
suite's conftest, bench.py): cache compiled executables under the repo
root's ``.jax_cache/`` so repeat runs — including the driver's
end-of-round benchmark invocation and the pre-commit hook's suite —
skip recompilation (~1.7 min off a cold bench run, measured).
"""

from __future__ import annotations

import os


def enable_compile_cache() -> None:
    """Point JAX's persistent compilation cache at <repo>/.jax_cache
    (derived from the package location; call before heavy compiles).

    An explicit ``JAX_COMPILATION_CACHE_DIR`` always wins.  For an
    installed distribution (e.g. the ``maelstrom-test`` console script)
    the derived root lands inside site-packages, where writes may fail
    or pollute the install tree — fall back to a per-user cache there.
    """
    import jax

    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return   # user already chose a cache location
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # a source checkout has the repo's marker files next to the package;
    # site-packages does not
    if os.path.exists(os.path.join(root, "pyproject.toml")):
        cache = os.path.join(root, ".jax_cache")
    else:
        cache = os.path.join(
            os.path.expanduser("~"), ".cache", "gossip_glomers_tpu",
            "jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
