"""Shared persistent-compilation-cache setup.

One policy for every entry point that compiles XLA programs (the test
suite's conftest, bench.py): cache compiled executables under the repo
root's ``.jax_cache/`` so repeat runs — including the driver's
end-of-round benchmark invocation and the pre-commit hook's suite —
skip recompilation (~1.7 min off a cold bench run, measured).
"""

from __future__ import annotations

import os


def enable_compile_cache() -> None:
    """Point JAX's persistent compilation cache at <repo>/.jax_cache
    (derived from the package location; call before heavy compiles)."""
    import jax

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(root, ".jax_cache"))
