"""Structured-topology neighbor exchange: gather-free gossip delivery.

The generic delivery primitive ``inbox[i] = OR_d payload[nbr[i, d]]`` is
a random gather, which on TPU reads a full tile per row — at bitset
width 1 that is ~1000x more HBM traffic than the useful bytes (measured
~48 ms/round at 1M nodes).  But every named Maelstrom topology is
*structured*: its neighbor map is a composition of contiguous reshapes
and shifts, which the VPU streams at full HBM bandwidth with zero
random access:

- **k-ary tree** (the reference's best topology, README.md:19): node
  i's parent is (i-1)//k — a ``repeat`` by k; node p's children are
  kp+1..kp+k — a pad + (.., M, k) reshape + OR-reduce.
- **grid** (Maelstrom's default): 4 row/column shifts with edge masks.
- **ring / line**: 2 shifts.

Layout: **words-major (W, N)** — the node axis is minor, so it packs
TPU lanes densely.  The node-major (N, W) layout puts W in the lane
dimension, which at W=1 wastes 127/128 of every vector register and
memory tile; words-major measured ~1000x faster for the exchange loop
at 1M nodes.

Each exchange maps the full (W, N) payload to the full (W, N) inbox and
equals the padded-adjacency gather over the corresponding topology from
parallel/topology.py exactly (tests assert this).  Under shard_map the
payload is all_gather-ed along the node axis first; the caller slices
its row block back out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..parallel.topology import grid_cols


def _zeros(payload: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.zeros(payload.shape[:-1] + (n,), payload.dtype)


def tree_exchange(payload: jnp.ndarray, branching: int = 4) -> jnp.ndarray:
    """inbox for the k-ary tree of parallel/topology.py::tree — i's
    neighbors are parent (i-1)//k and children ki+1..ki+k."""
    w, n = payload.shape
    k = branching
    if n == 1:
        return jnp.zeros_like(payload)
    # from parent: inbox[:, i] |= payload[:, (i-1)//k] for i >= 1
    n_parents = (n - 1 + k - 1) // k
    from_parent = jnp.repeat(payload[:, :n_parents], k, axis=1)[:, :n - 1]
    from_parent = jnp.concatenate([_zeros(payload, 1), from_parent], axis=1)
    # from children: inbox[:, p] |= OR payload[:, kp+1 .. kp+k]
    m = n_parents * k
    kids = jnp.concatenate([payload[:, 1:],
                            _zeros(payload, m - (n - 1))], axis=1)
    from_kids = jnp.bitwise_or.reduce(
        kids.reshape(w, n_parents, k), axis=2)
    from_kids = jnp.concatenate(
        [from_kids, _zeros(payload, n - n_parents)], axis=1)
    return from_parent | from_kids


def grid_exchange(payload: jnp.ndarray, cols: int) -> jnp.ndarray:
    """inbox for the 2D grid of parallel/topology.py::grid — width
    ``cols``, neighbors up/down/left/right, last row possibly ragged."""
    w, n = payload.shape
    c = min(cols, n)
    up = jnp.concatenate([payload[:, cols:], _zeros(payload, c)], axis=1)
    down = jnp.concatenate([_zeros(payload, c), payload[:, :n - c]], axis=1)
    left = jnp.concatenate([payload[:, 1:], _zeros(payload, 1)], axis=1)
    right = jnp.concatenate([_zeros(payload, 1), payload[:, :-1]], axis=1)
    # column masks kill the row wrap-around of the left/right shifts
    col_idx = jnp.arange(n, dtype=jnp.int32) % cols
    left = jnp.where((col_idx < cols - 1)[None, :], left, 0)
    right = jnp.where((col_idx > 0)[None, :], right, 0)
    return up | down | left | right


def ring_exchange(payload: jnp.ndarray) -> jnp.ndarray:
    """inbox for parallel/topology.py::ring (n >= 3)."""
    return (jnp.roll(payload, 1, axis=1)
            | jnp.roll(payload, -1, axis=1))


def circulant_exchange(payload: jnp.ndarray,
                       strides: list[int]) -> jnp.ndarray:
    """inbox for parallel/topology.py::circulant — the epidemic
    expander as pure rotations: one ±roll pair per stride."""
    out = None
    for s in strides:
        term = (jnp.roll(payload, s, axis=1)
                | jnp.roll(payload, -s, axis=1))
        out = term if out is None else out | term
    return out if out is not None else jnp.zeros_like(payload)


def line_exchange(payload: jnp.ndarray) -> jnp.ndarray:
    """inbox for parallel/topology.py::line."""
    fwd = jnp.concatenate([payload[:, 1:], _zeros(payload, 1)], axis=1)
    bwd = jnp.concatenate([_zeros(payload, 1), payload[:, :-1]], axis=1)
    return fwd | bwd


def sharded_roll(x_local: jnp.ndarray, s: int, n: int, n_shards: int,
                 axis_name: str = "nodes") -> jnp.ndarray:
    """Distributed ``jnp.roll(x, s, axis=1)`` for a words-major (W, N)
    array block-sharded over ``axis_name`` — the halo-exchange
    primitive.

    A global rotation by ``s`` touches at most two source shards per
    destination shard, so it decomposes into one or two ``ppermute``s of
    one block each plus a local stitch: O(block) bytes per shard per
    stride over ICI, versus the O(N) all_gather the generic sharded path
    pays.  This is the framework's ring collective — the same
    neighbor-exchange pattern ring-attention-style systems use on the
    sequence axis, applied to the node axis.

    Must run inside shard_map over a mesh with ``axis_name``; ``s`` and
    the shapes are static.
    """
    block = x_local.shape[1]
    assert block * n_shards == n, "node axis must shard evenly"
    s = s % n
    q, r = divmod(s, block)
    # out_local[:, c] = global[:, (p*B + c - s) mod N]:
    #   c in [r, B) -> cols [0, B-r) of block (p - q);
    #   c in [0, r) -> cols [B-r, B) of block (p - q - 1).
    def from_block_offset(off: int) -> jnp.ndarray:
        if off % n_shards == 0:
            return x_local
        perm = [((p - off) % n_shards, p) for p in range(n_shards)]
        return jax.lax.ppermute(x_local, axis_name, perm)

    block_b = from_block_offset(q)
    if r == 0:
        return block_b
    block_a = from_block_offset(q + 1)
    return jnp.concatenate([block_a[:, block - r:],
                            block_b[:, : block - r]], axis=1)


def make_sharded_exchange(topology: str, n: int, n_shards: int,
                          axis_name: str = "nodes", **kw):
    """Halo (ppermute-based) sharded exchange for rotation topologies:
    maps the LOCAL payload block directly to the LOCAL inbox block with
    O(block) communication.  Returns None for topologies without a
    rotation decomposition (tree/grid/line use the all_gather path)."""
    if topology == "ring":
        strides = [1]
    elif topology == "circulant":
        strides = list(kw["strides"])
    else:
        return None

    def exchange_local(p_local: jnp.ndarray) -> jnp.ndarray:
        out = None
        for s in strides:
            term = (sharded_roll(p_local, s, n, n_shards, axis_name)
                    | sharded_roll(p_local, -s, n, n_shards, axis_name))
            out = term if out is None else out | term
        return out

    return exchange_local


def make_exchange(topology: str, n: int, **kw):
    """Exchange closure for a named topology, or None if the topology
    has no structured form (fall back to the padded-adjacency gather)."""
    if topology == "tree":
        k = kw.get("branching", 4)
        return lambda p: tree_exchange(p, k)
    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        return lambda p: grid_exchange(p, cols)
    if topology == "ring":
        return ring_exchange
    if topology == "line":
        return line_exchange
    if topology == "circulant":
        strides = list(kw["strides"])
        return lambda p: circulant_exchange(p, strides)
    return None
