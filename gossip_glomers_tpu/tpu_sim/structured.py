"""Structured-topology neighbor exchange: gather-free gossip delivery.

The generic delivery primitive ``inbox[i] = OR_d payload[nbr[i, d]]`` is
a random gather, which on TPU reads a full tile per row — at bitset
width 1 that is ~1000x more HBM traffic than the useful bytes (measured
~48 ms/round at 1M nodes).  But every named Maelstrom topology is
*structured*: its neighbor map is a composition of contiguous reshapes
and shifts, which the VPU streams at full HBM bandwidth with zero
random access:

- **k-ary tree** (the reference's best topology, README.md:19): node
  i's parent is (i-1)//k — a ``repeat`` by k; node p's children are
  kp+1..kp+k — a pad + (.., M, k) reshape + OR-reduce.
- **grid** (Maelstrom's default): 4 row/column shifts with edge masks.
- **ring / line**: 2 shifts.

Layout: **words-major (W, N)** — the node axis is minor, so it packs
TPU lanes densely.  The node-major (N, W) layout puts W in the lane
dimension, which at W=1 wastes 127/128 of every vector register and
memory tile; words-major measured ~1000x faster for the exchange loop
at 1M nodes.

Each exchange maps the full (W, N) payload to the full (W, N) inbox and
equals the padded-adjacency gather over the corresponding topology from
parallel/topology.py exactly (tests assert this).  Under shard_map the
payload is all_gather-ed along the node axis first; the caller slices
its row block back out.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..parallel.topology import grid_cols


def _zeros(payload: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.zeros(payload.shape[:-1] + (n,), payload.dtype)


def tree_exchange(payload: jnp.ndarray, branching: int = 4) -> jnp.ndarray:
    """inbox for the k-ary tree of parallel/topology.py::tree — i's
    neighbors are parent (i-1)//k and children ki+1..ki+k."""
    w, n = payload.shape
    k = branching
    if n == 1:
        return jnp.zeros_like(payload)
    # from parent: inbox[:, i] |= payload[:, (i-1)//k] for i >= 1
    n_parents = (n - 1 + k - 1) // k
    from_parent = jnp.repeat(payload[:, :n_parents], k, axis=1)[:, :n - 1]
    from_parent = jnp.concatenate([_zeros(payload, 1), from_parent], axis=1)
    # from children: inbox[:, p] |= OR payload[:, kp+1 .. kp+k]
    m = n_parents * k
    kids = jnp.concatenate([payload[:, 1:],
                            _zeros(payload, m - (n - 1))], axis=1)
    from_kids = jnp.bitwise_or.reduce(
        kids.reshape(w, n_parents, k), axis=2)
    from_kids = jnp.concatenate(
        [from_kids, _zeros(payload, n - n_parents)], axis=1)
    return from_parent | from_kids


def grid_exchange(payload: jnp.ndarray, cols: int) -> jnp.ndarray:
    """inbox for the 2D grid of parallel/topology.py::grid — width
    ``cols``, neighbors up/down/left/right, last row possibly ragged."""
    w, n = payload.shape
    c = min(cols, n)
    up = jnp.concatenate([payload[:, cols:], _zeros(payload, c)], axis=1)
    down = jnp.concatenate([_zeros(payload, c), payload[:, :n - c]], axis=1)
    left = jnp.concatenate([payload[:, 1:], _zeros(payload, 1)], axis=1)
    right = jnp.concatenate([_zeros(payload, 1), payload[:, :-1]], axis=1)
    # column masks kill the row wrap-around of the left/right shifts
    col_idx = jnp.arange(n, dtype=jnp.int32) % cols
    left = jnp.where((col_idx < cols - 1)[None, :], left, 0)
    right = jnp.where((col_idx > 0)[None, :], right, 0)
    return up | down | left | right


def ring_exchange(payload: jnp.ndarray) -> jnp.ndarray:
    """inbox for parallel/topology.py::ring (n >= 3)."""
    return (jnp.roll(payload, 1, axis=1)
            | jnp.roll(payload, -1, axis=1))


def circulant_exchange(payload: jnp.ndarray,
                       strides: list[int]) -> jnp.ndarray:
    """inbox for parallel/topology.py::circulant — the epidemic
    expander as pure rotations: one ±roll pair per stride."""
    out = None
    for s in strides:
        term = (jnp.roll(payload, s, axis=1)
                | jnp.roll(payload, -s, axis=1))
        out = term if out is None else out | term
    return out if out is not None else jnp.zeros_like(payload)


def line_exchange(payload: jnp.ndarray) -> jnp.ndarray:
    """inbox for parallel/topology.py::line."""
    fwd = jnp.concatenate([payload[:, 1:], _zeros(payload, 1)], axis=1)
    bwd = jnp.concatenate([_zeros(payload, 1), payload[:, :-1]], axis=1)
    return fwd | bwd


def make_exchange(topology: str, n: int, **kw):
    """Exchange closure for a named topology, or None if the topology
    has no structured form (fall back to the padded-adjacency gather)."""
    if topology == "tree":
        k = kw.get("branching", 4)
        return lambda p: tree_exchange(p, k)
    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        return lambda p: grid_exchange(p, cols)
    if topology == "ring":
        return ring_exchange
    if topology == "line":
        return line_exchange
    if topology == "circulant":
        strides = list(kw["strides"])
        return lambda p: circulant_exchange(p, strides)
    return None
