"""Structured-topology neighbor exchange: gather-free gossip delivery.

The generic delivery primitive ``inbox[i] = OR_d payload[nbr[i, d]]`` is
a random gather, which on TPU reads a full tile per row — at bitset
width 1 that is ~1000x more HBM traffic than the useful bytes (measured
~48 ms/round at 1M nodes).  But every named Maelstrom topology is
*structured*: its neighbor map is a composition of contiguous reshapes
and shifts, which the VPU streams at full HBM bandwidth with zero
random access:

- **k-ary tree** (the reference's best topology, README.md:19): node
  i's parent is (i-1)//k — a ``repeat`` by k; node p's children are
  kp+1..kp+k — a pad + (.., M, k) reshape + OR-reduce.
- **grid** (Maelstrom's default): 4 row/column shifts with edge masks.
- **ring / line**: 2 shifts.

Layout: **words-major (W, N)** — the node axis is minor, so it packs
TPU lanes densely.  The node-major (N, W) layout puts W in the lane
dimension, which at W=1 wastes 127/128 of every vector register and
memory tile; the structured words-major round measures ~60-190x faster
than the node-major adjacency gather at 1M nodes / W=1 (chained
amortized timing: 61 ms/round gather vs 1.07 ms tree / 0.32 ms
circulant).

Each exchange maps the full (W, N) payload to the full (W, N) inbox and
equals the padded-adjacency gather over the corresponding topology from
parallel/topology.py exactly (tests assert this).  Under shard_map the
payload is all_gather-ed along the node axis first; the caller slices
its row block back out.
"""

from __future__ import annotations

import os
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..parallel.topology import grid_cols
from . import faults
from .engine import (sharded_roll, sharded_shift,  # noqa: F401 — the
                     windows_fold)
#   halo primitives are engine-owned now (engine.py module docstring);
#   re-exported here because every structured exchange builds on them
#   and external callers import them from this module.


def _parse_roll_fold_w(raw: str) -> tuple[int, int]:
    """Parse a ``GG_ROLL_FOLD_W``-style ``"lo,hi"`` window string."""
    parts = raw.split(",")
    try:
        lo, hi = (int(parts[0]), int(parts[1])) if len(parts) == 2 \
            else (None, None)
    except ValueError:
        lo = None
    if lo is None:
        raise ValueError(
            f"GG_ROLL_FOLD_W must be 'lo,hi' (two comma-separated "
            f"ints), got {raw!r}")
    return lo, hi


# [lo, hi] W-window where tree_from_kids picks the lane-roll fold over
# the reshape-fold.  The default was measured on this image's tunneled
# TPU chip (benchmarks/midw_probe.py; one chip generation, single
# session) — other generations may cross over elsewhere, so the window
# is overridable via ``GG_ROLL_FOLD_W=lo,hi`` (e.g. "0,0" disables the
# roll fold entirely).  Both lowerings are pinned bit-identical, so the
# knob is performance-only.  Read ONCE at import: a trace-time env read
# would be silently ignored by the jit cache for any already-traced
# shape (the cache key does not include the env), so mid-process
# changes could no-op without warning — set the env before importing
# this module, or assign this constant before the first trace.
ROLL_FOLD_W = _parse_roll_fold_w(os.environ.get("GG_ROLL_FOLD_W",
                                                "8,16"))


def _roll_fold_window() -> tuple[int, int]:
    """The import-time roll-fold window (see :data:`ROLL_FOLD_W`)."""
    return ROLL_FOLD_W


# FAULTED-round path pick by words count (the BENCH_PR3 n_values=2048
# i.e. W=64 tree row regression, resolved in PR 4): on the CPU BACKEND
# the words-major
# faulted round loses to the adjacency gather once the words axis is
# wide — XLA:CPU gathers rows at cache speed while the masked
# structured round re-touches the full (W, N) payload once per
# direction, so the measured crossover sits at W ≈ 8 at 1024 nodes
# (BENCH_PR4.json words_threshold rows: 1.8x at W=1, parity at W=8,
# 0.57-0.75x at W=16-64).  On TPU the structured path wins at every W
# (the recorded 60-190x tile-granularity effect — a TPU reads a full
# 8x128 tile per gathered row), so the fallback applies to CPU only.
# Read once at import, like ROLL_FOLD_W; performance-only (both paths
# are pinned bit-identical by tests/test_nemesis.py).
NEM_GATHER_MIN_W = int(os.environ.get("GG_NEM_GATHER_MIN_W", "8"))


def faulted_path_pick(n_words: int, backend: str | None = None) -> str:
    """``"structured"`` or ``"gather"`` — the faster faulted-round path
    for ``n_words`` bitset words on ``backend`` (default: the current
    JAX backend).  Used by harness.nemesis.run_broadcast_nemesis's
    ``structured="auto"`` mode; see :data:`NEM_GATHER_MIN_W`."""
    backend = backend or jax.default_backend()
    if backend == "cpu" and n_words >= NEM_GATHER_MIN_W:
        return "gather"
    return "structured"


def _zeros(payload: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.zeros(payload.shape[:-1] + (n,), payload.dtype)


def tree_from_parent(payload: jnp.ndarray,
                     branching: int = 4) -> jnp.ndarray:
    """inbox[:, i] = payload[:, (i-1)//k] for i >= 1 (zeros at the
    root) — the parent->child half of :func:`tree_exchange`."""
    w, n = payload.shape
    k = branching
    n_parents = (n - 1 + k - 1) // k
    fp = jnp.repeat(payload[:, :n_parents], k, axis=1)[:, :n - 1]
    return jnp.concatenate([_zeros(payload, 1), fp], axis=1)


def tree_from_kids(payload: jnp.ndarray,
                   branching: int = 4) -> jnp.ndarray:
    """inbox[:, p] = OR payload[:, kp+1 .. kp+k] — the child->parent
    half of :func:`tree_exchange`.

    Two lowerings, picked by the MEASURED W-crossover
    (benchmarks/midw_probe.py, 1M nodes, real chip): the
    reshape-fold's (W, N) <-> (W, N/k, k) retile cost is flat in W, so
    at mid W a lane-roll fold (k-1 rolls + one strided downselect) is
    faster — 1.86x at W=8, 1.53x at W=16 — while at W <= 4 the
    VMEM-resident reshape-fold wins (roll_fold 4.5x slower at W=1) and
    at W >= 32 the rolls' physical data movement overtakes it again
    (1.8x slower).  Both lowerings are bit-identical."""
    w, n = payload.shape
    k = branching
    n_parents = (n - 1 + k - 1) // k
    m = n_parents * k
    lo, hi = _roll_fold_window()
    if lo <= w <= hi:
        # pad first so the rolls' lane wraparound only pulls zeros
        ext = jnp.concatenate([payload, _zeros(payload, k)], axis=1)
        z = ext
        for s in range(1, k):
            z = z | jnp.roll(ext, -s, axis=1)
        fk = z[:, 1::k][:, :n_parents]
    else:
        kids = jnp.concatenate([payload[:, 1:],
                                _zeros(payload, m - (n - 1))], axis=1)
        fk = jnp.bitwise_or.reduce(kids.reshape(w, n_parents, k),
                                   axis=2)
    return jnp.concatenate([fk, _zeros(payload, n - n_parents)], axis=1)


def tree_exchange(payload: jnp.ndarray, branching: int = 4) -> jnp.ndarray:
    """inbox for the k-ary tree of parallel/topology.py::tree — i's
    neighbors are parent (i-1)//k and children ki+1..ki+k."""
    if payload.shape[1] == 1:
        return jnp.zeros_like(payload)
    return (tree_from_parent(payload, branching)
            | tree_from_kids(payload, branching))


def grid_terms(pu: jnp.ndarray, pd: jnp.ndarray, pl: jnp.ndarray,
               pr: jnp.ndarray, cols: int) -> jnp.ndarray:
    """Grid delivery from per-DIRECTION source payloads (all equal for
    the plain exchange; per-delay-class slices for the delayed one):
    up/down are ±cols shifts, left/right ±1 shifts with the ragged-row
    wrap masks."""
    w, n = pu.shape
    c = min(cols, n)
    up = jnp.concatenate([pu[:, c:], _zeros(pu, c)], axis=1)
    down = jnp.concatenate([_zeros(pd, c), pd[:, :n - c]], axis=1)
    left = jnp.concatenate([pl[:, 1:], _zeros(pl, 1)], axis=1)
    right = jnp.concatenate([_zeros(pr, 1), pr[:, :-1]], axis=1)
    # column masks kill the row wrap-around of the left/right shifts
    col_idx = jnp.arange(n, dtype=jnp.int32) % cols
    left = jnp.where((col_idx < cols - 1)[None, :], left, 0)
    right = jnp.where((col_idx > 0)[None, :], right, 0)
    return up | down | left | right


def grid_exchange(payload: jnp.ndarray, cols: int) -> jnp.ndarray:
    """inbox for the 2D grid of parallel/topology.py::grid — width
    ``cols``, neighbors up/down/left/right, last row possibly ragged."""
    return grid_terms(payload, payload, payload, payload, cols)


def line_terms(pf: jnp.ndarray, pb: jnp.ndarray) -> jnp.ndarray:
    """Line delivery from per-direction source payloads."""
    fwd = jnp.concatenate([pf[:, 1:], _zeros(pf, 1)], axis=1)
    bwd = jnp.concatenate([_zeros(pb, 1), pb[:, :-1]], axis=1)
    return fwd | bwd


def ring_exchange(payload: jnp.ndarray) -> jnp.ndarray:
    """inbox for parallel/topology.py::ring (n >= 3)."""
    return (jnp.roll(payload, 1, axis=1)
            | jnp.roll(payload, -1, axis=1))


def circulant_exchange(payload: jnp.ndarray,
                       strides: list[int]) -> jnp.ndarray:
    """inbox for parallel/topology.py::circulant — the epidemic
    expander as pure rotations: one ±roll pair per stride."""
    out = None
    for s in strides:
        term = (jnp.roll(payload, s, axis=1)
                | jnp.roll(payload, -s, axis=1))
        out = term if out is None else out | term
    return out if out is not None else jnp.zeros_like(payload)


def line_exchange(payload: jnp.ndarray) -> jnp.ndarray:
    """inbox for parallel/topology.py::line."""
    return line_terms(payload, payload)


def tree_parent_payload(p_local: jnp.ndarray, n: int, n_shards: int,
                        branching: int = 4,
                        axis_name: str = "nodes") -> jnp.ndarray:
    """Per-node PARENT payload for the heap-ordered k-ary tree, local
    block -> local block: out[:, c] = payload[:, (g-1)//k] for local col
    c at global node g (zeros at the root g = 0).  The from_parent half
    of :func:`tree_sharded_exchange`, also the delivery the per-edge
    sync diff rides (one delivery serves both edge directions)."""
    w, block = p_local.shape
    k = branching
    sub = block // k
    zcol = jnp.zeros((w, 1), p_local.dtype)
    # ext covers global columns [sB-1, sB+B): shard 0's missing left
    # halo arrives as ppermute zeros == "parent of node 0" == none.
    left = jax.lax.ppermute(
        p_local[:, -1:], axis_name,
        [(p, p + 1) for p in range(n_shards - 1)]) \
        if n_shards > 1 else zcol
    ext = jnp.concatenate([left, p_local], axis=1)
    # k multicast rounds: in round m, source shard q sends the parent
    # slice for destination shard d = qk + m.  Dests absent from a
    # round receive zeros, so OR-ing the rounds selects each dest's
    # single buffer.
    buf = None
    for m in range(k):
        sl = ext[:, m * sub: m * sub + sub + 1]
        pairs = [(q, q * k + m) for q in range(n_shards)
                 if q * k + m < n_shards]
        rv = jax.lax.ppermute(sl, axis_name, pairs)
        buf = rv if buf is None else buf | rv
    # local col c's parent sits at buf[ceil(c/k)] (buf[0] is the
    # left-halo column: zero on the shard owning node 0).
    return jnp.concatenate(
        [buf[:, :1], jnp.repeat(buf[:, 1:], k, axis=1)], axis=1)[:, :block]


def tree_sharded_exchange(p_local: jnp.ndarray, n: int, n_shards: int,
                          branching: int = 4,
                          axis_name: str = "nodes") -> jnp.ndarray:
    """Halo exchange for the heap-ordered k-ary tree: local payload
    block -> local inbox block, bit-exact with :func:`tree_exchange`.

    Key structure (B = block size, shard s owns global nodes
    [sB, (s+1)B), k | B): the parents of shard d's nodes occupy the
    contiguous global range [lo_d, lo_d + B/k] with lo_d =
    (dB-1)//k = (d//k)B + (d%k)(B/k) - 1 — i.e. ONE (B/k+1)-wide slice
    of shard d//k's block (its first column reaching one node into
    shard d//k - 1 when d%k == 0, covered by a 1-column left halo).
    Children flow the same map in reverse, pre-reduced by parent group
    on the child shard so only (B/k+1)-wide partial ORs travel.

    Communication per shard per round: a 1-column halo each way plus
    2k slice ppermutes of B/k+1 columns ≈ 2B columns total — versus
    (n_shards-1)·B for the all_gather path, with no redundant
    full-axis exchange compute.
    """
    w, block = p_local.shape
    k = branching
    assert block * n_shards == n, "node axis must shard evenly"
    assert block % k == 0 and block >= k, "tree halo needs k | block"
    from_parent = tree_parent_payload(p_local, n, n_shards, k, axis_name)
    from_kids = tree_kids_payload(p_local, n, n_shards, k, axis_name)
    return from_parent | from_kids


def tree_kids_payload(p_local: jnp.ndarray, n: int, n_shards: int,
                      branching: int = 4,
                      axis_name: str = "nodes") -> jnp.ndarray:
    """Per-node CHILDREN payload OR for the heap-ordered k-ary tree,
    local block -> local block: out[:, j] = OR payload[kj+1 .. kj+k]
    (the from_kids half of :func:`tree_sharded_exchange`)."""
    w, block = p_local.shape
    k = branching
    sub = block // k
    # ---- from_kids: inbox[j] |= OR payload[kj+1 .. kj+k] -------------
    # Pre-reduce on the child shard: group local cols by parent.
    # Col 0 (i = sB) is the LAST child of parent (sB-1)//k; cols
    # [k(o-1)+1, ko] form parent group o.
    body = p_local[:, 1:]
    if body.shape[1] < sub * k:
        body = jnp.concatenate(
            [body, jnp.zeros((w, sub * k - body.shape[1]),
                             p_local.dtype)], axis=1)
    groups = jnp.bitwise_or.reduce(body.reshape(w, sub, k), axis=2)
    partial = jnp.concatenate([p_local[:, :1], groups], axis=1)  # (w, sub+1)
    # reverse multicast: child shard s = qk + m sends its partial to
    # parent shard q, landing at ext_kids cols [m·sub, m·sub + sub].
    ek = jnp.zeros((w, block + 1), p_local.dtype)
    for m in range(k):
        pairs = [(q * k + m, q) for q in range(n_shards)
                 if q * k + m < n_shards]
        rv = jax.lax.ppermute(partial, axis_name, pairs)
        sl = slice(m * sub, m * sub + sub + 1)
        ek = ek.at[:, sl].set(ek[:, sl] | rv)
    # ext_kids col 0 is a partial OR for parent sB-1 — owned by the
    # shard to the left; hand it back and fold into that shard's last
    # parent column (which is its own ek col B).
    if n_shards > 1:
        back = jax.lax.ppermute(
            ek[:, :1], axis_name,
            [(p + 1, p) for p in range(n_shards - 1)])
        ek = ek.at[:, block:].set(ek[:, block:] | back)
    return ek[:, 1:]


def grid_sharded_exchange(p_local: jnp.ndarray, n: int, n_shards: int,
                          cols: int,
                          axis_name: str = "nodes") -> jnp.ndarray:
    """Halo exchange for the row-major 2D grid: up/down are zero-fill
    shifts by ±cols, left/right by ±1 with a global column mask killing
    the row wrap — bit-exact with :func:`grid_exchange`, communicating
    only a (cols+1)-column halo per direction per shard."""
    block = p_local.shape[1]
    assert block * n_shards == n, "node axis must shard evenly"
    up = sharded_shift(p_local, cols, n_shards, axis_name)
    down = sharded_shift(p_local, -cols, n_shards, axis_name)
    lf = sharded_shift(p_local, 1, n_shards, axis_name)
    rt = sharded_shift(p_local, -1, n_shards, axis_name)
    start = jax.lax.axis_index(axis_name) * block
    col_idx = (start + jnp.arange(block, dtype=jnp.int32)) % cols
    lf = jnp.where((col_idx < cols - 1)[None, :], lf, 0)
    rt = jnp.where((col_idx > 0)[None, :], rt, 0)
    return up | down | lf | rt


def line_sharded_exchange(p_local: jnp.ndarray, n: int, n_shards: int,
                          axis_name: str = "nodes") -> jnp.ndarray:
    """Halo exchange for the line: ±1 zero-fill shifts (1-column
    halos), bit-exact with :func:`line_exchange`."""
    assert p_local.shape[1] * n_shards == n
    return (sharded_shift(p_local, 1, n_shards, axis_name)
            | sharded_shift(p_local, -1, n_shards, axis_name))


def make_sharded_exchange(topology: str, n: int, n_shards: int,
                          axis_name: str = "nodes", **kw):
    """Halo (ppermute-based) sharded exchange: maps the LOCAL payload
    block directly to the LOCAL inbox block with O(block)
    communication — no all_gather, no redundant full-axis compute.

    Supported: ring and circulant (rotations), tree (parent/child
    slice multicast), grid and line (boundary shifts).  Returns None
    when the topology/shape has no halo decomposition (fall back to
    the all_gather path): node axis not evenly sharded, tree blocks
    not divisible by the branching factor, or grid rows wider than a
    block.
    """
    if n % n_shards != 0:
        return None
    block = n // n_shards
    if topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])

        def exchange_local(p_local: jnp.ndarray) -> jnp.ndarray:
            out = None
            for s in strides:
                term = (sharded_roll(p_local, s, n, n_shards, axis_name)
                        | sharded_roll(p_local, -s, n, n_shards,
                                       axis_name))
                out = term if out is None else out | term
            return out

        return exchange_local
    if topology == "tree":
        k = kw.get("branching", 4)
        if block % k != 0 or block < k:
            return None
        return lambda p: tree_sharded_exchange(p, n, n_shards, k,
                                               axis_name)
    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        if cols >= block:
            return None
        return lambda p: grid_sharded_exchange(p, n, n_shards, cols,
                                               axis_name)
    if topology == "line":
        if block < 2:
            return None
        return lambda p: line_sharded_exchange(p, n, n_shards, axis_name)
    return None


# -- reference-accounted sync diffs (no gather, no all_gather) ----------
#
# The anti-entropy server-message accounting needs the PER-EDGE set
# differences sum over directed edges (j -> i) of |recv_j \ recv_i| (the
# targeted pushes of SyncBroadcast, reference broadcast.go:97-108) —
# which the OR-union exchange destroys.  But every structured topology
# delivers per-DIRECTION terms where each node hears exactly one
# neighbor, and edges come in symmetric pairs: ONE delivery of recv_j to
# node i yields both |recv_j \ recv_i| and |recv_i \ recv_j|, so one
# half-exchange (parent->child, +s rolls, up/left shifts) prices the
# whole wave.  Cost: O(1) extra structured exchanges EVERY round (like
# the gather path, the diff is where-masked rather than cond-skipped —
# lax.cond branches would need equal sharding types under shard_map —
# so throughput benchmarks time with srv_ledger=False and account in a
# separate run); identical bit-for-bit to the adjacency-gather
# accounting (tpu_sim/broadcast.py::_sync_diff_pc).


def _dir_diff(term: jnp.ndarray, recv: jnp.ndarray,
              mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """() uint32 — both directed diffs of each edge, computed at the
    receiving end: term holds the neighbor's received-set (or zeros
    where the neighbor does not exist — those columns MUST be masked
    off, or the reverse diff would count the whole local set)."""
    per = (lax.population_count(term & ~recv)
           + lax.population_count(recv & ~term)).sum(axis=0)
    if mask is not None:
        per = jnp.where(mask, per, 0)
    return jnp.sum(per, dtype=jnp.uint32)


def tree_sync_diff(recv: jnp.ndarray, branching: int = 4) -> jnp.ndarray:
    w, n = recv.shape
    k = branching
    if n == 1:
        return jnp.uint32(0)
    n_parents = (n - 1 + k - 1) // k
    parent = jnp.repeat(recv[:, :n_parents], k, axis=1)[:, :n - 1]
    return _dir_diff(parent, recv[:, 1:])


def grid_sync_diff(recv: jnp.ndarray, cols: int) -> jnp.ndarray:
    w, n = recv.shape
    c = min(cols, n)
    # vertical edges i <-> i+cols (i + cols < n)
    vert = (_dir_diff(recv[:, c:], recv[:, :n - c]) if n > c
            else jnp.uint32(0))
    # horizontal edges i <-> i+1 within a row
    mask = (jnp.arange(n - 1, dtype=jnp.int32) % cols) < cols - 1
    horiz = _dir_diff(recv[:, 1:], recv[:, :-1], mask)
    return vert + horiz


def circulant_sync_diff(recv: jnp.ndarray,
                        strides: list[int]) -> jnp.ndarray:
    out = jnp.uint32(0)
    for s in strides:
        out = out + _dir_diff(jnp.roll(recv, s, axis=1), recv)
    return out


def line_sync_diff(recv: jnp.ndarray) -> jnp.ndarray:
    return _dir_diff(recv[:, 1:], recv[:, :-1])


def make_sync_diff(topology: str, n: int, **kw):
    """Full-axis (single-device) per-edge sync-diff closure
    ``diff(recv) -> uint32``, or None for unstructured topologies."""
    if topology == "tree":
        k = kw.get("branching", 4)
        return lambda r: tree_sync_diff(r, k)
    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        return lambda r: grid_sync_diff(r, cols)
    if topology == "ring":
        return lambda r: circulant_sync_diff(r, [1])
    if topology == "circulant":
        strides = list(kw["strides"])
        return lambda r: circulant_sync_diff(r, strides)
    if topology == "line":
        return line_sync_diff
    return None


def make_sharded_sync_diff(topology: str, n: int, n_shards: int,
                           axis_name: str = "nodes", **kw):
    """Halo-path sync diff: local received block -> LOCAL partial diff
    (caller psums).  Same feasibility conditions and O(block) ppermute
    cost as :func:`make_sharded_exchange`; None when no halo
    decomposition exists."""
    if n % n_shards != 0:
        return None
    block = n // n_shards

    def global_cols(width: int):
        start = jax.lax.axis_index(axis_name) * block
        return start + jnp.arange(width, dtype=jnp.int32)

    if topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])

        def diff_circ(recv: jnp.ndarray) -> jnp.ndarray:
            out = jnp.uint32(0)
            for s in strides:
                term = sharded_roll(recv, s, n, n_shards, axis_name)
                out = out + _dir_diff(term, recv)
            return out

        return diff_circ
    if topology == "tree":
        k = kw.get("branching", 4)
        if block % k != 0 or block < k:
            return None

        def diff_tree(recv: jnp.ndarray) -> jnp.ndarray:
            parent = tree_parent_payload(recv, n, n_shards, k, axis_name)
            return _dir_diff(parent, recv, global_cols(block) != 0)

        return diff_tree
    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        if cols >= block:
            return None

        def diff_grid(recv: jnp.ndarray) -> jnp.ndarray:
            g = global_cols(block)
            vert = _dir_diff(
                sharded_shift(recv, cols, n_shards, axis_name), recv,
                g < n - cols)
            horiz = _dir_diff(
                sharded_shift(recv, 1, n_shards, axis_name), recv,
                (g < n - 1) & (g % cols < cols - 1))
            return vert + horiz

        return diff_grid
    if topology == "line":
        if block < 2:
            return None

        def diff_line(recv: jnp.ndarray) -> jnp.ndarray:
            return _dir_diff(
                sharded_shift(recv, 1, n_shards, axis_name), recv,
                global_cols(block) < n - 1)

        return diff_line
    return None


def make_exchange(topology: str, n: int, **kw):
    """Exchange closure for a named topology, or None if the topology
    has no structured form (fall back to the padded-adjacency gather)."""
    if topology == "tree":
        k = kw.get("branching", 4)
        return lambda p: tree_exchange(p, k)
    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        return lambda p: grid_exchange(p, cols)
    if topology == "ring":
        return ring_exchange
    if topology == "line":
        return line_exchange
    if topology == "circulant":
        strides = list(kw["strides"])
        return lambda p: circulant_exchange(p, strides)
    return None


# -- partition faults on the structured path ----------------------------
#
# Maelstrom's partition nemesis applies at any workload size (reference
# README.md:18), so it must compose with the words-major structured
# delivery, not just the adjacency gather.  The key observation: a
# partition window is per-node group ids — STATIC data — and every
# structured delivery is a sum of per-DIRECTION terms (roll/shift/
# parent/child-slot maps), so each direction's receiver-side edge
# liveness under a window is a host-precomputable (N,) boolean mask:
# ``same[w, d, i] = group_w[i] == group_w[sender_d(i)]``.  At round t
# the live mask is ``exists & AND over active windows of same`` — the
# same masked-adjacency trick the gather path's _edge_live applies per
# edge (broadcast.py), applied per direction CLASS, so delivery stays
# gather-free and the partition costs one (D, N) mask AND per round
# instead of the ~60x slower gather path.
#
# Direction-row contract (shared by fault_dir_senders, the masked
# exchanges, and the masked sync diffs):
# - tree(k):   row 0 = parent edge at CHILD positions (masks both the
#              from_parent delivery and the pre-fold kids payload — one
#              symmetric edge, one mask); rows 1..k = child slot j at
#              PARENT positions (degree accounting only; row 1+j at
#              parent p mirrors row 0 at child kp+1+j).
# - grid:      up (i<-i+cols), down (i<-i-cols), left (i<-i+1, row-
#              local), right (i<-i-1, row-local).
# - ring:      +1, -1.   line: fwd (i<-i+1), bwd (i<-i-1).
# - circulant: +s0, -s0, +s1, -s1, ... per stride.
#
# live_deg[i] = live.sum(axis=0)[i] equals the node's live UNDIRECTED
# degree (each symmetric edge contributes exactly one receiver-side row
# entry at each endpoint), which is what the message ledgers need.


def fault_dir_senders(topology: str, n: int, **kw) -> np.ndarray | None:
    """(D, N) int64 — sender node index per direction row per receiver
    position, -1 where the edge does not exist (see the direction-row
    contract above).  None for unstructured topologies."""
    idx = np.arange(n, dtype=np.int64)
    if topology == "tree":
        k = kw.get("branching", 4)
        rows = [np.where(idx >= 1, (idx - 1) // k, -1)]
        for j in range(k):
            child = k * idx + 1 + j
            rows.append(np.where(child < n, child, -1))
        return np.stack(rows)
    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        col = idx % cols
        up = np.where(idx + cols < n, idx + cols, -1)
        down = np.where(idx - cols >= 0, idx - cols, -1)
        left = np.where((col < cols - 1) & (idx + 1 < n), idx + 1, -1)
        right = np.where(col > 0, idx - 1, -1)
        return np.stack([up, down, left, right])
    if topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])
        rows = []
        for s in strides:
            rows.append((idx - s) % n)
            rows.append((idx + s) % n)
        return np.stack(rows)
    if topology == "line":
        fwd = np.where(idx + 1 < n, idx + 1, -1)
        bwd = np.where(idx - 1 >= 0, idx - 1, -1)
        return np.stack([fwd, bwd])
    return None


def fault_masks(topology: str, n: int, groups: np.ndarray,
                **kw) -> tuple[np.ndarray, np.ndarray] | None:
    """Host-precomputed fault masks for a partition schedule:
    ``(exists (D, N) bool, same (P, D, N) bool)`` where ``groups`` is
    the schedule's (P, N) per-window per-node group ids
    (broadcast.Partitions.group).  None for unstructured topologies."""
    snd = fault_dir_senders(topology, n, **kw)
    if snd is None:
        return None
    exists = snd >= 0
    g = np.asarray(groups)
    sender_groups = g[:, np.clip(snd, 0, n - 1)]      # (P, D, N)
    same = g[:, None, :] == sender_groups
    return exists, same


def _mask_cols(x: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Zero the columns of (W, N) ``x`` where (N,) ``m`` is False."""
    return jnp.where(m[None, :], x, jnp.zeros((), x.dtype))


def tree_masked_exchange(payload: jnp.ndarray, live: jnp.ndarray,
                         branching: int = 4) -> jnp.ndarray:
    """:func:`tree_exchange` under per-edge liveness: live[0] masks the
    parent edge at child positions — applied to the from_parent
    delivery AND to the child payload pre-fold (one symmetric edge)."""
    w, n = payload.shape
    k = branching
    if n == 1:
        return jnp.zeros_like(payload)
    m = live[0]
    n_parents = (n - 1 + k - 1) // k
    from_parent = jnp.repeat(payload[:, :n_parents], k, axis=1)[:, :n - 1]
    from_parent = jnp.concatenate([_zeros(payload, 1), from_parent],
                                  axis=1)
    from_parent = _mask_cols(from_parent, m)
    masked = _mask_cols(payload, m)        # col 0 unused below ([1:])
    mcount = n_parents * k
    kids = jnp.concatenate([masked[:, 1:],
                            _zeros(payload, mcount - (n - 1))], axis=1)
    from_kids = jnp.bitwise_or.reduce(kids.reshape(w, n_parents, k),
                                      axis=2)
    from_kids = jnp.concatenate(
        [from_kids, _zeros(payload, n - n_parents)], axis=1)
    return from_parent | from_kids


def grid_masked_exchange(payload: jnp.ndarray, live: jnp.ndarray,
                         cols: int) -> jnp.ndarray:
    """:func:`grid_exchange` under per-edge liveness (the static
    row-wrap column masks are folded into the exists rows)."""
    w, n = payload.shape
    c = min(cols, n)
    up = jnp.concatenate([payload[:, c:], _zeros(payload, c)], axis=1)
    down = jnp.concatenate([_zeros(payload, c), payload[:, :n - c]],
                           axis=1)
    left = jnp.concatenate([payload[:, 1:], _zeros(payload, 1)], axis=1)
    right = jnp.concatenate([_zeros(payload, 1), payload[:, :-1]],
                            axis=1)
    return (_mask_cols(up, live[0]) | _mask_cols(down, live[1])
            | _mask_cols(left, live[2]) | _mask_cols(right, live[3]))


def circulant_masked_exchange(payload: jnp.ndarray, live: jnp.ndarray,
                              strides: list[int]) -> jnp.ndarray:
    out = None
    for i, s in enumerate(strides):
        term = (_mask_cols(jnp.roll(payload, s, axis=1), live[2 * i])
                | _mask_cols(jnp.roll(payload, -s, axis=1),
                             live[2 * i + 1]))
        out = term if out is None else out | term
    return out if out is not None else jnp.zeros_like(payload)


def line_masked_exchange(payload: jnp.ndarray,
                         live: jnp.ndarray) -> jnp.ndarray:
    fwd = jnp.concatenate([payload[:, 1:], _zeros(payload, 1)], axis=1)
    bwd = jnp.concatenate([_zeros(payload, 1), payload[:, :-1]], axis=1)
    return _mask_cols(fwd, live[0]) | _mask_cols(bwd, live[1])


def tree_masked_sync_diff(recv: jnp.ndarray, live: jnp.ndarray,
                          branching: int = 4) -> jnp.ndarray:
    w, n = recv.shape
    k = branching
    if n == 1:
        return jnp.uint32(0)
    n_parents = (n - 1 + k - 1) // k
    parent = jnp.repeat(recv[:, :n_parents], k, axis=1)[:, :n - 1]
    return _dir_diff(parent, recv[:, 1:], live[0][1:])


def grid_masked_sync_diff(recv: jnp.ndarray, live: jnp.ndarray,
                          cols: int) -> jnp.ndarray:
    w, n = recv.shape
    c = min(cols, n)
    up = jnp.concatenate([recv[:, c:], _zeros(recv, c)], axis=1)
    left = jnp.concatenate([recv[:, 1:], _zeros(recv, 1)], axis=1)
    return (_dir_diff(up, recv, live[0])
            + _dir_diff(left, recv, live[2]))


def circulant_masked_sync_diff(recv: jnp.ndarray, live: jnp.ndarray,
                               strides: list[int]) -> jnp.ndarray:
    out = jnp.uint32(0)
    for i, s in enumerate(strides):
        out = out + _dir_diff(jnp.roll(recv, s, axis=1), recv,
                              live[2 * i])
    return out


def line_masked_sync_diff(recv: jnp.ndarray,
                          live: jnp.ndarray) -> jnp.ndarray:
    fwd = jnp.concatenate([recv[:, 1:], _zeros(recv, 1)], axis=1)
    return _dir_diff(fwd, recv, live[0])


# sharded (halo) masked variants: the live rows shard over the node
# axis exactly like the state — every mask application lands on LOCAL
# receiver columns (the tree's kids pre-fold mask is at child
# positions, local to the child shard), so the masked halo exchange
# adds zero ICI traffic over the unmasked one.


def tree_masked_sharded_exchange(p_local, live_local, n, n_shards,
                                 branching=4, axis_name="nodes"):
    m = live_local[0]
    from_parent = _mask_cols(
        tree_parent_payload(p_local, n, n_shards, branching, axis_name),
        m)
    from_kids = tree_kids_payload(
        _mask_cols(p_local, m), n, n_shards, branching, axis_name)
    return from_parent | from_kids


def grid_masked_sharded_exchange(p_local, live_local, n, n_shards,
                                 cols, axis_name="nodes"):
    up = sharded_shift(p_local, cols, n_shards, axis_name)
    down = sharded_shift(p_local, -cols, n_shards, axis_name)
    lf = sharded_shift(p_local, 1, n_shards, axis_name)
    rt = sharded_shift(p_local, -1, n_shards, axis_name)
    return (_mask_cols(up, live_local[0]) | _mask_cols(down, live_local[1])
            | _mask_cols(lf, live_local[2]) | _mask_cols(rt, live_local[3]))


def circulant_masked_sharded_exchange(p_local, live_local, n, n_shards,
                                      strides, axis_name="nodes"):
    out = None
    for i, s in enumerate(strides):
        term = (_mask_cols(sharded_roll(p_local, s, n, n_shards,
                                        axis_name), live_local[2 * i])
                | _mask_cols(sharded_roll(p_local, -s, n, n_shards,
                                          axis_name),
                             live_local[2 * i + 1]))
        out = term if out is None else out | term
    return out


def line_masked_sharded_exchange(p_local, live_local, n, n_shards,
                                 axis_name="nodes"):
    fwd = sharded_shift(p_local, 1, n_shards, axis_name)
    bwd = sharded_shift(p_local, -1, n_shards, axis_name)
    return (_mask_cols(fwd, live_local[0])
            | _mask_cols(bwd, live_local[1]))


class StructuredFaults(NamedTuple):
    """Everything a words-major BroadcastSim needs to run a partition
    schedule gather-free: the host-precomputed masks plus the masked
    exchange/diff closures (built by :func:`make_faulted`).

    - ``exists``: (D, N) bool — static edge-existence per direction row.
    - ``same``: (P, D, N) bool — per window, per direction, receiver-
      side same-group mask.
    - ``exchange(payload, live)`` / ``sync_diff(recv, live)``:
      full-axis closures; ``live`` is the (D, N) combined mask.
    - ``sharded_exchange`` / ``sharded_sync_diff``: halo-path closures
      over local blocks (None when no halo decomposition exists — the
      caller falls back to the all_gather path with the full-axis
      closures)."""

    exists: np.ndarray
    same: np.ndarray
    exchange: Callable
    sync_diff: Callable
    sharded_exchange: Callable | None
    sharded_sync_diff: Callable | None


def _masked_diffs(topology: str, n: int, n_shards: int | None,
                  axis_name: str = "nodes", halo: bool | None = None,
                  **kw):
    """The masked per-edge sync-diff closures ``(df, sdf | None)`` —
    ``df(recv, live)`` full-axis, ``sdf`` halo-path — shared by
    :func:`make_faulted` and :func:`make_delayed_faulted` (one
    definition of the accounting per topology).  None for unstructured
    topologies; ``sdf`` is None when the halo gates fail (``halo``:
    the precomputed :func:`has_sharded_exchange` predicate, probed
    here only when the caller has not already)."""
    if topology == "tree":
        k = kw.get("branching", 4)
        df = lambda r, lv: tree_masked_sync_diff(r, lv, k)  # noqa: E731
    elif topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        df = lambda r, lv: grid_masked_sync_diff(r, lv, cols)  # noqa: E731
    elif topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])
        df = lambda r, lv: circulant_masked_sync_diff(  # noqa: E731
            r, lv, strides)
    elif topology == "line":
        df = line_masked_sync_diff
    else:
        return None

    sdf = None
    if halo is None:
        halo = has_sharded_exchange(topology, n, n_shards,
                                    axis_name=axis_name, **kw)
    if halo:
        if topology == "tree":
            k = kw.get("branching", 4)

            def sdf(r, lv):
                parent = tree_parent_payload(r, n, n_shards, k,
                                             axis_name)
                return _dir_diff(parent, r, lv[0])
        elif topology == "grid":
            cols = kw.get("cols") or grid_cols(n)

            def sdf(r, lv):
                up = sharded_shift(r, cols, n_shards, axis_name)
                lf = sharded_shift(r, 1, n_shards, axis_name)
                return (_dir_diff(up, r, lv[0])
                        + _dir_diff(lf, r, lv[2]))
        elif topology in ("ring", "circulant"):
            strides = [1] if topology == "ring" else list(kw["strides"])

            def sdf(r, lv):
                out = jnp.uint32(0)
                for i, s in enumerate(strides):
                    out = out + _dir_diff(
                        sharded_roll(r, s, n, n_shards, axis_name), r,
                        lv[2 * i])
                return out
        elif topology == "line":
            def sdf(r, lv):
                fwd = sharded_shift(r, 1, n_shards, axis_name)
                return _dir_diff(fwd, r, lv[0])

    return df, sdf


def make_faulted(topology: str, n: int, groups: np.ndarray,
                 n_shards: int | None = None, axis_name: str = "nodes",
                 **kw) -> StructuredFaults | None:
    """Build the :class:`StructuredFaults` bundle for a topology under
    a partition schedule (``groups``: the (P, N) per-window group ids
    of broadcast.Partitions).  None for unstructured topologies; the
    sharded closures are None when the halo gates fail (same conditions
    as :func:`make_sharded_exchange`)."""
    masks = fault_masks(topology, n, groups, **kw)
    if masks is None:
        return None
    exists, same = masks
    if topology == "tree":
        k = kw.get("branching", 4)
        ex = lambda p, lv: tree_masked_exchange(p, lv, k)  # noqa: E731
    elif topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        ex = lambda p, lv: grid_masked_exchange(p, lv, cols)  # noqa: E731
    elif topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])
        ex = lambda p, lv: circulant_masked_exchange(  # noqa: E731
            p, lv, strides)
    elif topology == "line":
        ex = line_masked_exchange
    else:
        return None
    halo = has_sharded_exchange(topology, n, n_shards,
                                axis_name=axis_name, **kw)
    df, sdf = _masked_diffs(topology, n, n_shards,
                            axis_name=axis_name, halo=halo, **kw)

    sex = None
    if halo:
        if topology == "tree":
            k = kw.get("branching", 4)
            sex = lambda p, lv: tree_masked_sharded_exchange(  # noqa: E731
                p, lv, n, n_shards, k, axis_name)
        elif topology == "grid":
            cols = kw.get("cols") or grid_cols(n)
            sex = lambda p, lv: grid_masked_sharded_exchange(  # noqa: E731
                p, lv, n, n_shards, cols, axis_name)
        elif topology in ("ring", "circulant"):
            strides = [1] if topology == "ring" else list(kw["strides"])
            sex = lambda p, lv: circulant_masked_sharded_exchange(  # noqa: E731
                p, lv, n, n_shards, strides, axis_name)
        elif topology == "line":
            sex = lambda p, lv: line_masked_sharded_exchange(  # noqa: E731
                p, lv, n, n_shards, axis_name)

    return StructuredFaults(exists, same, ex, df, sex, sdf)


# -- per-direction delay classes on the structured path -----------------
#
# Maelstrom's injected latency (reference README.md:16: 100 ms per hop)
# is per-EDGE; on the structured path a delay is per direction CLASS
# (every +s edge of a circulant, the parent->child direction of the
# tree, ...): direction d delivers the payload flooded delta_d rounds
# ago, read from a ring of past payloads.  That covers the uniform and
# per-direction latency configurations at full structured speed — the
# per-edge-RANDOM delay regime stays on the gather path
# (broadcast._gather_or_delayed), whose ring is node-sharded too.
#
# Direction-class order (the contract shared with gather_delays_for):
# tree(k): (parent->child, child->parent); grid: (up, down, left,
# right) receiver-side like the fault rows; ring/line: (fwd, bwd) =
# receiver i <- i+1, i <- i-1; circulant: (+s0, -s0, +s1, ...).


class StructuredDelays(NamedTuple):
    """Delayed structured delivery bundle (from :func:`make_delayed`).

    - ``dir_delays``: per-direction-class delays in rounds (>= 1).
    - ``ring``: history ring length == max delay.
    - ``exchange(history, t)``: full-axis closure over the (L, W, N)
      ring of past payloads -> (W, N) inbox.
    - ``sharded_exchange``: halo-path closure over the LOCAL (L, W,
      block) ring (None when no halo decomposition exists; there is no
      all_gather fallback — use the gather delayed path then)."""

    dir_delays: tuple
    ring: int
    exchange: Callable
    sharded_exchange: Callable | None


def gather_delays_for(topology: str, n: int, dir_delays, nbrs,
                      **kw) -> np.ndarray:
    """The (N, D_adj) per-edge delays array (for broadcast's gather
    path) equivalent to per-direction-class ``dir_delays`` — the bridge
    the equivalence tests and mixed-path runs use.  Pad slots get 1.

    Raises when two direction classes alias the same physical edge
    with different delays (e.g. a circulant stride with 2s ≡ 0 mod n,
    where +s and -s are one edge): no per-edge array can represent
    that, so the bridge contract would silently break."""
    snd = fault_dir_senders(topology, n, **kw)
    if topology == "tree":
        k = kw.get("branching", 4)
        if len(dir_delays) != 2:
            raise ValueError("tree takes (down, up) delays")
        row_delays = [dir_delays[0]] + [dir_delays[1]] * k
    else:
        row_delays = list(dir_delays)
    if len(row_delays) != snd.shape[0]:
        raise ValueError(
            f"{topology} takes {snd.shape[0]} direction delays, got "
            f"{len(dir_delays)}")
    nbrs = np.asarray(nbrs)
    out = np.ones(nbrs.shape, np.int32)
    assigned = np.zeros(nbrs.shape, bool)
    for d, delay in enumerate(row_delays):
        s = snd[d]
        mask = (nbrs == s[:, None]) & (s[:, None] >= 0)
        clash = assigned & mask & (out != np.int32(delay))
        if clash.any():
            raise ValueError(
                "direction classes alias the same edge with different "
                f"delays (direction row {d}); per-edge delays cannot "
                "represent this")
        out = np.where(mask, np.int32(delay), out)
        assigned |= mask
    return out


def _take_delayed(hist: jnp.ndarray, t: jnp.ndarray, delay: int,
                  ring: int) -> jnp.ndarray:
    """The payload flooded ``delay-1`` rounds before t (zeros before
    round delay-1: nothing was in flight yet)."""
    src_t = t - (delay - 1)
    sl = lax.dynamic_index_in_dim(hist, src_t % ring, axis=0,
                                  keepdims=False)
    return jnp.where(src_t >= 0, sl, jnp.zeros_like(sl))


def has_sharded_exchange(topology: str, n: int, n_shards: int | None,
                         axis_name: str = "nodes", **kw) -> bool:
    """Whether the topology/shape has a halo decomposition — the ONE
    availability predicate behind every halo-gated builder."""
    return (n_shards is not None
            and make_sharded_exchange(topology, n, n_shards,
                                      axis_name=axis_name,
                                      **kw) is not None)


def _delayed_impl(topology: str, n: int, dir_delays,
                  n_shards: int | None, axis_name: str,
                  halo: bool | None = None, **kw):
    """ONE implementation of per-direction-class delayed delivery per
    topology, shared by :func:`make_delayed` (unmasked) and
    :func:`make_delayed_faulted` (window-masked): returns
    ``(ex_impl, sex_impl | None)`` where each takes ``(hist, t, lv)``
    with ``lv`` either None (no partitions) or a {delay: (D, rows)
    liveness} dict evaluated at each delay's send round.  Masks apply
    at the same positions as the masked exchanges (receiver columns;
    the tree's child-position mask pre-fold).  Coerces and validates
    ``dir_delays`` once for both entry points; returns (dd, ex_impl,
    sex_impl | None)."""
    dd = tuple(int(x) for x in dir_delays)
    if any(d < 1 for d in dd):
        raise ValueError("direction delays are rounds >= 1")
    ring = max(dd)
    if halo is None:
        halo = has_sharded_exchange(topology, n, n_shards,
                                    axis_name=axis_name, **kw)

    def take(hist, t, d):
        return _take_delayed(hist, t, dd[d], ring)

    def m(x, lv, d, row):
        return x if lv is None else _mask_cols(x, lv[dd[d]][row])

    if topology == "tree":
        k = kw.get("branching", 4)
        if len(dd) != 2:
            raise ValueError("tree takes (down, up) delays")

        def ex(hist, t, lv):
            fp = m(tree_from_parent(take(hist, t, 0), k), lv, 0, 0)
            fk = tree_from_kids(m(take(hist, t, 1), lv, 1, 0), k)
            return fp | fk

        sex = None
        if halo:
            def sex(hist, t, lv):
                fp = m(tree_parent_payload(take(hist, t, 0), n,
                                           n_shards, k, axis_name),
                       lv, 0, 0)
                fk = tree_kids_payload(m(take(hist, t, 1), lv, 1, 0),
                                       n, n_shards, k, axis_name)
                return fp | fk

        return dd, ex, sex

    if topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])
        if len(dd) != 2 * len(strides):
            raise ValueError("circulant takes (+s, -s) delays per stride")

        def ex(hist, t, lv):
            out = None
            for i, s in enumerate(strides):
                term = (m(jnp.roll(take(hist, t, 2 * i), s, axis=1),
                          lv, 2 * i, 2 * i)
                        | m(jnp.roll(take(hist, t, 2 * i + 1), -s,
                                     axis=1), lv, 2 * i + 1, 2 * i + 1))
                out = term if out is None else out | term
            return out

        sex = None
        if n_shards is not None and n % n_shards == 0:
            def sex(hist, t, lv):
                out = None
                for i, s in enumerate(strides):
                    term = (m(sharded_roll(take(hist, t, 2 * i), s, n,
                                           n_shards, axis_name),
                              lv, 2 * i, 2 * i)
                            | m(sharded_roll(take(hist, t, 2 * i + 1),
                                             -s, n, n_shards,
                                             axis_name),
                                lv, 2 * i + 1, 2 * i + 1))
                    out = term if out is None else out | term
                return out

        return dd, ex, sex

    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        if len(dd) != 4:
            raise ValueError("grid takes (up, down, left, right) delays")

        def ex(hist, t, lv):
            pu, pd_, pl_, pr = (take(hist, t, d) for d in range(4))
            if lv is None:
                return grid_terms(pu, pd_, pl_, pr, cols)
            # grid_terms folds the static row-wrap masks; window masks
            # land per direction on the delivered terms, so apply them
            # via four single-direction grid_terms calls:
            z = _zeros(pu, pu.shape[1])
            up = m(grid_terms(pu, z, z, z, cols), lv, 0, 0)
            down = m(grid_terms(z, pd_, z, z, cols), lv, 1, 1)
            left = m(grid_terms(z, z, pl_, z, cols), lv, 2, 2)
            right = m(grid_terms(z, z, z, pr, cols), lv, 3, 3)
            return up | down | left | right

        sex = None
        if halo:
            def sex(hist, t, lv):
                block = hist.shape[2]
                up = m(sharded_shift(take(hist, t, 0), cols, n_shards,
                                     axis_name), lv, 0, 0)
                down = m(sharded_shift(take(hist, t, 1), -cols,
                                       n_shards, axis_name), lv, 1, 1)
                lf = sharded_shift(take(hist, t, 2), 1, n_shards,
                                   axis_name)
                rt = sharded_shift(take(hist, t, 3), -1, n_shards,
                                   axis_name)
                start = jax.lax.axis_index(axis_name) * block
                col_idx = (start
                           + jnp.arange(block, dtype=jnp.int32)) % cols
                lf = jnp.where((col_idx < cols - 1)[None, :], lf, 0)
                rt = jnp.where((col_idx > 0)[None, :], rt, 0)
                return up | down | m(lf, lv, 2, 2) | m(rt, lv, 3, 3)

        return dd, ex, sex

    if topology == "line":
        if len(dd) != 2:
            raise ValueError("line takes (fwd, bwd) delays")

        def ex(hist, t, lv):
            pf, pb = take(hist, t, 0), take(hist, t, 1)
            if lv is None:
                return line_terms(pf, pb)
            z = _zeros(pf, pf.shape[1])
            return (m(line_terms(pf, z), lv, 0, 0)
                    | m(line_terms(z, pb), lv, 1, 1))

        sex = None
        if halo:
            def sex(hist, t, lv):
                return (m(sharded_shift(take(hist, t, 0), 1, n_shards,
                                        axis_name), lv, 0, 0)
                        | m(sharded_shift(take(hist, t, 1), -1,
                                          n_shards, axis_name),
                            lv, 1, 1))

        return dd, ex, sex

    return None


def make_delayed(topology: str, n: int, dir_delays,
                 n_shards: int | None = None, axis_name: str = "nodes",
                 **kw) -> StructuredDelays | None:
    """Build the :class:`StructuredDelays` bundle.  ``dir_delays``
    length: tree 2, grid 4, ring/line 2, circulant 2*len(strides).
    None for unstructured topologies.

    Aliasing note: if two direction classes are the same physical edge
    (a circulant stride with 2s ≡ 0 mod n), the structured delivery
    ORs both classes — the edge effectively carries BOTH delays.  The
    gather bridge (:func:`gather_delays_for`) cannot represent that
    and raises instead."""
    impl = _delayed_impl(topology, n, dir_delays, n_shards, axis_name,
                         **kw)
    if impl is None:
        return None
    dd, ex_impl, sex_impl = impl
    sex = (None if sex_impl is None
           else (lambda h, t: sex_impl(h, t, None)))
    return StructuredDelays(dd, max(dd),
                            lambda h, t: ex_impl(h, t, None), sex)


class FaultedDelayed(NamedTuple):
    """Delays AND partition windows composed on the structured path
    (from :func:`make_delayed_faulted`): each direction class delivers
    its past payload masked by the window liveness AT ITS SEND ROUND —
    drops happen at send time, exactly like the gather path's
    ``live_at_send`` (broadcast._gather_or_delayed) and Maelstrom.

    ``exchange(history, t, live_rows)`` / the sharded variant take the
    per-round liveness closure (BroadcastSim._live_rows over
    ``exists``/``same``) and evaluate it at each direction's send
    round; ``exists``/``same`` follow the StructuredFaults layout."""

    exists: np.ndarray
    same: np.ndarray
    dir_delays: tuple
    ring: int
    exchange: Callable
    sharded_exchange: Callable | None
    # masked per-edge sync-diff closures for the srv (Maelstrom-
    # comparable) ledger — the gather path's documented current-state
    # approximation under delays, with the diff over live edges at
    # round t (shared with make_faulted via _masked_diffs)
    sync_diff: Callable | None = None
    sharded_sync_diff: Callable | None = None


def make_delayed_faulted(topology: str, n: int, dir_delays,
                         groups: np.ndarray,
                         n_shards: int | None = None,
                         axis_name: str = "nodes",
                         **kw) -> FaultedDelayed | None:
    """Compose per-direction-class delays with a partition schedule,
    gather-free.  Masks follow :func:`fault_masks`; delays and the
    delivery bodies are shared with :func:`make_delayed` via
    :func:`_delayed_impl` (same direction-class order and aliasing
    caveat)."""
    masks = fault_masks(topology, n, groups, **kw)
    if masks is None:
        return None
    exists, same = masks
    halo = has_sharded_exchange(topology, n, n_shards,
                                axis_name=axis_name, **kw)
    impl = _delayed_impl(topology, n, dir_delays, n_shards, axis_name,
                         halo=halo, **kw)
    if impl is None:
        return None
    dd, ex_impl, sex_impl = impl
    df, sdf = _masked_diffs(topology, n, n_shards,
                            axis_name=axis_name, halo=halo, **kw)

    def lv_by_delay(live_rows, t):
        # one liveness evaluation per DISTINCT send round, shared by
        # all directions with that delay
        return {d: live_rows(t - (d - 1)) for d in sorted(set(dd))}

    def ex(hist, t, live_rows):
        return ex_impl(hist, t, lv_by_delay(live_rows, t))

    sex = None
    if sex_impl is not None:
        def sex(hist, t, live_rows):
            return sex_impl(hist, t, lv_by_delay(live_rows, t))

    return FaultedDelayed(exists, same, dd, max(dd), ex, sex, df, sdf)


# -- per-EDGE random delays on the structured path ----------------------
#
# Maelstrom's default latency model is random per EDGE (reference
# README.md:16 plus jitter), not per direction class — previously only
# the adjacency gather could run it (~390x slower per round at 1M
# nodes).  The decomposition that made partitions gather-free applies
# here too: delays take values from a SMALL STATIC set, so a random
# (D, N) per-direction-per-receiver delay matrix splits into
# |delay_set| receiver-side boolean masks per direction —
# ``rows[d] == v`` — and delivery is
#
#   inbox = OR over (d, v) of mask_cols(term_d(history@v), rows[d]==v)
#
# i.e. each direction reads each delay class's ring slice, masked to
# the receivers whose edge has that delay.  Cost: D x |delay_set|
# structured terms per round (still zero random access) instead of the
# gather's per-edge reads.  The delay rows ride along as ONE traced
# (D, N) int32 array (sharded with the node axis on the halo path);
# the masks are computed on the fly by an elementwise compare.
#
# Row contract: grid/ring/line/circulant follow the fault direction
# rows (receiver-side, :func:`fault_dir_senders` order).  The tree
# takes TWO rows, both indexed at CHILD positions: row 0 = the
# parent->child edge's delay (receiver = the child), row 1 = the
# child->parent edge's delay (receiver = the parent; child-position
# indexing is what lets the kids delivery mask the payload PRE-fold,
# exactly like the fault mask).


def _ed_mask(rows, wl, d: int, v: int):
    """The (direction, delay-class) receiver mask of the edge-delayed
    delivery: this direction's edges with delay ``v`` — AND, when a
    window-liveness dict ``wl`` rides along (make_edge_delayed_faulted),
    the partition liveness of direction ``d`` at delay class ``v``'s
    SEND round (drops happen at send time, like every other mode)."""
    m = rows[d] == v
    return m if wl is None else m & wl[v][d]


class EdgeDelays(NamedTuple):
    """Per-edge-random delayed structured delivery (from
    :func:`make_edge_delayed`).

    - ``delay_rows``: (D, N) int32 host array (see the row contract
      above); passed each round as a traced array, not baked into the
      program.
    - ``delay_set``: distinct delay values (static).
    - ``ring``: history ring length == max delay.
    - ``exchange(history, t, rows)``: full-axis closure over the
      (L, W, N) ring -> (W, N) inbox.
    - ``sharded_exchange(history, t, rows_local)``: halo-path closure
      over LOCAL blocks (None when no halo decomposition exists; no
      all_gather fallback — use the gather delayed path then)."""

    delay_rows: np.ndarray
    delay_set: tuple
    ring: int
    exchange: Callable
    sharded_exchange: Callable | None


def make_edge_delayed(topology: str, n: int, delay_rows,
                      n_shards: int | None = None,
                      axis_name: str = "nodes",
                      **kw) -> EdgeDelays | None:
    """Build the :class:`EdgeDelays` bundle for random per-edge delays
    over a small static value set.  ``delay_rows``: (D, N) ints >= 1,
    D = 2 for tree (see row contract), else the fault direction-row
    count.  None for unstructured topologies.

    Aliasing note: as with :func:`make_delayed`, two direction classes
    that are one physical edge (circulant stride 2s ≡ 0 mod n) OR
    their terms — the edge carries both rows' delays; the gather
    bridge (:func:`gather_delays_from_rows`) raises instead."""
    dr = np.asarray(delay_rows, np.int32)
    if dr.min() < 1:
        raise ValueError("edge delays are rounds >= 1")
    delay_set = tuple(int(v) for v in np.unique(dr))
    ring = max(delay_set)
    # host-side presence: (d, v) pairs with no receiver are skipped
    # entirely — a constant-rows matrix costs exactly make_delayed
    present = {(d, v): bool((dr[d] == v).any())
               for d in range(dr.shape[0]) for v in delay_set}
    halo = has_sharded_exchange(topology, n, n_shards,
                                axis_name=axis_name, **kw)

    def take(hist, t, v):
        return _take_delayed(hist, t, v, ring)

    def acc(out, term):
        return term if out is None else out | term

    if topology == "tree":
        k = kw.get("branching", 4)
        if dr.shape != (2, n):
            raise ValueError("tree takes (2, N) delay rows "
                             "(down, up — both at child positions)")

        def ex(hist, t, rows, wl=None):
            out = None
            for v in delay_set:
                pv = take(hist, t, v)
                if present[(0, v)]:
                    out = acc(out, _mask_cols(tree_from_parent(pv, k),
                                              _ed_mask(rows, wl, 0, v)))
                if present[(1, v)]:
                    out = acc(out, tree_from_kids(
                        _mask_cols(pv, _ed_mask(rows, wl, 1, v)), k))
            return out

        sex = None
        if halo:
            def sex(hist, t, rows, wl=None):
                out = None
                for v in delay_set:
                    pv = take(hist, t, v)
                    if present[(0, v)]:
                        out = acc(out, _mask_cols(
                            tree_parent_payload(pv, n, n_shards, k,
                                                axis_name),
                            _ed_mask(rows, wl, 0, v)))
                    if present[(1, v)]:
                        out = acc(out, tree_kids_payload(
                            _mask_cols(pv, _ed_mask(rows, wl, 1, v)),
                            n, n_shards, k, axis_name))
                return out

        return EdgeDelays(dr, delay_set, ring, ex, sex)

    if topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])
        if dr.shape != (2 * len(strides), n):
            raise ValueError("circulant takes (2*len(strides), N) "
                             "delay rows")

        def ex(hist, t, rows, wl=None):
            out = None
            for v in delay_set:
                pv = take(hist, t, v)
                for i, s in enumerate(strides):
                    if present[(2 * i, v)]:
                        out = acc(out, _mask_cols(
                            jnp.roll(pv, s, axis=1),
                            _ed_mask(rows, wl, 2 * i, v)))
                    if present[(2 * i + 1, v)]:
                        out = acc(out, _mask_cols(
                            jnp.roll(pv, -s, axis=1),
                            _ed_mask(rows, wl, 2 * i + 1, v)))
            return out

        sex = None
        if n_shards is not None and n % n_shards == 0:
            def sex(hist, t, rows, wl=None):
                out = None
                for v in delay_set:
                    pv = take(hist, t, v)
                    for i, s in enumerate(strides):
                        if present[(2 * i, v)]:
                            out = acc(out, _mask_cols(
                                sharded_roll(pv, s, n, n_shards,
                                             axis_name),
                                _ed_mask(rows, wl, 2 * i, v)))
                        if present[(2 * i + 1, v)]:
                            out = acc(out, _mask_cols(
                                sharded_roll(pv, -s, n, n_shards,
                                             axis_name),
                                _ed_mask(rows, wl, 2 * i + 1, v)))
                return out

        return EdgeDelays(dr, delay_set, ring, ex, sex)

    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)
        if dr.shape != (4, n):
            raise ValueError("grid takes (4, N) delay rows "
                             "(up, down, left, right)")

        def ex(hist, t, rows, wl=None):
            out = None
            for v in delay_set:
                pv = take(hist, t, v)
                z = _zeros(pv, pv.shape[1])
                terms = (grid_terms(pv, z, z, z, cols),
                         grid_terms(z, pv, z, z, cols),
                         grid_terms(z, z, pv, z, cols),
                         grid_terms(z, z, z, pv, cols))
                for d in range(4):
                    if present[(d, v)]:
                        out = acc(out, _mask_cols(
                            terms[d], _ed_mask(rows, wl, d, v)))
            return out

        sex = None
        if halo:
            def sex(hist, t, rows, wl=None):
                block = hist.shape[2]
                start = jax.lax.axis_index(axis_name) * block
                col_idx = (start
                           + jnp.arange(block, dtype=jnp.int32)) % cols
                lm = (col_idx < cols - 1)[None, :]
                rm = (col_idx > 0)[None, :]
                out = None
                for v in delay_set:
                    pv = take(hist, t, v)
                    if present[(0, v)]:
                        out = acc(out, _mask_cols(
                            sharded_shift(pv, cols, n_shards,
                                          axis_name),
                            _ed_mask(rows, wl, 0, v)))
                    if present[(1, v)]:
                        out = acc(out, _mask_cols(
                            sharded_shift(pv, -cols, n_shards,
                                          axis_name),
                            _ed_mask(rows, wl, 1, v)))
                    if present[(2, v)]:
                        lf = jnp.where(
                            lm, sharded_shift(pv, 1, n_shards,
                                              axis_name), 0)
                        out = acc(out, _mask_cols(
                            lf, _ed_mask(rows, wl, 2, v)))
                    if present[(3, v)]:
                        rt = jnp.where(
                            rm, sharded_shift(pv, -1, n_shards,
                                              axis_name), 0)
                        out = acc(out, _mask_cols(
                            rt, _ed_mask(rows, wl, 3, v)))
                return out

        return EdgeDelays(dr, delay_set, ring, ex, sex)

    if topology == "line":
        if dr.shape != (2, n):
            raise ValueError("line takes (2, N) delay rows (fwd, bwd)")

        def ex(hist, t, rows, wl=None):
            out = None
            for v in delay_set:
                pv = take(hist, t, v)
                z = _zeros(pv, pv.shape[1])
                if present[(0, v)]:
                    out = acc(out, _mask_cols(line_terms(pv, z),
                                              _ed_mask(rows, wl, 0, v)))
                if present[(1, v)]:
                    out = acc(out, _mask_cols(line_terms(z, pv),
                                              _ed_mask(rows, wl, 1, v)))
            return out

        sex = None
        if halo:
            def sex(hist, t, rows, wl=None):
                out = None
                for v in delay_set:
                    pv = take(hist, t, v)
                    if present[(0, v)]:
                        out = acc(out, _mask_cols(
                            sharded_shift(pv, 1, n_shards, axis_name),
                            _ed_mask(rows, wl, 0, v)))
                    if present[(1, v)]:
                        out = acc(out, _mask_cols(
                            sharded_shift(pv, -1, n_shards, axis_name),
                            _ed_mask(rows, wl, 1, v)))
                return out

        return EdgeDelays(dr, delay_set, ring, ex, sex)

    return None


def gather_delays_from_rows(topology: str, n: int, delay_rows, nbrs,
                            **kw) -> np.ndarray:
    """The (N, D_adj) per-edge delays array (broadcast's gather path)
    equivalent to per-direction-per-receiver ``delay_rows`` — the
    bridge the EdgeDelays equivalence tests and mixed-path runs use.
    Pad slots get 1.  Raises when aliased direction classes (circulant
    2s ≡ 0 mod n) carry different delays for one physical edge."""
    snd = fault_dir_senders(topology, n, **kw)
    dr = np.asarray(delay_rows, np.int64)
    if topology == "tree":
        k = kw.get("branching", 4)
        if dr.shape != (2, n):
            raise ValueError("tree takes (2, N) delay rows")
        # receiver-side rows for the full fault-row layout: row 0 is
        # already receiver-side (child); rows 1..k (child slot j at
        # PARENT positions) read the up-delay at the child position
        rows_recv = [dr[0]]
        for j in range(k):
            c = snd[1 + j]
            rows_recv.append(np.where(
                c >= 0, dr[1][np.clip(c, 0, n - 1)], 1))
    else:
        if dr.shape != (snd.shape[0], n):
            raise ValueError(
                f"{topology} takes ({snd.shape[0]}, N) delay rows")
        rows_recv = list(dr)
    nbrs = np.asarray(nbrs)
    out = np.ones(nbrs.shape, np.int32)
    assigned = np.zeros(nbrs.shape, bool)
    for d, vals in enumerate(rows_recv):
        s = snd[d]
        mask = (nbrs == s[:, None]) & (s[:, None] >= 0)
        want = np.broadcast_to(vals[:, None].astype(np.int32),
                               nbrs.shape)
        clash = assigned & mask & (out != want)
        if clash.any():
            raise ValueError(
                "direction classes alias the same edge with different "
                f"delays (direction row {d}); per-edge delays cannot "
                "represent this")
        out = np.where(mask, want, out)
        assigned |= mask
    return out


class FaultedEdgeDelays(NamedTuple):
    """Random per-edge delays COMPOSED with partition windows on the
    structured path (from :func:`make_edge_delayed_faulted`) — closing
    Maelstrom's default nemesis configuration (random per-hop latency
    AND partitions together, reference README.md:16,18) gather-free.

    Delivery follows the :class:`EdgeDelays` row contract; each
    (direction, delay-class) term is additionally masked by the
    partition liveness of that direction at ITS send round
    (``live_by_delay`` evaluates one liveness per distinct delay value,
    shared by all directions with that value — drops happen at send
    time, exactly like make_delayed_faulted's delay classes).

    ``exists``/``same`` follow the fault direction-row contract
    (ledger live degree + the masked srv diffs); ``del_same`` is the
    (P, D_rows, N) DELIVERY-row twin (differs only for the tree, whose
    two child-position rows both read the parent edge's window)."""

    delay_rows: np.ndarray
    delay_set: tuple
    ring: int
    exists: np.ndarray
    same: np.ndarray
    del_same: np.ndarray
    exchange: Callable            # (hist, t, rows, wl) -> inbox
    sharded_exchange: Callable | None
    live_by_delay: Callable       # (del_same, pstarts, pends, t) -> wl
    sync_diff: Callable | None = None
    sharded_sync_diff: Callable | None = None


def make_edge_delayed_faulted(topology: str, n: int, delay_rows,
                              groups: np.ndarray,
                              n_shards: int | None = None,
                              axis_name: str = "nodes",
                              **kw) -> FaultedEdgeDelays | None:
    """Compose random per-edge delays with a partition schedule,
    gather-free.  ``delay_rows``/aliasing follow
    :func:`make_edge_delayed` (whose delivery bodies are shared);
    window masks follow :func:`fault_masks`.  None for unstructured
    topologies."""
    ed = make_edge_delayed(topology, n, delay_rows, n_shards,
                           axis_name=axis_name, **kw)
    if ed is None:
        return None
    masks = fault_masks(topology, n, groups, **kw)
    exists, same = masks
    if topology == "tree":
        # both delivery rows are the parent edge at child positions
        del_same = np.concatenate([same[:, :1], same[:, :1]], axis=1)
    else:
        del_same = same
    halo = has_sharded_exchange(topology, n, n_shards,
                                axis_name=axis_name, **kw)
    df, sdf = _masked_diffs(topology, n, n_shards,
                            axis_name=axis_name, halo=halo, **kw)
    delay_set = ed.delay_set

    def live_by_delay(dsame, pstarts, pends, t):
        # one window-liveness evaluation per DISTINCT delay value at
        # that value's send round, shared by all directions
        out = {}
        ones = jnp.ones(dsame.shape[1:], bool)
        for v in delay_set:
            tt = t - (v - 1)
            out[v] = windows_fold(
                pstarts, pends, tt,
                lambda w, active, lv: lv & (dsame[w] | ~active), ones)
        return out

    return FaultedEdgeDelays(ed.delay_rows, delay_set, ed.ring,
                             exists, same, del_same,
                             ed.exchange, ed.sharded_exchange,
                             live_by_delay, df, sdf)


# -- the FULL nemesis (crash/loss/dup FaultPlan) on the structured path -
#
# PR 2's FaultPlan ran gather-path only: crash liveness and the
# loss/dup coins were evaluated per adjacency slot, a random gather per
# round (~60-190x slower than the words-major exchanges at 1M nodes).
# The partition decomposition (make_faulted) extends to the whole
# Maelstrom fault model:
#
# - **amnesia at crash entry** is per-COLUMN: a (C, N) down array
#   evaluated elementwise at round t (faults.wm_up_cols) wipes the
#   crashing columns of the (W, N) state — no index, no gather.
# - **crash liveness per edge** decomposes per direction row into a
#   host-precomputed (C, D, N) "either endpoint down" mask, AND-folded
#   at round t exactly like the partition ``same`` masks.
# - **loss/dup coins** are stateless hashes of (t, src, dst): with
#   host-precomputed (D, N) sender/receiver id rows they evaluate
#   ELEMENTWISE per direction — bit-identical to the gather path's
#   per-slot streams (same triples, same coins), zero random access.
# - **duplicate delivery** re-delivers the source's full received set:
#   per direction that is the same structured term applied to
#   ``received`` under the dup coin mask; its ledger charge
#   (popcount-at-source per dup edge) rides the same per-direction
#   relocation applied to the (1, N) popcount vector (``src_pc``).
#
# Delivery direction-row contract (nemesis_dir_pairs) — loss is per
# DIRECTION (the two directions of a link drop independently), so the
# tree cannot reuse the symmetric one-mask contract of fault_masks:
#
# - tree(k): TWO rows, both indexed at CHILD positions (the EdgeDelays
#   row contract): row 0 = the parent->child edge (src = parent(i),
#   dst = i), masking the from_parent delivery; row 1 = the
#   child->parent edge (src = i, dst = parent(i)), masking the kids
#   payload PRE-fold.
# - grid / ring / line / circulant: the fault_dir_senders rows
#   (receiver-side, dst = i).
#
# The message ledger still needs the per-node live UNDIRECTED degree,
# which the 2-row tree contract cannot give per node — the DEGREE
# contract (fault_dir_senders, 1+k receiver-side rows for the tree)
# rides along for the ledgers, evaluated elementwise from its own
# host-precomputed masks (faults.WMNemesisArrays.deg_*).


def nemesis_dir_pairs(topology: str, n: int, **kw):
    """(src, dst, exists), each (D, N) — the nemesis DELIVERY
    direction-row contract (see above).  ``src``/``dst`` are global
    node ids with -1 at pad positions; None for unstructured
    topologies."""
    idx = np.arange(n, dtype=np.int64)
    if topology == "tree":
        k = kw.get("branching", 4)
        parent = np.where(idx >= 1, (idx - 1) // k, -1)
        child = np.where(idx >= 1, idx, -1)
        src = np.stack([parent, child])
        dst = np.stack([child, parent])
        return src, dst, src >= 0
    snd = fault_dir_senders(topology, n, **kw)
    if snd is None:
        return None
    dst = np.where(snd >= 0, idx[None, :], -1)
    return snd, dst, snd >= 0


def _same_groups(groups: np.ndarray, src: np.ndarray,
                 dst: np.ndarray, n: int) -> np.ndarray:
    """(P, D, N) bool — per partition window, are the edge's endpoints
    in the same group (pad positions read True; exists masks them)."""
    g = np.asarray(groups)
    if g.shape[0] == 0:
        return np.zeros((0,) + src.shape, bool)
    sg = g[:, np.clip(src, 0, n - 1)]
    dg = g[:, np.clip(dst, 0, n - 1)]
    return sg == dg


def _nem_closures(topology: str, n: int, n_shards: int | None,
                  axis_name: str, halo: bool, **kw):
    """The nemesis delivery closures: ``(ex, spc, sex, sspc)`` where
    ``ex(take, lv)`` ORs direction d's structured term of ``take(d)``
    masked by ``lv[d]`` (tree row 1 masks the payload PRE-fold), and
    ``spc(d, pc)`` relocates a (1, rows) per-node count vector to
    direction d's contract positions (the dup ledger's
    popcount-at-source — every relocation is a pure repeat/shift/roll,
    so counts survive where OR-folds would not).  ``sex``/``sspc`` are
    the halo-path twins over local blocks (None without a halo
    decomposition)."""
    if topology == "tree":
        k = kw.get("branching", 4)

        def ex(take, lv):
            fp = _mask_cols(tree_from_parent(take(0), k), lv[0])
            fk = tree_from_kids(_mask_cols(take(1), lv[1]), k)
            return fp | fk

        def spc(d, pc):
            return tree_from_parent(pc, k) if d == 0 else pc

        sex = sspc = None
        if halo:
            def sex(take, lv):
                fp = _mask_cols(
                    tree_parent_payload(take(0), n, n_shards, k,
                                        axis_name), lv[0])
                fk = tree_kids_payload(_mask_cols(take(1), lv[1]), n,
                                       n_shards, k, axis_name)
                return fp | fk

            def sspc(d, pc):
                return (tree_parent_payload(pc, n, n_shards, k,
                                            axis_name)
                        if d == 0 else pc)

        return ex, spc, sex, sspc

    if topology in ("ring", "circulant"):
        strides = [1] if topology == "ring" else list(kw["strides"])

        def ex(take, lv):
            out = None
            for i, s in enumerate(strides):
                term = (_mask_cols(jnp.roll(take(2 * i), s, axis=1),
                                   lv[2 * i])
                        | _mask_cols(jnp.roll(take(2 * i + 1), -s,
                                              axis=1), lv[2 * i + 1]))
                out = term if out is None else out | term
            return out

        def spc(d, pc):
            i, back = divmod(d, 2)
            return jnp.roll(pc, -strides[i] if back else strides[i],
                            axis=1)

        sex = sspc = None
        if halo:
            def sex(take, lv):
                out = None
                for i, s in enumerate(strides):
                    term = (_mask_cols(
                        sharded_roll(take(2 * i), s, n, n_shards,
                                     axis_name), lv[2 * i])
                        | _mask_cols(
                            sharded_roll(take(2 * i + 1), -s, n,
                                         n_shards, axis_name),
                            lv[2 * i + 1]))
                    out = term if out is None else out | term
                return out

            def sspc(d, pc):
                i, back = divmod(d, 2)
                return sharded_roll(pc, -strides[i] if back
                                    else strides[i], n, n_shards,
                                    axis_name)

        return ex, spc, sex, sspc

    if topology == "grid":
        cols = kw.get("cols") or grid_cols(n)

        def one_dir(d, p):
            z = _zeros(p, p.shape[1])
            args = [z, z, z, z]
            args[d] = p
            return grid_terms(*args, cols)

        def ex(take, lv):
            out = None
            for d in range(4):
                term = _mask_cols(one_dir(d, take(d)), lv[d])
                out = term if out is None else out | term
            return out

        def spc(d, pc):
            return one_dir(d, pc)

        sex = sspc = None
        if halo:
            def sharded_dir(d, p):
                if d == 0:
                    return sharded_shift(p, cols, n_shards, axis_name)
                if d == 1:
                    return sharded_shift(p, -cols, n_shards, axis_name)
                block = p.shape[1]
                start = jax.lax.axis_index(axis_name) * block
                col_idx = (start
                           + jnp.arange(block, dtype=jnp.int32)) % cols
                if d == 2:
                    t = sharded_shift(p, 1, n_shards, axis_name)
                    return jnp.where((col_idx < cols - 1)[None, :], t, 0)
                t = sharded_shift(p, -1, n_shards, axis_name)
                return jnp.where((col_idx > 0)[None, :], t, 0)

            def sex(take, lv):
                out = None
                for d in range(4):
                    term = _mask_cols(sharded_dir(d, take(d)), lv[d])
                    out = term if out is None else out | term
                return out

            def sspc(d, pc):
                return sharded_dir(d, pc)

        return ex, spc, sex, sspc

    if topology == "line":
        def one_line(d, p):
            z = _zeros(p, p.shape[1])
            return line_terms(p, z) if d == 0 else line_terms(z, p)

        def ex(take, lv):
            return (_mask_cols(one_line(0, take(0)), lv[0])
                    | _mask_cols(one_line(1, take(1)), lv[1]))

        def spc(d, pc):
            return one_line(d, pc)

        sex = sspc = None
        if halo:
            def sharded_line(d, p):
                return sharded_shift(p, 1 if d == 0 else -1, n_shards,
                                     axis_name)

            def sex(take, lv):
                return (_mask_cols(sharded_line(0, take(0)), lv[0])
                        | _mask_cols(sharded_line(1, take(1)), lv[1]))

            def sspc(d, pc):
                return sharded_line(d, pc)

        return ex, spc, sex, sspc

    return None


class StructuredNemesis(NamedTuple):
    """Everything a words-major BroadcastSim needs to run a compiled
    :class:`~.faults.FaultPlan` (crash/restart amnesia, loss, dup)
    gather-free, optionally composed with partition windows and
    per-direction-class delays (built by :func:`make_nemesis`).

    - ``arrs``: the traced mask operand (faults.WMNemesisArrays) —
      threaded through the drivers next to the plan, positionally
      sharded with the node axis on the halo path.
    - ``dir_delays``/``ring``: per-direction-class delays composed in
      (None → every edge is 1 hop); the delay contract and aliasing
      caveat of :func:`make_delayed` apply.
    - ``exchange(take, lv)`` / ``src_pc(d, pc)``: full-axis delivery
      and count-relocation closures (see :func:`_nem_closures`);
      ``sharded_*`` are the halo twins (None → all_gather fallback).
    - ``sync_diff(recv, rows)`` / ``sharded_sync_diff``: the masked
      per-edge diff closures over the DEGREE contract (the same
      :func:`_masked_diffs` accounting the partition-only bundles
      carry) — the LOSS-ONLY srv ledger's sync-wave term, fed the
      both-coin rows from faults.wm_srv_rows."""

    arrs: "faults.WMNemesisArrays"
    dir_delays: tuple | None
    ring: int
    exchange: Callable
    src_pc: Callable
    sharded_exchange: Callable | None
    sharded_src_pc: Callable | None
    sync_diff: Callable | None
    sharded_sync_diff: Callable | None


def make_nemesis(topology: str, n: int, spec: "faults.NemesisSpec",
                 groups: np.ndarray | None = None,
                 dir_delays=None, n_shards: int | None = None,
                 axis_name: str = "nodes",
                 **kw) -> StructuredNemesis | None:
    """Build the :class:`StructuredNemesis` bundle: the words-major
    mask decomposition of ``spec`` (a host NemesisSpec — the crash
    windows must be host data to precompute the per-direction masks),
    composed with an optional partition schedule (``groups``: the
    (P, N) per-window group ids of broadcast.Partitions) and optional
    per-direction-class ``dir_delays``.  Pass the bundle to
    BroadcastSim(nemesis=..., fault_plan=spec.compile()).  None for
    unstructured topologies; the sharded closures are None when the
    halo gates fail (the sim then uses the all_gather fallback)."""
    if spec.n_nodes != n:
        raise ValueError(f"spec is for {spec.n_nodes} nodes, "
                         f"topology has {n}")
    if spec.has_membership:
        raise ValueError(
            "the words-major structured path does not support "
            "membership events yet: the per-direction mask "
            "decomposition (down_pair/down_cols) has no per-row "
            "join/leave columns, so a membership-bearing plan would "
            "silently mis-simulate — run join/leave campaigns on the "
            "gather path (structured=False)")
    pairs = nemesis_dir_pairs(topology, n, **kw)
    if pairs is None:
        return None
    src, dst, exists = pairs
    idx = np.arange(n, dtype=np.int64)
    deg_src = fault_dir_senders(topology, n, **kw)
    deg_dst = np.where(deg_src >= 0, idx[None, :], -1)
    g = (np.zeros((0, n), np.int8) if groups is None
         else np.asarray(groups))
    down_pair = (faults.crash_down_rows(spec, src)
                 | faults.crash_down_rows(spec, dst))
    deg_down_pair = (faults.crash_down_rows(spec, deg_src)
                     | faults.crash_down_rows(spec, deg_dst))
    arrs = faults.WMNemesisArrays(
        exists=jnp.asarray(exists),
        same=jnp.asarray(_same_groups(g, src, dst, n)),
        down_pair=jnp.asarray(down_pair),
        src=jnp.asarray(np.clip(src, 0, n - 1).astype(np.uint32)),
        dst=jnp.asarray(np.clip(dst, 0, n - 1).astype(np.uint32)),
        deg_exists=jnp.asarray(deg_src >= 0),
        deg_same=jnp.asarray(_same_groups(g, deg_src, deg_dst, n)),
        deg_down_pair=jnp.asarray(deg_down_pair),
        deg_src=jnp.asarray(np.clip(deg_src, 0, n - 1)
                            .astype(np.uint32)),
        deg_dst=jnp.asarray(np.clip(deg_dst, 0, n - 1)
                            .astype(np.uint32)),
        down_cols=jnp.asarray(faults.crash_down_rows(spec, idx)))
    if dir_delays is not None:
        dd = tuple(int(x) for x in dir_delays)
        if len(dd) != src.shape[0]:
            raise ValueError(
                f"{topology} takes {src.shape[0]} direction delays, "
                f"got {len(dd)}")
        if any(d < 1 for d in dd):
            raise ValueError("direction delays are rounds >= 1")
        ring = max(dd)
    else:
        dd, ring = None, 1
    halo = has_sharded_exchange(topology, n, n_shards,
                                axis_name=axis_name, **kw)
    ex, spc, sex, sspc = _nem_closures(topology, n, n_shards,
                                       axis_name, halo, **kw)
    # the masked per-edge diff closures (one accounting definition per
    # topology, shared with make_faulted/make_delayed_faulted): the
    # loss-only srv ledger's sync term, over the deg-contract rows
    diffs = _masked_diffs(topology, n, n_shards,
                          axis_name=axis_name, halo=halo, **kw)
    df, sdf = diffs if diffs is not None else (None, None)
    return StructuredNemesis(arrs, dd, ring, ex, spc, sex, sspc,
                             df, sdf)
