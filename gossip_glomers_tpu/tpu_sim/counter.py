"""Vectorized grow-only counter (challenge 4) on TPU.

Semantics mirrored from the reference node (counter/add.go, main.go):

- ``add`` acks before durability: deltas buffer locally in ``pending``
  (the channel + kvUpdater accumulator, add.go:33-47).
- Flushing is read-then-CAS against ONE sequentially-consistent KV key
  (updateKV, add.go:67-95); contention means losers retry with a
  refreshed read.
- ``read`` serves each node's cached view of the KV, refreshed by a
  periodic poll (add.go:29-31, main.go:50-62) — deliberately stale-able.

Two flush modes:

- **cas** (parity-flavored): one CAS winner per round — a seeded
  per-round pseudo-random pick among the fresh-read contenders (whose
  cached value matches the KV); everyone else observes the new value
  next round (the reference's failed-CAS → re-read → retry loop, one
  linearization step per round).  Drains one contender per round,
  reproducing the contention behavior of N nodes CAS-ing one key; the
  randomized pick mirrors the reference's jittered retry contention
  (add.go:56-58) instead of a systematic lowest-index bias, while the
  4-messages-per-contender-per-wave ledger is winner-agnostic (pinned
  by test_counter_ledger_matches_harness_contention).
- **allreduce** (scaled regime): every reachable node's pending sum is
  applied in one ``psum`` — the g-counter as a collective, for the
  1k-node+ partitioned benchmark (BASELINE.json config 3).

The KV service is reachability-gated: node i can flush/poll only while
it can reach the KV (partition windows mask it out, survey §5 fault
model).  State is a struct-of-arrays over the node axis, shardable with
shard_map exactly like the broadcast sim.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import faults, kvstore, provenance, telemetry, traffic
from .engine import (Collectives, DcnRound, HOSTS_AXIS, collectives,
                     dcn_carry_init, dcn_carry_specs,
                     donate_argnums_for, fori_rounds, jit_program,
                     node_axes, node_shards, resolve_block,
                     resolve_dcn_mode, scan_blocks)


class KVReach(NamedTuple):
    """Which nodes can reach the KV service per round: window w is
    active for rounds [starts[w], ends[w]); while active, nodes with
    ``blocked[w, i]`` cannot flush or poll."""

    starts: jnp.ndarray    # (P,) int32
    ends: jnp.ndarray      # (P,) int32
    blocked: jnp.ndarray   # (P, N) bool

    @staticmethod
    def none(n_nodes: int) -> "KVReach":
        return KVReach(jnp.zeros((0,), jnp.int32),
                       jnp.zeros((0,), jnp.int32),
                       jnp.zeros((0, n_nodes), bool))


class CounterState(NamedTuple):
    pending: jnp.ndarray   # (N,) int32 — acked, unflushed deltas
    cached: jnp.ndarray    # (N,) int32 — each node's last-read KV value
    kv: jnp.ndarray        # () int32 — the seq-kv key's value
    t: jnp.ndarray         # () int32
    msgs: jnp.ndarray      # () uint32 — KV request/response messages
    # kv_backend="device" (PR 14): the authoritative sharded key rows
    # (tpu_sim/kvstore.py) — ``kv`` above becomes the derived one-psum
    # view of them.  None (an empty pytree subtree) on the host
    # backend, so every existing driver is untouched.
    rows: "kvstore.KVRows | None" = None


def _reach(t: jnp.ndarray, row_ids: jnp.ndarray,
           sched: KVReach) -> jnp.ndarray:
    """(rows,) bool — who can reach the KV this round."""
    n_windows = sched.starts.shape[0]
    ok = jnp.ones(row_ids.shape, bool)
    if n_windows == 0:
        return ok

    def body(w, ok):
        active = (sched.starts[w] <= t) & (t < sched.ends[w])
        return ok & ~(active & sched.blocked[w][row_ids])

    return lax.fori_loop(0, n_windows, body, ok)


class CounterSim:
    """Round-synchronous g-counter simulator.

    Drive with :meth:`add` (host-side op injection, the ``add`` handler)
    and :meth:`step`; read with :meth:`reads` (each node's cached value,
    NOT the KV — reference read semantics, add.go:29-31).
    """

    def __init__(self, n_nodes: int, *, mode: str = "cas",
                 poll_every: int = 4,
                 kv_sched: KVReach | None = None,
                 mesh: Mesh | None = None, seed: int = 0,
                 winner_key: str = "auto",
                 fault_plan: "faults.FaultPlan | None" = None,
                 union_block: "int | str | None" = None,
                 kv_backend: str = "host",
                 kv_amnesia: bool = False,
                 stale_prob: float = 0.0,
                 stale_until: int = 0,
                 stale_seed: int | None = None,
                 dcn_mode=None) -> None:
        """``fault_plan`` (tpu_sim/faults.py): the crash/loss nemesis.
        A down node cannot flush, poll, or win the CAS; on restart its
        AMNESIA row loses ``pending`` (acked-but-unflushed deltas die
        with the process — exactly the reference's ack-before-
        durability risk) and ``cached`` (recovered from the KV at the
        next reachable poll/flush: the repair loop).  The plan's loss
        stream models transient per-round KV unreachability (a dropped
        exchange retried next round); duplicate delivery has no effect
        on a read/CAS protocol (the KV correlates by msg id) and is
        ignored here.

        ``union_block``: destination-slab size of the faulted
        ALLREDUCE's per-node fault-gate evaluation (liveness + the KV
        loss coin), run as an ``engine.scan_blocks`` sweep — the same
        streaming-coin driver the kafka union rides (ISSUE 5).  The
        counter's masks are O(N), so this is a driver-uniformity knob
        rather than a memory cliff; None defers to ``GG_UNION_BLOCK``
        (auto = materialized at every practical N), and parity across
        block sizes is pinned by tests/test_nemesis.py.

        ``kv_backend`` (PR 14): ``"host"`` models the seq-kv key as the
        replicated ``kv`` scalar (the Maelstrom service node, host
        ``KVService`` twin); ``"device"`` hosts the key in the sharded
        :class:`~.kvstore.KVRows` slab — ``kv`` each round is DERIVED
        from the rows in one psum view, and the round's winning CAS is
        a masked compare-update against them, so the serving path is
        device-resident end to end.  Bit-exact vs the host backend in
        ``(pending, cached, kv, t, msgs)`` (tests/test_kvstore.py).
        ``kv_amnesia=True`` additionally wipes a restarting owner's
        rows (the durable-service default False is the KVService pin).
        ``stale_prob``/``stale_until``/``stale_seed``: seq-kv stale
        reads as seeded :func:`~.kvstore.stale_coin` coins (device
        backend, cas mode): a behind, non-winning reader's refresh may
        re-serve its last-observed value for rounds < ``stale_until``
        — the same coins the harness KVService draws via
        ``stale_coin_fn`` (the wire-count calibration satellite).
        Dup streams are REJECTED loudly on the device backend
        (:func:`~.kvstore.reject_dup_stream`, ROADMAP item 6).

        ``dcn_mode`` (PR 20): the DCN latency-hiding engine mode —
        None defers to the ``GG_DCN_PIPELINE``/``GG_DCN_STALE_K`` env
        knobs, else a :class:`~.engine.DcnMode` or canonical mode
        string.  ``pipelined`` is bit-exact on every driver; a
        ``stale_k`` mode is certified ONLY for the allreduce host-KV
        data plane (the entire exchange is ``reduce_sum``) on a
        hierarchical mesh — the cas winner fold, device-KV reads, and
        the observed/traffic calibration paths refuse loudly."""
        if mode not in ("cas", "allreduce"):
            raise ValueError(f"unknown mode {mode!r}")
        self._dcn = resolve_dcn_mode(dcn_mode)
        if self._dcn.stale_k:
            if mode != "allreduce":
                raise ValueError(
                    f"dcn_mode {self._dcn.label()!r} needs "
                    "mode='allreduce': the cas winner's reduce_min "
                    "fold has no certified staleness semantics")
            if kv_backend != "host":
                raise ValueError(
                    f"dcn_mode {self._dcn.label()!r} needs "
                    "kv_backend='host': device-KV reads have no "
                    "certified staleness semantics")
            if mesh is None or HOSTS_AXIS not in mesh.axis_names:
                raise ValueError(
                    f"dcn_mode {self._dcn.label()!r} needs a "
                    "hierarchical (hosts x nodes) mesh: a flat mesh "
                    "has no DCN level to lag")
        if winner_key not in ("auto", "packed", "wide"):
            raise ValueError(f"unknown winner_key {winner_key!r}")
        if kv_backend not in ("host", "device"):
            raise ValueError(f"unknown kv_backend {kv_backend!r}")
        if kv_backend != "device" and (kv_amnesia or stale_prob):
            raise ValueError(
                "kv_amnesia/stale_prob need kv_backend='device' "
                "(host-backend staleness lives in harness KVService)")
        if stale_prob and mode != "cas":
            raise ValueError("stale_prob models the cas read-retry "
                             "loop; allreduce has no read path")
        if kv_backend == "device":
            kvstore.reject_dup_stream(fault_plan, "CounterSim")
        self.n_nodes = n_nodes
        self.mode = mode
        self.poll_every = poll_every
        self.mesh = mesh
        self.seed = seed
        self.kv_backend = kv_backend
        self.kv_amnesia = bool(kv_amnesia)
        self._device_kv = kv_backend == "device"
        if self._device_kv:
            # ONE seq-kv key, routed + sharded by the store's
            # stateless hash (the 1-key special case of the layout)
            self._kv_layout = kvstore.make_layout(1, n_nodes,
                                                  seed=seed)
            self._key_at = jnp.asarray(self._kv_layout.key_at)
        self._stale_num = (int(kvstore.stale_num_of(stale_prob))
                           if stale_prob else 0)
        self._stale_until = int(stale_until)
        self._stale_seed = seed if stale_seed is None else stale_seed
        # cas-winner key layouts:
        # - "packed" (n < 2^24): per-round hashed priority in the high
        #   bits, row id in the low bits (tie-break + winner recovery),
        #   packed into one int32 for a single pmin collective.
        # - "wide" (any n < 2^31): the packed key would truncate the
        #   priority below useful entropy, so the argmin splits into TWO
        #   collectives — pmin the full 32-bit hashed priority, then
        #   pmin the row id among rows achieving it (lowest-row
        #   tie-break, matching the packed layout's semantics).  This
        #   lifts the 2^24-node cap to the broadcast path's demonstrated
        #   16.8M+ reach at the cost of one extra pmin per round.
        #   Both pmins ride the mesh 'nodes' axis directly, so the wide
        #   winner IS the sharded driver at scale: the compiled sharded
        #   step carries psum/pmin collectives only — no all-gather
        #   (pinned by tests/test_engine.py::
        #   test_counter_wide_sharded_step_hlo_has_no_all_gather, the
        #   counter twin of the kafka sharded-presence HLO gate).
        # "auto" keeps the measured-and-pinned packed behavior wherever
        # it fits and switches to wide only when it must.
        self._row_bits = max(1, (n_nodes - 1).bit_length())
        # strict: at n == 2^31 the wide row sentinel (int32 max) would
        # collide with the last row id, and int32(n) itself overflows
        if mode == "cas" and n_nodes >= 2**31:
            raise ValueError("cas winner keys support n_nodes < 2^31")
        if winner_key == "packed" and self._row_bits >= 24:
            raise ValueError(
                "packed cas winner keys need n_nodes <= 2^23 (24+ row "
                "bits leave too few priority bits for a randomized "
                "winner); use winner_key='wide' or 'auto'")
        self._wide = (winner_key == "wide"
                      or (winner_key == "auto" and self._row_bits >= 24))
        self.kv_sched = (kv_sched if kv_sched is not None
                         else KVReach.none(n_nodes))
        self.fault_plan = fault_plan
        if fault_plan is not None \
                and fault_plan.down.shape[1] != n_nodes:
            raise ValueError(
                f"FaultPlan is for {fault_plan.down.shape[1]} nodes, "
                f"sim has {n_nodes}")
        n_sh = node_shards(mesh)
        # two uint32 coin/mask evaluations per node row
        self._ub = resolve_block(max(1, n_nodes // n_sh), union_block,
                                 per_row_bytes=8)
        self._node_spec = (P(node_axes(mesh)) if mesh is not None
                           else None)
        # raw jitted run-program handles by donate flag — the contract
        # auditor (tpu_sim/audit.py) lowers these directly
        self._run_progs: dict = {}
        # open-loop traffic drivers, keyed by (TrafficSpec, donate)
        self._traffic_progs: dict = {}
        # telemetry-on observed drivers, keyed by (TelemetrySpec,
        # donate) — PR 8
        self._obs_progs: dict = {}
        # DCN staleness carry (PR 20): the (age, outbox-slots) pair
        # the stale drivers thread as explicit donated I/O — layout
        # discovered once by a probing eval_shape of the round, held
        # on the instance between program calls, reset by init_state
        self._dcn_shapes = None
        self._dcn_carry = None
        if self._dcn.stale_k:
            self._dcn_shapes = self._probe_dcn()
            self._dcn_carry = dcn_carry_init(self._dcn_shapes, mesh)
        self._step = self._build_step()
        self._run_n = self._build_run_n(donate=False)
        # the donated twin: same traced rounds, state buffers consumed
        # and reused in place (engine.py module docstring)
        self._run_n_donated = self._build_run_n(donate=True)

    def _probe_dcn(self) -> list:
        """The staleness carry layout: eval_shape a PROBING twin of
        the round (collectives record each outbox slot's per-shard
        shape instead of consuming a carry)."""
        mesh = self.mesh
        probe = DcnRound.probing(self._dcn)
        sched_spec = KVReach(P(), P(), P(None, None))
        fp_specs, fp_args = self._fp_extra()

        def step(state: CounterState, sched: KVReach,
                 *fp) -> CounterState:
            coll = collectives(state.pending.shape[0], mesh,
                               dcn=probe)
            return self._round(state, coll, sched,
                               fp[0] if fp else None)

        prog = jit_program(step, mesh=mesh,
                           in_specs=(self._state_spec(), sched_spec)
                           + fp_specs,
                           out_specs=self._state_spec())
        jax.eval_shape(prog, self.init_state(), self.kv_sched,
                       *fp_args)
        return list(probe.shapes)

    def init_state(self) -> CounterState:
        # pending and cached start equal but must be DISTINCT buffers:
        # the donated run_fused driver donates the whole pytree, and
        # XLA rejects donating one buffer twice
        def z():
            arr = jnp.zeros((self.n_nodes,), jnp.int32)
            if self.mesh is not None:
                arr = shard_put(
                    arr, NamedSharding(self.mesh, self._node_spec))
            return arr

        rows = (kvstore.init_rows(self._kv_layout, self.mesh)
                if self._device_kv else None)
        if getattr(self, "_dcn_shapes", None) is not None:
            # a fresh run starts with empty outboxes and age 0 (the
            # first round refreshes) — the staleness carry is run
            # state, not program state
            self._dcn_carry = dcn_carry_init(self._dcn_shapes,
                                             self.mesh)
        return CounterState(pending=z(), cached=z(), kv=jnp.int32(0),
                            t=jnp.int32(0), msgs=jnp.uint32(0),
                            rows=rows)

    # -- op injection ------------------------------------------------------

    def add(self, state: CounterState,
            deltas: np.ndarray) -> CounterState:
        """Buffer acked deltas: ``deltas`` is (N,) per-node int32 (the
        batched form of the ``add`` handler — ack precedes durability,
        add.go:33-41)."""
        d = jnp.asarray(deltas, jnp.int32)
        if self.mesh is not None:
            d = shard_put(d, NamedSharding(self.mesh, self._node_spec))
        return state._replace(pending=state.pending + d)

    # -- round -------------------------------------------------------------

    def _round(self, state: CounterState, coll: Collectives,
               sched: KVReach, plan=None) -> CounterState:
        """One round: flush attempts + the periodic cache poll.

        ``coll`` is the engine's collective surface (identity
        single-device; psum/pmin over 'nodes' under shard_map).

        ``plan`` (the traced FaultPlan operand): amnesia rows first —
        a node restarting this round loses ``pending`` and ``cached``
        — then down/KV-lossy nodes are masked out of reach, so they
        neither flush nor poll; their committed sums sit safely in the
        KV until the repair loop re-reads them.
        """
        row_ids = coll.row_ids

        def allsum(x):
            return coll.reduce_sum(jnp.sum(x))

        reach = _reach(state.t, row_ids, self.kv_sched)
        if plan is not None:
            wipe = faults.amnesia(plan, state.t, row_ids)
            state = state._replace(
                pending=jnp.where(wipe, 0, state.pending),
                cached=jnp.where(wipe, 0, state.cached))
            if self._device_kv and self.kv_amnesia:
                # the KV rows are node state: a restarting owner loses
                # its registers through the SAME amnesia coin (PR 14)
                state = state._replace(rows=kvstore.rows_wipe(
                    state.rows, plan, state.t, row_ids))
            if self._ub is not None and self.mode == "allreduce":
                # streaming fault gate (ISSUE 5): evaluate the per-node
                # liveness + KV-loss coins slab by slab on the engine's
                # scan_blocks driver — the counter twin of the kafka
                # blocked union (stateless coins ⇒ bit-identical to the
                # materialized gate at any block size)
                rows, ub = row_ids.shape[0], self._ub
                t = state.t

                def gate_blk(carry, lo):
                    ids = lax.dynamic_slice_in_dim(row_ids, lo, ub)
                    g = (faults.node_up(plan, t, ids)
                         & ~faults.kv_drop(plan, t, ids))
                    return lax.dynamic_update_slice_in_dim(
                        carry, g, lo, axis=0)

                reach = reach & scan_blocks(
                    gate_blk, jnp.zeros((rows,), bool), rows, ub)
            else:
                reach = (reach
                         & faults.node_up(plan, state.t, row_ids)
                         & ~faults.kv_drop(plan, state.t, row_ids))
        want = (state.pending > 0) & reach

        if self._device_kv:
            # the authoritative value is READ from the sharded rows
            # (one psum view) — the carried ``kv`` scalar is only the
            # previous round's view and must agree except after a
            # row-wipe (kv_amnesia), where the store is the truth
            ka = self._key_at[row_ids]
            kv0 = kvstore.rows_view(state.rows, ka, 1,
                                    coll.reduce_sum)[0, 0]
        else:
            kv0 = state.kv

        if self.mode == "allreduce":
            flushed = jnp.where(want, state.pending, 0)
            total = allsum(flushed)
            kv = kv0 + total
            pending = state.pending - flushed
            # each flush is a read + CAS round-trip: 4 messages
            attempts = allsum(want.astype(jnp.uint32)) * jnp.uint32(4)
            winner_mask = want
        else:
            # cas mode: fresh-read holders CAS first; ONE wins (the KV
            # linearizes one CAS per round; everyone else fails,
            # re-reads, retries — add.go:78-88's retry loop).  The
            # winner is a seeded per-round hash-min over the
            # contenders, mirroring the reference's jittered retry
            # contention (add.go:56-58) instead of a systematic
            # lowest-index bias: key = hashed priority (high bits) |
            # row id (low bits, tie-break + winner recovery).
            fresh = want & (state.cached == kv0)
            x = (row_ids.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
                 + (state.t.astype(jnp.uint32)
                    + jnp.uint32(self.seed)) * jnp.uint32(0x85EBCA6B))
            x = x ^ (x >> 16)
            x = x * jnp.uint32(0x7FEB352D)
            x = x ^ (x >> 15)
            if self._wide:
                # wide layout: argmin as two pmins — full-hash priority
                # first (capped below the all-ones no-candidate
                # sentinel), then lowest row id among its achievers
                prix = jnp.minimum(x, jnp.uint32(0xFFFFFFFE))
                cand_pri = jnp.where(fresh, prix,
                                     jnp.uint32(0xFFFFFFFF))
                best_pri = coll.reduce_min(jnp.min(cand_pri))
                has_winner = best_pri < jnp.uint32(0xFFFFFFFF)
                cand_row = jnp.where(fresh & (prix == best_pri),
                                     row_ids, jnp.int32(2**31 - 1))
                best_row = coll.reduce_min(jnp.min(cand_row))
                winner = jnp.where(has_winner, best_row,
                                   jnp.int32(self.n_nodes))
            else:
                pri_bits = 31 - self._row_bits
                # cap the priority below all-ones so a real key can
                # never collide with the no-candidate sentinel
                pri = jnp.minimum(
                    (x >> jnp.uint32(32 - pri_bits)).astype(jnp.int32),
                    jnp.int32(2**pri_bits - 2))
                key = (pri << self._row_bits) | row_ids
                candidates = jnp.where(fresh, key, jnp.int32(2**31 - 1))
                best = coll.reduce_min(jnp.min(candidates))
                has_winner = best < jnp.int32(2**31 - 1)
                winner = jnp.where(
                    has_winner,
                    best & jnp.int32((1 << self._row_bits) - 1),
                    jnp.int32(self.n_nodes))
            winner_delta = allsum(
                jnp.where(row_ids == winner, state.pending, 0))
            kv = kv0 + jnp.where(has_winner, winner_delta, 0)
            winner_mask = (row_ids == winner)
            pending = jnp.where(winner_mask, 0, state.pending)
            # every contender pays a read + CAS exchange (4 msgs);
            # losers' CAS fails and they re-read next round.
            attempts = allsum(want.astype(jnp.uint32)) * jnp.uint32(4)

        # cache refresh: every CAS attempt starts with a fresh read
        # (updateKV -> readKV, add.go:67-71), so all contenders see the
        # new value for their next attempt; idle nodes poll every
        # poll_every rounds (reference 700 ms poll, main.go:50-62).
        # poll_every=0 disables the poll loop entirely (for scenarios
        # round-aligned against a harness run with the poll timer
        # pushed out of the measurement window).
        if self.poll_every > 0:
            polled = reach & ((state.t % jnp.int32(self.poll_every)) == 0)
        else:
            polled = jnp.zeros_like(reach)
        refreshed = jnp.broadcast_to(kv, state.cached.shape)
        if self._stale_num:
            # seq-kv staleness (PR 14): a behind, non-winning reader's
            # refresh is served its LAST-OBSERVED value when the
            # seeded coin fires (read-your-writes + per-reader
            # monotonicity hold; the coin stream is the one the host
            # KVService draws via stale_coin_fn, so both backends
            # retry in lockstep — the wire-count calibration)
            h = kvstore.stale_coin(self._stale_seed, state.t, row_ids)
            stale = ((h < jnp.uint32(self._stale_num))
                     & (state.t < jnp.int32(self._stale_until))
                     & ~winner_mask & (state.cached != kv))
            refreshed = jnp.where(stale, state.cached, refreshed)
        cached = jnp.where(want | winner_mask | polled, refreshed,
                           state.cached)
        attempts = attempts + allsum(
            (polled & ~winner_mask).astype(jnp.uint32)) * jnp.uint32(2)
        rows = state.rows
        if self._device_kv:
            # commit the round's one linearization step into the
            # sharded rows: a masked CAS from the pre-round view —
            # guaranteed to hit (frm IS the authoritative value), so
            # the carried scalar and the store never diverge
            changed = jnp.reshape(kv != kv0, (1,))
            rows = kvstore.cas_apply(rows, ka, changed,
                                     jnp.reshape(kv0, (1,)),
                                     jnp.reshape(kv, (1,)))
        return CounterState(pending=pending, cached=cached, kv=kv,
                            t=state.t + 1, msgs=state.msgs + attempts,
                            rows=rows)

    def _state_spec(self):
        node_spec = self._node_spec
        rows = (kvstore.rows_spec(self.mesh) if self._device_kv
                else None)
        return CounterState(node_spec, node_spec, P(), P(), P(),
                            rows=rows)

    def _fp_extra(self):
        """(in_specs, args) for the FaultPlan operand — replicated,
        threaded as an explicit traced argument like the KV schedule."""
        if self.fault_plan is None:
            return (), ()
        return ((faults.plan_specs(),), (self.fault_plan,))

    def _build_step(self):
        mesh = self.mesh

        if mesh is None:
            fp_args0 = self._fp_extra()[1]

            def step(state: CounterState, *fp) -> CounterState:
                return self._round(
                    state, collectives(self.n_nodes), self.kv_sched,
                    fp[0] if fp else None)
            prog0 = jit_program(step)
            return lambda state: prog0(state, *fp_args0)

        sched_spec = KVReach(P(), P(), P(None, None))
        fp_specs, fp_args = self._fp_extra()

        if self._dcn.stale_k:
            # staleness carry as EXPLICIT donated I/O on the step
            # program: a stepwise run sees the same refresh cadence as
            # the fused driver (the carried age decides)
            cspecs = dcn_carry_specs(self._dcn_shapes, mesh)

            def step_st(state: CounterState, dcnc, sched: KVReach,
                        *fp):
                age, slots = dcnc
                ctx = DcnRound(self._dcn, age=age, carry=slots)
                coll = collectives(state.pending.shape[0], mesh,
                                   dcn=ctx)
                out = self._round(state, coll, sched,
                                  fp[0] if fp else None)
                return out, (age + 1, ctx.carry_out())

            prog_st = jit_program(
                step_st, mesh=mesh,
                in_specs=(self._state_spec(), cspecs, sched_spec)
                + fp_specs,
                out_specs=(self._state_spec(), cspecs),
                donate_argnums=(1,))

            def run_step(state):
                out, self._dcn_carry = prog_st(
                    state, self._dcn_carry, self.kv_sched, *fp_args)
                return out
            return run_step

        def step(state: CounterState, sched: KVReach,
                 *fp) -> CounterState:
            coll = collectives(state.pending.shape[0], mesh,
                               dcn=self._dcn)
            return self._round(state, coll, sched,
                               fp[0] if fp else None)

        prog = jit_program(step, mesh=mesh,
                           in_specs=(self._state_spec(), sched_spec)
                           + fp_specs,
                           out_specs=self._state_spec())
        return lambda state: prog(state, self.kv_sched, *fp_args)

    def _build_run_n(self, donate: bool):
        """Multi-round runner as ONE device program (dynamic fori_loop
        bound) — one dispatch per run() call instead of per round.  Also
        sidesteps a CPU-backend hazard: piling up many un-synced
        multi-device dispatches can interleave their collectives across
        programs and deadlock the in-process rendezvous.

        ``donate``: consume the input state's buffers (the
        :meth:`run_fused` driver) so the fused loop holds ONE live state
        copy instead of input + output."""
        mesh = self.mesh
        dn = donate_argnums_for(donate, 0)
        fp_specs, fp_args = self._fp_extra()

        if mesh is None:
            def run_n(state: CounterState, n, *fp) -> CounterState:
                coll = collectives(self.n_nodes)
                if fp:
                    # the engine's per-round fault operand: the plan
                    # rides as a driver argument — never donated,
                    # never baked in as a constant
                    return fori_rounds(
                        lambda s, p: self._round(s, coll,
                                                 self.kv_sched, p),
                        state, n, operand=fp[0])
                return fori_rounds(
                    lambda s: self._round(s, coll, self.kv_sched),
                    state, n)
            prog0 = jit_program(run_n, donate_argnums=dn)
            self._run_progs[donate] = (
                prog0, lambda state, n: (state, n) + fp_args)
            return lambda state, n: prog0(state, n, *fp_args)

        sched_spec = KVReach(P(), P(), P(None, None))

        if self._dcn.stale_k:
            cspecs = dcn_carry_specs(self._dcn_shapes, mesh)
            dn_st = (0, 1) if donate else ()

            def run_n_st(state: CounterState, dcnc,
                         sched: KVReach, n, *fp):
                def rnd(carry, p=None):
                    s, a, sl = carry
                    ctx = DcnRound(self._dcn, age=a, carry=sl)
                    coll = collectives(s.pending.shape[0], mesh,
                                       dcn=ctx)
                    s2 = self._round(s, coll, sched, p)
                    return (s2, a + 1, ctx.carry_out())

                age, slots = dcnc
                if fp:
                    s, a, sl = fori_rounds(rnd, (state, age, slots),
                                           n, operand=fp[0])
                else:
                    s, a, sl = fori_rounds(lambda c: rnd(c),
                                           (state, age, slots), n)
                return s, (a, sl)

            prog_st = jit_program(
                run_n_st, mesh=mesh,
                in_specs=(self._state_spec(), cspecs, sched_spec,
                          P()) + fp_specs,
                out_specs=(self._state_spec(), cspecs),
                donate_argnums=dn_st)
            self._run_progs[donate] = (
                prog_st,
                lambda state, n: (state, self._dcn_carry,
                                  self.kv_sched, n) + fp_args)

            def run_st(state, n):
                out, self._dcn_carry = prog_st(
                    state, self._dcn_carry, self.kv_sched, n,
                    *fp_args)
                return out
            return run_st

        def run_n(state: CounterState, sched: KVReach,
                  n, *fp) -> CounterState:
            coll = collectives(state.pending.shape[0], mesh,
                               dcn=self._dcn)
            if fp:
                return fori_rounds(
                    lambda s, p: self._round(s, coll, sched, p),
                    state, n, operand=fp[0])
            return fori_rounds(lambda s: self._round(s, coll, sched),
                               state, n)

        prog = jit_program(
            run_n, mesh=mesh,
            in_specs=(self._state_spec(), sched_spec, P()) + fp_specs,
            out_specs=self._state_spec(), donate_argnums=dn)
        self._run_progs[donate] = (
            prog,
            lambda state, n: (state, self.kv_sched, n) + fp_args)
        return lambda state, n: prog(state, self.kv_sched, n, *fp_args)

    def step(self, state: CounterState) -> CounterState:
        return self._step(state)

    def run(self, state: CounterState, n_rounds: int) -> CounterState:
        return self._run_n(state, jnp.int32(n_rounds))

    def run_fused(self, state: CounterState,
                  n_rounds: int) -> CounterState:
        """Single-dispatch donation-first driver: bit-identical to
        :meth:`run` (and to ``n_rounds`` chained :meth:`step` calls) but
        the input state's buffers are DONATED — updated in place, so the
        whole fused loop keeps one live state copy.  The passed-in state
        must not be used again afterwards."""
        return self._run_n_donated(state, jnp.int32(n_rounds))

    # -- flight-recorder telemetry (PR 8) ----------------------------------

    def _tel_series(self, s0: CounterState, s1: CounterState,
                    coll: Collectives, sched: KVReach, plan) -> tuple:
        """One round's telemetry row (telemetry.SIM_SERIES['counter']
        order), traced: recomputes the round's reach/want gates from
        the SAME pure evaluators the round used (stateless coins ⇒
        bit-identical), so flush attempts/acks/conflicts are exact
        without instrumenting the round body — telemetry reads state,
        never feeds back into it.  Every partial is evaluated over
        the LOCAL rows and the whole row globalizes in ONE packed
        ``reduce_sum`` (a per-scalar psum apiece would multiply the
        round's collective count — the overhead budget of
        BENCH_PR8)."""
        row_ids = coll.row_ids
        reach = _reach(s0.t, row_ids, sched)
        pend0 = s0.pending
        live_loc = jnp.ones(row_ids.shape, bool)
        if plan is not None:
            live_loc = faults.node_up(plan, s0.t, row_ids)
            wipe = faults.amnesia(plan, s0.t, row_ids)
            pend0 = jnp.where(wipe, 0, pend0)
            reach = (reach & live_loc
                     & ~faults.kv_drop(plan, s0.t, row_ids))
        want = (pend0 > 0) & reach
        acks = want & (s1.pending == 0)

        def cnt(x):
            return jnp.sum(x.astype(jnp.uint32), dtype=jnp.uint32)

        g = coll.reduce_sum(jnp.stack(
            [cnt(live_loc), cnt(s1.pending), cnt(want), cnt(acks)]))
        return (g[0], g[1], g[2], g[3], g[2] - g[3],
                s1.kv.astype(jnp.uint32),
                s1.msgs)

    def _prov_record(self, s0: CounterState, s2: CounterState, prov,
                     coll: Collectives, sched: KVReach, plan):
        """One round's provenance stamps (PR 9), traced: a PURE reader
        like :meth:`_tel_series` — the flush gates are recomputed from
        the same stateless evaluators the round used, so the record
        can never drift from the round.  Per node, first-occurrence
        (:func:`provenance.stamp`):

        - ``flush_round``: the node's positive pending first drained
          to zero through a REACHABLE flush (an amnesia wipe is not a
          flush: the wiping node is down, so ``reach`` is False);
        - ``flush_kv``: the KV value that flush landed in (``s2.kv``);
        - ``visible_round``: every node's cache has caught up to the
          node's flush value (``min(cached) >= flush_kv`` — one extra
          pmin, no gather)."""
        row_ids = coll.row_ids
        reach = _reach(s0.t, row_ids, sched)
        pend0 = s0.pending
        if plan is not None:
            wipe = faults.amnesia(plan, s0.t, row_ids)
            pend0 = jnp.where(wipe, 0, pend0)
            reach = (reach & faults.node_up(plan, s0.t, row_ids)
                     & ~faults.kv_drop(plan, s0.t, row_ids))
        flushed = (pend0 > 0) & reach & (s2.pending == 0)
        newf = flushed & (prov.flush_round < 0)
        fr = jnp.where(newf, s2.t, prov.flush_round)
        fk = jnp.where(newf, s2.kv, prov.flush_kv)
        min_cached = coll.reduce_min(jnp.min(s2.cached))
        vr = provenance.stamp(
            prov.visible_round,
            (fr >= 0) & (min_cached >= fk), s2.t)
        return provenance.CounterProv(flush_round=fr, flush_kv=fk,
                                      visible_round=vr)

    def _build_run_obs(self, tspec: "telemetry.TelemetrySpec | None",
                       pspec, donate: bool):
        """The telemetry-/provenance-on fused driver (PR 8 / PR 9):
        the round unchanged, a ``(state, tel?, prov?)`` carry donated
        together."""
        tl = tspec is not None
        pv = pspec is not None
        if not (tl or pv):
            raise ValueError(
                "observed drivers need a TelemetrySpec and/or a "
                "ProvenanceSpec")
        if tl and (tspec.workload != "counter" or tspec.traffic):
            raise ValueError(
                "run_observed needs a TelemetrySpec(workload="
                "'counter', traffic=False); open-loop runs record "
                "through run_traffic(tel=...)")
        if self._dcn.stale_k:
            raise ValueError(
                f"dcn_mode {self._dcn.label()!r}: the observed "
                "drivers do not thread the DCN staleness carry — "
                "telemetry/provenance calibration under staleness is "
                "undecided; run sync or pipelined")
        mesh = self.mesh
        n_carry = 1 + int(tl) + int(pv)
        dn = donate_argnums_for(donate, *range(n_carry))
        fp_specs, fp_args = self._fp_extra()
        tel_mask = tspec.static_mask if tl else None
        ip = 1 + int(tl)

        def carry_of(state, tel, prov):
            return ((state,) + ((tel,) if tl else ())
                    + ((prov,) if pv else ()))

        def one(carry, sched, coll, plan):
            s = carry[0]
            s2 = self._round(s, coll, sched, plan)
            out = (s2,)
            if tl:
                out += (telemetry.record(
                    carry[1], s.t,
                    self._tel_series(s, s2, coll, sched, plan),
                    tel_mask),)
            if pv:
                out += (self._prov_record(s, s2, carry[ip], coll,
                                          sched, plan),)
            return out

        if mesh is None:
            def run_n(*a):
                a = list(a)
                state = a.pop(0)
                tel = a.pop(0) if tl else None
                prov0 = a.pop(0) if pv else None
                n = a.pop(0)
                fp = tuple(a)
                coll = collectives(self.n_nodes)
                plan = fp[0] if fp else None
                return fori_rounds(
                    lambda c: one(c, self.kv_sched, coll, plan),
                    carry_of(state, tel, prov0), n)

            prog = jit_program(run_n, donate_argnums=dn)

            def args_fn(state, tel, prov, n):
                return carry_of(state, tel, prov) + (n,) + fp_args
        else:
            sched_spec = KVReach(P(), P(), P(None, None))
            tel_in = ((telemetry.state_specs(),) if tl else ())
            prov_in = ((provenance.counter_specs(node_axes(mesh)),) if pv else ())

            def run_n(*a):
                a = list(a)
                state = a.pop(0)
                tel = a.pop(0) if tl else None
                prov0 = a.pop(0) if pv else None
                sched, n = a.pop(0), a.pop(0)
                fp = tuple(a)
                coll = collectives(state.pending.shape[0], mesh,
                                   dcn=self._dcn)
                plan = fp[0] if fp else None
                return fori_rounds(lambda c: one(c, sched, coll, plan),
                                   carry_of(state, tel, prov0), n)

            prog = jit_program(
                run_n, mesh=mesh,
                in_specs=(self._state_spec(),) + tel_in + prov_in
                + (sched_spec, P()) + fp_specs,
                out_specs=(self._state_spec(),) + tel_in + prov_in,
                check_vma=False, donate_argnums=dn)

            def args_fn(state, tel, prov, n):
                return carry_of(state, tel, prov) \
                    + (self.kv_sched, n) + fp_args

        runner = lambda state, tel, prov, n: prog(
            *args_fn(state, tel, prov, n))
        return prog, args_fn, runner

    def telemetry_state(self, tspec) -> "telemetry.TelemetryState":
        return telemetry.init_state(tspec)

    def provenance_state(self, pspec) -> "provenance.CounterProv":
        prov = provenance.init_counter(self.n_nodes)
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, self._node_spec)
            prov = provenance.CounterProv(
                *(shard_put(a, sh) for a in prov))
        return prov

    def run_observed(self, state: CounterState, tel, tspec,
                     n_rounds: int, *, donate: bool = False,
                     prov=None, prov_spec=None):
        """Telemetry-/provenance-on :meth:`run_fused`: ``n_rounds``
        rounds as one device program with the per-round metrics ring
        and/or the per-node flush→kv→visibility stamps recorded next
        to the state — bit-exact to the plain drivers (the recorders
        only read state).  With ``donate`` every carry leaf is
        consumed.  Returns the carry in order: ``(state, tel?,
        prov?)``."""
        if (tel is None) != (tspec is None):
            raise ValueError(
                "pass tel and tel_spec together (build the ring with "
                "telemetry.init_state(spec))")
        provenance.prov_key(prov, prov_spec, "counter")
        key = (tspec, prov_spec, donate)
        if key not in self._obs_progs:
            self._obs_progs[key] = self._build_run_obs(
                tspec, prov_spec, donate)
        return self._obs_progs[key][2](state, tel, prov,
                                       jnp.int32(n_rounds))

    def audit_observed_program(self, tspec, *, donate: bool = True,
                               prov_spec=None):
        """(jitted, example_args) of the observed driver — the handle
        the contract auditor lowers (census + donation of the EXACT
        program :meth:`run_observed` executes)."""
        key = (tspec, prov_spec, donate)
        if key not in self._obs_progs:
            self._obs_progs[key] = self._build_run_obs(
                tspec, prov_spec, donate)
        prog, args_fn, _ = self._obs_progs[key]
        tel = (telemetry.init_state(tspec) if tspec is not None
               else None)
        prov = (self.provenance_state(prov_spec)
                if prov_spec is not None else None)
        return prog, args_fn(self.init_state(), tel, prov,
                             jnp.int32(8))

    # -- open-loop traffic (PR 7) -----------------------------------------

    def _traffic_round(self, state: CounterState, ts, tspec, tplan,
                       sched: KVReach, coll: Collectives, plan, ub,
                       tel=None, tel_mask=None):
        """One traffic-injected round (traced): classify this round's
        arrivals (home node down → deferred; per-node ``intake`` cap →
        deferred; op slots exhausted → deferred), fold the accepted
        adds into ``pending`` (each op adds delta 1 — ack before
        durability, add.go:33-41), run the ordinary round, then
        advance the per-op tracker:

        - a node whose whole pending drained this round (the cas
          winner / an allreduce flush) FLUSHES its clients' open ops —
          each records ``op_aux = kv_after`` (the KV value its delta
          is folded into).  An AMNESIA wipe is not a flush: the wipe
          round itself is excluded by the liveness gate, and the
          wiped ops are marked ``op_aux = -2`` (permanently lost —
          their deltas died with the process), so a LATER flush at
          the restarted node can never claim them: they stay in
          flight forever and surface as lost acked writes;
        - an op completes when every node's cached read has reached
          its flush value (``min(cached) >= op_aux`` — the per-op form
          of the counter convergence predicate "every cache equals the
          KV"), so completion stalls while any crashed cache is empty
          and recovers with the poll loop: the serving-curve cliff."""
        rows = state.pending.shape[0]
        bc = rows * tspec.n_clients // self.n_nodes
        p = coll.row_ids[0] // jnp.int32(rows)
        ids = p * jnp.int32(bc) + jnp.arange(bc, dtype=jnp.int32)
        arr = traffic.arrive(tplan, state.t, ids)
        node_loc = traffic.local_node_cols(tspec, bc)
        node_glob = coll.row_ids[0] + node_loc
        up_t = (faults.node_up(plan, state.t, coll.row_ids)
                if plan is not None else jnp.ones((rows,), bool))
        accept = (faults.node_up(plan, state.t, node_glob)
                  if plan is not None else jnp.ones(arr.shape, bool))
        if tspec.intake is not None:
            accept = accept & (
                traffic.intake_rank(arr, tspec.clients_per_node)
                < tspec.intake)
        ts, ok, _k = traffic.issue(ts, arr, accept, state.t,
                                   coll.reduce_sum)
        add = jnp.zeros((rows,), jnp.int32).at[node_loc].add(
            ok.astype(jnp.int32))
        state = state._replace(pending=state.pending + add)
        if plan is not None:
            # ops whose delta dies in this round's amnesia wipe are
            # LOST, permanently (op_aux = -2): without the mark, a
            # post-restart flush at the same node would claim them and
            # the certifier would miss a lost acked write.  New
            # arrivals cannot land at a wiping node (down ⇒ deferred).
            cl_wiped = faults.amnesia(plan, state.t,
                                      coll.row_ids)[node_loc]
            ts = ts._replace(op_aux=jnp.where(
                ((ts.issue_round >= 0) & (ts.op_aux == -1)
                 & (ts.done_round < 0) & cl_wiped[:, None]),
                jnp.int32(-2), ts.op_aux))
        pend0 = state.pending
        s2 = self._round(state, coll, sched, plan)
        flushed = (pend0 > 0) & (s2.pending == 0) & up_t
        cl_fl = flushed[node_loc]
        open_unflushed = ((ts.issue_round >= 0) & (ts.op_aux == -1)
                          & (ts.done_round < 0))
        aux = jnp.where(open_unflushed & cl_fl[:, None], s2.kv,
                        ts.op_aux)
        ts = ts._replace(op_aux=aux)
        min_cached = coll.reduce_min(jnp.min(s2.cached))

        def bit_fn(lo, block):
            a = lax.dynamic_slice_in_dim(aux, lo, block, axis=0)
            return (a >= 0) & (min_cached >= a)

        ts = traffic.done_scan(ts, bit_fn, s2.t, coll.reduce_sum, ub)
        if tel is None:
            return s2, ts
        # telemetry row (PR 8): s0 = the post-injection state (this
        # round's arrivals count as pending adds), tracker totals
        # appended — recorded AFTER the tracker advanced, so the ring
        # cross-checks the final ledgers exactly
        vals = (self._tel_series(state, s2, coll, sched, plan)
                + traffic.tel_series(ts, coll.reduce_sum))
        return s2, ts, telemetry.record(tel, state.t, vals, tel_mask)

    def _build_traffic(self, tspec: "traffic.TrafficSpec",
                       donate: bool, tel_spec=None):
        if tspec.n_nodes != self.n_nodes:
            raise ValueError(
                f"TrafficSpec is for {tspec.n_nodes} nodes, sim has "
                f"{self.n_nodes}")
        mesh = self.mesh
        if self._dcn.stale_k:
            raise ValueError(
                f"dcn_mode {self._dcn.label()!r}: the open-loop "
                "traffic driver does not thread the DCN staleness "
                "carry — per-op latency tracking under staleness is "
                "undecided; run sync or pipelined")
        n_sh = node_shards(mesh)
        if tspec.n_clients % n_sh != 0:
            raise ValueError(
                f"n_clients={tspec.n_clients} must shard evenly over "
                f"the {n_sh}-way node axis")
        ub = traffic.traffic_block(tspec.n_clients // n_sh)
        tl = tel_spec is not None
        mask = tel_spec.static_mask if tl else None
        dn = donate_argnums_for(donate, *((0, 1, 2) if tl else (0, 1)))
        fp_specs, fp_args = self._fp_extra()

        def body(c, op, sched, coll, plan):
            if tl:
                return self._traffic_round(
                    c[0], c[1], tspec, op, sched, coll, plan, ub,
                    tel=c[2], tel_mask=mask)
            return self._traffic_round(
                c[0], c[1], tspec, op, sched, coll, plan, ub)

        if mesh is None:
            def run(state, *rest):
                rest = list(rest)
                tel = rest.pop(0) if tl else None
                ts, n, tplan, sched = rest[0], rest[1], rest[2], rest[3]
                fp = rest[4:]
                coll = collectives(self.n_nodes)
                plan = fp[0] if fp else None
                carry = (state, ts, tel) if tl else (state, ts)
                return fori_rounds(
                    lambda c, op: body(c, op, sched, coll, plan),
                    carry, n, operand=tplan)

            prog = jit_program(run, donate_argnums=dn)
        else:
            sched_spec = KVReach(P(), P(), P(None, None))
            t_specs = traffic.state_specs(True, node_axes(mesh))

            def run(state, *rest):
                rest = list(rest)
                tel = rest.pop(0) if tl else None
                ts, n, tplan, sched = rest[0], rest[1], rest[2], rest[3]
                fp = rest[4:]
                coll = collectives(state.pending.shape[0], mesh,
                                   dcn=self._dcn)
                plan = fp[0] if fp else None
                carry = (state, ts, tel) if tl else (state, ts)
                return fori_rounds(
                    lambda c, op: body(c, op, sched, coll, plan),
                    carry, n, operand=tplan)

            tel_in = (telemetry.state_specs(),) if tl else ()
            prog = jit_program(
                run, mesh=mesh,
                in_specs=(self._state_spec(),) + tel_in
                + (t_specs, P(), traffic.plan_specs(), sched_spec)
                + fp_specs,
                out_specs=(self._state_spec(), t_specs) + tel_in,
                check_vma=False, donate_argnums=dn)

        def args_fn(state, ts, n, tplan, tel=None):
            pre = (state, tel) if tl else (state,)
            return pre + (ts, n, tplan, self.kv_sched) + fp_args

        runner = lambda state, ts, n, tplan, tel=None: prog(
            *args_fn(state, ts, n, tplan, tel))
        return prog, args_fn, runner

    def traffic_state(self, tspec) -> traffic.TrafficState:
        return traffic.init_state(tspec, self.mesh)

    def run_traffic(self, state: CounterState,
                    ts: traffic.TrafficState, tspec, n_rounds: int, *,
                    donate: bool = False, tel=None, tel_spec=None):
        """Open-loop serving driver: ``n_rounds`` rounds as ONE device
        program, each round injecting the spec's seeded arrivals
        before the ordinary flush/poll round and advancing the per-op
        latency tracker after it (tpu_sim/traffic.py).  Arrivals ride
        the compiled :class:`~.traffic.TrafficPlan` as a traced
        operand next to the FaultPlan, so fault campaigns and serving
        load compose in one fused program.  With ``donate`` both the
        sim state and the tracker are consumed (updated in place).

        ``tel``/``tel_spec`` (PR 8): a telemetry ring + its
        ``TelemetrySpec(traffic=True)`` — the per-round series record
        next to the tracker and the call returns ``(state, ts, tel)``
        (the ring donated with the rest).

        Programs are cached by the spec's STATIC shape
        (``TrafficSpec.program_key``): a serving-curve load sweep
        reuses one compiled program across its rates — the plan rides
        as a traced operand."""
        key = (tspec.program_key, donate,
               telemetry.tel_key(tel, tel_spec, "counter"))
        if key not in self._traffic_progs:
            self._traffic_progs[key] = self._build_traffic(
                tspec, donate, tel_spec)
        return self._traffic_progs[key][2](state, ts,
                                           jnp.int32(n_rounds),
                                           tspec.compile(), tel)

    def audit_traffic_program(self, tspec, *, donate: bool = True,
                              tel_spec=None):
        """(jitted, example_args) of the traffic driver — the handle
        the contract auditor lowers (census + donation of the EXACT
        program :meth:`run_traffic` executes)."""
        key = (tspec.program_key, donate, tel_spec)
        if key not in self._traffic_progs:
            self._traffic_progs[key] = self._build_traffic(
                tspec, donate, tel_spec)
        prog, args_fn, _ = self._traffic_progs[key]
        tel = (telemetry.init_state(tel_spec) if tel_spec is not None
               else None)
        return prog, args_fn(self.init_state(),
                             self.traffic_state(tspec), jnp.int32(4),
                             tspec.compile(), tel)

    # -- reads -------------------------------------------------------------

    def reads(self, state: CounterState) -> np.ndarray:
        """(N,) int32 — each node's ``read`` reply (cached value only,
        add.go:29-31)."""
        return np.asarray(state.cached)

    def kv_value(self, state: CounterState) -> int:
        return int(state.kv)

    def audit_run_program(self, *, donate: bool = True,
                          rounds: int = 8):
        """(jitted, example_args) of the fused multi-round driver —
        the handle the contract auditor lowers to check the donation
        alias table of the EXACT program :meth:`run_fused` runs."""
        prog, args_fn = self._run_progs[donate]
        return prog, args_fn(self.init_state(), jnp.int32(rounds))


# -- scenario-axis batch hooks (PR 10, tpu_sim/scenario.py) --------------


def _build_batch_round(sim: "CounterSim"):
    """Per-scenario round closure for the scenario-axis batch drivers:
    the sim's own :meth:`CounterSim._round` with identity collectives
    (each scenario's node axis is fully local under scenario sharding)
    and the scenario's OWN plan as the traced operand."""
    coll = collectives(sim.n_nodes)

    def rnd(state, plan):
        return sim._round(state, coll, sim.kv_sched, plan)
    return rnd


def _batch_converged(state: CounterState, member=None) -> jnp.ndarray:
    """() bool, traced — one scenario's convergence predicate: pending
    fully drained AND every node's cached read equals the KV (the
    traced twin of run_counter_nemesis's host check).  ``member``
    ((N,) bool, PR 17) restricts the cached-read check to MEMBER rows
    (a left row's wiped cache can never re-poll); pending stays
    summed over ALL rows — a non-member row's pending is structurally
    zero (join rows enter empty, leave rows are wiped), so any
    residue is a real undrained delta."""
    cached_ok = state.cached == state.kv
    if member is not None:
        cached_ok = cached_ok | ~member
    return (jnp.sum(state.pending) == 0) & jnp.all(cached_ok)


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def audit_contracts():
    """The counter drivers' :class:`~.audit.ProgramContract` rows: the
    wide two-pmin winner's sharded step (collective-based end to end,
    no all-gather — the PR 4 gate) and the donated fused driver's
    donation + memory contract."""
    from .audit import AuditProgram, ProgramContract
    from .engine import analytic_peak_bytes
    from .engine import operand_bytes as engine_operand_bytes

    def wide_step(mesh):
        sim = CounterSim(32, mode="cas", poll_every=2,
                         winner_key="wide", mesh=mesh)
        sched_spec = KVReach(P(), P(), P(None, None))

        def step(state, sched):
            # sim._dcn resolved from the env at construction — the
            # */dcn-pipelined-* rebinds re-issue this row under
            # GG_DCN_PIPELINE=1
            coll = collectives(state.pending.shape[0], mesh,
                               dcn=sim._dcn)
            return sim._round(state, coll, sched)

        prog = jit_program(step, mesh=mesh,
                           in_specs=(sim._state_spec(), sched_spec),
                           out_specs=sim._state_spec())
        return AuditProgram(prog, (sim.init_state(), sim.kv_sched))

    def traffic_run(mesh):
        # big enough that state dominates the per-round temps (the
        # memory band then audits the donated-footprint claim, not
        # XLA's toy-shape buffer alignment)
        n, k = 1024, 8
        tspec = traffic.TrafficSpec(
            n_nodes=n, n_clients=n, ops_per_client=k, until=8,
            rate=0.5, seed=11)
        spec = faults.NemesisSpec(n_nodes=n, seed=5,
                                  crash=((2, 4, (1,)),),
                                  loss_rate=0.1, loss_until=6)
        sim = CounterSim(n, mode="cas", poll_every=2, mesh=mesh,
                         fault_plan=spec.compile())
        prog, args = sim.audit_traffic_program(tspec)
        # per-shard parameter shapes in the compiled header
        n_sh = 1 if mesh is None else 8
        state_bytes = (2 * n * 4 + n * 4 + 3 * n * k * 4) // n_sh
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                (tspec.compile(), sim.fault_plan)),
            slab_bytes=n * k * 4 // n_sh)   # tracker-scan temps
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def fused_donated(mesh):
        del mesh
        n = 4096
        sim = CounterSim(n, mode="cas", poll_every=2)
        prog, args = sim.audit_run_program(donate=True)
        state_bytes = 2 * n * 4           # pending + cached (+ scalars)
        analytic = analytic_peak_bytes(state_bytes=state_bytes,
                                       donated=True)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="counter/sharded-step-wide",
            build=wide_step,
            collectives={"all-reduce": None},
            notes="wide two-pmin winner: psum/pmin collectives only — "
                  "NO all-gather, no ppermute needed (the PR 4 "
                  "counter gate)"),
        ProgramContract(
            name="counter/sharded-traffic-run",
            build=traffic_run,
            collectives={"all-reduce": None},
            donation=True,
            mem_lo=0.2, mem_hi=6.0,
            notes="open-loop traffic driver under crash+loss (PR 7): "
                  "shard-local injection, flush tracking, and the "
                  "pmin cache-visibility fold stay all-reduce-only — "
                  "no gather, no ppermute; (state, tracker) alias in "
                  "place"),
        ProgramContract(
            name="counter/fused-donated",
            build=fused_donated,
            collectives={},
            donation=True,
            mem_lo=0.2, mem_hi=4.0,
            needs_mesh=False,
            notes="donated fori driver: the (pending, cached) node "
                  "rows alias in place; compiled peak within band of "
                  "1x state + hash/select temps"),
    ]
