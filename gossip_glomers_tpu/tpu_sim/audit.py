"""Program-contract auditor: static analysis over compiled HLO plus an
AST determinism lint — the repo's invariants as ONE mechanical check.

The properties that broke (or nearly broke) past PRs are all *static*
properties of the compiled programs, yet until PR 6 each was enforced by
a one-off artifact: three regex tests pinned "no all-gather in the
sharded step", one pinned test audited the analytic memory formula at
one shape, and nothing at all watched for silently-dropped buffer
donation (XLA drops ``donate_argnums`` on any layout/dtype mismatch
without failing), host callbacks sneaking into a round, or a
nondeterminism source landing in traced code.  This module turns each
property into a declarative **checker** over the compiled HLO text /
buffer assignment, and a :class:`ProgramContract` **registry** lets
every driver the engine builds state its contract once:

- **collective census** — which collective ops (``all-gather``,
  ``all-reduce``, ``collective-permute``, ``all-to-all``, ...) the
  compiled program may contain, with per-op count caps.  The PR 4/5
  no-all-gather gates are the special case "cap 0".
- **donation contract** — the argnums a driver donates must actually
  appear in the compiled ``input_output_alias`` table and alias at
  least the declared state bytes.  This is the checker that makes a
  silently-dropped donation loud.
- **host boundary** — no host callbacks (``custom-call`` with a
  callback target), no infeed/outfeed/send/recv, and no XLA rng ops
  (traced randomness must come from the repo's stateless counter
  hashes) anywhere inside a round or fused-run program.
- **memory contract** — the compiled ``memory_analysis()`` peak must
  sit within a stated ratio band of the driver's
  ``engine.analytic_peak_bytes`` claim, auditing the ONE audited
  formula automatically for every registered driver instead of via a
  single pinned test.

The registry lives with the drivers: each stateful sim module exports
``audit_contracts()`` (broadcast gather / words-major halo, counter
wide, kafka union / faulted-union materialized + blocked / matmul
oracle, plus the donated fused drivers), and :func:`default_registry`
collects them.  ``scripts/audit.py`` runs the registry on the CPU
8-way virtual mesh and emits the ``AUDIT_PR*.json`` artifact; the
tier-1 tests prove every checker *falsifiable* with deliberately
broken programs (tests/test_audit.py).

The determinism lint (:func:`lint_paths`) is the static half of a race
detector for this codebase: seed-replay and resume bit-exactness
require that traced round code never consults a nondeterminism source.
It walks the package AST and flags, inside TRACED scope only (see
``_TRACED_ROOTS``): ``np.random``/``random.``/``time.`` calls and
argless ``datetime.now()``; iteration over ``set``/``dict`` (order
leaks into traced constants); and Python ``if``/``while`` on traced
values (host control flow on device data breaks under ``jit`` and
forks replay).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Mapping, NamedTuple

import numpy as np

from . import engine

# -- HLO text analysis ---------------------------------------------------

# the collective family the census tracks: anything in this tuple that
# a contract does not explicitly allow is forbidden (cap 0)
COLLECTIVE_OPS = ("all-gather", "all-reduce", "reduce-scatter",
                  "collective-permute", "all-to-all",
                  "collective-broadcast")

# ops that cross the host/device boundary or draw XLA-stateful
# randomness — never allowed inside a round/fused-run program
_HOST_OPS = ("infeed", "outfeed", "send", "recv",
             "rng", "rng-bit-generator", "rng-get-and-update-state")
_CALLBACK_TARGET = re.compile(r"callback|py_func|python", re.I)

_METADATA = re.compile(r"metadata=\{[^{}]*\}")


def _strip_metadata(hlo: str) -> str:
    """Drop ``metadata={...}`` spans (op_name/source_file strings can
    contain arbitrary text that would false-positive the op regexes)."""
    return _METADATA.sub("", hlo)


def _count_op(hlo: str, op: str) -> int:
    """Occurrences of instruction opcode ``op`` in HLO text: the opcode
    token directly followed by its operand list.  Async pairs count the
    ``-start`` half only (``-done`` carries no new communication)."""
    return len(re.findall(rf"(?<![\w-]){re.escape(op)}(?:-start)?\(",
                          hlo))


def collective_census(hlo: str) -> dict[str, int]:
    """Count the collective ops in one compiled module's text (every
    computation included — fused/while bodies too).  Returns only the
    ops present."""
    hlo = _strip_metadata(hlo)
    out = {}
    for op in COLLECTIVE_OPS:
        n = _count_op(hlo, op)
        if n:
            out[op] = n
    return out


class AliasEntry(NamedTuple):
    """One ``input_output_alias`` row: output tuple index <- (parameter
    number, parameter tuple index)."""

    output_index: tuple
    param_number: int
    param_index: tuple
    kind: str


def _brace_span(text: str, start: int) -> str:
    """The contents of the brace group opening at ``text[start] == '{'``
    (nested braces balanced)."""
    depth, i = 0, start
    while i < len(text):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start + 1:i]
        i += 1
    raise ValueError("unbalanced braces in HLO header")


_ALIAS_ROW = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{([\d,\s]*)\}(?:,\s*([\w-]+))?\)")


def parse_io_aliases(hlo: str) -> list[AliasEntry]:
    """The compiled module's ``input_output_alias`` table, parsed from
    the HloModule header.  EMPTY when XLA dropped every donation — the
    silent failure mode this parser exists to make loud: jax only warns
    (once) when a donated buffer cannot alias, and the program silently
    keeps input + output copies live."""
    key = "input_output_alias="
    pos = hlo.find(key)
    if pos < 0:
        return []
    body = _brace_span(hlo, pos + len(key))
    out = []
    for m in _ALIAS_ROW.finditer(body):
        oidx = tuple(int(x) for x in m.group(1).split(",") if x.strip())
        pidx = tuple(int(x) for x in m.group(3).split(",") if x.strip())
        out.append(AliasEntry(oidx, int(m.group(2)), pidx,
                              m.group(4) or "may-alias"))
    return out


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

_SHAPE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")


def _split_top(s: str) -> list[str]:
    """Split on top-level commas (shape layouts carry nested
    ``{1,0}``/``[8,4]`` groups)."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "[({":
            depth += 1
        elif ch in "])}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _shape_bytes(token: str) -> int:
    m = _SHAPE.search(token)
    if not m or m.group(1) not in _DTYPE_BYTES:
        return 0
    n = 1
    for d in m.group(2).split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES[m.group(1)]


def entry_param_bytes(hlo: str) -> list[int]:
    """Byte size of each entry parameter, parsed from the
    ``entry_computation_layout`` header (jax flattens pytree args, so
    every leaf is its own parameter)."""
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo,
                  re.S)
    if not m:
        return []
    body = re.sub(r"/\*.*?\*/", "", m.group(1))
    return [_shape_bytes(tok) for tok in _split_top(body)]


def donated_alias_bytes(hlo: str) -> int:
    """Total bytes the alias table covers, computed STATICALLY from
    the HLO header (alias rows × parameter shapes).  This — not
    ``memory_analysis().alias_size_in_bytes`` — is the donation
    checker's source of truth: an executable deserialized from the
    persistent compilation cache keeps its header but reports
    ``alias_size_in_bytes == 0``, which would fail (and mis-price)
    every donated contract on a warm cache."""
    sizes = entry_param_bytes(hlo)
    seen: set[int] = set()
    total = 0
    for e in parse_io_aliases(hlo):
        if e.param_number in seen:
            continue
        seen.add(e.param_number)
        if e.param_number < len(sizes):
            total += sizes[e.param_number]
    return total


def host_boundary_violations(hlo: str) -> list[str]:
    """Everything in the module that crosses the host/device boundary
    or draws XLA-stateful randomness: infeed/outfeed/send/recv ops,
    rng ops, and ``custom-call``s whose target is a host callback
    (``jax.pure_callback`` / ``io_callback`` / debug prints compile to
    these).  A round program must return an empty list."""
    stripped = _strip_metadata(hlo)
    out = []
    for op in _HOST_OPS:
        n = _count_op(stripped, op)
        if n:
            out.append(f"{op} x{n}")
    for m in re.finditer(r'custom_call_target="([^"]+)"', hlo):
        if _CALLBACK_TARGET.search(m.group(1)):
            out.append(f'custom-call target "{m.group(1)}"')
    return out


# XLA prints replica groups in two encodings: the explicit brace form
# ``{{0,1,2,3},{4,5,6,7}}`` and (when the grouping is a reshape of an
# iota) the compact ``[G,S]<=[dims]`` form, optionally with a
# ``T(perm)`` transpose of the iota before the reshape
_IOTA_GROUPS = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_ALL_GATHER = re.compile(r"(?<![\w-])all-gather(?:-start)?\(")


def _parse_replica_groups(line: str) -> "list[list[int]] | None":
    """One instruction line's replica groups as device-id lists, in
    either encoding.  ``None`` when the line declares no groups and
    ``[]`` for ``replica_groups={}`` — both mean ONE flattened world
    group."""
    m = _IOTA_GROUPS.search(line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose(
                [int(x) for x in m.group(4).split(",")])
        return [[int(d) for d in row] for row in ids.reshape(g, s)]
    pos = line.find("replica_groups={")
    if pos < 0:
        return None
    body = _brace_span(line, pos + len("replica_groups="))
    return [[int(x) for x in grp.split(",") if x.strip()]
            for grp in re.findall(r"\{([\d,\s]*)\}", body)]


def dcn_gather_violations(hlo: str, per_host: int) -> list[str]:
    """Every ``all-gather`` whose replica groups cross a host boundary
    (host = device id // ``per_host`` under the hosts-major device
    order of ``pick_mesh_2d``) — the DCN scale-out gate: a structured
    exchange may widen INSIDE a host's ICI block, but an operand
    all-gather over the DCN axis turns the slow links into the
    bottleneck and is forbidden (gather-path widens are exempt by
    simply not declaring ``dcn_per_host`` on those contracts)."""
    out = []
    for line in _strip_metadata(hlo).splitlines():
        if not _ALL_GATHER.search(line):
            continue
        groups = _parse_replica_groups(line)
        if not groups:
            out.append("all-gather over the flattened world group "
                       "(crosses every host)")
            continue
        for grp in groups:
            hosts = sorted({d // per_host for d in grp})
            if len(hosts) > 1:
                out.append(
                    f"all-gather group {grp} spans hosts {hosts}")
    return out


# -- program contracts ---------------------------------------------------


class AuditProgram(NamedTuple):
    """What a contract's ``build`` hands the auditor: the jitted
    program plus example arguments to lower it with, and the two
    declared expectations the HLO cannot state for itself."""

    jitted: Callable
    args: tuple
    # donation contract: the state bytes that must appear in the alias
    # table (0 = this program donates nothing)
    donated_bytes: int = 0
    # memory contract: the driver's engine.analytic_peak_bytes claim
    # for this exact shape (None = no memory check)
    analytic_peak_bytes: int | None = None


@dataclass(frozen=True)
class ProgramContract:
    """One driver's declared static contract (module docstring).

    ``collectives`` maps allowed op -> max count (None = unbounded);
    any :data:`COLLECTIVE_OPS` member not listed is FORBIDDEN — the
    no-all-gather gates are simply contracts that omit ``all-gather``.
    ``mem_lo``/``mem_hi`` bound compiled_peak / analytic_peak when the
    built program declares an analytic claim: ``mem_hi`` is the loud
    failure for an analytic-peak *lie* (claimed formula far below what
    XLA actually holds live), ``mem_lo`` catches the inverse (formula
    wildly over-claims, i.e. prices buffers the program no longer
    has)."""

    name: str
    build: Callable[[object], AuditProgram]   # mesh (or None) -> built
    collectives: Mapping[str, int | None] = field(default_factory=dict)
    donation: bool = False
    host_clean: bool = True
    mem_lo: float = 0.0
    mem_hi: float | None = None
    needs_mesh: bool = True
    # DCN gate (PR 15): devices per host block; when set, no all-gather
    # replica group in the compiled HLO may cross a host boundary
    dcn_per_host: int | None = None
    notes: str = ""


def _check_census(contract: ProgramContract, hlo: str) -> dict:
    census = collective_census(hlo)
    errors = []
    for op, n in census.items():
        cap = contract.collectives.get(op, 0)
        if cap is not None and n > cap:
            errors.append(
                f"{op}: {n} in compiled HLO, contract allows "
                f"{cap}")
    return {"ok": not errors, "counts": census, "errors": errors,
            "allowed": {k: v for k, v in contract.collectives.items()}}


def _check_donation(contract: ProgramContract, hlo: str,
                    built: AuditProgram) -> dict:
    aliases = parse_io_aliases(hlo)
    alias_bytes = donated_alias_bytes(hlo)
    res = {"entries": len(aliases), "alias_bytes": alias_bytes,
           "expected_bytes": built.donated_bytes}
    if not contract.donation:
        res["ok"] = True
        return res
    errors = []
    if not aliases:
        errors.append(
            "donated program compiled with an EMPTY input_output_alias "
            "table — XLA dropped the donation (layout/dtype mismatch?)")
    elif alias_bytes < built.donated_bytes:
        errors.append(
            f"alias table covers {alias_bytes} bytes, the donated "
            f"state is {built.donated_bytes} — some state buffers no "
            "longer alias in place")
    res.update(ok=not errors, errors=errors)
    return res


def _check_host(contract: ProgramContract, hlo: str) -> dict:
    violations = host_boundary_violations(hlo)
    ok = not (contract.host_clean and violations)
    return {"ok": ok, "violations": violations}


def _check_dcn(contract: ProgramContract, hlo: str) -> dict:
    if contract.dcn_per_host is None:
        return {"ok": True, "checked": False}
    violations = dcn_gather_violations(hlo, contract.dcn_per_host)
    return {"ok": not violations, "checked": True,
            "per_host": contract.dcn_per_host,
            "violations": violations}


def _check_memory(contract: ProgramContract, built: AuditProgram,
                  footprint) -> dict:
    if contract.mem_hi is None or built.analytic_peak_bytes is None:
        return {"ok": True, "checked": False}
    if footprint is None:
        # backend exposes no memory_analysis — record, don't fail
        return {"ok": True, "checked": False,
                "note": "no memory_analysis on this backend"}
    peak = footprint["peak_live_bytes"]
    ratio = peak / max(1, built.analytic_peak_bytes)
    ok = contract.mem_lo <= ratio <= contract.mem_hi
    return {"ok": ok, "checked": True,
            "analytic_peak_bytes": built.analytic_peak_bytes,
            "compiled_peak_bytes": peak,
            "ratio": round(ratio, 4),
            "band": [contract.mem_lo, contract.mem_hi]}


def audit_contract(contract: ProgramContract, mesh=None) -> dict:
    """Compile one contract's program and run every checker.  Returns
    the verdict dict (the per-contract row of ``AUDIT_PR*.json``)."""
    built = contract.build(mesh if contract.needs_mesh else None)
    compiled = built.jitted.lower(*built.args).compile()
    hlo = compiled.as_text()
    footprint = engine._footprint_of(compiled)
    if footprint is not None:
        # an executable deserialized from the persistent compilation
        # cache reports alias_size_in_bytes == 0 while its header
        # keeps the alias table — re-derive the alias term statically
        # so the peak (args + outs + temps − aliases) prices donation
        # identically cold and warm (see donated_alias_bytes)
        static_alias = donated_alias_bytes(hlo)
        if static_alias > footprint["alias_bytes"]:
            footprint["peak_live_bytes"] -= (static_alias
                                             - footprint["alias_bytes"])
            footprint["alias_bytes"] = static_alias
    checks = {
        "collectives": _check_census(contract, hlo),
        "donation": _check_donation(contract, hlo, built),
        "host_boundary": _check_host(contract, hlo),
        "dcn": _check_dcn(contract, hlo),
        "memory": _check_memory(contract, built, footprint),
    }
    return {"name": contract.name, "notes": contract.notes,
            "ok": all(c["ok"] for c in checks.values()),
            "checks": checks}


def default_registry() -> list[ProgramContract]:
    """Every registered driver contract, collected from the sims (each
    stateful sim module owns its own ``audit_contracts()``; telemetry
    registers the observed-driver rows, PR 8; provenance the
    stamp-carrying rows, PR 9; kvstore the sharded-rows CAS drivers
    and txn the wound-or-die transaction rounds, PR 14; dcn the
    hierarchical ICI x DCN re-audits with the host-crossing gather
    gate, PR 15; membership the census and resized-carry rows,
    PR 17)."""
    from . import (broadcast, counter, dcn, kafka, kvstore,
                   membership, provenance, scenario, telemetry, txn)
    out: list[ProgramContract] = []
    for mod in (broadcast, counter, kafka, telemetry, provenance,
                scenario, kvstore, txn, dcn, membership):
        out.extend(mod.audit_contracts())
    names = [c.name for c in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate contract names: {sorted(names)}")
    return out


def run_audit(mesh, contracts=None) -> dict:
    """Audit the whole registry on ``mesh``.  Never raises on a failed
    contract — the report carries per-contract verdicts and a global
    ``ok`` (scripts/audit.py turns that into the exit code)."""
    contracts = (default_registry() if contracts is None
                 else list(contracts))
    rows = [audit_contract(c, mesh) for c in contracts]
    return {"ok": all(r["ok"] for r in rows),
            "n_contracts": len(rows),
            "contracts": rows}


# -- determinism lint ----------------------------------------------------
#
# TRACED scope = the code that runs inside jit/shard_map at round time,
# where a nondeterminism source or host branch on device data breaks
# seed replay.  Three detection mechanisms, all static:
#
#   1. per-file name patterns for the known traced roots (the round
#      functions and the device-side fault evaluators);
#   2. any function whose decorator list mentions jit / shard_map;
#   3. any `def` nested inside a traced root OR inside a program
#      BUILDER (the `_build_*`/`_step_prog`/`make_*` methods whose
#      nested `def`s become the jitted program bodies — their enclosing
#      method runs on host, the nested defs do not).
#
# Host-side code (drivers, staging, benchmarks, spec builders like
# faults.random_spec) is deliberately out of scope: np.random there is
# fine and often the point.

def _faults_roots() -> str:
    # faults.py DECLARES its own host/device split
    # (faults.TRACED_EVALUATORS; totality pinned by tests/test_audit.py)
    from . import faults
    return ("^(" + "|".join(re.escape(n)
                            for n in faults.TRACED_EVALUATORS) + ")$")


def _traffic_roots() -> str:
    # traffic.py declares its split the same way (PR 7; totality
    # pinned by tests/test_traffic.py)
    from . import traffic
    return ("^(" + "|".join(re.escape(n)
                            for n in traffic.TRACED_EVALUATORS) + ")$")


def _telemetry_roots() -> str:
    # telemetry.py declares its split the same way (PR 8; totality
    # pinned by tests/test_telemetry.py)
    from . import telemetry
    return ("^(" + "|".join(re.escape(n)
                            for n in telemetry.TRACED_EVALUATORS)
            + ")$")


def _provenance_roots() -> str:
    # provenance.py declares its split the same way (PR 9; totality
    # pinned by tests/test_provenance.py)
    from . import provenance
    return ("^(" + "|".join(re.escape(n)
                            for n in provenance.TRACED_EVALUATORS)
            + ")$")


def _scenario_roots() -> str:
    # scenario.py declares its split the same way (PR 10; totality
    # pinned by tests/test_scenario.py).  The batch runners' nested
    # per-scenario bodies are traced via the _BUILDERS mechanism
    # (run_*_batch below).
    from . import scenario
    return ("^(" + "|".join(re.escape(n)
                            for n in scenario.TRACED_EVALUATORS)
            + ")$")


def _fuzz_roots() -> str:
    # harness/fuzz.py is PURE HOST code and declares an EMPTY traced
    # tuple (PR 10) — the pattern matches nothing, so the lint walks
    # the file but claims no traced scope there; totality pinned by
    # tests/test_scenario.py.
    from ..harness import fuzz
    return ("^(" + "|".join(re.escape(n)
                            for n in fuzz.TRACED_EVALUATORS) + ")$")


def _membership_roots() -> str:
    # membership.py declares its split the same way (PR 17; totality
    # pinned by tests/test_membership.py)
    from . import membership
    return ("^(" + "|".join(re.escape(n)
                            for n in membership.TRACED_EVALUATORS)
            + ")$")


def _kvstore_roots() -> str:
    # kvstore.py declares its split the same way (PR 14; totality
    # pinned by tests/test_kvstore.py)
    from . import kvstore
    return ("^(" + "|".join(re.escape(n)
                            for n in kvstore.TRACED_EVALUATORS)
            + ")$")


def _txn_roots() -> str:
    # txn.py's traced module-level surface is tiny (the batch
    # convergence predicate); the round body is the TxnSim._round
    # method plus the _build_* builder closures — _round is rooted
    # below, the builders ride the _BUILDERS mechanism.  Totality
    # pinned by tests/test_txn.py.
    from . import txn
    return ("^(_round$|"
            + "|".join(re.escape(n) + "$"
                       for n in txn.TRACED_EVALUATORS) + ")")


def _harness_txn_roots() -> str:
    # harness/txn.py is PURE HOST campaign driving (PR 14) — same
    # empty-traced-tuple contract as harness/fuzz.py; totality pinned
    # by tests/test_txn.py.
    from ..harness import txn as harness_txn
    return ("^(" + "|".join(re.escape(n)
                            for n in harness_txn.TRACED_EVALUATORS)
            + ")$")


def _harness_membership_roots() -> str:
    # harness/membership.py is PURE HOST campaign driving (PR 17) —
    # same empty-traced-tuple contract as harness/fuzz.py; totality
    # pinned by tests/test_membership.py.
    from ..harness import membership as harness_membership
    return ("^(" + "|".join(
        re.escape(n)
        for n in harness_membership.TRACED_EVALUATORS) + ")$")


def _frontier_roots() -> str:
    # harness/frontier.py is PURE HOST cartography (PR 13) — same
    # empty-traced-tuple contract as harness/fuzz.py (the traced
    # serving_loop / signature_eval live in tpu_sim/scenario.py);
    # totality pinned by tests/test_frontier.py.
    from ..harness import frontier
    return ("^(" + "|".join(re.escape(n)
                            for n in frontier.TRACED_EVALUATORS)
            + ")$")


_TRACED_ROOTS: dict[str, str] = {
    "tpu_sim/broadcast.py":
        r"^(_round|flood_step$|_wm_round_single$|_sharded_round"
        r"|_live_rows$|_edge_live$|_popcount$|_flood_loop$"
        r"|_flood_ledger$|_traffic_inject$|_traffic_done$"
        r"|_tel_series$|_traffic_tel$|_prov_attribute$"
        r"|_batch_converged$)",
    "tpu_sim/counter.py":
        r"^(_round$|_reach$|_traffic_round$|_tel_series$"
        r"|_prov_record$|_batch_converged$)",
    "tpu_sim/kafka.py":
        r"^(_round$|_rank_within_key$|_alloc$|_traffic_round$"
        r"|_tel_series$|_prov_record$|_batch_converged$)",
    "tpu_sim/faults.py": _faults_roots(),
    "tpu_sim/traffic.py": _traffic_roots(),
    "tpu_sim/telemetry.py": _telemetry_roots(),
    "tpu_sim/provenance.py": _provenance_roots(),
    "tpu_sim/scenario.py": _scenario_roots(),
    "tpu_sim/kvstore.py": _kvstore_roots(),
    "tpu_sim/membership.py": _membership_roots(),
    "tpu_sim/txn.py": _txn_roots(),
    "harness/txn.py": _harness_txn_roots(),
    "harness/membership.py": _harness_membership_roots(),
    "harness/fuzz.py": _fuzz_roots(),
    "harness/frontier.py": _frontier_roots(),
    "tpu_sim/engine.py":
        r"^(sharded_roll$|sharded_shift$|collectives$|fori_rounds$"
        r"|windows_fold$|scan_blocks$|scan_rounds$|while_converge$)",
    # structured.py's traced code is entirely nested inside its make_*
    # builders — covered by the _BUILDERS mechanism below
}

# builder methods whose nested `def`s are traced program bodies
# (run_\w+_batch: the scenario-axis batch runners, PR 10 — their
# nested per-scenario closures become the vmapped program bodies;
# _?dispatch_\w+_batch: the async dispatch halves those runners split
# into, PR 13 — same nested closures, now enqueued without blocking)
_BUILDERS = re.compile(
    r"^(_build_\w+|_step_prog|_run_prog|run_rounds|build_fixed"
    r"|poll_batch_program|alloc_offsets|run_\w+_batch"
    r"|_?dispatch_\w+_batch)$")
# structured.py's exchange/diff/nemesis factories — its make_* arm is
# scoped to THAT file only: host-side make_* factories elsewhere
# (harness staging, wire helpers) may nest closures that legitimately
# use rngs/clocks
_STRUCTURED_BUILDERS = re.compile(r"^make_\w+$")


def _is_builder(name: str, relpath: str) -> bool:
    if _BUILDERS.match(name):
        return True
    return bool(relpath.endswith("tpu_sim/structured.py")
                and _STRUCTURED_BUILDERS.match(name))

_JIT_DECORATOR = re.compile(r"\b(jit|shard_map)\b")

# rng / clock modules that must never be consulted in traced scope
_BANNED_CALL = re.compile(
    r"^(np|numpy)\.random\.|^random\.|^time\."
    r"|^(datetime\.)?datetime\.(now|utcnow|today)$")


@dataclass(frozen=True)
class LintFinding:
    path: str
    line: int
    rule: str        # "rng-or-clock" | "set-dict-order" | "traced-branch"
    func: str        # the traced function the finding is inside
    msg: str

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "func": self.func, "msg": self.msg}


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` attribute chains as a dotted string (None for anything
    dynamic)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "_fields"}
_TRACED_CALL_ROOTS = {"jnp", "lax", "jax", "faults"}


def _is_static_test(test: ast.AST) -> bool:
    """`x is None` / `x is not None` tests (and and/or/not compositions
    of them) are structural — pytree SHAPE branches like "is the ledger
    leaf present", decided at trace time — not value branches."""
    if isinstance(test, ast.BoolOp):
        return all(_is_static_test(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_static_test(test.operand)
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops))


class _TracedNames(ast.NodeVisitor):
    """Names in one traced function that hold device values: assigned
    from jnp./lax./jax./faults. call chains, or propagated from other
    traced names.  Two passes reach a fixpoint for the simple
    straight-line flows rounds are written in."""

    def __init__(self) -> None:
        self.names: set[str] = set()
        self._changed = False

    def run(self, fn: ast.AST) -> set[str]:
        for _ in range(3):
            self._changed = False
            self.visit(fn)
            if not self._changed:
                break
        return self.names

    def _expr_traced(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                root = _dotted(sub.func)
                if root and root.split(".")[0] in _TRACED_CALL_ROOTS:
                    return True
            if isinstance(sub, ast.Name) and sub.id in self.names:
                return True
        return False

    def _bind(self, target: ast.AST) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name) and sub.id not in self.names:
                self.names.add(sub.id)
                self._changed = True

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._expr_traced(node.value):
            for t in node.targets:
                self._bind(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self._expr_traced(node.value):
            self._bind(node.target)
        self.generic_visit(node)


class _TracedScopeLinter(ast.NodeVisitor):
    """Apply the three rules inside ONE traced function (nested traced
    `def`s are linted by their own instances — skip them here)."""

    def __init__(self, path: str, fn: ast.FunctionDef,
                 findings: list[LintFinding]) -> None:
        self.path = path
        self.fn = fn
        self.findings = findings
        self.traced = _TracedNames().run(fn)
        # the state pytree param: rounds are written state-first
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args
                 if a.arg != "self"]
        self.state_param = names[0] if names else None

    def _flag(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(LintFinding(
            self.path, getattr(node, "lineno", 0), rule,
            self.fn.name, msg))

    # rule 1: rng / clock calls -------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        if dotted and _BANNED_CALL.search(dotted):
            self._flag(node, "rng-or-clock",
                       f"{dotted}() inside traced `{self.fn.name}` — "
                       "traced code must draw from the stateless "
                       "counter hashes (faults._edge_hash family), "
                       "never a host rng/clock")
        self.generic_visit(node)

    # rule 2: set/dict iteration ------------------------------------
    def _iter_unordered(self, it: ast.AST) -> str | None:
        if isinstance(it, (ast.Set, ast.SetComp, ast.DictComp)):
            return "set/dict literal"
        if isinstance(it, ast.Dict):
            return "dict literal"
        if isinstance(it, ast.Call):
            dotted = _dotted(it.func)
            if dotted in ("set", "frozenset", "dict"):
                return f"{dotted}()"
            if dotted and dotted.split(".")[-1] in ("keys", "values",
                                                    "items"):
                return f".{dotted.split('.')[-1]}()"
        return None

    def _check_iter(self, node: ast.AST, it: ast.AST) -> None:
        what = self._iter_unordered(it)
        if what:
            self._flag(node, "set-dict-order",
                       f"iteration over {what} inside traced "
                       f"`{self.fn.name}`: insertion/hash order leaks "
                       "into traced constants — wrap in sorted()")

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    # rule 3: Python branch on traced values ------------------------
    def _test_on_traced(self, test: ast.AST) -> str | None:
        if _is_static_test(test):
            return None

        def scan(node: ast.AST) -> str | None:
            # `x.shape[0] > 4`-style tests are static: prune the whole
            # subtree under a static attribute access
            if (isinstance(node, ast.Attribute)
                    and node.attr in _STATIC_ATTRS):
                return None
            if (isinstance(node, ast.Attribute)
                    and node.attr not in _STATIC_ATTRS
                    and isinstance(node.value, ast.Name)
                    and node.value.id == self.state_param):
                return f"{self.state_param}.{node.attr}"
            if isinstance(node, ast.Name) and node.id in self.traced:
                return node.id
            for child in ast.iter_child_nodes(node):
                hit = scan(child)
                if hit:
                    return hit
            return None

        return scan(test)

    def _check_branch(self, node: ast.AST, kind: str) -> None:
        hit = self._test_on_traced(node.test)
        if hit:
            self._flag(node, "traced-branch",
                       f"Python {kind} on traced value `{hit}` inside "
                       f"`{self.fn.name}`: host control flow on device "
                       "data — use jnp.where / lax.cond")

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_branch(node, "conditional expression")
        self.generic_visit(node)

    # nested defs get their own linter instance — do not descend
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is not self.fn:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _root_pattern_for(relpath: str) -> re.Pattern | None:
    for suffix, pat in _TRACED_ROOTS.items():
        if relpath.endswith(suffix):
            return re.compile(pat)
    return None


def _has_jit_decorator(fn: ast.FunctionDef) -> bool:
    return any(_JIT_DECORATOR.search(ast.unparse(d))
               for d in fn.decorator_list)


def lint_source(src: str, relpath: str) -> list[LintFinding]:
    """Run the determinism lint over one module's source.  ``relpath``
    picks the traced-root name patterns (module docstring)."""
    tree = ast.parse(src, filename=relpath)
    pat = _root_pattern_for(relpath)
    findings: list[LintFinding] = []

    def walk(node: ast.AST, in_traced: bool, in_builder: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                traced = (in_traced or in_builder
                          or bool(pat and pat.match(child.name))
                          or _has_jit_decorator(child))
                if traced:
                    _TracedScopeLinter(relpath, child,
                                       findings).visit(child)
                walk(child, traced, _is_builder(child.name, relpath))
            else:
                walk(child, in_traced, in_builder)

    walk(tree, False, False)
    return findings


def lint_paths(root: "str | Path") -> list[LintFinding]:
    """Determinism lint over every ``.py`` under ``root`` (the
    ``gossip_glomers_tpu/`` package in CI)."""
    root = Path(root)
    findings: list[LintFinding] = []
    for path in sorted(root.rglob("*.py")):
        # POSIX-normalized so the _TRACED_ROOTS suffix match holds on
        # every host os
        rel = path.relative_to(root.parent).as_posix()
        findings.extend(lint_source(path.read_text(), rel))
    return findings
