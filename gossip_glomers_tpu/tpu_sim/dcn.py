"""DCN scale-out contracts (PR 15): the hierarchical ICI x DCN
re-audits.

Every row here compiles an EXISTING driver on the hierarchical
``("hosts", "nodes")`` mesh of :func:`~..parallel.mesh.pick_mesh_2d`
(2 hosts x 4 devices on the CPU 8-way virtual backend — the same
global shape the multi-process parity suite pins bit-exact against a
real 2-process gloo cluster) and adds the one check the 1-D registry
cannot state: ``dcn_per_host`` — **no all-gather replica group may
cross a host boundary**.  Structured exchanges, the counter wide
round, and the kafka union round move operands with ppermute circuits
and psums that decompose per axis, so intra-host ICI widens are the
only gathers allowed; an operand gather over the slow DCN links is
exactly the scaling failure the hierarchy exists to avoid.  The
gather-path broadcast widen legitimately spans the composed axis —
those contracts simply stay in their own modules without the gate.

Most rows REBIND a sibling module's registered contract onto the 2-D
mesh (same build closure, mesh pinned): if a future change makes a
round program hierarchy-unaware, the rebound row fails before any
multi-host run does.  The broadcast structured row is built here
because the sibling's build hardcodes a 1-D shard layout; this one
threads ``node_shards``/``node_axes`` like the harness does.
"""

from __future__ import annotations

import contextlib
import os

from . import faults

HOSTS = 2          # CI hierarchy: 2 "hosts" x 4 devices
PER_HOST = 4


@contextlib.contextmanager
def _pipelined_env():
    """Rebuild a sibling contract under ``GG_DCN_PIPELINE=1`` (PR 20):
    the sims resolve the env contract in their constructors, so the
    SAME build closure compiles the pipelined twin of its round — the
    audit then pins the double-buffered DCN circuit under the same
    gather gate and memory band as the synchronous row."""
    old = os.environ.get("GG_DCN_PIPELINE")
    os.environ["GG_DCN_PIPELINE"] = "1"
    try:
        yield
    finally:
        if old is None:
            del os.environ["GG_DCN_PIPELINE"]
        else:
            os.environ["GG_DCN_PIPELINE"] = old


def _pipelined(row, dcn_name, notes):
    """A ``*/dcn-*`` row re-issued with round pipelining ON: same
    build closure, env-pinned mode, caps/donation/memory band carried
    over — the two in-flight half-block partials are per-level psum/
    ppermute circuits over the SAME collective families, and the extra
    in-flight partial is at most one per-shard operand copy, priced
    inside the sibling's analytic band."""
    from .audit import ProgramContract

    def build(mesh, _build=row.build):
        with _pipelined_env():
            return _build(mesh)

    return ProgramContract(
        name=dcn_name, build=build, collectives=row.collectives,
        donation=row.donation, mem_lo=row.mem_lo, mem_hi=row.mem_hi,
        needs_mesh=row.needs_mesh, dcn_per_host=PER_HOST, notes=notes)


def _mesh2d():
    from ..parallel.mesh import pick_mesh_2d

    mesh = pick_mesh_2d(hosts=HOSTS)
    if mesh is None:
        raise RuntimeError(
            f"dcn contracts need a {HOSTS}-host hierarchy "
            f"({HOSTS * PER_HOST} devices; force_virtual_devices)")
    return mesh


def _rebind(rows, name, dcn_name, notes):
    """A sibling module's registered contract, re-issued on the 2-D
    mesh with the host-crossing gather gate added.  Caps, donation,
    and the memory band carry over unchanged — node rows shard over
    the COMPOSED hosts x nodes axes at the same global shard count, so
    the per-shard byte claims still price the compiled header."""
    from .audit import ProgramContract

    row = next(r for r in rows if r.name == name)

    def build(mesh, _build=row.build):
        del mesh
        return _build(_mesh2d())

    return ProgramContract(
        name=dcn_name, build=build, collectives=row.collectives,
        donation=row.donation, mem_lo=row.mem_lo, mem_hi=row.mem_hi,
        needs_mesh=False, dcn_per_host=PER_HOST, notes=notes)


def audit_contracts():
    """The ``*/dcn-*`` rows: structured broadcast nemesis round,
    counter wide round + donated traffic driver, kafka union round,
    and the host-sharded counter scenario batch — all on the
    hierarchical mesh, all under the DCN gather gate."""
    from . import broadcast, counter, kafka, scenario, structured
    from .audit import AuditProgram, ProgramContract
    from .broadcast import BroadcastSim, make_inject
    from .engine import node_axes, node_shards
    from ..parallel.topology import to_padded_neighbors, tree

    def structured_nem(mesh):
        del mesh
        mesh = _mesh2d()
        n, nv = 64, 64
        spec = faults.NemesisSpec(n_nodes=n, seed=9,
                                  crash=((1, 3, (0, 5)),),
                                  loss_rate=0.15, loss_until=5,
                                  dup_rate=0.1, dup_until=5)
        sim = BroadcastSim(
            to_padded_neighbors(tree(n)), n_values=nv, sync_every=4,
            srv_ledger=False, mesh=mesh,
            exchange=structured.make_exchange("tree", n),
            fault_plan=spec.compile(),
            nemesis=structured.make_nemesis(
                "tree", n, spec, n_shards=node_shards(mesh),
                axis_name=node_axes(mesh)))
        prog, args_fn = sim.audit_step_program()
        state, _ = sim.stage(make_inject(n, nv))
        return AuditProgram(prog, args_fn(state))

    bcast_row = ProgramContract(
        name="broadcast/dcn-halo-wm-nem",
        build=structured_nem,
        collectives={"all-reduce": None,
                     "collective-permute": None},
        needs_mesh=False,
        dcn_per_host=PER_HOST,
        notes="structured words-major nemesis round on the "
              "hierarchical mesh: the per-axis ppermute halo + "
              "mask decomposition stays gather-free, and no "
              "replica group crosses a host block")
    wide_row = _rebind(
        counter.audit_contracts(),
        "counter/sharded-step-wide", "counter/dcn-wide-round",
        notes="wide two-pmin winner on the hierarchical mesh: "
              "psum/pmin reduce over BOTH axes (partial-per-host "
              "then DCN) — still no gather anywhere")
    traffic_row = _rebind(
        counter.audit_contracts(),
        "counter/sharded-traffic-run", "counter/dcn-traffic-run",
        notes="open-loop traffic driver on the hierarchical "
              "mesh: donation survives the 2-D resharding (the "
              "state aliases in place) and the compiled peak "
              "stays in the per-host analytic memory band")
    union_row = _rebind(
        kafka.audit_contracts(),
        "kafka/sharded-step-union", "kafka/dcn-union-round",
        notes="blocked psum-of-OR + ppermute prefix scan on the "
              "hierarchical mesh: presence unions decompose "
              "per axis, no host-crossing gather")
    return [
        bcast_row, wide_row, traffic_row, union_row,
        _rebind(
            scenario.audit_contracts(),
            "counter/scenario-batch-run", "counter/dcn-scenario-batch",
            notes="host-sharded scenario batch: the leading scenario "
                  "axis splits over DCN, every node axis runs "
                  "locally — cap-0 census, donation and the "
                  "per-host memory band intact on the 2-D mesh"),
        # -- pipelined twins (PR 20 tentpole): the same builds under
        # GG_DCN_PIPELINE=1 — bit-exact by the integer-operand
        # restriction, same caps/donation/memory band, DCN gate on
        _pipelined(
            bcast_row, "broadcast/dcn-pipelined-halo-wm-nem",
            notes="pipelined structured nemesis round: the ledger "
                  "psums split their hosts level into two in-flight "
                  "half-block all-reduces; the halo ppermutes are "
                  "per-level already — still gather-free"),
        _pipelined(
            wide_row, "counter/dcn-pipelined-wide-round",
            notes="pipelined wide round: the per-host psum/pmin "
                  "partials double-buffer over the hosts axis as two "
                  "half-block all-reduces — integer operands, "
                  "bit-exact vs the sync row, still no gather"),
        _pipelined(
            traffic_row, "counter/dcn-pipelined-traffic-run",
            notes="pipelined open-loop traffic driver: donation "
                  "survives with the double-buffered DCN partials in "
                  "flight and the compiled peak stays inside the "
                  "sync row's analytic band"),
        _pipelined(
            union_row, "kafka/dcn-pipelined-union-round",
            notes="pipelined union round: presence-union psums and "
                  "the offset prefix scan split their hosts level "
                  "into two in-flight half-block circuits — no "
                  "host-crossing gather appears"),
    ]
