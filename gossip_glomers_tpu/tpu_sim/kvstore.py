"""Device-resident, node-sharded ``lin-kv``/``seq-kv`` service (PR 14).

Maelstrom's special service nodes ``seq-kv`` and ``lin-kv`` (PAPER.md
§1, Layer 0) were the last host component in the serving path
(harness/services.py): every counter flush and kafka offset CAS
round-tripped off device.  This module promotes the KV store to a
device-resident sim with the same layout discipline as every other
workload state:

- **Sharded key rows.** Key ``k`` lives in exactly one row of a
  ``(N, cap)`` slab at ``[owner(k), slot(k)]`` — owner chosen by a
  stateless hash (:func:`owner_of`, same ``_mix32`` family as the fault
  coins, so routing is a pure function of ``(key, n_nodes, seed)`` on
  host and device alike), slot by per-owner rank.  The slab shards
  ``P('nodes', None)`` exactly like node state, so under ``shard_map``
  each shard holds only its own keys.
- **CAS as a masked compare-update.** A request batch is three
  replicated ``(K,)`` vectors (``on``/``frm``/``to``); each owner row
  applies ``vals == frm`` → ``to`` element-wise and bumps the row's
  version on hit (:func:`cas_apply`).  No gather, no scatter across
  shards: requests are replicated, rows are local, the compare-update
  is pure arithmetic — the sharded step's HLO carries all-reduce only
  (pinned by the ``kvstore/sharded-cas-step`` audit contract).
- **Linearization from the round counter.** One request batch commits
  per round; the store's serialization order IS the round order, the
  same clock every sim already linearizes against.  Within a round the
  batch must be conflict-free (one writer per key) — the counter's
  one-winner CAS and the txn workload's wound-or-die winner fold
  (tpu_sim/txn.py) both guarantee it by construction.
- **Reads as one psum.** :func:`rows_view` scatters the local rows
  into a replicated ``(2, K)`` (value, version) view and globalizes it
  in ONE ``reduce_sum`` — the read path costs one all-reduce per round
  regardless of K.
- **Faults compose.** ``kv_amnesia=True`` wipes a restarting owner's
  rows via the SAME :func:`faults.amnesia` coin as node state
  (:func:`rows_wipe`): a crashed owner shard loses its keys, exactly
  like acked-unflushed deltas die with a counter node.  The default
  (``False``) models Maelstrom's always-up service node — the
  bit-exact pin against the host ``KVService``.
- **Staleness as seeded coins.** The seq-kv flavor's
  ``stale_read_prob`` becomes :func:`stale_coin` — a stateless
  ``(seed, round, node)`` hash with a numpy twin
  (:func:`host_stale_coin`), so the host harness and the device sim
  draw the SAME stale reads and the flush retry loop sees the same
  wire-message counts on both backends
  (tests/test_kvstore.py calibration).

srv-ledger semantics (ROADMAP item 6, decided here): KV messages are
**charged at send**.  A request from a node that crashes mid-round has
already been charged (the reach gate samples liveness at the round
edge, so "mid-round" death is modeled as dying with the request in
flight: request charged, no reply charged — the pair is counted
together as the 4-msg attempt, matching the harness where the timeout
path re-charges on retry).  Duplicate delivery of KV *request* streams
is REJECTED loudly (:func:`reject_dup_stream`): a duplicated CAS
re-applied against the authoritative device rows would double-commit,
and the host harness correlates by msg id instead — the two paths
cannot be calibrated, so the ledger refuses rather than drifting.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax.sharding import NamedSharding, PartitionSpec as P

from . import faults
from .engine import collectives, fori_rounds, jit_program

# Host/device split, DECLARED (PR 6): the determinism lint
# (tpu_sim/audit.py) treats exactly TRACED_EVALUATORS as traced scope.
# tests/test_kvstore.py pins the split TOTAL.
TRACED_EVALUATORS = (
    "owner_of", "rows_view", "cas_apply", "cas_ver_apply",
    "write_apply", "rows_wipe", "stale_coin")
HOST_SIDE = (
    "host_owner_of", "make_layout", "init_rows", "rows_spec",
    "host_stale_coin", "stale_num_of", "reject_dup_stream",
    "audit_contracts")

# distinct stream salts (the faults.py convention): routing and the
# seq-kv stale coin draw independent streams from the same seed
_SALT_ROUTE = 0x4B565F31      # "KV_1"
_SALT_STALE = 0x5EC4C0DE      # the KVService host-rng salt family


class KVLayout(NamedTuple):
    """Host-side static key layout: where every key's row lives.

    ``key_at[i, c]`` is the key hosted at node i, slot c (-1 = empty).
    Baked into traced programs as a replicated constant — each shard
    local-gathers its own rows' keys; the layout never moves at
    runtime (stateless-hash routing, no directory service)."""

    owner: np.ndarray     # (K,) int32 — owning node per key
    slot: np.ndarray      # (K,) int32 — row slot at the owner
    key_at: np.ndarray    # (N, cap) int32 — key per row slot, -1 empty
    n_keys: int
    n_nodes: int
    cap: int
    seed: int


class KVRows(NamedTuple):
    """The device store: one (value, version) register per key row,
    sharded over nodes like every sim state.  Versions start at 0 and
    bump once per committed write — the txn workload's wound-or-die
    CAS compares against them (:func:`cas_ver_apply`)."""

    vals: jnp.ndarray     # (N, cap) int32
    vers: jnp.ndarray     # (N, cap) int32


def host_owner_of(keys: np.ndarray, n_nodes: int,
                  seed: int = 0) -> np.ndarray:
    """(K,) int32 — numpy twin of :func:`owner_of` (op staging and the
    layout builder route with the same hash the device uses)."""
    x = (np.asarray(keys).astype(np.uint32) * np.uint32(0x27D4EB2F)
         ^ np.uint32((seed ^ _SALT_ROUTE) & 0xFFFFFFFF))
    return (faults._mix32_np(x) % np.uint32(n_nodes)).astype(np.int32)


def owner_of(keys: jnp.ndarray, n_nodes: int,
             seed: int = 0) -> jnp.ndarray:
    """(K,) int32 — owning node per key: a stateless ``_mix32`` hash,
    bit-identical to :func:`host_owner_of`."""
    x = (keys.astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
         ^ jnp.uint32((seed ^ _SALT_ROUTE) & 0xFFFFFFFF))
    return (faults._mix32(x) % jnp.uint32(n_nodes)).astype(jnp.int32)


def make_layout(n_keys: int, n_nodes: int, *, seed: int = 0,
                min_cap: int = 1) -> KVLayout:
    """Build the static sharded layout for keys ``0..n_keys-1``:
    stateless-hash owners, per-owner slot ranks, capacity padded to
    the max-loaded owner (``cap`` rows per node, empty slots -1)."""
    keys = np.arange(n_keys, dtype=np.int32)
    owner = host_owner_of(keys, n_nodes, seed)
    slot = np.zeros(n_keys, np.int32)
    counts = np.zeros(n_nodes, np.int32)
    for k in range(n_keys):        # key order: deterministic ranks
        slot[k] = counts[owner[k]]
        counts[owner[k]] += 1
    cap = max(int(min_cap), int(counts.max()) if n_keys else 0)
    key_at = np.full((n_nodes, cap), -1, np.int32)
    key_at[owner, slot] = keys
    return KVLayout(owner=owner, slot=slot, key_at=key_at,
                    n_keys=n_keys, n_nodes=n_nodes, cap=cap,
                    seed=seed)


def init_rows(layout: KVLayout, mesh=None) -> KVRows:
    """All-zero rows (Maelstrom's counter key starts at 0; absent txn
    registers read as (0, version 0)).  vals and vers are DISTINCT
    buffers so the donated drivers can consume the whole pytree."""
    def z():
        arr = jnp.zeros((layout.n_nodes, layout.cap), jnp.int32)
        if mesh is not None:
            from .engine import node_axes

            arr = shard_put(
                arr, NamedSharding(mesh, P(node_axes(mesh), None)))
        return arr

    return KVRows(vals=z(), vers=z())


def rows_spec(mesh=None) -> KVRows:
    """shard_map in/out specs for a :class:`KVRows` operand."""
    if mesh is None:
        return KVRows(vals=None, vers=None)
    from .engine import node_axes

    spec = P(node_axes(mesh), None)
    return KVRows(vals=spec, vers=spec)


# -- traced evaluators ---------------------------------------------------


def rows_view(rows: KVRows, key_at: jnp.ndarray, n_keys: int,
              reduce_sum) -> jnp.ndarray:
    """(2, K) int32 replicated (values row 0, versions row 1): each
    shard scatters its local rows into the key axis, then ONE packed
    ``reduce_sum`` globalizes both planes — the whole read path is a
    single all-reduce, never a gather."""
    occ = key_at >= 0
    idx = jnp.where(occ, key_at, 0).ravel()
    v = jnp.zeros((n_keys,), jnp.int32).at[idx].add(
        jnp.where(occ, rows.vals, 0).ravel())
    r = jnp.zeros((n_keys,), jnp.int32).at[idx].add(
        jnp.where(occ, rows.vers, 0).ravel())
    return reduce_sum(jnp.stack([v, r]))


def cas_apply(rows: KVRows, key_at: jnp.ndarray, on: jnp.ndarray,
              frm: jnp.ndarray, to: jnp.ndarray) -> KVRows:
    """CAS as a masked compare-update: for every key ``k`` with
    ``on[k]``, if the owner row's value equals ``frm[k]`` it becomes
    ``to[k]`` and the version bumps; misses leave the row untouched
    (the caller observes hit/miss through the next round's
    :func:`rows_view`, i.e. one linearization step per round).
    Requests are replicated ``(K,)``; the update is element-wise over
    local rows — zero collectives."""
    occ = key_at >= 0
    idx = jnp.where(occ, key_at, 0)
    hit = occ & on[idx] & (rows.vals == frm[idx])
    return KVRows(vals=jnp.where(hit, to[idx], rows.vals),
                  vers=jnp.where(hit, rows.vers + 1, rows.vers))


def cas_ver_apply(rows: KVRows, key_at: jnp.ndarray, on: jnp.ndarray,
                  ver: jnp.ndarray, val: jnp.ndarray) -> KVRows:
    """Version-compare CAS (the txn workload's commit primitive):
    write ``val[k]`` iff the row's VERSION still equals ``ver[k]`` —
    optimistic concurrency over the per-key version registers.  Same
    masked-update shape as :func:`cas_apply`, zero collectives."""
    occ = key_at >= 0
    idx = jnp.where(occ, key_at, 0)
    hit = occ & on[idx] & (rows.vers == ver[idx])
    return KVRows(vals=jnp.where(hit, val[idx], rows.vals),
                  vers=jnp.where(hit, rows.vers + 1, rows.vers))


def write_apply(rows: KVRows, key_at: jnp.ndarray, on: jnp.ndarray,
                val: jnp.ndarray) -> KVRows:
    """Unconditional masked write (seq-kv ``write``): set and bump
    version, no compare."""
    occ = key_at >= 0
    idx = jnp.where(occ, key_at, 0)
    hit = occ & on[idx]
    return KVRows(vals=jnp.where(hit, val[idx], rows.vals),
                  vers=jnp.where(hit, rows.vers + 1, rows.vers))


def rows_wipe(rows: KVRows, plan, t, row_ids: jnp.ndarray) -> KVRows:
    """Crash amnesia over KV rows (``kv_amnesia=True``): an owner
    restarting this round loses its registers, via the SAME
    :func:`faults.amnesia` coin that wipes node state — the store is
    node state, so it dies like node state."""
    wipe = faults.amnesia(plan, t, row_ids)[:, None]
    return KVRows(vals=jnp.where(wipe, 0, rows.vals),
                  vers=jnp.where(wipe, 0, rows.vers))


def stale_coin(seed, t, ids: jnp.ndarray) -> jnp.ndarray:
    """uint32 per-(round, node) stale-read coin for the seq-kv flavor:
    a read is served stale iff ``stale_coin(...) < stale_num`` (and the
    reader is behind).  Stateless ``_mix32`` hash — bit-identical to
    :func:`host_stale_coin`, so the harness KVService can draw the
    same coins and the two backends retry in lockstep."""
    x = (ids.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
         ^ t.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         ^ jnp.uint32(seed) ^ jnp.uint32(_SALT_STALE))
    return faults._mix32(x)


# -- host twins / knobs --------------------------------------------------


def host_stale_coin(seed: int, t: int, node) -> np.ndarray:
    """numpy twin of :func:`stale_coin` (inject into
    ``KVService(stale_coin_fn=...)`` for the calibration test)."""
    t_term = np.uint32((int(t) * 0x9E3779B9) & 0xFFFFFFFF)
    x = (np.asarray(node, np.int64).astype(np.uint32)
         * np.uint32(0xC2B2AE35)
         ^ t_term ^ np.uint32(seed & 0xFFFFFFFF)
         ^ np.uint32(_SALT_STALE))
    return faults._mix32_np(x)


def stale_num_of(prob: float) -> np.uint32:
    """Probability → uint32 coin threshold (the faults.py rate
    convention)."""
    return faults._rate_to_num(prob)


def reject_dup_stream(fault_plan, where: str) -> None:
    """The still-open half of ROADMAP item 6, refused LOUDLY: a dup
    stream over KV *requests* would re-apply CAS/write batches against
    the authoritative device rows (double-commit), while the host
    harness dedups by msg id — the ledgers cannot be calibrated.
    Raise at sim construction rather than drift silently."""
    if fault_plan is None:
        return
    if int(np.asarray(fault_plan.dup_num)) > 0:
        raise ValueError(
            f"{where}: kv_backend='device' refuses dup streams "
            "(dup_rate > 0) — duplicated KV request delivery against "
            "the authoritative device rows is undefined (a re-applied "
            "CAS double-commits; the host harness correlates by msg "
            "id).  srv-ledger calibration covers loss + crash only "
            "(ROADMAP item 6); use dup_rate=0 with the device "
            "backend.")


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def audit_contracts():
    """The KV store's :class:`~.audit.ProgramContract` rows: the
    sharded CAS step (all-reduce only — the zero-all-gather HLO gate
    over the request/view path) and the donated fused CAS loop (cap-0,
    rows alias in place, analytic memory band)."""
    from .audit import AuditProgram, ProgramContract
    from .engine import analytic_peak_bytes

    def sharded_cas_step(mesh):
        n, k = 32, 24
        layout = make_layout(k, n, seed=3)
        key_at = jnp.asarray(layout.key_at)
        spec = rows_spec(mesh)

        def step(rows, on, frm, to):
            coll = collectives(rows.vals.shape[0], mesh)
            ka = key_at[coll.row_ids]
            rows = cas_apply(rows, ka, on, frm, to)
            return rows, rows_view(rows, ka, k, coll.reduce_sum)

        prog = jit_program(
            step, mesh=mesh,
            in_specs=(spec, P(), P(), P()),
            out_specs=(spec, P()))
        view0 = jnp.zeros((k,), jnp.int32)
        args = (init_rows(layout, mesh), jnp.ones((k,), bool),
                view0, view0 + 7)
        return AuditProgram(prog, args)

    def fused_cas_donated(mesh):
        del mesh
        n, k, rounds = 256, 512, 16
        layout = make_layout(k, n, seed=3)
        key_at = jnp.asarray(layout.key_at)
        coll = collectives(n)

        def run(rows, n_rounds):
            def body(carry):
                rows, t = carry
                view = rows_view(rows, key_at, k, coll.reduce_sum)
                on = jnp.ones((k,), bool)
                rows = cas_apply(rows, key_at, on, view[0],
                                 view[0] + 1)
                return rows, t + 1

            return fori_rounds(body, (rows, jnp.int32(0)), n_rounds)

        prog = jit_program(run, donate_argnums=(0,))
        state_bytes = 2 * n * layout.cap * 4
        analytic = analytic_peak_bytes(state_bytes=state_bytes,
                                       donated=True)
        return AuditProgram(prog, (init_rows(layout), jnp.int32(rounds)),
                            donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="kvstore/sharded-cas-step",
            build=sharded_cas_step,
            collectives={"all-reduce": None},
            notes="sharded key rows, replicated request batch: the "
                  "masked compare-update is element-wise and the read "
                  "view is ONE packed psum — all-reduce only, NO "
                  "all-gather (the tentpole HLO gate)"),
        ProgramContract(
            name="kvstore/fused-cas-donated",
            build=fused_cas_donated,
            collectives={},
            donation=True,
            mem_lo=0.2, mem_hi=4.0,
            needs_mesh=False,
            notes="donated fori CAS loop: the (vals, vers) KV rows "
                  "alias in place; compiled peak within band of 1x "
                  "rows + view/select temps"),
    ]
