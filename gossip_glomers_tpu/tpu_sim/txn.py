"""txn-rw-register: batched multi-key read/write transactions (PR 14).

The sixth workload — Maelstrom's ``txn-rw-register`` challenge on the
device-resident KV store (tpu_sim/kvstore.py).  Each node runs one
client issuing a seeded sequence of transactions; a transaction is a
fixed batch of ``ops_per_txn`` read/write operations over DISTINCT
keys (staged host-side, :func:`stage_txn_ops` — the numpy mirror of
Maelstrom's workload generator).  A :class:`~.traffic.TrafficPlan`
drives arrivals: the node's next transaction slot opens when its
client's seeded arrival coin fires (PR 7's open-loop machinery,
unchanged).

**Wound-or-die via CAS on per-key versions.**  Every round, each live
node with an open transaction claims its key set at priority
``issue_round * N + node`` (older transactions outrank younger — no
starvation; node id breaks ties).  A per-key ``reduce_min`` fold finds
the best claimant of every key; a transaction commits iff it holds ALL
its keys — winners therefore have pairwise-disjoint key sets, so the
round's writes are conflict-free by construction and land through
:func:`kvstore.cas_ver_apply` (compare on the versions the winner
read; nobody else wrote them this round, so every commit CAS hits —
optimistic concurrency whose conflicts were already resolved by the
priority fold).  Losers keep their issue stamp and retry next round,
exactly the reference's failed-CAS → re-read → retry loop.

**Serialization order IS the round order.**  One conflict-free batch
commits per round; transactions serialize by ``(commit_round, node)``
— the same round counter every sim linearizes against, so the
host-side cycle check (:func:`harness.checkers.check_txn_serializable`)
certifies that the device-recorded read/write version graph embeds in
round order.

**Faults compose.**  The FaultPlan gates liveness (a down node's
transaction stalls, its issue stamp survives — retries after restart)
and per-round KV reachability (``kv_drop`` coins); ``kv_amnesia=True``
wipes a restarting owner's registers through the same amnesia coin as
node state, which RESETS versions — a later commit then re-installs an
already-committed (key, version) pair and the checker reports the lost
update loudly (the falsifiable-by-construction direction).  Dup
streams are rejected loudly (ROADMAP item 6; kvstore.reject_dup_stream).

Ledger: charge-at-send — every attempt (active claim, win or lose)
pays ``4 * ops_per_txn`` messages (a read round-trip + a CAS
round-trip per op), whether or not the node dies before the replies.

Provenance rides the state: per-transaction ``issue_round`` (first
attempt) and ``commit_round`` stamps — the causal audit trail
:func:`harness.txn.run_txn_nemesis` folds into its verdict.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import faults, kvstore, traffic
from .engine import (Collectives, collectives, donate_argnums_for,
                     fori_rounds, jit_program, node_axes,
                     resolve_dcn_mode)

# Host/device split, DECLARED (PR 6): tests/test_txn.py pins it total.
# The round body itself is the TxnSim._round method plus the nested
# closures of the _build_* builders and _build_batch_round — all
# covered by the lint's method-root + builder mechanisms
# (tpu_sim/audit.py _TRACED_ROOTS / _BUILDERS).
TRACED_EVALUATORS = ("_batch_converged",)
HOST_SIDE = ("ops_specs", "stage_txn_ops", "history_of",
             "final_registers", "_build_batch_round",
             "audit_contracts")

_INF = 2 ** 31 - 1


class TxnOps(NamedTuple):
    """Host-staged per-node transaction programs, threaded as a traced
    operand (stackable along a leading scenario axis, like the kafka
    send batches): slot ``(i, s)`` is node i's s-th transaction."""

    keys: jnp.ndarray    # (N, T, O) int32 — distinct within a txn
    write: jnp.ndarray   # (N, T, O) bool — op is a write
    wval: jnp.ndarray    # (N, T, O) int32 — value written (unique ids)


class TxnState(NamedTuple):
    rows: kvstore.KVRows          # (N, C) sharded key registers
    arrived: jnp.ndarray          # (N,) int32 — txns offered so far
    cur: jnp.ndarray              # (N,) int32 — current open slot
    issue: jnp.ndarray            # (N,) int32 — open slot's first
                                  #   attempt round (-1 = fresh)
    issue_round: jnp.ndarray      # (N, T) int32 — provenance stamp
    commit_round: jnp.ndarray     # (N, T) int32 — -1 until committed
    op_ver: jnp.ndarray           # (N, T, O) int32 — version read
                                  #   (reads) / installed (writes)
    op_val: jnp.ndarray           # (N, T, O) int32 — value read/written
    t: jnp.ndarray                # () int32
    msgs: jnp.ndarray             # () uint32 — charge-at-send ledger


def ops_specs(axes="nodes") -> TxnOps:
    """shard_map in_specs for the ops operand (node-sharded; ``axes``
    is the sim's ``engine.node_axes`` result)."""
    node3 = P(axes, None, None)
    return TxnOps(node3, node3, node3)


def stage_txn_ops(n_nodes: int, txns_per_node: int, ops_per_txn: int,
                  n_keys: int, seed: int) -> TxnOps:
    """Host-side seeded workload staging (the numpy mirror — the
    device never draws an rng): per slot, ``ops_per_txn`` DISTINCT
    keys, ~half writes (every transaction writes at least one key, so
    each commit moves the version graph), and write values that are
    globally unique ids (``1 + txn_id * O + op``) — value uniqueness
    is what lets the checker tie an observed value to its writer."""
    rng = np.random.default_rng(seed)
    if ops_per_txn > n_keys:
        raise ValueError("ops_per_txn must be <= n_keys (distinct "
                         "keys within a transaction)")
    n, t_dim, o = n_nodes, txns_per_node, ops_per_txn
    keys = np.zeros((n, t_dim, o), np.int32)
    for i in range(n):
        for s in range(t_dim):
            keys[i, s] = rng.choice(n_keys, size=o, replace=False)
    write = rng.random((n, t_dim, o)) < 0.5
    write[:, :, 0] = True
    txn_id = (np.arange(n)[:, None] * t_dim
              + np.arange(t_dim)[None, :])
    wval = (1 + txn_id[:, :, None] * o
            + np.arange(o)[None, None, :]).astype(np.int32)
    return TxnOps(keys=jnp.asarray(keys), write=jnp.asarray(write),
                  wval=jnp.asarray(wval))


class TxnSim:
    """Round-synchronous txn-rw-register simulator over the sharded
    device KV (the workload is kvstore-native; there is no host
    backend to switch away from)."""

    def __init__(self, n_nodes: int, n_keys: int, *,
                 txns_per_node: int = 4, ops_per_txn: int = 2,
                 tspec: "traffic.TrafficSpec | None" = None,
                 rate: float = 0.5, until: int | None = None,
                 mesh: Mesh | None = None, seed: int = 0,
                 workload_seed: int = 0,
                 fault_plan: "faults.FaultPlan | None" = None,
                 kv_amnesia: bool = False,
                 dcn_mode: "str | None" = None) -> None:
        """``tspec``: the arrival driver — one client per node,
        ``ops_per_client == txns_per_node`` (each arrival opens the
        node's next transaction slot).  None builds a Poisson spec
        from ``rate``/``until``/``workload_seed``.  ``workload_seed``
        also seeds :func:`stage_txn_ops`."""
        kvstore.reject_dup_stream(fault_plan, "TxnSim")
        if fault_plan is not None \
                and fault_plan.down.shape[1] != n_nodes:
            raise ValueError(
                f"FaultPlan is for {fault_plan.down.shape[1]} nodes, "
                f"sim has {n_nodes}")
        if tspec is None:
            tspec = traffic.TrafficSpec(
                n_nodes=n_nodes, n_clients=n_nodes,
                ops_per_client=txns_per_node,
                until=(4 * txns_per_node if until is None
                       else until),
                rate=rate, seed=workload_seed)
        if tspec.n_clients != n_nodes:
            raise ValueError("txn workload runs ONE client per node "
                             f"(n_clients={tspec.n_clients}, "
                             f"n_nodes={n_nodes})")
        if tspec.ops_per_client != txns_per_node:
            raise ValueError(
                f"tspec.ops_per_client={tspec.ops_per_client} must "
                f"equal txns_per_node={txns_per_node}")
        self.n_nodes = n_nodes
        self.n_keys = n_keys
        self.txns_per_node = txns_per_node
        self.ops_per_txn = ops_per_txn
        self.tspec = tspec
        self.mesh = mesh
        # -- DCN mode (PR 20): sync (default) or pipelined; the
        # wound-or-die version-CAS winner fold is a reduce_min over
        # live claimants — a k-round-stale winner set would commit
        # wounded transactions, so staleness refuses here.
        self._dcn = resolve_dcn_mode(dcn_mode)
        if self._dcn.stale_k:
            raise ValueError(
                f"dcn_mode={self._dcn.label()!r}: txn has no "
                "certified staleness semantics — the wound-or-die "
                "version-CAS fold (reduce_min over claimant stamps) "
                "must see the current round's claims or wounded "
                "transactions commit; run sync or pipelined")
        self.seed = seed
        self.workload_seed = workload_seed
        self.fault_plan = fault_plan
        self.kv_amnesia = bool(kv_amnesia)
        self.layout = kvstore.make_layout(n_keys, n_nodes, seed=seed)
        self._key_at = jnp.asarray(self.layout.key_at)
        self.ops = stage_txn_ops(n_nodes, txns_per_node, ops_per_txn,
                                 n_keys, workload_seed)
        self._na = node_axes(mesh)
        self._node_spec = P(self._na) if mesh is not None else None
        self._run_progs: dict = {}
        self._step = self._build_step()
        self._run_n = self._build_run_n(donate=False)
        self._run_n_donated = self._build_run_n(donate=True)

    def init_state(self) -> TxnState:
        n, t_dim, o = self.n_nodes, self.txns_per_node, self.ops_per_txn

        def z(shape):
            arr = jnp.zeros(shape, jnp.int32)
            if self.mesh is not None:
                spec = P(self._na, *([None] * (len(shape) - 1)))
                arr = shard_put(
                    arr, NamedSharding(self.mesh, spec))
            return arr

        return TxnState(
            rows=kvstore.init_rows(self.layout, self.mesh),
            arrived=z((n,)), cur=z((n,)), issue=z((n,)) - 1,
            issue_round=z((n, t_dim)) - 1,
            commit_round=z((n, t_dim)) - 1,
            op_ver=z((n, t_dim, o)) - 1,
            op_val=z((n, t_dim, o)) - 1,
            t=jnp.int32(0), msgs=jnp.uint32(0))

    # -- round -------------------------------------------------------------

    def _round(self, state: TxnState, ops: TxnOps, tplan,
               coll: Collectives, plan=None) -> TxnState:
        """One round: arrivals → wound-or-die key claim → winners
        commit (read versions recorded, writes via version-CAS) —
        see the module docstring.  Collectives: ONE per-key
        ``reduce_min`` (the priority fold) + ONE packed ``reduce_sum``
        (the (value, version) view and the winners' write requests
        globalize together) — all-reduce only, no gather (the
        ``txn/sharded-step`` audit contract)."""
        row_ids = coll.row_ids
        rows_n = row_ids.shape[0]
        n, k = self.n_nodes, self.n_keys
        t_dim, o = self.txns_per_node, self.ops_per_txn
        kv = state.rows
        up = jnp.ones((rows_n,), bool)
        if plan is not None:
            if self.kv_amnesia:
                kv = kvstore.rows_wipe(kv, plan, state.t, row_ids)
            up = (faults.node_up(plan, state.t, row_ids)
                  & ~faults.kv_drop(plan, state.t, row_ids))
        ka = self._key_at[row_ids]

        # arrivals: the node's client coin opens the next slot
        arr = traffic.arrive(tplan, state.t, row_ids)
        arrived = jnp.minimum(state.arrived + arr.astype(jnp.int32),
                              jnp.int32(t_dim))
        active = up & (state.cur < arrived)
        issue = jnp.where(active & (state.issue < 0), state.t,
                          state.issue)

        # the open slot's ops
        curc = jnp.clip(state.cur, 0, t_dim - 1)
        sel = curc[:, None, None]
        keys_n = jnp.take_along_axis(ops.keys, sel, axis=1)[:, 0]
        wr_n = jnp.take_along_axis(ops.write, sel, axis=1)[:, 0]
        wv_n = jnp.take_along_axis(ops.wval, sel, axis=1)[:, 0]

        # wound-or-die: per-key best (lowest) priority claim — older
        # transactions outrank younger, node id tie-breaks
        prio = issue * jnp.int32(n) + row_ids
        claim = jnp.where(active[:, None],
                          jnp.broadcast_to(prio[:, None], keys_n.shape),
                          jnp.int32(_INF))
        local_best = jnp.full((k,), _INF, jnp.int32).at[
            keys_n.ravel()].min(claim.ravel())
        best = coll.reduce_min(local_best)
        win = active & jnp.all(best[keys_n] == prio[:, None], axis=1)

        # one packed psum: the (value, version) view + the winners'
        # write requests (winners hold disjoint key sets, so at most
        # one writer contributes per key and scatter-add is exact)
        occ = ka >= 0
        idx = jnp.where(occ, ka, 0).ravel()
        v_loc = jnp.zeros((k,), jnp.int32).at[idx].add(
            jnp.where(occ, kv.vals, 0).ravel())
        r_loc = jnp.zeros((k,), jnp.int32).at[idx].add(
            jnp.where(occ, kv.vers, 0).ravel())
        g = coll.reduce_sum(jnp.stack([v_loc, r_loc]))
        vals_k, vers_k = g[0], g[1]
        rd_val = vals_k[keys_n]                      # (rows, O)
        rd_ver = vers_k[keys_n]
        w_mask = win[:, None] & wr_n
        w_on = jnp.zeros((k,), jnp.int32).at[keys_n.ravel()].add(
            w_mask.astype(jnp.int32).ravel())
        w_val = jnp.zeros((k,), jnp.int32).at[keys_n.ravel()].add(
            jnp.where(w_mask, wv_n, 0).ravel())
        w_ver = jnp.zeros((k,), jnp.int32).at[keys_n.ravel()].add(
            jnp.where(w_mask, rd_ver, 0).ravel())
        req = coll.reduce_sum(jnp.stack([w_on, w_val, w_ver]))
        kv = kvstore.cas_ver_apply(kv, ka, req[0] > 0, req[2], req[1])

        # record the winners' transaction results at their open slot
        ar = jnp.arange(rows_n, dtype=jnp.int32)
        slot_w = jnp.where(win, curc, jnp.int32(t_dim))  # T = drop
        new_ver = jnp.where(wr_n, rd_ver + 1, rd_ver)
        new_val = jnp.where(wr_n, wv_n, rd_val)
        op_ver = state.op_ver.at[ar[:, None], slot_w[:, None],
                                 jnp.arange(o)[None, :]].set(
            new_ver, mode="drop")
        op_val = state.op_val.at[ar[:, None], slot_w[:, None],
                                 jnp.arange(o)[None, :]].set(
            new_val, mode="drop")
        commit_round = state.commit_round.at[ar, slot_w].set(
            state.t, mode="drop")
        first = active & (state.issue < 0)
        slot_f = jnp.where(first, curc, jnp.int32(t_dim))
        issue_round = state.issue_round.at[ar, slot_f].set(
            state.t, mode="drop")

        # charge-at-send: every attempt pays a read + CAS round-trip
        # per op, winners and woundees alike
        attempts = coll.reduce_sum(jnp.sum(active.astype(jnp.uint32),
                                           dtype=jnp.uint32))
        msgs = state.msgs + attempts * jnp.uint32(4 * o)
        return TxnState(
            rows=kv, arrived=arrived,
            cur=state.cur + win.astype(jnp.int32),
            issue=jnp.where(win, jnp.int32(-1), issue),
            issue_round=issue_round, commit_round=commit_round,
            op_ver=op_ver, op_val=op_val,
            t=state.t + 1, msgs=msgs)

    def _state_spec(self) -> TxnState:
        node = self._node_spec
        node2 = (P(self._na, None) if self.mesh is not None
                 else None)
        node3 = (P(self._na, None, None) if self.mesh is not None
                 else None)
        return TxnState(
            rows=kvstore.rows_spec(self.mesh),
            arrived=node, cur=node, issue=node,
            issue_round=node2, commit_round=node2,
            op_ver=node3, op_val=node3, t=P(), msgs=P())

    def _fp_extra(self):
        if self.fault_plan is None:
            return (), ()
        return ((faults.plan_specs(),), (self.fault_plan,))

    def _operand(self):
        return (self.ops, self.tspec.compile())

    def _build_step(self):
        mesh = self.mesh
        fp_specs, fp_args = self._fp_extra()

        def step(state, ops, tplan, *fp):
            coll = (collectives(self.n_nodes) if mesh is None
                    else collectives(state.arrived.shape[0], mesh,
                                     dcn=self._dcn))
            return self._round(state, ops, tplan, coll,
                               fp[0] if fp else None)

        if mesh is None:
            prog = jit_program(step)
        else:
            prog = jit_program(
                step, mesh=mesh,
                in_specs=(self._state_spec(), ops_specs(self._na),
                          traffic.plan_specs()) + fp_specs,
                out_specs=self._state_spec(), check_vma=False)
        return lambda state: prog(state, *self._operand(), *fp_args)

    def _build_run_n(self, donate: bool):
        mesh = self.mesh
        dn = donate_argnums_for(donate, 0)
        fp_specs, fp_args = self._fp_extra()

        def run_n(state, ops, tplan, n_rounds, *fp):
            coll = (collectives(self.n_nodes) if mesh is None
                    else collectives(state.arrived.shape[0], mesh,
                                     dcn=self._dcn))
            plan = fp[0] if fp else None
            return fori_rounds(
                lambda s, op: self._round(s, op[0], op[1], coll,
                                          op[2]),
                state, n_rounds, operand=(ops, tplan, plan))

        if mesh is None:
            prog = jit_program(run_n, donate_argnums=dn)
        else:
            prog = jit_program(
                run_n, mesh=mesh,
                in_specs=(self._state_spec(), ops_specs(self._na),
                          traffic.plan_specs(), P()) + fp_specs,
                out_specs=self._state_spec(), check_vma=False,
                donate_argnums=dn)
        self._run_progs[donate] = (
            prog, lambda state, n: (state,) + self._operand()
            + (n,) + fp_args)
        return lambda state, n: prog(state, *self._operand(), n,
                                     *fp_args)

    def step(self, state: TxnState) -> TxnState:
        return self._step(state)

    def run(self, state: TxnState, n_rounds: int) -> TxnState:
        return self._run_n(state, jnp.int32(n_rounds))

    def run_fused(self, state: TxnState, n_rounds: int) -> TxnState:
        """Donation-first :meth:`run`: bit-identical, state consumed."""
        return self._run_n_donated(state, jnp.int32(n_rounds))

    def audit_run_program(self, *, donate: bool = True,
                          rounds: int = 8):
        """(jitted, example_args) for the contract auditor."""
        prog, args_fn = self._run_progs[donate]
        return prog, args_fn(self.init_state(), jnp.int32(rounds))


# -- host-side extraction ------------------------------------------------


def history_of(state: TxnState, ops: TxnOps) -> list[dict]:
    """The device-recorded transaction history, host-readable: one
    entry per STARTED transaction slot (txn id = ``node * T + slot``),
    ``status`` committed/open, the commit/issue round stamps, and the
    per-op (kind, key, version, value) records the serializability
    checker consumes.  Open transactions carry no op records — their
    effects never landed (wound-or-die losers hold no locks)."""
    cr = np.asarray(state.commit_round)
    ir = np.asarray(state.issue_round)
    ver = np.asarray(state.op_ver)
    val = np.asarray(state.op_val)
    keys = np.asarray(ops.keys)
    write = np.asarray(ops.write)
    n, t_dim = cr.shape
    hist = []
    for i in range(n):
        for s in range(t_dim):
            if ir[i, s] < 0 and cr[i, s] < 0:
                continue
            committed = cr[i, s] >= 0
            entry = {
                "id": int(i * t_dim + s), "node": int(i),
                "slot": int(s),
                "status": "committed" if committed else "open",
                "issue_round": int(ir[i, s]),
                "commit_round": int(cr[i, s]),
                "ops": []}
            if committed:
                for j in range(ver.shape[2]):
                    entry["ops"].append({
                        "kind": "w" if write[i, s, j] else "r",
                        "key": int(keys[i, s, j]),
                        "ver": int(ver[i, s, j]),
                        "val": int(val[i, s, j])})
            hist.append(entry)
    return hist


def final_registers(state: TxnState, layout: kvstore.KVLayout) -> dict:
    """``{key: (value, version)}`` — the store's final registers (the
    checker's zero-lost-acked-commits anchor)."""
    vals = np.asarray(state.rows.vals)
    vers = np.asarray(state.rows.vers)
    out = {}
    for key in range(layout.n_keys):
        i, c = int(layout.owner[key]), int(layout.slot[key])
        out[int(key)] = (int(vals[i, c]), int(vers[i, c]))
    return out


# -- scenario-axis batch hooks (PR 10, tpu_sim/scenario.py) --------------


def _build_batch_round(sim: "TxnSim"):
    """Per-scenario round closure for the scenario-axis batch drivers:
    identity collectives (each scenario's node axis is local under
    scenario sharding), the scenario's own (plan, ops, tplan) as
    traced operands."""
    coll = collectives(sim.n_nodes)

    def rnd(state, plan, ops, tplan):
        return sim._round(state, ops, tplan, coll, plan)
    return rnd


def _batch_converged(state: TxnState) -> jnp.ndarray:
    """() bool, traced — every offered transaction committed.  Checked
    only at/after the scenario's clear round, which the runners pin
    ``>= tspec.until``, so no further arrivals can reopen it."""
    return jnp.all(state.cur >= state.arrived)


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def audit_contracts():
    """The txn workload's :class:`~.audit.ProgramContract` rows: the
    sharded wound-or-die step (all-reduce only — one per-key pmin +
    one packed psum, no gather) and the donated fused run (cap-0,
    state incl. the KV rows aliasing in place, analytic memory
    band)."""
    from .audit import AuditProgram, ProgramContract
    from .engine import analytic_peak_bytes
    from .engine import operand_bytes as engine_operand_bytes

    def sharded_step(mesh):
        spec = faults.NemesisSpec(n_nodes=32, seed=7,
                                  crash=((2, 4, (3,)),),
                                  loss_rate=0.1, loss_until=6)
        sim = TxnSim(32, 16, txns_per_node=4, ops_per_txn=2,
                     mesh=mesh, fault_plan=spec.compile())
        prog = sim._step  # the lambda wraps the jitted program;
        del prog
        fp_specs, fp_args = sim._fp_extra()

        def step(state, ops, tplan, *fp):
            coll = collectives(state.arrived.shape[0], mesh)
            return sim._round(state, ops, tplan, coll,
                              fp[0] if fp else None)

        jitted = jit_program(
            step, mesh=mesh,
            in_specs=(sim._state_spec(), ops_specs(sim._na),
                      traffic.plan_specs()) + fp_specs,
            out_specs=sim._state_spec(), check_vma=False)
        return AuditProgram(
            jitted, (sim.init_state(),) + sim._operand() + fp_args)

    def fused_donated(mesh):
        del mesh
        n, k, t_dim, o = 1024, 256, 8, 2
        sim = TxnSim(n, k, txns_per_node=t_dim, ops_per_txn=o,
                     rate=0.5, until=24)
        prog, args = sim.audit_run_program(donate=True)
        cap = sim.layout.cap
        state_bytes = (2 * n * cap + 3 * n + 2 * n * t_dim
                       + 2 * n * t_dim * o) * 4
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(sim._operand()),
            donated=True)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="txn/sharded-step",
            build=sharded_step,
            collectives={"all-reduce": None},
            notes="wound-or-die round under crash+loss: ONE per-key "
                  "pmin (the priority fold) + packed psums (view + "
                  "write requests) — all-reduce only, NO all-gather "
                  "(the tentpole HLO gate)"),
        ProgramContract(
            name="txn/fused-donated",
            build=fused_donated,
            collectives={},
            donation=True,
            mem_lo=0.2, mem_hi=4.0,
            needs_mesh=False,
            notes="donated fori txn run: the whole TxnState (KV rows "
                  "+ per-txn records) aliases in place; peak within "
                  "band of 1x state + staged-ops operand"),
    ]
