"""Scenario-axis fault-space batching (PR 10): S independent nemesis
campaigns as ONE compiled program.

Every nemesis artifact in this repo is seed-deterministic, stateless-
hash-driven, and JSON-able (faults.NemesisSpec -> FaultPlan; the
loss/dup coins are pure (t, src, dst) hashes), and nothing in a faulted
round depends on host control flow — so a whole *batch* of fault
campaigns vmaps: the per-scenario FaultPlans (and partition schedules,
and per-edge delay matrices) are padded to common window counts and
STACKED leaf-by-leaf with a leading scenario axis (faults.batch_plans /
:func:`batch_partitions`), and ``jax.vmap`` of the ordinary gather-path
round slices them back into per-scenario operands.  One dispatch then
runs hundreds of crash x loss x dup x partition x delay campaigns —
the scenario-diversity multiplier no process-per-node harness
(Maelstrom included) can imitate: coverage goes from "27 cells" to
"the fault space" (benchmarks/fault_sweep.py ``--fuzz``,
harness/fuzz.py).

**Placement** (engine.scenario_placement): with a mesh and S a
multiple of the device count, the SCENARIO axis is sharded over the
mesh — each device runs S/devices whole scenarios with identity
collectives, so the compiled batch program contains ZERO collective
ops (cap-0 census rows in :func:`audit_contracts`).  Smaller or uneven
batches pad up with inert filler scenarios (:func:`pad_batch`) rather
than shard the node axis: the fuzzer's unit of work is the scenario.

**Certification without host round-trips**: the per-scenario driver
(:func:`certify_loop`) is a check-then-step ``fori_loop`` that records
each scenario's FIRST converged round on device and then FREEZES the
scenario (a per-scenario ``where`` select), reproducing the sequential
``run_*_nemesis`` loop — which stops stepping at convergence —
BIT-EXACTLY: final state, msgs ledgers, converged rounds, and (when a
ring rides the carry) the telemetry series all match the
single-scenario runners (tests/test_scenario.py, single-device and
8-way mesh).  The batched outputs are tiny per-scenario rows
(converged round, msgs at clear, final ledger) plus the stacked final
states — ONE host transfer after the dispatch, nothing per scenario
in the hot loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import broadcast as B
from . import counter as CT
from . import faults, kafka as KF, telemetry, traffic
from . import txn as TX
from .engine import (host_view, node_axes, node_shards,
                     resolve_dcn_mode, scenario_placement,
                     scenario_program)


def _refuse_stale_dcn(where: str, runner_kw: dict | None = None):
    """PR-20 staleness gate for the batch dispatchers: every scenario
    /serving cell runs its node axis LOCALLY under scenario sharding
    (identity collectives — pipelining is inert here and sync rows
    stay bit-identical), but a bounded-staleness request has no
    per-scenario carry to ride, so it must refuse loudly instead of
    silently running sync.  Checks the explicit ``runner_kw`` mode
    first, then the ``GG_DCN_STALE_K`` environment contract."""
    setting = (runner_kw or {}).get("dcn_mode")
    mode = resolve_dcn_mode(setting)
    if mode.stale_k:
        raise ValueError(
            f"dcn_mode={mode.label()!r}: {where} runs every "
            "scenario's node axis locally under scenario sharding — "
            "there is no DCN level inside a cell and no staleness "
            "carry threaded through the batch program, so bounded "
            "staleness is undecided here; run the batch sync or "
            "pipelined (or unset GG_DCN_STALE_K)")

# The module's host/device split, DECLARED (the PR-6 faults.py
# pattern): the determinism lint (tpu_sim/audit.py) treats exactly
# TRACED_EVALUATORS as traced scope; tests/test_scenario.py pins the
# split TOTAL.  `_build_batch_program`'s nested defs are traced via
# the builder mechanism (audit._BUILDERS); the `_dispatch_*_batch` /
# `dispatch_serving_batch` builders carry the traced `one`/`step1`
# closures and are matched by the same mechanism.
TRACED_EVALUATORS = ("certify_loop", "serving_loop", "signature_eval")
HOST_SIDE = (
    "batch_partitions", "pad_batch", "stack_pytrees", "stage_kafka_batch",
    "run_broadcast_batch", "run_counter_batch", "run_kafka_batch",
    "run_scenario_batch", "batch_state_bytes", "audit_contracts",
    "_build_batch_program", "_place", "_verdict_rows",
    "_audit_program",
    "_dispatch_broadcast_batch", "_collect_broadcast_batch",
    "_dispatch_counter_batch", "_collect_counter_batch",
    "_dispatch_kafka_batch", "_collect_kafka_batch",
    "_dispatch_txn_batch", "_collect_txn_batch", "run_txn_batch",
    "dispatch_scenario_batch", "collect_scenario_batch",
    "dispatch_serving_batch", "collect_serving_batch",
    "run_serving_batch", "serving_state_bytes",
    "pad_serving_batch", "_serving_common", "_serving_sig",
    "_sig_setup", "_replicated_out", "_refuse_stale_dcn")


# -- scenario cases ------------------------------------------------------


@dataclass(frozen=True)
class Scenario:
    """One cell of the fault space — JSON-able, seed-deterministic.

    ``spec`` is the crash/loss/dup nemesis; ``parts`` an optional
    partition-schedule meta dict (broadcast only, the
    ``Partitions.to_meta`` shape); ``delays`` an optional (N, D)
    per-edge delay matrix as nested lists (broadcast gather path
    only); ``workload_seed`` seeds the kafka send staging."""

    spec: faults.NemesisSpec
    parts: dict | None = None
    delays: tuple | None = None
    workload_seed: int = 0

    def __post_init__(self) -> None:
        if self.delays is not None:
            object.__setattr__(
                self, "delays",
                tuple(tuple(int(v) for v in row)
                      for row in self.delays))

    def to_meta(self) -> dict:
        return {"spec": self.spec.to_meta(), "parts": self.parts,
                "delays": (None if self.delays is None
                           else [list(r) for r in self.delays]),
                "workload_seed": self.workload_seed}

    @staticmethod
    def from_meta(meta: dict) -> "Scenario":
        return Scenario(
            spec=faults.NemesisSpec.from_meta(meta["spec"]),
            parts=meta.get("parts"),
            delays=(None if meta.get("delays") is None
                    else tuple(tuple(r) for r in meta["delays"])),
            workload_seed=int(meta.get("workload_seed", 0)))


@dataclass(frozen=True)
class ScenarioBatch:
    """S scenarios + the static run shape they share — JSON-able
    (:meth:`to_meta`), dispatched by :func:`run_scenario_batch`.
    ``runner_kw`` holds the per-workload static knobs (broadcast:
    ``n_values``/``topology``/``sync_every``; counter: ``mode``/
    ``poll_every``; kafka: ``n_keys``/``capacity``/``max_sends``/
    ``resync_every``/``rounds``/``send_prob``)."""

    workload: str
    scenarios: tuple = field(default_factory=tuple)
    runner_kw: dict = field(default_factory=dict)
    max_recovery_rounds: int = 64

    def __post_init__(self) -> None:
        if self.workload not in ("broadcast", "counter", "kafka",
                                 "txn"):
            raise ValueError(
                f"unknown scenario workload {self.workload!r}")
        if not self.scenarios:
            raise ValueError("a ScenarioBatch needs >= 1 scenario")
        object.__setattr__(self, "scenarios", tuple(
            sc if isinstance(sc, Scenario) else Scenario(spec=sc)
            for sc in self.scenarios))
        n = self.scenarios[0].spec.n_nodes
        for sc in self.scenarios:
            if sc.spec.n_nodes != n:
                raise ValueError(
                    "scenario batch mixes node counts "
                    f"{n} and {sc.spec.n_nodes}")

    @property
    def n_nodes(self) -> int:
        return self.scenarios[0].spec.n_nodes

    def to_meta(self) -> dict:
        return {"workload": self.workload,
                "scenarios": [sc.to_meta() for sc in self.scenarios],
                "runner_kw": dict(self.runner_kw),
                "max_recovery_rounds": self.max_recovery_rounds}

    @staticmethod
    def from_meta(meta: dict) -> "ScenarioBatch":
        return ScenarioBatch(
            workload=str(meta["workload"]),
            scenarios=tuple(Scenario.from_meta(m)
                            for m in meta["scenarios"]),
            runner_kw=dict(meta.get("runner_kw", {})),
            max_recovery_rounds=int(meta.get("max_recovery_rounds",
                                             64)))


def pad_batch(batch: ScenarioBatch, multiple: int) -> tuple:
    """(padded batch, n_real): pad the scenario list up to a multiple
    of ``multiple`` with inert fault-free filler scenarios (zero-rate,
    windowless — they converge immediately and are dropped from the
    results), so a mesh can always take scenario placement
    (engine.scenario_placement)."""
    s = len(batch.scenarios)
    if multiple <= 1 or s % multiple == 0:
        return batch, s
    pad = multiple - s % multiple
    filler = Scenario(spec=faults.NemesisSpec(n_nodes=batch.n_nodes))
    has_delays = any(sc.delays is not None for sc in batch.scenarios)
    if has_delays:
        d0 = next(sc.delays for sc in batch.scenarios
                  if sc.delays is not None)
        ones = tuple(tuple(1 for _ in row) for row in d0)
        filler = Scenario(spec=filler.spec, delays=ones)
    return ScenarioBatch(
        workload=batch.workload,
        scenarios=batch.scenarios + (filler,) * pad,
        runner_kw=batch.runner_kw,
        max_recovery_rounds=batch.max_recovery_rounds), s


# -- batched operands ----------------------------------------------------


def batch_partitions(metas, n_nodes: int) -> B.Partitions:
    """Pad + stack per-scenario partition schedules (None = no
    windows) into one batched :class:`~.broadcast.Partitions` with a
    leading scenario axis.  Pad windows are never-active ``[0, 0)``
    with an all-zero group row — the same padding semantics as
    faults.pad_plan (bit-identical evaluation)."""
    parts = [B.Partitions.none(n_nodes) if m is None
             else B.Partitions.from_meta(m) for m in metas]
    p_max = max(int(p.starts.shape[0]) for p in parts)
    if p_max == 0:
        z = jnp.zeros((len(parts), 0), jnp.int32)
        return B.Partitions(z, z, jnp.zeros(
            (len(parts), 0, n_nodes), jnp.int8))

    def pad(p: B.Partitions) -> B.Partitions:
        c = int(p.starts.shape[0])
        if c == p_max:
            return p
        extra = p_max - c
        return B.Partitions(
            jnp.concatenate([p.starts,
                             jnp.zeros((extra,), jnp.int32)]),
            jnp.concatenate([p.ends, jnp.zeros((extra,), jnp.int32)]),
            jnp.concatenate([p.group, jnp.zeros((extra, n_nodes),
                                                jnp.int8)], axis=0))

    parts = [pad(p) for p in parts]
    return B.Partitions(*(jnp.stack([p[i] for p in parts])
                          for i in range(3)))


def stack_pytrees(trees):
    """Stack a list of identically-structured pytrees leaf-by-leaf
    along a new leading scenario axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def stage_kafka_batch(batch: ScenarioBatch, rounds: int, *,
                      n_keys: int, max_sends: int,
                      send_prob: float, quiesce: int = 0) -> tuple:
    """(S, R, N, Smax) send batches for a kafka scenario batch —
    per scenario EXACTLY the vectorized commit-free staging of
    harness.nemesis.stage_kafka_ops (same rng call order, so the
    sequential runner replays the identical campaign), padded with -1
    no-op rounds from the scenario's own clear round to the common
    horizon ``rounds`` (a padded round stages nothing — the same
    empty batch the sequential recovery loop drives).  ``quiesce``
    is the leaving-node drain margin (PR 17) — forwarded verbatim."""
    from ..harness.nemesis import stage_kafka_ops

    sks_all, svs_all = [], []
    for sc in batch.scenarios:
        r_s = max(sc.spec.clear_round,
                  int(batch.runner_kw.get("rounds") or 0))
        sks, svs, _crs = stage_kafka_ops(
            sc.spec, r_s, n_keys=n_keys, max_sends=max_sends,
            send_prob=send_prob, workload_seed=sc.workload_seed,
            commits=False, quiesce=quiesce)
        if r_s < rounds:
            pad = rounds - r_s
            n = sc.spec.n_nodes
            sks = np.concatenate(
                [sks, np.full((pad, n, max_sends), -1, np.int32)])
            svs = np.concatenate(
                [svs, np.zeros((pad, n, max_sends), np.int32)])
        sks_all.append(sks)
        svs_all.append(svs)
    return (jnp.asarray(np.stack(sks_all)),
            jnp.asarray(np.stack(svs_all)))


# -- the per-scenario certification driver (traced) ----------------------


def certify_loop(step1, conv, state, clear, max_rec: int,
                 r_total: int, tel=None, tel_row=None, tel_mask=None):
    """ONE scenario's whole campaign as a fixed-trip ``fori_loop``
    (traced; vmapped over the scenario axis by the batch programs):

    - before each round, if the scenario is past its own clear round
      and not yet converged, test convergence and record the FIRST
      converged round (`conv_round`; -1 = never within bound);
    - record ``msgs`` when ``t == clear`` (the faulted-phase ledger
      check_recovery's degraded-throughput ratio needs);
    - step only while ACTIVE (not converged, not past
      ``clear + max_rec``) — a frozen scenario carries its final state
      unchanged, which is exactly where the sequential
      ``run_*_nemesis`` loop stops stepping, so the batched final
      state is bit-identical to the sequential one;
    - with a telemetry ring (``tel``), record each ACTIVE round's row
      (``tel_row(s0, s1)``) — frozen scenarios stop recording, like
      the sequential observed drivers stop stepping.

    Returns ``(state, conv_round, msgs_at_clear[, tel])``.
    """
    bound = clear + jnp.int32(max_rec)

    def check(st, cr):
        done_now = (st.t >= clear) & (cr < 0) & conv(st)
        return jnp.where(done_now, st.t, cr)

    def freeze(active, new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(active, a, b), new, old)

    if tel is None:
        def body(i, carry):
            st, cr, mc = carry
            cr = check(st, cr)
            mc = jnp.where(st.t == clear, st.msgs, mc)
            active = (cr < 0) & (st.t < bound)
            st = freeze(active, step1(st, i), st)
            return (st, cr, mc)

        st, cr, mc = lax.fori_loop(
            0, r_total, body, (state, jnp.int32(-1), jnp.uint32(0)))
        return st, check(st, cr), mc

    def body_tel(i, carry):
        st, cr, mc, tl = carry
        cr = check(st, cr)
        mc = jnp.where(st.t == clear, st.msgs, mc)
        active = (cr < 0) & (st.t < bound)
        s2 = step1(st, i)
        tl = freeze(active,
                    telemetry.record(tl, st.t, tel_row(st, s2),
                                     tel_mask), tl)
        st = freeze(active, s2, st)
        return (st, cr, mc, tl)

    st, cr, mc, tl = lax.fori_loop(
        0, r_total, body_tel,
        (state, jnp.int32(-1), jnp.uint32(0), tel))
    return st, check(st, cr), mc, tl


# -- batch program construction ------------------------------------------

# compiled batch programs, keyed by the full static shape (workload,
# scenario count, state shapes, trip count, telemetry spec, mesh)
_PROGS: dict = {}


def _place(args, mesh):
    """Device-put every batched operand with its scenario sharding
    (leading axis over the mesh's device axis) when scenario placement
    applies; no-op off mesh.  (Donation is the program's concern —
    _build_batch_program's donate_argnums.)"""
    s = jax.tree_util.tree_leaves(args[0])[0].shape[0]
    if scenario_placement(s, mesh) == "single":
        return args
    sh = NamedSharding(mesh, P(node_axes(mesh)))
    return tuple(
        jax.tree_util.tree_map(lambda x: shard_put(x, sh), a)
        for a in args)


def _build_batch_program(workload: str, per_scenario, example_args,
                         mesh, donate_argnums, key):
    """Build (or fetch) the ONE compiled program of a batch shape:
    ``jax.vmap`` of the per-scenario certify driver, scenario-sharded
    via engine.scenario_program.  Cached so a fuzz sweep reuses one
    compiled program across every batch of the same shape."""
    full_key = (workload, key, id(mesh),
                jax.tree_util.tree_structure(example_args),
                tuple((tuple(leaf.shape), str(leaf.dtype))
                      for leaf in
                      jax.tree_util.tree_leaves(example_args)))
    if full_key not in _PROGS:
        _PROGS[full_key] = scenario_program(
            per_scenario, example_args, mesh=mesh,
            donate_argnums=donate_argnums)
    return _PROGS[full_key]


def _replicated_out(out):
    """A dispatched batch's outputs, pulled to host when the mesh
    spans processes (PR 15): every certify/collect read below is a
    host-side numpy consumer, and a cross-process shard cannot be
    fetched directly — ``engine.host_view`` replicates each leaf
    first.  Single-process dispatches pass through untouched, so the
    returned ``final`` pytree keeps its device arrays there."""
    leaves = jax.tree_util.tree_leaves(out)
    if not any(isinstance(leaf, jax.Array)
               and not leaf.is_fully_addressable for leaf in leaves):
        return out
    return jax.tree_util.tree_map(
        lambda x: (host_view(x) if isinstance(x, jax.Array) else x),
        out)


def _verdict_rows(batch: ScenarioBatch, conv_round, msgs_clear,
                  msgs_final, lost_lists, extra=None) -> dict:
    """Assemble the batch result: per-scenario verdict rows via the
    batched recovery certifier (checkers.check_recovery_batch — a
    single planted bad scenario fails loudly and names its index)."""
    from ..harness.checkers import check_recovery_batch

    clears = np.array([sc.spec.clear_round
                       for sc in batch.scenarios], np.int64)
    ok, det = check_recovery_batch(
        clear_rounds=clears,
        converged_rounds=np.asarray(conv_round, np.int64),
        max_recovery_rounds=batch.max_recovery_rounds,
        lost_writes=lost_lists,
        msgs_at_clear=np.asarray(msgs_clear, np.int64),
        msgs_at_converged=np.asarray(msgs_final, np.int64))
    rows = []
    for i, sc in enumerate(batch.scenarios):
        row = dict(det["scenarios"][i])
        row.update(workload=batch.workload, scenario=i,
                   spec=sc.spec.to_meta(),
                   msgs_total=int(np.asarray(msgs_final)[i]))
        if sc.parts is not None:
            row["parts"] = sc.parts
        if sc.delays is not None:
            row["delays"] = [list(r) for r in sc.delays]
        if extra is not None:
            row.update(extra[i])
        rows.append(row)
    return {"ok": ok, "workload": batch.workload,
            "n_scenarios": len(rows),
            "failing": det["failing"], "scenarios": rows}


# -- per-workload batch drivers ------------------------------------------


def _sig_setup(telemetry_spec, r_total: int, extra_series=()):
    """Host-side validation + column lookup shared by the batch
    dispatchers when ``signatures=True``: the ring is the signature's
    only source, so it must exist, cover the whole horizon (no wrap —
    row t IS round t), and record every column the evaluator reads."""
    if telemetry_spec is None:
        raise ValueError(
            "signatures=True needs a telemetry_spec — the behavioral "
            "signature is derived from the telemetry ring (no new "
            "host callbacks)")
    if telemetry_spec.rounds < r_total:
        raise ValueError(
            f"signature ring must cover the whole horizon without "
            f"wrapping: rounds={telemetry_spec.rounds} < "
            f"r_total={r_total}")
    cols = telemetry.signature_columns(telemetry_spec)
    missing = [s for s in extra_series
               if s not in telemetry_spec.series]
    if missing:
        raise ValueError(
            f"behavioral signatures for workload "
            f"{telemetry_spec.workload!r} also need series {missing} "
            f"recorded; got series={list(telemetry_spec.series)}")
    return cols


def signature_eval(tel, conv_round, clear, bp_class,
                   msgs_col: int, progress_col: int,
                   churn=0) -> jnp.ndarray:
    """One scenario's (5,) int32 behavioral signature (traced; vmapped
    by the batch programs next to the certify/serving drivers):

    ``[stall_bucket, depth_bucket, bp_class, recovery_bucket,
    churn_bucket]``

    - stall: log2 bucket of the FIRST pre-convergence round whose msgs
      ledger went quiet (``telemetry.ring_stall_round`` — the
      first-divergence round);
    - depth: log2 bucket of the LAST round the workload's progress
      gauge still moved (``telemetry.ring_progress_depth`` — the
      provenance critical-path depth, ring-derived);
    - bp_class: the caller's dominant backpressure class (a small
      workload-specific int — see the dispatchers);
    - recovery: log2 bucket of ``conv_round - clear`` (127 = never
      converged within bound — its own coverage cell);
    - churn (PR 17): log2 bucket of the membership event count the
      scenario's plan carries (``faults.plan_churn`` — joins +
      leaves; 0 for a membership-free plan), so the adaptive fuzzer's
      coverage map separates churn shapes.

    Everything reads the ring + scalars the run already carries: ZERO
    extra collectives, ZERO host callbacks."""
    stall = telemetry.ring_stall_round(tel.ring, tel.wrote, msgs_col,
                                       conv_round)
    depth = telemetry.ring_progress_depth(tel.ring, tel.wrote,
                                          progress_col)
    cr = jnp.asarray(conv_round, jnp.int32)
    rec_b = jnp.where(
        cr >= 0,
        telemetry.log2_bucket(jnp.maximum(cr - clear, 0)),
        jnp.int32(127))
    churn_b = telemetry.log2_bucket(jnp.asarray(churn, jnp.int32))
    return jnp.stack([telemetry.log2_bucket(stall),
                      telemetry.log2_bucket(depth),
                      jnp.asarray(bp_class, jnp.int32), rec_b,
                      churn_b])


def _dispatch_broadcast_batch(batch: ScenarioBatch, *, mesh=None,
                              telemetry_spec=None,
                              signatures: bool = False,
                              n_windows: int | None = None,
                              min_rounds: int = 0) -> dict:
    """Stage + enqueue S broadcast campaigns (the device half of
    :func:`run_broadcast_batch`).  Returns the async handle
    :func:`_collect_broadcast_batch` finishes — JAX async dispatch
    means the device executes while the host moves on (the pipelined
    fuzzer overlaps collect(i) with dispatch(i+1)).

    ``signatures`` (PR 13) appends the per-scenario (5,) behavioral
    signature (:func:`signature_eval`; requires ``telemetry_spec``
    with an unwrapped ring).  ``n_windows`` pads every FaultPlan to a
    fixed crash-window count and ``min_rounds`` floors the trip count
    — the shape-bucket knobs that let one compiled program serve many
    campaigns (extra frozen trips are no-ops: certify_loop is
    clear-driven)."""
    kw = batch.runner_kw
    n = batch.n_nodes
    nv = int(kw.get("n_values") or 2 * n)
    topology = kw.get("topology", "grid")
    sync_every = int(kw.get("sync_every", 4))
    from ..parallel.topology import grid, to_padded_neighbors, tree
    nbrs_np = to_padded_neighbors(
        {"grid": grid, "tree": tree}[topology](n))
    nbrs = jnp.asarray(nbrs_np, jnp.int32)
    nbr_mask = jnp.asarray(nbrs_np >= 0)

    scs = batch.scenarios
    s_count = len(scs)
    dup_on = any(sc.spec.dup_rate > 0 for sc in scs)
    has_mem = any(sc.spec.has_membership for sc in scs)
    has_delays = any(sc.delays is not None for sc in scs)
    if has_delays:
        dmats = []
        for sc in scs:
            d = (np.asarray(sc.delays, np.int32)
                 if sc.delays is not None
                 else np.ones(nbrs_np.shape, np.int32))
            if d.shape != nbrs_np.shape:
                raise ValueError(
                    f"scenario delays shape {d.shape} != adjacency "
                    f"{nbrs_np.shape}")
            dmats.append(np.where(nbrs_np >= 0, d, 1))
        delay_set = tuple(int(v) for v in
                          np.unique(np.stack(dmats)))
        delays_b = jnp.asarray(np.stack(dmats))
        ring = max(delay_set)
    else:
        delay_set, delays_b, ring = (), None, 0

    plans = faults.batch_plans([sc.spec for sc in scs], n_windows)
    parts_b = batch_partitions([sc.parts for sc in scs], n)
    clears = jnp.asarray(
        np.array([sc.spec.clear_round for sc in scs], np.int32))
    max_clear = int(np.max(np.asarray(clears)))
    r_total = max(max_clear + batch.max_recovery_rounds,
                  int(min_rounds))

    # values are acked where they are INJECTED: a non-founding row
    # (pre-join, PR 17) stages nothing, so its round-robin values are
    # simply never offered in that scenario and its target shrinks
    # accordingly (membership-free scenarios: founding = everyone,
    # bit-identical to the unmasked staging)
    founding = np.stack([sc.spec.host_members(0) for sc in scs])
    inject = B.make_inject(n, nv)
    injs_np = np.where(founding[:, :, None],
                       inject.astype(np.uint32)[None], np.uint32(0))
    targets_np = np.bitwise_or.reduce(injs_np, axis=1)   # (S, W)
    targets = jnp.asarray(targets_np)

    def one_state(i):
        rec = jnp.asarray(injs_np[i])
        hist = (jnp.zeros((ring, n, B.num_words(nv)), jnp.uint32)
                if has_delays else None)
        return B.BroadcastState(received=rec, frontier=jnp.copy(rec),
                                t=jnp.int32(0), msgs=jnp.uint32(0),
                                history=hist, srv_msgs=None)

    states = stack_pytrees([one_state(i) for i in range(s_count)])
    rnd = B._build_batch_round(nbrs, nbr_mask, sync_every=sync_every,
                               dup_on=dup_on, delay_set=delay_set)
    tl = telemetry_spec is not None
    tel_mask = telemetry_spec.static_mask if tl else None
    sim = (B.BroadcastSim(nbrs_np, n_values=nv, sync_every=sync_every,
                          srv_ledger=False) if tl else None)
    if signatures:
        ms_col, pg_col = _sig_setup(telemetry_spec, r_total)
        kn_col = telemetry_spec.names.index("known_bits")

    def sig_of(res, clear, churn):
        if not signatures:
            return res
        st, cr, mc, tlf = res
        last = jnp.maximum(jnp.minimum(
            tlf.wrote.astype(jnp.int32),
            jnp.int32(telemetry_spec.rounds)) - 1, 0)
        known = tlf.ring[last, kn_col].astype(jnp.int32)
        bp = telemetry.log2_bucket(
            jnp.maximum(jnp.int32(n * nv) - known, 0))
        return st, cr, mc, tlf, signature_eval(tlf, cr, clear, bp,
                                               ms_col, pg_col, churn)

    def conv_of(plan, clear, target):
        member = (faults.member_at(plan, clear, jnp.arange(n))
                  if has_mem else None)
        return lambda st: B._batch_converged(st, target, member)

    if has_delays:
        def one(state, plan, parts, delays, clear, target, *tel_a):
            step1 = lambda st, i: rnd(st, plan, parts,  # noqa: E731
                                      delays)
            conv = conv_of(plan, clear, target)
            row = ((lambda s0, s1: sim._tel_series(
                s0, s1, plan, lambda x: x)) if tl else None)
            return sig_of(certify_loop(
                step1, conv, state, clear,
                batch.max_recovery_rounds, r_total,
                tel_a[0] if tl else None, row, tel_mask), clear,
                faults.plan_churn(plan))

        args = [states, plans, parts_b, delays_b, clears, targets]
    else:
        def one(state, plan, parts, clear, target, *tel_a):
            step1 = lambda st, i: rnd(st, plan, parts)  # noqa: E731
            conv = conv_of(plan, clear, target)
            row = ((lambda s0, s1: sim._tel_series(
                s0, s1, plan, lambda x: x)) if tl else None)
            return sig_of(certify_loop(
                step1, conv, state, clear,
                batch.max_recovery_rounds, r_total,
                tel_a[0] if tl else None, row, tel_mask), clear,
                faults.plan_churn(plan))

        args = [states, plans, parts_b, clears, targets]
    dn = (0,) + ((len(args),) if tl else ())
    if tl:
        args.append(stack_pytrees(
            [telemetry.init_state(telemetry_spec)
             for _ in range(s_count)]))
    args = _place(tuple(args), mesh)
    prog = _build_batch_program(
        "broadcast", one, args, mesh, dn,
        key=(n, nv, topology, sync_every, s_count, r_total, dup_on,
             delay_set, int(plans.starts.shape[1]),
             int(parts_b.starts.shape[1]), telemetry_spec,
             signatures, has_mem))
    out = prog(*args)
    return {"out": out, "batch": batch,
            "telemetry_spec": telemetry_spec, "signatures": signatures,
            "n": n, "nv": nv, "topology": topology,
            "targets_np": targets_np}


def _collect_broadcast_batch(handle: dict) -> dict:
    """Block on + certify a dispatched broadcast batch (the host half
    of :func:`run_broadcast_batch`)."""
    out = _replicated_out(handle["out"])
    batch = handle["batch"]
    telemetry_spec = handle["telemetry_spec"]
    n, nv = handle["n"], handle["nv"]
    s_count = len(batch.scenarios)
    tl = telemetry_spec is not None
    final, conv_round, msgs_clear = out[0], out[1], out[2]
    rec = np.asarray(final.received)                  # (S, N, W)
    # evidence is member-scoped (PR 17): a value survives iff some
    # row that is STILL A MEMBER at the scenario's clear round holds
    # it, and only values actually acked (present in the scenario's
    # founding-masked target) can be lost.  Membership-free scenarios
    # reduce to the original all-rows / all-values check.
    members = np.stack([sc.spec.host_members(sc.spec.clear_round)
                        for sc in batch.scenarios])   # (S, N)
    targets_np = handle["targets_np"]                 # (S, W)
    anywhere = np.bitwise_or.reduce(
        np.where(members[:, :, None], rec, 0), axis=1)  # (S, W)
    lost_lists = [
        [v for v in range(nv)
         if ((targets_np[i, v // 32] >> (v % 32)) & 1)
         and not (anywhere[i, v // 32] >> (v % 32)) & 1]
        for i in range(s_count)]
    res = _verdict_rows(batch, conv_round, msgs_clear,
                        np.asarray(final.msgs), lost_lists)
    res.update(n_nodes=n, n_values=nv, topology=handle["topology"],
               final=final)
    if tl:
        res["telemetry"] = [
            telemetry.series_arrays(
                jax.tree_util.tree_map(lambda x, i=i: x[i], out[3]),
                telemetry_spec)
            for i in range(s_count)]
    if handle["signatures"]:
        res["signatures"] = np.asarray(out[4])
    return res


def run_broadcast_batch(batch: ScenarioBatch, *, mesh=None,
                        telemetry_spec=None, signatures: bool = False,
                        n_windows: int | None = None,
                        min_rounds: int = 0) -> dict:
    """S broadcast campaigns in ONE dispatch: values injected
    round-robin at round 0, per-scenario convergence = every node
    holds every value, lost acked writes = values absent from every
    node at the scenario's own stop round.  The fault space per
    scenario: crash/loss/dup (``spec``) x partition windows
    (``parts``) x per-edge delays (``delays`` — static delay classes,
    the history-ring gather path).  Returns the batch verdict dict
    (see :func:`_verdict_rows`) plus per-scenario telemetry series
    when ``telemetry_spec`` rides along and the (S, 4) behavioral
    signature matrix with ``signatures``."""
    return _collect_broadcast_batch(_dispatch_broadcast_batch(
        batch, mesh=mesh, telemetry_spec=telemetry_spec,
        signatures=signatures, n_windows=n_windows,
        min_rounds=min_rounds))


def _dispatch_counter_batch(batch: ScenarioBatch, *, mesh=None,
                            telemetry_spec=None,
                            signatures: bool = False,
                            n_windows: int | None = None,
                            min_rounds: int = 0) -> dict:
    """Stage + enqueue S g-counter campaigns; see
    :func:`_dispatch_broadcast_batch` for the dispatch/collect and
    signature/shape-bucket contracts."""
    kw = batch.runner_kw
    n = batch.n_nodes
    mode = kw.get("mode", "cas")
    poll_every = int(kw.get("poll_every", 2))
    scs = batch.scenarios
    s_count = len(scs)
    has_mem = any(sc.spec.has_membership for sc in scs)
    sim = CT.CounterSim(n, mode=mode, poll_every=poll_every)
    deltas = np.arange(1, n + 1, dtype=np.int32)
    # deltas are acked where they are STAGED: a non-founding row
    # (pre-join, PR 17) stages nothing, so each scenario's acked sum
    # is its founding rows' deltas (membership-free: everyone)
    founding = np.stack([sc.spec.host_members(0) for sc in scs])
    deltas_s = np.where(founding, deltas[None],
                        0).astype(np.int32)           # (S, N)
    ackeds_np = deltas_s.sum(axis=1)                  # (S,)
    acked_sum = int(deltas.sum())

    plans = faults.batch_plans([sc.spec for sc in scs], n_windows)
    clears = jnp.asarray(
        np.array([sc.spec.clear_round for sc in scs], np.int32))
    r_total = max(int(np.max(np.asarray(clears)))
                  + batch.max_recovery_rounds, int(min_rounds))

    def one_state(i):
        st = sim.init_state()
        return st._replace(pending=st.pending
                           + jnp.asarray(deltas_s[i]))

    states = stack_pytrees([one_state(i) for i in range(s_count)])
    rnd = CT._build_batch_round(sim)
    tl = telemetry_spec is not None
    tel_mask = telemetry_spec.static_mask if tl else None
    from .engine import collectives
    coll = collectives(n)
    if signatures:
        ms_col, pg_col = _sig_setup(telemetry_spec, r_total,
                                    extra_series=("pending_total",))
        pd_col = telemetry_spec.names.index("pending_total")

    def sig_of(res, clear, acked, churn):
        if not signatures:
            return res
        st, cr, mc, tlf = res
        last = jnp.maximum(jnp.minimum(
            tlf.wrote.astype(jnp.int32),
            jnp.int32(telemetry_spec.rounds)) - 1, 0)
        kv_t = tlf.ring[last, pg_col].astype(jnp.int32)
        pend = tlf.ring[last, pd_col].astype(jnp.int32)
        bp = telemetry.log2_bucket(
            jnp.maximum(acked - kv_t - pend, 0))
        return st, cr, mc, tlf, signature_eval(tlf, cr, clear, bp,
                                               ms_col, pg_col, churn)

    def one(state, plan, clear, *rest):
        if has_mem:
            acked, *tel_a = rest
            member = faults.member_at(plan, clear, jnp.arange(n))
        else:
            tel_a = rest
            acked = jnp.int32(acked_sum)
            member = None
        step1 = lambda st, i: rnd(st, plan)            # noqa: E731
        conv = lambda st: CT._batch_converged(st,      # noqa: E731
                                              member)
        row = ((lambda s0, s1: sim._tel_series(
            s0, s1, coll, sim.kv_sched, plan)) if tl else None)
        return sig_of(certify_loop(
            step1, conv, state, clear,
            batch.max_recovery_rounds, r_total,
            tel_a[0] if tl else None, row, tel_mask), clear, acked,
            faults.plan_churn(plan))

    args = [states, plans, clears]
    if has_mem:
        args.append(jnp.asarray(ackeds_np, jnp.int32))
    dn = (0,) + ((len(args),) if tl else ())
    if tl:
        args.append(stack_pytrees(
            [telemetry.init_state(telemetry_spec)
             for _ in range(s_count)]))
    args = _place(tuple(args), mesh)
    prog = _build_batch_program(
        "counter", one, args, mesh, dn,
        key=(n, mode, poll_every, s_count, r_total,
             int(plans.starts.shape[1]), telemetry_spec, signatures,
             has_mem))
    out = prog(*args)
    return {"out": out, "batch": batch,
            "telemetry_spec": telemetry_spec, "signatures": signatures,
            "n": n, "mode": mode, "acked_sum": acked_sum,
            "ackeds_np": ackeds_np}


def _collect_counter_batch(handle: dict) -> dict:
    """Block on + certify a dispatched counter batch."""
    out = _replicated_out(handle["out"])
    batch = handle["batch"]
    telemetry_spec = handle["telemetry_spec"]
    n, mode = handle["n"], handle["mode"]
    ackeds = handle["ackeds_np"]
    s_count = len(batch.scenarios)
    tl = telemetry_spec is not None
    final, conv_round, msgs_clear = out[0], out[1], out[2]
    kv = np.asarray(final.kv)
    pend = np.asarray(final.pending).sum(axis=1)
    shortfall = ackeds - kv - pend
    lost_lists = [([{"lost_sum": int(shortfall[i])}]
                   if shortfall[i] != 0 else [])
                  for i in range(s_count)]
    res = _verdict_rows(batch, conv_round, msgs_clear,
                        np.asarray(final.msgs), lost_lists,
                        extra=[{"acked_sum": int(ackeds[i]),
                                "kv": int(kv[i])}
                               for i in range(s_count)])
    res.update(n_nodes=n, mode=mode, final=final)
    if tl:
        res["telemetry"] = [
            telemetry.series_arrays(
                jax.tree_util.tree_map(lambda x, i=i: x[i], out[3]),
                telemetry_spec)
            for i in range(s_count)]
    if handle["signatures"]:
        res["signatures"] = np.asarray(out[4])
    return res


def run_counter_batch(batch: ScenarioBatch, *, mesh=None,
                      telemetry_spec=None, signatures: bool = False,
                      n_windows: int | None = None,
                      min_rounds: int = 0) -> dict:
    """S g-counter campaigns in ONE dispatch: per-node deltas acked at
    round 0 (the sequential runner's default ``arange(1, n+1)``),
    convergence = pending drained AND every cached read equals the KV,
    lost acked writes = the final ``acked_sum - kv - pending``
    shortfall (amnesia-killed deltas)."""
    return _collect_counter_batch(_dispatch_counter_batch(
        batch, mesh=mesh, telemetry_spec=telemetry_spec,
        signatures=signatures, n_windows=n_windows,
        min_rounds=min_rounds))


def _dispatch_kafka_batch(batch: ScenarioBatch, *, mesh=None,
                          telemetry_spec=None,
                          signatures: bool = False,
                          n_windows: int | None = None,
                          min_rounds: int = 0) -> dict:
    """Stage + enqueue S replicated-log campaigns; see
    :func:`_dispatch_broadcast_batch` for the dispatch/collect and
    signature/shape-bucket contracts."""
    kw = batch.runner_kw
    n = batch.n_nodes
    n_keys = int(kw.get("n_keys", 4))
    capacity = int(kw.get("capacity", 64))
    max_sends = int(kw.get("max_sends", 2))
    resync_every = int(kw.get("resync_every", 4))
    send_prob = float(kw.get("send_prob", 0.7))
    scs = batch.scenarios
    s_count = len(scs)
    has_mem = any(sc.spec.has_membership for sc in scs)
    sim = KF.KafkaSim(n, n_keys, capacity=capacity,
                      max_sends=max_sends, resync_every=resync_every)

    plans = faults.batch_plans([sc.spec for sc in scs], n_windows)
    clears_np = np.array(
        [max(sc.spec.clear_round, int(kw.get("rounds") or 0))
         for sc in scs], np.int32)
    clears = jnp.asarray(clears_np)
    max_clear = int(clears_np.max())
    r_total = max(max_clear + batch.max_recovery_rounds,
                  int(min_rounds))
    # a LEAVING node drains before it goes (PR 17): no sends staged
    # at it within a resync period of its leave round, so every slot
    # it acked has replicated before its presence row dies — the
    # graceful-decommission contract the zero-lost-writes certificate
    # rests on (same quiesce in the sequential runner: bit-parity)
    quiesce = (resync_every + 2) if has_mem else 0
    sks, svs = stage_kafka_batch(batch, r_total, n_keys=n_keys,
                                 max_sends=max_sends,
                                 send_prob=send_prob, quiesce=quiesce)

    states = stack_pytrees([sim.init_state()
                            for _ in range(s_count)])
    rnd = KF._build_batch_round(sim)
    tl = telemetry_spec is not None
    tel_mask = telemetry_spec.static_mask if tl else None
    full_scan = (tl and "present_bits_full" in telemetry_spec.series)
    from .engine import collectives
    coll = collectives(n)
    if signatures:
        ms_col, pg_col = _sig_setup(telemetry_spec, r_total,
                                    extra_series=("alloc_total",))
        al_col = telemetry_spec.names.index("alloc_total")

    def sig_of(res, clear, churn):
        if not signatures:
            return res
        st, cr, mc, tlf = res
        last = jnp.maximum(jnp.minimum(
            tlf.wrote.astype(jnp.int32),
            jnp.int32(telemetry_spec.rounds)) - 1, 0)
        alloc = tlf.ring[last, al_col].astype(jnp.int32)
        pres = tlf.ring[last, pg_col].astype(jnp.int32)
        bp = telemetry.log2_bucket(jnp.maximum(alloc - pres, 0))
        return st, cr, mc, tlf, signature_eval(tlf, cr, clear, bp,
                                               ms_col, pg_col, churn)

    def one(state, plan, sk_r, sv_r, clear, *tel_a):
        def step1(st, i):
            sk = lax.dynamic_index_in_dim(sk_r, i, axis=0,
                                          keepdims=False)
            sv = lax.dynamic_index_in_dim(sv_r, i, axis=0,
                                          keepdims=False)
            return rnd(st, plan, sk, sv)

        member = (faults.member_at(plan, clear, jnp.arange(n))
                  if has_mem else None)
        conv = lambda st: KF._batch_converged(st,      # noqa: E731
                                              member)
        row = ((lambda s0, s1: sim._tel_series(
            s0, s1, coll, plan, full_scan)) if tl else None)
        return sig_of(certify_loop(
            step1, conv, state, clear,
            batch.max_recovery_rounds, r_total,
            tel_a[0] if tl else None, row, tel_mask), clear,
            faults.plan_churn(plan))

    args = [states, plans, sks, svs, clears]
    dn = (0,) + ((len(args),) if tl else ())
    if tl:
        args.append(stack_pytrees(
            [telemetry.init_state(telemetry_spec)
             for _ in range(s_count)]))
    args = _place(tuple(args), mesh)
    prog = _build_batch_program(
        "kafka", one, args, mesh, dn,
        key=(n, n_keys, capacity, max_sends, resync_every, s_count,
             r_total, int(plans.starts.shape[1]), telemetry_spec,
             signatures, has_mem))
    out = prog(*args)
    return {"out": out, "batch": batch,
            "telemetry_spec": telemetry_spec, "signatures": signatures,
            "n": n, "n_keys": n_keys}


def _collect_kafka_batch(handle: dict) -> dict:
    """Block on + certify a dispatched kafka batch."""
    out = _replicated_out(handle["out"])
    batch = handle["batch"]
    telemetry_spec = handle["telemetry_spec"]
    n, n_keys = handle["n"], handle["n_keys"]
    s_count = len(batch.scenarios)
    tl = telemetry_spec is not None
    final, conv_round, msgs_clear = out[0], out[1], out[2]
    pres = np.asarray(final.present) > 0              # (S, N, K, Wc)
    log_vals = np.asarray(final.log_vals)             # (S, K, C)
    lost_lists = []
    for i in range(s_count):
        allocated = log_vals[i] >= 0
        anywhere = np.zeros_like(allocated)
        p = np.asarray(final.present)[i]              # (N, K, Wc)
        bits = np.unpackbits(
            p.view(np.uint8), axis=-1, bitorder="little")
        anywhere = bits.any(axis=0)[:, :allocated.shape[1]]
        lost = [(int(k), int(c) + 1)
                for k, c in zip(*np.nonzero(allocated
                                            & ~anywhere))]
        kvv = np.asarray(final.kv_val)[i]
        lc = np.asarray(final.local_committed)[i]
        over = lc > np.where(kvv > 0, kvv, 0)[None, :]
        lost += [{"committed_over_cell": (int(a), int(b))}
                 for a, b in zip(*np.nonzero(over))]
        lost_lists.append(lost)
    res = _verdict_rows(
        batch, conv_round, msgs_clear, np.asarray(final.msgs),
        lost_lists,
        extra=[{"n_allocated": int((log_vals[i] >= 0).sum())}
               for i in range(s_count)])
    res.update(n_nodes=n, n_keys=n_keys, final=final)
    if tl:
        res["telemetry"] = [
            telemetry.series_arrays(
                jax.tree_util.tree_map(lambda x, i=i: x[i], out[3]),
                telemetry_spec)
            for i in range(s_count)]
    if handle["signatures"]:
        res["signatures"] = np.asarray(out[4])
    return res


def run_kafka_batch(batch: ScenarioBatch, *, mesh=None,
                    telemetry_spec=None, signatures: bool = False,
                    n_windows: int | None = None,
                    min_rounds: int = 0) -> dict:
    """S replicated-log campaigns in ONE dispatch: per-scenario seeded
    send traffic at live nodes (commit-free vectorized staging — the
    sequential runner's ``commits=False`` regime), the FAULTED
    origin-union replication path, convergence = every node's presence
    bitset identical, lost acked writes = allocated slots present at
    NO node (+ any committed-offset cache exceeding the shared
    cell)."""
    return _collect_kafka_batch(_dispatch_kafka_batch(
        batch, mesh=mesh, telemetry_spec=telemetry_spec,
        signatures=signatures, n_windows=n_windows,
        min_rounds=min_rounds))


def _dispatch_txn_batch(batch: ScenarioBatch, *, mesh=None,
                        telemetry_spec=None,
                        signatures: bool = False,
                        n_windows: int | None = None,
                        min_rounds: int = 0) -> dict:
    """Stage + enqueue S txn-rw-register campaigns (PR 14): each
    scenario's seeded transaction program and arrival schedule ride
    as stacked traced operands (TxnOps / batched TrafficPlan), the
    wound-or-die round runs with identity collectives under scenario
    sharding, and serializability is certified host-side at collect
    (``checkers.check_txn_serializable`` per scenario)."""
    if telemetry_spec is not None or signatures:
        raise ValueError(
            "the txn workload's observability record is the "
            "per-transaction stamp pair riding TxnState — telemetry "
            "rings / behavioral signatures are not wired for it")
    for i, sc in enumerate(batch.scenarios):
        if sc.spec.dup_rate:
            raise ValueError(
                "txn scenarios cannot carry dup streams "
                "(kvstore.reject_dup_stream: a re-applied CAS would "
                "double-commit)")
        if sc.spec.has_membership:
            raise ValueError(
                f"txn scenario {i} carries membership events "
                "(join/leave), which the txn workload does not "
                "support yet: the wound-or-die commit path and the "
                "per-transaction stamp ledger assume a fixed client "
                "roster — run membership churn on the "
                "broadcast/counter/kafka workloads instead")
    kw = batch.runner_kw
    n = batch.n_nodes
    n_keys = int(kw.get("n_keys", 8))
    t_dim = int(kw.get("txns_per_node", 4))
    o = int(kw.get("ops_per_txn", 2))
    rate = float(kw.get("rate", 0.5))
    until = int(kw.get("until") or 4 * t_dim)
    kv_amnesia = bool(kw.get("kv_amnesia", False))
    scs = batch.scenarios
    s_count = len(scs)
    sim = TX.TxnSim(n, n_keys, txns_per_node=t_dim, ops_per_txn=o,
                    rate=rate, until=until, kv_amnesia=kv_amnesia)

    plans = faults.batch_plans([sc.spec for sc in scs], n_windows)
    # convergence is meaningful only past BOTH horizons (the
    # sequential runner's clear = max(spec.clear_round, until))
    clears_np = np.array([max(sc.spec.clear_round, until)
                          for sc in scs], np.int32)
    clears = jnp.asarray(clears_np)
    r_total = max(int(clears_np.max()) + batch.max_recovery_rounds,
                  int(min_rounds))
    ops = stack_pytrees([
        TX.stage_txn_ops(n, t_dim, o, n_keys, sc.workload_seed)
        for sc in scs])
    tplans = traffic.batch_tplans([
        traffic.TrafficSpec(n_nodes=n, n_clients=n,
                            ops_per_client=t_dim, until=until,
                            rate=rate, seed=sc.workload_seed)
        for sc in scs])
    states = stack_pytrees([sim.init_state()
                            for _ in range(s_count)])
    rnd = TX._build_batch_round(sim)

    def one(state, plan, ops_s, tplan, clear):
        step1 = lambda st, i: rnd(st, plan, ops_s, tplan)  # noqa: E731
        return certify_loop(step1, TX._batch_converged, state, clear,
                            batch.max_recovery_rounds, r_total)

    args = _place((states, plans, ops, tplans, clears), mesh)
    prog = _build_batch_program(
        "txn", one, args, mesh, (0,),
        key=(n, n_keys, t_dim, o, rate, until, kv_amnesia, s_count,
             r_total, int(plans.starts.shape[1])))
    out = prog(*args)
    return {"out": out, "batch": batch, "telemetry_spec": None,
            "signatures": False, "n": n, "sim": sim, "ops": ops}


def _collect_txn_batch(handle: dict) -> dict:
    """Block on + certify a dispatched txn batch: the batched recovery
    rows AND a per-scenario serializability verdict over the recorded
    history (lost updates / lost acked commits land in the row's
    lost-writes evidence; any other anomaly still fails the row)."""
    from ..harness.checkers import check_txn_serializable

    out = _replicated_out(handle["out"])
    batch = handle["batch"]
    sim, ops = handle["sim"], handle["ops"]
    s_count = len(batch.scenarios)
    final, conv_round, msgs_clear = out[0], out[1], out[2]
    lost_lists, ser_rows = [], []
    for i in range(s_count):
        st_i = jax.tree_util.tree_map(lambda x, i=i: x[i], final)
        ops_i = jax.tree_util.tree_map(lambda x, i=i: x[i], ops)
        hist = TX.history_of(st_i, ops_i)
        ok_ser, det = check_txn_serializable(
            hist, final=TX.final_registers(st_i, sim.layout))
        lost_lists.append(
            [p for p in det["problems"]
             if p["kind"] in ("lost-update", "lost-acked-commit")])
        ser_rows.append(
            {"serializable": ok_ser, "ser_by_kind": det["by_kind"],
             "n_txns": len(hist),
             "n_committed": det["n_committed"]})
    res = _verdict_rows(batch, conv_round, msgs_clear,
                        np.asarray(final.msgs), lost_lists,
                        extra=ser_rows)
    # a non-serializable history fails its row even when recovery
    # certified clean (e.g. a planted cycle with zero lost writes)
    for i, row in enumerate(res["scenarios"]):
        if not ser_rows[i]["serializable"]:
            row["ok"] = False
    res["failing"] = [i for i, row in enumerate(res["scenarios"])
                      if not row["ok"]]
    res["ok"] = not res["failing"]
    res.update(n_nodes=handle["n"], final=final)
    return res


def run_txn_batch(batch: ScenarioBatch, *, mesh=None,
                  telemetry_spec=None, signatures: bool = False,
                  n_windows: int | None = None,
                  min_rounds: int = 0) -> dict:
    """S txn-rw-register campaigns in ONE dispatch: per-scenario
    seeded transactions and arrivals, wound-or-die commits on the
    sharded device KV, convergence = every offered transaction
    committed, certification = bounded recovery AND a serializable
    device-recorded history with zero lost acked commits."""
    return _collect_txn_batch(_dispatch_txn_batch(
        batch, mesh=mesh, telemetry_spec=telemetry_spec,
        signatures=signatures, n_windows=n_windows,
        min_rounds=min_rounds))


_RUNNERS = {"broadcast": run_broadcast_batch,
            "counter": run_counter_batch,
            "kafka": run_kafka_batch,
            "txn": run_txn_batch}
_DISPATCHERS = {"broadcast": _dispatch_broadcast_batch,
                "counter": _dispatch_counter_batch,
                "kafka": _dispatch_kafka_batch,
                "txn": _dispatch_txn_batch}
_COLLECTORS = {"broadcast": _collect_broadcast_batch,
               "counter": _collect_counter_batch,
               "kafka": _collect_kafka_batch,
               "txn": _collect_txn_batch}


def dispatch_scenario_batch(batch: ScenarioBatch, *, mesh=None,
                            telemetry_spec=None,
                            signatures: bool = False,
                            n_windows: int | None = None,
                            min_rounds: int = 0,
                            pad_to: int | None = None,
                            pad_to_mesh: bool = True) -> dict:
    """Pad + enqueue one :class:`ScenarioBatch` and return its async
    handle WITHOUT blocking on device results — JAX async dispatch
    keeps the device busy while the host stages or certifies another
    batch (the depth-2 pipeline in harness.fuzz).  Finish with
    :func:`collect_scenario_batch`.  ``pad_to`` rounds the scenario
    count up to a multiple of the given bucket (the shape-bucket
    knob: a ragged tail batch padded to the same power-of-two count
    reuses the full batch's compiled program instead of paying a
    fresh XLA compile)."""
    _refuse_stale_dcn("a scenario batch")
    n_real = len(batch.scenarios)
    mult = 1
    if mesh is not None and pad_to_mesh:
        mult = node_shards(mesh)
    if pad_to:
        mult = max(mult, int(pad_to))
    if mult > 1:
        batch, n_real = pad_batch(batch, mult)
    handle = _DISPATCHERS[batch.workload](
        batch, mesh=mesh, telemetry_spec=telemetry_spec,
        signatures=signatures, n_windows=n_windows,
        min_rounds=min_rounds)
    handle["n_real"] = n_real
    return handle


def collect_scenario_batch(handle: dict) -> dict:
    """Block on + certify a dispatched scenario batch, dropping any
    mesh-padding filler rows (scenarios, telemetry, signatures) from
    the result."""
    res = _COLLECTORS[handle["batch"].workload](handle)
    n_real = handle["n_real"]
    if n_real < res["n_scenarios"]:
        res["scenarios"] = res["scenarios"][:n_real]
        res["failing"] = [i for i in res["failing"] if i < n_real]
        if "telemetry" in res:
            res["telemetry"] = res["telemetry"][:n_real]
        if "signatures" in res:
            res["signatures"] = res["signatures"][:n_real]
        res["n_scenarios"] = n_real
        res["ok"] = not res["failing"]
    return res


def run_scenario_batch(batch: ScenarioBatch, *, mesh=None,
                       telemetry_spec=None, signatures: bool = False,
                       n_windows: int | None = None,
                       min_rounds: int = 0,
                       pad_to: int | None = None,
                       pad_to_mesh: bool = True) -> dict:
    """Dispatch one :class:`ScenarioBatch` (pad to the device count
    first when a mesh is given, dropping the filler rows from the
    result) — the fuzzer's unit of work.  ``signatures`` appends the
    per-scenario behavioral signature matrix; ``n_windows`` /
    ``min_rounds`` / ``pad_to`` are the shape-bucket knobs (pad crash
    windows / floor the trip count / round the scenario count up)
    that keep one compiled program hot across heterogeneous
    campaigns."""
    return collect_scenario_batch(dispatch_scenario_batch(
        batch, mesh=mesh, telemetry_spec=telemetry_spec,
        signatures=signatures, n_windows=n_windows,
        min_rounds=min_rounds, pad_to=pad_to,
        pad_to_mesh=pad_to_mesh))


# -- serving-frontier batching (PR 13) -----------------------------------


@dataclass(frozen=True)
class ServingCell:
    """One (offered load x fault x topology) grid cell — JSON-able.
    ``traffic`` carries the cell's open-loop load (rate/burst/seed/
    until ride the traced TrafficPlan; client shape must match the
    batch), ``spec`` the optional nemesis, ``topology`` the broadcast
    adjacency ("grid"/"tree"; counter/kafka exchange over the KV, so
    they ignore it), ``coords`` free-form grid coordinates echoed into
    the verdict rows (the frontier table's axes)."""

    traffic: traffic.TrafficSpec
    spec: faults.NemesisSpec | None = None
    topology: str = "grid"
    coords: tuple = ()

    def __post_init__(self) -> None:
        object.__setattr__(self, "coords", tuple(self.coords))

    @property
    def clear_round(self) -> int:
        """The cell's fault-clear horizon — ``run_serving``'s
        ``clear``: traffic horizon, extended to the nemesis clear."""
        return max(self.traffic.until,
                   self.spec.clear_round if self.spec else 0)

    def to_meta(self) -> dict:
        return {"traffic": self.traffic.to_meta(),
                "spec": (None if self.spec is None
                         else self.spec.to_meta()),
                "topology": self.topology,
                "coords": list(self.coords)}

    @staticmethod
    def from_meta(meta: dict) -> "ServingCell":
        return ServingCell(
            traffic=traffic.TrafficSpec.from_meta(meta["traffic"]),
            spec=(None if meta.get("spec") is None
                  else faults.NemesisSpec.from_meta(meta["spec"])),
            topology=str(meta.get("topology", "grid")),
            coords=tuple(meta.get("coords", ())))


@dataclass(frozen=True)
class ServingBatch:
    """S serving cells + the static shape they share — the frontier
    sweep's unit of work (:func:`run_serving_batch`).  ``runner_kw``
    holds the per-workload sim statics (broadcast: ``n_values``/
    ``sync_every``; counter: ``mode``/``poll_every``; kafka:
    ``n_keys``/``capacity`` (REQUIRED — the sequential default is
    rate-dependent and a batch mixes rates)/``max_sends``/
    ``resync_every``)."""

    workload: str
    cells: tuple = field(default_factory=tuple)
    runner_kw: dict = field(default_factory=dict)
    max_recovery_rounds: int = 96
    drain_every: int = 8

    def __post_init__(self) -> None:
        if self.workload not in ("broadcast", "counter", "kafka"):
            raise ValueError(
                f"unknown serving workload {self.workload!r}")
        if not self.cells:
            raise ValueError("a ServingBatch needs >= 1 cell")
        if self.max_recovery_rounds < 1 or self.drain_every < 1:
            raise ValueError(
                "max_recovery_rounds and drain_every must be >= 1")
        object.__setattr__(self, "cells", tuple(self.cells))
        c0 = self.cells[0]
        key = c0.traffic.program_key[:4]
        for c in self.cells:
            if c.traffic.program_key[:4] != key:
                raise ValueError(
                    "serving batch mixes traffic statics "
                    f"{key} and {c.traffic.program_key[:4]} — the "
                    "client shape (n_nodes, n_clients, "
                    "ops_per_client, intake) is compiled; only "
                    "rate/kind/burst/seed/until ride the plan")
            if (c.spec is not None
                    and c.spec.n_nodes != c0.traffic.n_nodes):
                raise ValueError(
                    f"cell nemesis is for {c.spec.n_nodes} nodes, "
                    f"traffic for {c0.traffic.n_nodes}")

    @property
    def n_nodes(self) -> int:
        return self.cells[0].traffic.n_nodes

    def to_meta(self) -> dict:
        return {"workload": self.workload,
                "cells": [c.to_meta() for c in self.cells],
                "runner_kw": dict(self.runner_kw),
                "max_recovery_rounds": self.max_recovery_rounds,
                "drain_every": self.drain_every}

    @staticmethod
    def from_meta(meta: dict) -> "ServingBatch":
        return ServingBatch(
            workload=str(meta["workload"]),
            cells=tuple(ServingCell.from_meta(m)
                        for m in meta["cells"]),
            runner_kw=dict(meta.get("runner_kw", {})),
            max_recovery_rounds=int(meta.get("max_recovery_rounds",
                                             96)),
            drain_every=int(meta.get("drain_every", 8)))


def pad_serving_batch(batch: ServingBatch, multiple: int) -> tuple:
    """(padded batch, n_real): duplicate the last cell up to a
    multiple of ``multiple`` (filler rows are dropped from the
    results) so a mesh can take scenario placement."""
    s = len(batch.cells)
    if multiple <= 1 or s % multiple == 0:
        return batch, s
    pad = multiple - s % multiple
    return ServingBatch(
        workload=batch.workload,
        cells=batch.cells + (batch.cells[-1],) * pad,
        runner_kw=batch.runner_kw,
        max_recovery_rounds=batch.max_recovery_rounds,
        drain_every=batch.drain_every), s


def serving_loop(step1, all_done, state, ts, clear, drain_every: int,
                 max_rec: int, r_total: int, tel=None):
    """ONE serving cell's whole run as a fixed-trip ``fori_loop``
    (traced; vmapped over the cell axis by the frontier batch
    programs) — the device twin of harness.serving.run_serving's host
    loop, BIT-EXACTLY:

    - drive unconditionally to the cell's own ``clear`` round (the
      sequential driven + fault-outlasting phases), recording ``msgs``
      when ``t == clear``;
    - past clear, test "all issued ops completed" ONLY at the drain
      checkpoints the sequential loop observes — every ``drain_every``
      rounds, plus the final partial chunk at ``clear + max_rec`` —
      and record the FIRST satisfied checkpoint round (``fr``; -1 =
      still-open ops at the bound, the sequential loop's exhausted
      drain);
    - freeze the cell (state, tracker, ring) once satisfied or past
      the bound — exactly where the sequential loop stops driving, so
      mid-chunk completions keep stepping (and counting msgs) just
      like the sequential drain chunk runs to its checkpoint.

    ``step1(st, tr, tl, i) -> (st', tr', tl')`` owns the whole traffic
    round INCLUDING the telemetry row (``tl`` may be None).  Returns
    ``(state, tracker, fr, msgs_at_clear, tel)``."""
    bound = clear + jnp.int32(max_rec)

    def check(st, tr, fr):
        d = st.t - clear
        at_cp = (d >= jnp.int32(0)) & (
            (lax.rem(d, jnp.int32(drain_every)) == 0)
            | (d >= jnp.int32(max_rec)))
        return jnp.where(at_cp & (fr < 0) & all_done(tr), st.t, fr)

    def freeze(active, new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(active, a, b), new, old)

    def body(i, carry):
        st, tr, tl, fr, mc = carry
        fr = check(st, tr, fr)
        mc = jnp.where(st.t == clear, st.msgs, mc)
        active = (fr < 0) & (st.t < bound)
        s2, t2, tl2 = step1(st, tr, tl, i)
        st = freeze(active, s2, st)
        tr = freeze(active, t2, tr)
        tl = freeze(active, tl2, tl)
        return (st, tr, tl, fr, mc)

    st, tr, tl, fr, mc = lax.fori_loop(
        0, r_total, body,
        (state, ts, tel, jnp.int32(-1), jnp.uint32(0)))
    fr = check(st, tr, fr)
    return st, tr, fr, mc, tl


def _serving_common(batch: ServingBatch, n_windows, n_burst,
                    min_rounds):
    """The workload-independent staging every serving dispatcher
    shares: stacked traffic plans + trackers, padded fault plans
    (fault-free cells ride an all-zero plan — value-identical to the
    sequential plan=None path: zero-threshold coins never fire),
    per-cell clear rounds, and the common trip count."""
    cells = batch.cells
    tplans = traffic.batch_tplans([c.traffic for c in cells], n_burst)
    trackers = stack_pytrees(
        [traffic.init_state(c.traffic, None) for c in cells])
    n = batch.n_nodes
    specs = [c.spec if c.spec is not None
             else faults.NemesisSpec(n_nodes=n) for c in cells]
    for i, sp in enumerate(specs):
        if sp.has_membership:
            raise ValueError(
                f"serving cell {i} carries membership events "
                "(join/leave), which the serving batch path does not "
                "support yet: the open-loop traffic tracker has no "
                "join/leave-aware intake gating — run membership "
                "churn on the closed-loop scenario batches "
                "(dispatch_scenario_batch) instead")
    plans = faults.batch_plans(specs, n_windows)
    clears_np = np.array([c.clear_round for c in cells], np.int32)
    r_total = max(int(clears_np.max()) + batch.max_recovery_rounds,
                  int(min_rounds))
    return tplans, trackers, plans, jnp.asarray(clears_np), r_total


def _serving_sig(batch: ServingBatch, telemetry_spec, r_total: int):
    """(ms_col, pg_col, sig_fn) for the serving dispatchers: the
    serving backpressure class comes from the TRACKER (0 = clean, 1 =
    deferral-dominated — intake/slot backpressure, 2 = in-flight-
    dominated — completion stall), the stall/depth buckets from the
    ring (:func:`signature_eval`)."""
    ms_col, pg_col = _sig_setup(telemetry_spec, r_total)

    def sig_fn(tr, tlf, fr, clear):
        inf = (jnp.sum(tr.issued_k)
               - tr.completed.astype(jnp.int32))
        de = tr.deferred.astype(jnp.int32)
        bp = jnp.where((de == jnp.int32(0)) & (inf == jnp.int32(0)),
                       jnp.int32(0),
                       jnp.where(de >= inf, jnp.int32(1),
                                 jnp.int32(2)))
        return signature_eval(tlf, fr, clear, bp, ms_col, pg_col)

    return sig_fn


def dispatch_serving_batch(batch: ServingBatch, *, mesh=None,
                           telemetry_spec=None,
                           signatures: bool = False,
                           n_windows: int | None = None,
                           n_burst: int | None = None,
                           min_rounds: int = 0,
                           pad_to_mesh: bool = True) -> dict:
    """Stage + enqueue a whole (load x fault x topology) serving grid
    as ONE compiled, scenario-sharded batch program: per-cell
    TrafficPlans and FaultPlans stacked leaf-by-leaf, per-cell
    adjacency stacked as an operand (broadcast), the per-cell
    :func:`serving_loop` vmapped over the cell axis — zero collective
    ops, donation over BOTH the stacked sim state and the stacked
    tracker carry.  Finish with :func:`collect_serving_batch`;
    ``run_serving_batch`` = collect(dispatch(...)) and documents the
    knobs.  ``telemetry_spec=True`` builds the default traffic ring
    sized to the horizon (what ``signatures`` needs)."""
    from .engine import collectives

    _refuse_stale_dcn("a serving batch", batch.runner_kw)
    n_real = len(batch.cells)
    if mesh is not None and pad_to_mesh:
        batch, n_real = pad_serving_batch(
            batch, node_shards(mesh))
    cells = batch.cells
    s_count = len(cells)
    n = batch.n_nodes
    kw = batch.runner_kw
    tspec0 = cells[0].traffic
    tplans, trackers, plans, clears, r_total = _serving_common(
        batch, n_windows, n_burst, min_rounds)
    if telemetry_spec is True:
        telemetry_spec = telemetry.TelemetrySpec(
            workload=batch.workload, rounds=r_total, traffic=True)
    tl = telemetry_spec is not None
    tel_mask = telemetry_spec.static_mask if tl else None
    if tl and telemetry_spec.rounds < r_total:
        raise ValueError(
            f"serving telemetry ring must cover the horizon without "
            f"wrapping: rounds={telemetry_spec.rounds} < "
            f"r_total={r_total} (the per-cell freeze round indexes "
            "the unwrapped ring)")
    sig_fn = (_serving_sig(batch, telemetry_spec, r_total)
              if signatures else None)
    coll = collectives(n)
    ub = traffic.traffic_block(tspec0.n_clients)
    max_rec, drain_every = batch.max_recovery_rounds, batch.drain_every

    def all_done(tr):
        return tr.completed >= jnp.sum(
            tr.issued_k).astype(jnp.uint32)

    if batch.workload == "broadcast":
        nv = int(kw.get("n_values")
                 or tspec0.n_clients * tspec0.ops_per_client)
        sync_every = int(kw.get("sync_every", 4))
        from ..parallel.topology import (grid, to_padded_neighbors,
                                         tree)
        mats = [to_padded_neighbors(
            {"grid": grid, "tree": tree}[c.topology](n))
            for c in cells]
        deg = max(m.shape[1] for m in mats)
        mats = [np.pad(m, ((0, 0), (0, deg - m.shape[1])),
                       constant_values=-1) for m in mats]
        stacked = np.stack(mats)
        nbrs_b = jnp.asarray(stacked, jnp.int32)      # (S, N, D)
        mask_b = jnp.asarray(stacked >= 0)
        sim = B.BroadcastSim(mats[0], n_values=nv,
                             sync_every=sync_every, srv_ledger=False)
        sim._traffic_validate(tspec0)
        dup_on = any(c.spec is not None and c.spec.dup_rate > 0
                     for c in cells)
        parts0 = B.Partitions.none(n)
        states = stack_pytrees([sim.init_state(
            np.zeros((n, sim.n_words), np.uint32))
            for _ in range(s_count)])

        def one(state, tr, tplan, plan, nbrs, nbr_mask, clear,
                *tel_a):
            def step1(st, t_, tl_c, i):
                s, t2 = sim._traffic_inject(st, t_, tspec0, tplan,
                                            plan, coll)
                s2 = B.flood_step(
                    s, nbrs=nbrs, nbr_mask=nbr_mask, parts=parts0,
                    sync_every=sync_every, plan=plan, dup_on=dup_on,
                    union_block=sim._ub)
                t2 = sim._traffic_done(s2, t2, tspec0, coll, ub)
                if tl_c is None:
                    return s2, t2, None
                return s2, t2, sim._traffic_tel(s, s2, t2, plan,
                                                coll, tl_c, tel_mask)

            out = serving_loop(step1, all_done, state, tr, clear,
                               drain_every, max_rec, r_total,
                               tel_a[0] if tl else None)
            st, t2, fr, mc, tlf = out
            res = (st, t2, fr, mc) + ((tlf,) if tl else ())
            if signatures:
                res = res + (sig_fn(t2, tlf, fr, clear),)
            return res

        args = [states, trackers, tplans, plans, nbrs_b, mask_b,
                clears]
        key = ("serving", n, nv, sync_every, dup_on, deg)
    elif batch.workload == "counter":
        mode = kw.get("mode", "cas")
        poll_every = int(kw.get("poll_every", 2))
        sim = CT.CounterSim(n, mode=mode, poll_every=poll_every)
        states = stack_pytrees([sim.init_state()
                                for _ in range(s_count)])

        def one(state, tr, tplan, plan, clear, *tel_a):
            def step1(st, t_, tl_c, i):
                out = sim._traffic_round(
                    st, t_, tspec0, tplan, sim.kv_sched, coll, plan,
                    ub, tl_c, tel_mask)
                return out if tl_c is not None else out + (None,)

            out = serving_loop(step1, all_done, state, tr, clear,
                               drain_every, max_rec, r_total,
                               tel_a[0] if tl else None)
            st, t2, fr, mc, tlf = out
            res = (st, t2, fr, mc) + ((tlf,) if tl else ())
            if signatures:
                res = res + (sig_fn(t2, tlf, fr, clear),)
            return res

        args = [states, trackers, tplans, plans, clears]
        key = ("serving", n, mode, poll_every)
    else:
        if "capacity" not in kw:
            raise ValueError(
                "kafka serving batches need an explicit "
                "runner_kw['capacity']: the sequential default is "
                "sized from the cell's rate, and a frontier batch "
                "mixes rates (one compiled shape per batch)")
        n_keys = int(kw.get("n_keys", 16))
        capacity = int(kw["capacity"])
        max_sends = int(kw.get("max_sends", 4))
        resync_every = int(kw.get("resync_every", 4))
        sim = KF.KafkaSim(n, n_keys, capacity=capacity,
                          max_sends=max_sends,
                          resync_every=resync_every)
        # the helper sim carries no FaultPlan, so its own
        # _repl_mode() would pick the nemesis-blind "union" path;
        # an ACTIVE batch must ride "union_nem" (inert/zero plans
        # are value-identical there: zero-threshold coins never
        # fire, the resync cadence gates on TRACED plan activity —
        # kafka._step — and the msgs ledger is repl_mode-blind)
        active = any(c.spec is not None
                     and (len(c.spec.crash) > 0
                          or (c.spec.loss_rate > 0
                              and c.spec.loss_until > 0))
                     for c in cells)
        repl_mode = "union_nem" if active else "union"
        tel_full = (tl and "present_bits_full"
                    in telemetry_spec.series)
        states = stack_pytrees([sim.init_state()
                                for _ in range(s_count)])

        def one(state, tr, tplan, plan, clear, *tel_a):
            def step1(st, t_, tl_c, i):
                out = sim._traffic_round(
                    st, t_, tspec0, tplan, sim.kv_sched, coll, plan,
                    repl_mode, ub, tl_c, tel_mask, tel_full)
                return out if tl_c is not None else out + (None,)

            out = serving_loop(step1, all_done, state, tr, clear,
                               drain_every, max_rec, r_total,
                               tel_a[0] if tl else None)
            st, t2, fr, mc, tlf = out
            res = (st, t2, fr, mc) + ((tlf,) if tl else ())
            if signatures:
                res = res + (sig_fn(t2, tlf, fr, clear),)
            return res

        args = [states, trackers, tplans, plans, clears]
        key = ("serving", n, n_keys, capacity, max_sends,
               resync_every, repl_mode)

    dn = (0, 1) + ((len(args),) if tl else ())
    if tl:
        args.append(stack_pytrees(
            [telemetry.init_state(telemetry_spec)
             for _ in range(s_count)]))
    args = _place(tuple(args), mesh)
    prog = _build_batch_program(
        f"serving-{batch.workload}", one, args, mesh, dn,
        key=key + (s_count, r_total, drain_every, max_rec,
                   tspec0.program_key, telemetry_spec, signatures,
                   int(plans.starts.shape[1])))
    out = prog(*args)
    return {"out": out, "batch": batch, "n_real": n_real,
            "telemetry_spec": telemetry_spec,
            "signatures": signatures, "r_total": r_total}


def collect_serving_batch(handle: dict) -> dict:
    """Block on + certify a dispatched serving batch: per-cell
    latency summary, the EXACT sequential converged-round rule, the
    sequential per-cell ``check_recovery`` verdict (open in-flight
    ops = lost acked writes), conservation ANDed in — then drop any
    mesh-padding filler cells.  Wall-clock fields are deliberately
    absent (one dispatch serves the whole grid; throughput belongs to
    the benchmark that timed it)."""
    from ..harness.checkers import check_recovery

    out = _replicated_out(handle["out"])
    batch = handle["batch"]
    telemetry_spec = handle["telemetry_spec"]
    tl = telemetry_spec is not None
    n_real = handle["n_real"]
    cells = batch.cells[:n_real]
    final, trackers, fr, mc = out[0], out[1], out[2], out[3]
    fr_np = np.asarray(fr)
    mc_np = np.asarray(mc)
    msgs_np = np.asarray(final.msgs)
    max_rec = batch.max_recovery_rounds
    rows, failing, all_ok = [], [], True
    for i, cell in enumerate(cells):
        ts_i = jax.tree_util.tree_map(lambda x, i=i: x[i], trackers)
        summ = traffic.latency_summary(ts_i)
        clear = cell.clear_round
        done_r = np.asarray(ts_i.done_round)
        if summ["issued"] == 0:
            converged_round = clear
        elif summ["in_flight"] == 0:
            converged_round = max(clear, int(done_r.max()))
        else:
            converged_round = None
        lost = ([{"open_ops": summ["in_flight"]}]
                if summ["in_flight"] else [])
        ok, det = check_recovery(
            clear_round=clear, converged_round=converged_round,
            max_recovery_rounds=max_rec, lost_writes=lost,
            msgs_at_clear=int(mc_np[i]),
            msgs_at_converged=int(msgs_np[i]), latency=summ)
        ok = ok and summ["conserved"]
        drained = (int(fr_np[i]) - clear if fr_np[i] >= 0
                   else max_rec)
        total_rounds = clear + drained
        det.update(
            workload=batch.workload, cell=i,
            coords=list(cell.coords), topology=cell.topology,
            n_nodes=batch.n_nodes, traffic=cell.traffic.to_meta(),
            **summ,
            offered_per_round=traffic.offered_per_round(cell.traffic),
            sustained_per_round=summ["completed"] / max(1,
                                                        total_rounds),
            driven_rounds=cell.traffic.until,
            total_rounds=total_rounds,
            msgs_total=int(msgs_np[i]), ok=ok)
        if cell.spec is not None:
            det["spec"] = cell.spec.to_meta()
        rows.append(det)
        if not ok:
            failing.append(i)
        all_ok = all_ok and ok
    res = {"ok": all_ok, "workload": batch.workload,
           "n_cells": len(cells), "failing": failing, "cells": rows,
           "final": final, "trackers": trackers}
    if tl:
        res["telemetry"] = [
            telemetry.series_arrays(
                jax.tree_util.tree_map(lambda x, i=i: x[i], out[4]),
                telemetry_spec)
            for i in range(len(cells))]
    if handle["signatures"]:
        sig = np.asarray(out[5 if tl else 4])
        res["signatures"] = sig[:len(cells)]
        for i, row in enumerate(rows):
            row["signature"] = [int(v) for v in sig[i]]
    return res


def run_serving_batch(batch: ServingBatch, *, mesh=None,
                      telemetry_spec=None, signatures: bool = False,
                      n_windows: int | None = None,
                      n_burst: int | None = None,
                      min_rounds: int = 0,
                      pad_to_mesh: bool = True) -> dict:
    """A whole (offered load x fault x topology) serving grid in ONE
    compiled, zero-collective batch dispatch — per-cell p50/p99/max
    latency, sustained throughput, backpressure counts, and
    ``check_recovery`` verdicts, BIT-EXACT against sequential
    ``run_serving`` rows (tests/test_frontier.py pins single-device
    and 8-way mesh).  ``signatures`` appends the per-cell (5,)
    behavioral signature (requires a telemetry ring covering the
    horizon; pass ``telemetry_spec=True`` for the default);
    ``n_windows``/``n_burst``/``min_rounds`` are the shape-bucket
    knobs (pad crash windows / burst windows / floor the trip count)
    that keep ONE compiled program hot across heterogeneous grids."""
    return collect_serving_batch(dispatch_serving_batch(
        batch, mesh=mesh, telemetry_spec=telemetry_spec,
        signatures=signatures, n_windows=n_windows, n_burst=n_burst,
        min_rounds=min_rounds, pad_to_mesh=pad_to_mesh))


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def batch_state_bytes(workload: str, s_local: int, n: int, *,
                      nv: int = 0, n_keys: int = 0,
                      capacity: int = 0) -> int:
    """Per-shard donated state bytes of a scenario-batch program
    (``s_local`` scenarios per device) — the donation/memory claim of
    the contract rows."""
    if workload == "broadcast":
        per = 2 * n * ((nv + 31) // 32) * 4
    elif workload == "counter":
        per = 2 * n * 4
    else:
        wc = (capacity + 31) // 32
        per = (n * n_keys * wc * 4 + n_keys * capacity * 4
               + n_keys * 4 + n * n_keys * 4)
    return s_local * per


def serving_state_bytes(workload: str, s_local: int, n: int,
                        n_clients: int, ops_per_client: int, *,
                        nv: int = 0, n_keys: int = 0,
                        capacity: int = 0) -> int:
    """Per-shard donated bytes of a serving-frontier batch program:
    the sim state (:func:`batch_state_bytes`) PLUS the stacked per-op
    tracker carry — ``issued_k`` (C,) + the three (C, K) op tables +
    the three scalar counters, all 4-byte — which the frontier
    programs donate alongside the state (donate_argnums (0, 1))."""
    tracker = 4 * (n_clients + 3 * n_clients * ops_per_client + 3)
    return (batch_state_bytes(workload, s_local, n, nv=nv,
                              n_keys=n_keys, capacity=capacity)
            + s_local * tracker)


def audit_contracts():
    """The scenario-batch drivers' :class:`~.audit.ProgramContract`
    rows: scenario placement runs every scenario's node axis LOCALLY,
    so the compiled batch program must contain ZERO collective ops of
    any kind (the cap-0 census over the whole COLLECTIVE_OPS family),
    alias the whole stacked state carry in place (donation scaled by
    S/devices), and sit in the analytic memory band of S_local x the
    single-scenario state."""
    from .audit import AuditProgram, ProgramContract
    from .engine import analytic_peak_bytes
    from .engine import operand_bytes as engine_operand_bytes

    def _specs(n, s):
        out = []
        for i in range(s):
            out.append(Scenario(spec=faults.random_spec(
                n, seed=i + 1, horizon=8,
                n_crash_windows=1 + i % 2, loss_rate=0.1,
                dup_rate=0.05 if i % 2 else 0.0)))
        return tuple(out)

    def broadcast_batch(mesh):
        n, nv, s = 32, 64, 16
        batch = ScenarioBatch(
            workload="broadcast", scenarios=_specs(n, s),
            runner_kw={"n_values": nv, "topology": "tree",
                       "sync_every": 4}, max_recovery_rounds=16)
        prog, args = _audit_program("broadcast", batch, mesh)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = batch_state_bytes("broadcast", s_local, n,
                                        nv=nv)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                faults.batch_plans([sc.spec
                                    for sc in batch.scenarios])),
            slab_bytes=s_local * n * ((nv + 31) // 32) * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def counter_batch(mesh):
        n, s = 32, 16
        batch = ScenarioBatch(
            workload="counter", scenarios=_specs(n, s),
            runner_kw={"mode": "cas", "poll_every": 2},
            max_recovery_rounds=16)
        prog, args = _audit_program("counter", batch, mesh)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = batch_state_bytes("counter", s_local, n)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                faults.batch_plans([sc.spec
                                    for sc in batch.scenarios])),
            slab_bytes=s_local * n * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def kafka_batch(mesh):
        n, s = 16, 16
        batch = ScenarioBatch(
            workload="kafka", scenarios=_specs(n, s),
            runner_kw={"n_keys": 4, "capacity": 32, "max_sends": 1,
                       "resync_every": 2, "send_prob": 0.5},
            max_recovery_rounds=12)
        prog, args = _audit_program("kafka", batch, mesh)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = batch_state_bytes("kafka", s_local, n,
                                        n_keys=4, capacity=32)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                faults.batch_plans([sc.spec
                                    for sc in batch.scenarios])),
            slab_bytes=s_local * n * n * 1 * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def _cells(n, s, until=10, n_clients=None):
        n_clients = n_clients or n
        out = []
        for i in range(s):
            spec = (None if i % 2 == 0 else faults.random_spec(
                n, seed=i + 1, horizon=until, n_crash_windows=1,
                loss_rate=0.1))
            out.append(ServingCell(
                traffic=traffic.TrafficSpec(
                    n_nodes=n, n_clients=n_clients, ops_per_client=2,
                    until=until, rate=0.2 + 0.1 * (i % 3), seed=i),
                spec=spec,
                topology="tree" if i % 4 == 3 else "grid",
                coords=(i % 3, i % 2, i % 4 == 3)))
        return tuple(out)

    def _serving_runner(b, mesh):
        return run_serving_batch(b, mesh=mesh)

    def broadcast_frontier(mesh):
        n, s = 16, 16
        batch = ServingBatch(
            workload="broadcast", cells=_cells(n, s),
            runner_kw={"sync_every": 4}, max_recovery_rounds=16,
            drain_every=4)
        prog, args = _audit_program("broadcast", batch, mesh,
                                    runner=_serving_runner)
        s_local = s // (1 if mesh is None else 8)
        nv = n * 2
        state_bytes = serving_state_bytes("broadcast", s_local, n,
                                          n, 2, nv=nv)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                (faults.batch_plans(
                    [c.spec or faults.NemesisSpec(n_nodes=n)
                     for c in batch.cells]),
                 traffic.batch_tplans(
                     [c.traffic for c in batch.cells]))),
            slab_bytes=s_local * n * ((nv + 31) // 32) * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def counter_frontier(mesh):
        n, s = 16, 16
        batch = ServingBatch(
            workload="counter", cells=_cells(n, s),
            runner_kw={"mode": "cas", "poll_every": 2},
            max_recovery_rounds=16, drain_every=4)
        prog, args = _audit_program("counter", batch, mesh,
                                    runner=_serving_runner)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = serving_state_bytes("counter", s_local, n,
                                          n, 2)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                (faults.batch_plans(
                    [c.spec or faults.NemesisSpec(n_nodes=n)
                     for c in batch.cells]),
                 traffic.batch_tplans(
                     [c.traffic for c in batch.cells]))),
            slab_bytes=s_local * n * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def kafka_frontier(mesh):
        n, s = 8, 16
        batch = ServingBatch(
            workload="kafka", cells=_cells(n, s),
            runner_kw={"n_keys": 4, "capacity": 32, "max_sends": 2,
                       "resync_every": 2},
            max_recovery_rounds=12, drain_every=4)
        prog, args = _audit_program("kafka", batch, mesh,
                                    runner=_serving_runner)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = serving_state_bytes("kafka", s_local, n, n, 2,
                                          n_keys=4, capacity=32)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                (faults.batch_plans(
                    [c.spec or faults.NemesisSpec(n_nodes=n)
                     for c in batch.cells]),
                 traffic.batch_tplans(
                     [c.traffic for c in batch.cells]))),
            slab_bytes=s_local * n * n * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="broadcast/scenario-batch-run",
            build=broadcast_batch,
            collectives={},
            donation=True,
            mem_lo=0.05, mem_hi=8.0,
            notes="scenario-sharded batched broadcast campaigns: S "
                  "whole scenarios vmapped, node axis local per "
                  "scenario — ZERO collective ops of any kind in the "
                  "compiled batch program; stacked state carry "
                  "aliases in place"),
        ProgramContract(
            name="counter/scenario-batch-run",
            build=counter_batch,
            collectives={},
            donation=True,
            mem_lo=0.02, mem_hi=12.0,
            notes="scenario-sharded batched counter campaigns: cap-0 "
                  "census over the whole collective family (identity "
                  "collectives per scenario)"),
        ProgramContract(
            name="kafka/scenario-batch-run",
            build=kafka_batch,
            collectives={},
            donation=True,
            mem_lo=0.02, mem_hi=12.0,
            notes="scenario-sharded batched kafka campaigns on the "
                  "faulted origin-union path: the batched program "
                  "keeps the union elementwise per scenario — no "
                  "all-gather, no ppermute, no matmul mask"),
        ProgramContract(
            name="broadcast/frontier-batch-run",
            build=broadcast_frontier,
            collectives={},
            donation=True,
            mem_lo=0.01, mem_hi=16.0,
            notes="serving-frontier batch (PR 13): a whole load x "
                  "fault x topology grid as ONE scenario-sharded "
                  "dispatch — zero collective ops; donation covers "
                  "the stacked sim state AND the stacked per-op "
                  "tracker carry"),
        ProgramContract(
            name="counter/frontier-batch-run",
            build=counter_frontier,
            collectives={},
            donation=True,
            mem_lo=0.01, mem_hi=20.0,
            notes="counter serving-frontier batch: cap-0 census, "
                  "stacked state + tracker donation (PR 13)"),
        ProgramContract(
            name="kafka/frontier-batch-run",
            build=kafka_frontier,
            collectives={},
            donation=True,
            mem_lo=0.01, mem_hi=20.0,
            notes="kafka serving-frontier batch on the explicit "
                  "union_nem/union replication path: cap-0 census, "
                  "stacked state + tracker donation (PR 13)"),
    ]


def _audit_program(workload: str, batch, mesh, runner=None):
    """(jitted, example_args) of a batch driver: run the runner once
    with :func:`engine.scenario_program` intercepted so the EXACT
    jitted object the batch executed (and its staged operand shapes)
    is what the contract auditor lowers — the ``audit_step_program``
    convention, applied to the batch drivers.  The runner DONATES its
    state args, so the captured operands are handed back as
    ``ShapeDtypeStruct`` leaves (lowering needs avals, not buffers).
    ``runner`` overrides the default ``_RUNNERS[workload]`` entry —
    the serving-frontier contracts pass :func:`run_serving_batch`
    (same interception, different batch driver)."""
    import contextlib

    captured = {}
    orig = scenario_program

    def capture(per_scenario, example_args, **kw):
        prog = orig(per_scenario, example_args, **kw)
        captured["prog"] = prog
        captured["args"] = tuple(
            jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), a)
            for a in example_args)
        return prog

    if runner is None:
        def runner(b, m):
            return _RUNNERS[workload](b, mesh=m)

    import gossip_glomers_tpu.tpu_sim.scenario as _self
    with contextlib.ExitStack() as stack:
        stack.callback(setattr, _self, "scenario_program", orig)
        setattr(_self, "scenario_program", capture)
        _PROGS.clear()
        runner(batch, mesh)
    return captured["prog"], captured["args"]
