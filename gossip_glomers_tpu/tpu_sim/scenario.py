"""Scenario-axis fault-space batching (PR 10): S independent nemesis
campaigns as ONE compiled program.

Every nemesis artifact in this repo is seed-deterministic, stateless-
hash-driven, and JSON-able (faults.NemesisSpec -> FaultPlan; the
loss/dup coins are pure (t, src, dst) hashes), and nothing in a faulted
round depends on host control flow — so a whole *batch* of fault
campaigns vmaps: the per-scenario FaultPlans (and partition schedules,
and per-edge delay matrices) are padded to common window counts and
STACKED leaf-by-leaf with a leading scenario axis (faults.batch_plans /
:func:`batch_partitions`), and ``jax.vmap`` of the ordinary gather-path
round slices them back into per-scenario operands.  One dispatch then
runs hundreds of crash x loss x dup x partition x delay campaigns —
the scenario-diversity multiplier no process-per-node harness
(Maelstrom included) can imitate: coverage goes from "27 cells" to
"the fault space" (benchmarks/fault_sweep.py ``--fuzz``,
harness/fuzz.py).

**Placement** (engine.scenario_placement): with a mesh and S a
multiple of the device count, the SCENARIO axis is sharded over the
mesh — each device runs S/devices whole scenarios with identity
collectives, so the compiled batch program contains ZERO collective
ops (cap-0 census rows in :func:`audit_contracts`).  Smaller or uneven
batches pad up with inert filler scenarios (:func:`pad_batch`) rather
than shard the node axis: the fuzzer's unit of work is the scenario.

**Certification without host round-trips**: the per-scenario driver
(:func:`certify_loop`) is a check-then-step ``fori_loop`` that records
each scenario's FIRST converged round on device and then FREEZES the
scenario (a per-scenario ``where`` select), reproducing the sequential
``run_*_nemesis`` loop — which stops stepping at convergence —
BIT-EXACTLY: final state, msgs ledgers, converged rounds, and (when a
ring rides the carry) the telemetry series all match the
single-scenario runners (tests/test_scenario.py, single-device and
8-way mesh).  The batched outputs are tiny per-scenario rows
(converged round, msgs at clear, final ledger) plus the stacked final
states — ONE host transfer after the dispatch, nothing per scenario
in the hot loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import broadcast as B
from . import counter as CT
from . import faults, kafka as KF, telemetry
from .engine import scenario_placement, scenario_program

# The module's host/device split, DECLARED (the PR-6 faults.py
# pattern): the determinism lint (tpu_sim/audit.py) treats exactly
# TRACED_EVALUATORS as traced scope; tests/test_scenario.py pins the
# split TOTAL.  `_build_batch_program`'s nested defs are traced via
# the builder mechanism (audit._BUILDERS).
TRACED_EVALUATORS = ("certify_loop",)
HOST_SIDE = (
    "batch_partitions", "pad_batch", "stack_pytrees", "stage_kafka_batch",
    "run_broadcast_batch", "run_counter_batch", "run_kafka_batch",
    "run_scenario_batch", "batch_state_bytes", "audit_contracts",
    "_build_batch_program", "_place", "_verdict_rows",
    "_audit_program")


# -- scenario cases ------------------------------------------------------


@dataclass(frozen=True)
class Scenario:
    """One cell of the fault space — JSON-able, seed-deterministic.

    ``spec`` is the crash/loss/dup nemesis; ``parts`` an optional
    partition-schedule meta dict (broadcast only, the
    ``Partitions.to_meta`` shape); ``delays`` an optional (N, D)
    per-edge delay matrix as nested lists (broadcast gather path
    only); ``workload_seed`` seeds the kafka send staging."""

    spec: faults.NemesisSpec
    parts: dict | None = None
    delays: tuple | None = None
    workload_seed: int = 0

    def __post_init__(self) -> None:
        if self.delays is not None:
            object.__setattr__(
                self, "delays",
                tuple(tuple(int(v) for v in row)
                      for row in self.delays))

    def to_meta(self) -> dict:
        return {"spec": self.spec.to_meta(), "parts": self.parts,
                "delays": (None if self.delays is None
                           else [list(r) for r in self.delays]),
                "workload_seed": self.workload_seed}

    @staticmethod
    def from_meta(meta: dict) -> "Scenario":
        return Scenario(
            spec=faults.NemesisSpec.from_meta(meta["spec"]),
            parts=meta.get("parts"),
            delays=(None if meta.get("delays") is None
                    else tuple(tuple(r) for r in meta["delays"])),
            workload_seed=int(meta.get("workload_seed", 0)))


@dataclass(frozen=True)
class ScenarioBatch:
    """S scenarios + the static run shape they share — JSON-able
    (:meth:`to_meta`), dispatched by :func:`run_scenario_batch`.
    ``runner_kw`` holds the per-workload static knobs (broadcast:
    ``n_values``/``topology``/``sync_every``; counter: ``mode``/
    ``poll_every``; kafka: ``n_keys``/``capacity``/``max_sends``/
    ``resync_every``/``rounds``/``send_prob``)."""

    workload: str
    scenarios: tuple = field(default_factory=tuple)
    runner_kw: dict = field(default_factory=dict)
    max_recovery_rounds: int = 64

    def __post_init__(self) -> None:
        if self.workload not in ("broadcast", "counter", "kafka"):
            raise ValueError(
                f"unknown scenario workload {self.workload!r}")
        if not self.scenarios:
            raise ValueError("a ScenarioBatch needs >= 1 scenario")
        object.__setattr__(self, "scenarios", tuple(
            sc if isinstance(sc, Scenario) else Scenario(spec=sc)
            for sc in self.scenarios))
        n = self.scenarios[0].spec.n_nodes
        for sc in self.scenarios:
            if sc.spec.n_nodes != n:
                raise ValueError(
                    "scenario batch mixes node counts "
                    f"{n} and {sc.spec.n_nodes}")

    @property
    def n_nodes(self) -> int:
        return self.scenarios[0].spec.n_nodes

    def to_meta(self) -> dict:
        return {"workload": self.workload,
                "scenarios": [sc.to_meta() for sc in self.scenarios],
                "runner_kw": dict(self.runner_kw),
                "max_recovery_rounds": self.max_recovery_rounds}

    @staticmethod
    def from_meta(meta: dict) -> "ScenarioBatch":
        return ScenarioBatch(
            workload=str(meta["workload"]),
            scenarios=tuple(Scenario.from_meta(m)
                            for m in meta["scenarios"]),
            runner_kw=dict(meta.get("runner_kw", {})),
            max_recovery_rounds=int(meta.get("max_recovery_rounds",
                                             64)))


def pad_batch(batch: ScenarioBatch, multiple: int) -> tuple:
    """(padded batch, n_real): pad the scenario list up to a multiple
    of ``multiple`` with inert fault-free filler scenarios (zero-rate,
    windowless — they converge immediately and are dropped from the
    results), so a mesh can always take scenario placement
    (engine.scenario_placement)."""
    s = len(batch.scenarios)
    if multiple <= 1 or s % multiple == 0:
        return batch, s
    pad = multiple - s % multiple
    filler = Scenario(spec=faults.NemesisSpec(n_nodes=batch.n_nodes))
    has_delays = any(sc.delays is not None for sc in batch.scenarios)
    if has_delays:
        d0 = next(sc.delays for sc in batch.scenarios
                  if sc.delays is not None)
        ones = tuple(tuple(1 for _ in row) for row in d0)
        filler = Scenario(spec=filler.spec, delays=ones)
    return ScenarioBatch(
        workload=batch.workload,
        scenarios=batch.scenarios + (filler,) * pad,
        runner_kw=batch.runner_kw,
        max_recovery_rounds=batch.max_recovery_rounds), s


# -- batched operands ----------------------------------------------------


def batch_partitions(metas, n_nodes: int) -> B.Partitions:
    """Pad + stack per-scenario partition schedules (None = no
    windows) into one batched :class:`~.broadcast.Partitions` with a
    leading scenario axis.  Pad windows are never-active ``[0, 0)``
    with an all-zero group row — the same padding semantics as
    faults.pad_plan (bit-identical evaluation)."""
    parts = [B.Partitions.none(n_nodes) if m is None
             else B.Partitions.from_meta(m) for m in metas]
    p_max = max(int(p.starts.shape[0]) for p in parts)
    if p_max == 0:
        z = jnp.zeros((len(parts), 0), jnp.int32)
        return B.Partitions(z, z, jnp.zeros(
            (len(parts), 0, n_nodes), jnp.int8))

    def pad(p: B.Partitions) -> B.Partitions:
        c = int(p.starts.shape[0])
        if c == p_max:
            return p
        extra = p_max - c
        return B.Partitions(
            jnp.concatenate([p.starts,
                             jnp.zeros((extra,), jnp.int32)]),
            jnp.concatenate([p.ends, jnp.zeros((extra,), jnp.int32)]),
            jnp.concatenate([p.group, jnp.zeros((extra, n_nodes),
                                                jnp.int8)], axis=0))

    parts = [pad(p) for p in parts]
    return B.Partitions(*(jnp.stack([p[i] for p in parts])
                          for i in range(3)))


def stack_pytrees(trees):
    """Stack a list of identically-structured pytrees leaf-by-leaf
    along a new leading scenario axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def stage_kafka_batch(batch: ScenarioBatch, rounds: int, *,
                      n_keys: int, max_sends: int,
                      send_prob: float) -> tuple:
    """(S, R, N, Smax) send batches for a kafka scenario batch —
    per scenario EXACTLY the vectorized commit-free staging of
    harness.nemesis.stage_kafka_ops (same rng call order, so the
    sequential runner replays the identical campaign), padded with -1
    no-op rounds from the scenario's own clear round to the common
    horizon ``rounds`` (a padded round stages nothing — the same
    empty batch the sequential recovery loop drives)."""
    from ..harness.nemesis import stage_kafka_ops

    sks_all, svs_all = [], []
    for sc in batch.scenarios:
        r_s = max(sc.spec.clear_round,
                  int(batch.runner_kw.get("rounds") or 0))
        sks, svs, _crs = stage_kafka_ops(
            sc.spec, r_s, n_keys=n_keys, max_sends=max_sends,
            send_prob=send_prob, workload_seed=sc.workload_seed,
            commits=False)
        if r_s < rounds:
            pad = rounds - r_s
            n = sc.spec.n_nodes
            sks = np.concatenate(
                [sks, np.full((pad, n, max_sends), -1, np.int32)])
            svs = np.concatenate(
                [svs, np.zeros((pad, n, max_sends), np.int32)])
        sks_all.append(sks)
        svs_all.append(svs)
    return (jnp.asarray(np.stack(sks_all)),
            jnp.asarray(np.stack(svs_all)))


# -- the per-scenario certification driver (traced) ----------------------


def certify_loop(step1, conv, state, clear, max_rec: int,
                 r_total: int, tel=None, tel_row=None, tel_mask=None):
    """ONE scenario's whole campaign as a fixed-trip ``fori_loop``
    (traced; vmapped over the scenario axis by the batch programs):

    - before each round, if the scenario is past its own clear round
      and not yet converged, test convergence and record the FIRST
      converged round (`conv_round`; -1 = never within bound);
    - record ``msgs`` when ``t == clear`` (the faulted-phase ledger
      check_recovery's degraded-throughput ratio needs);
    - step only while ACTIVE (not converged, not past
      ``clear + max_rec``) — a frozen scenario carries its final state
      unchanged, which is exactly where the sequential
      ``run_*_nemesis`` loop stops stepping, so the batched final
      state is bit-identical to the sequential one;
    - with a telemetry ring (``tel``), record each ACTIVE round's row
      (``tel_row(s0, s1)``) — frozen scenarios stop recording, like
      the sequential observed drivers stop stepping.

    Returns ``(state, conv_round, msgs_at_clear[, tel])``.
    """
    bound = clear + jnp.int32(max_rec)

    def check(st, cr):
        done_now = (st.t >= clear) & (cr < 0) & conv(st)
        return jnp.where(done_now, st.t, cr)

    def freeze(active, new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(active, a, b), new, old)

    if tel is None:
        def body(i, carry):
            st, cr, mc = carry
            cr = check(st, cr)
            mc = jnp.where(st.t == clear, st.msgs, mc)
            active = (cr < 0) & (st.t < bound)
            st = freeze(active, step1(st, i), st)
            return (st, cr, mc)

        st, cr, mc = lax.fori_loop(
            0, r_total, body, (state, jnp.int32(-1), jnp.uint32(0)))
        return st, check(st, cr), mc

    def body_tel(i, carry):
        st, cr, mc, tl = carry
        cr = check(st, cr)
        mc = jnp.where(st.t == clear, st.msgs, mc)
        active = (cr < 0) & (st.t < bound)
        s2 = step1(st, i)
        tl = freeze(active,
                    telemetry.record(tl, st.t, tel_row(st, s2),
                                     tel_mask), tl)
        st = freeze(active, s2, st)
        return (st, cr, mc, tl)

    st, cr, mc, tl = lax.fori_loop(
        0, r_total, body_tel,
        (state, jnp.int32(-1), jnp.uint32(0), tel))
    return st, check(st, cr), mc, tl


# -- batch program construction ------------------------------------------

# compiled batch programs, keyed by the full static shape (workload,
# scenario count, state shapes, trip count, telemetry spec, mesh)
_PROGS: dict = {}


def _place(args, mesh):
    """Device-put every batched operand with its scenario sharding
    (leading axis over the mesh's device axis) when scenario placement
    applies; no-op off mesh.  (Donation is the program's concern —
    _build_batch_program's donate_argnums.)"""
    s = jax.tree_util.tree_leaves(args[0])[0].shape[0]
    if scenario_placement(s, mesh) == "single":
        return args
    sh = NamedSharding(mesh, P("nodes"))
    return tuple(
        jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), a)
        for a in args)


def _build_batch_program(workload: str, per_scenario, example_args,
                         mesh, donate_argnums, key):
    """Build (or fetch) the ONE compiled program of a batch shape:
    ``jax.vmap`` of the per-scenario certify driver, scenario-sharded
    via engine.scenario_program.  Cached so a fuzz sweep reuses one
    compiled program across every batch of the same shape."""
    full_key = (workload, key, id(mesh),
                jax.tree_util.tree_structure(example_args),
                tuple((tuple(leaf.shape), str(leaf.dtype))
                      for leaf in
                      jax.tree_util.tree_leaves(example_args)))
    if full_key not in _PROGS:
        _PROGS[full_key] = scenario_program(
            per_scenario, example_args, mesh=mesh,
            donate_argnums=donate_argnums)
    return _PROGS[full_key]


def _verdict_rows(batch: ScenarioBatch, conv_round, msgs_clear,
                  msgs_final, lost_lists, extra=None) -> dict:
    """Assemble the batch result: per-scenario verdict rows via the
    batched recovery certifier (checkers.check_recovery_batch — a
    single planted bad scenario fails loudly and names its index)."""
    from ..harness.checkers import check_recovery_batch

    clears = np.array([sc.spec.clear_round
                       for sc in batch.scenarios], np.int64)
    ok, det = check_recovery_batch(
        clear_rounds=clears,
        converged_rounds=np.asarray(conv_round, np.int64),
        max_recovery_rounds=batch.max_recovery_rounds,
        lost_writes=lost_lists,
        msgs_at_clear=np.asarray(msgs_clear, np.int64),
        msgs_at_converged=np.asarray(msgs_final, np.int64))
    rows = []
    for i, sc in enumerate(batch.scenarios):
        row = dict(det["scenarios"][i])
        row.update(workload=batch.workload, scenario=i,
                   spec=sc.spec.to_meta(),
                   msgs_total=int(np.asarray(msgs_final)[i]))
        if sc.parts is not None:
            row["parts"] = sc.parts
        if sc.delays is not None:
            row["delays"] = [list(r) for r in sc.delays]
        if extra is not None:
            row.update(extra[i])
        rows.append(row)
    return {"ok": ok, "workload": batch.workload,
            "n_scenarios": len(rows),
            "failing": det["failing"], "scenarios": rows}


# -- per-workload batch drivers ------------------------------------------


def run_broadcast_batch(batch: ScenarioBatch, *, mesh=None,
                        telemetry_spec=None) -> dict:
    """S broadcast campaigns in ONE dispatch: values injected
    round-robin at round 0, per-scenario convergence = every node
    holds every value, lost acked writes = values absent from every
    node at the scenario's own stop round.  The fault space per
    scenario: crash/loss/dup (``spec``) x partition windows
    (``parts``) x per-edge delays (``delays`` — static delay classes,
    the history-ring gather path).  Returns the batch verdict dict
    (see :func:`_verdict_rows`) plus per-scenario telemetry series
    when ``telemetry_spec`` rides along."""
    kw = batch.runner_kw
    n = batch.n_nodes
    nv = int(kw.get("n_values") or 2 * n)
    topology = kw.get("topology", "grid")
    sync_every = int(kw.get("sync_every", 4))
    from ..parallel.topology import grid, to_padded_neighbors, tree
    nbrs_np = to_padded_neighbors(
        {"grid": grid, "tree": tree}[topology](n))
    nbrs = jnp.asarray(nbrs_np, jnp.int32)
    nbr_mask = jnp.asarray(nbrs_np >= 0)

    scs = batch.scenarios
    s_count = len(scs)
    dup_on = any(sc.spec.dup_rate > 0 for sc in scs)
    has_delays = any(sc.delays is not None for sc in scs)
    if has_delays:
        dmats = []
        for sc in scs:
            d = (np.asarray(sc.delays, np.int32)
                 if sc.delays is not None
                 else np.ones(nbrs_np.shape, np.int32))
            if d.shape != nbrs_np.shape:
                raise ValueError(
                    f"scenario delays shape {d.shape} != adjacency "
                    f"{nbrs_np.shape}")
            dmats.append(np.where(nbrs_np >= 0, d, 1))
        delay_set = tuple(int(v) for v in
                          np.unique(np.stack(dmats)))
        delays_b = jnp.asarray(np.stack(dmats))
        ring = max(delay_set)
    else:
        delay_set, delays_b, ring = (), None, 0

    plans = faults.batch_plans([sc.spec for sc in scs])
    parts_b = batch_partitions([sc.parts for sc in scs], n)
    clears = jnp.asarray(
        np.array([sc.spec.clear_round for sc in scs], np.int32))
    max_clear = int(np.max(np.asarray(clears)))
    r_total = max_clear + batch.max_recovery_rounds

    inject = B.make_inject(n, nv)
    target = jnp.asarray(np.bitwise_or.reduce(
        inject.astype(np.uint32), axis=0))
    targets = jnp.broadcast_to(target, (s_count,) + target.shape)

    def one_state():
        rec = jnp.asarray(inject.astype(np.uint32))
        hist = (jnp.zeros((ring, n, B.num_words(nv)), jnp.uint32)
                if has_delays else None)
        return B.BroadcastState(received=rec, frontier=jnp.copy(rec),
                                t=jnp.int32(0), msgs=jnp.uint32(0),
                                history=hist, srv_msgs=None)

    states = stack_pytrees([one_state() for _ in range(s_count)])
    rnd = B._build_batch_round(nbrs, nbr_mask, sync_every=sync_every,
                               dup_on=dup_on, delay_set=delay_set)
    tl = telemetry_spec is not None
    tel_mask = telemetry_spec.static_mask if tl else None
    sim = (B.BroadcastSim(nbrs_np, n_values=nv, sync_every=sync_every,
                          srv_ledger=False) if tl else None)

    if has_delays:
        def one(state, plan, parts, delays, clear, target, *tel_a):
            step1 = lambda st, i: rnd(st, plan, parts,  # noqa: E731
                                      delays)
            conv = lambda st: B._batch_converged(st,   # noqa: E731
                                                 target)
            row = ((lambda s0, s1: sim._tel_series(
                s0, s1, plan, lambda x: x)) if tl else None)
            return certify_loop(step1, conv, state, clear,
                                batch.max_recovery_rounds, r_total,
                                tel_a[0] if tl else None, row,
                                tel_mask)

        args = [states, plans, parts_b, delays_b, clears, targets]
    else:
        def one(state, plan, parts, clear, target, *tel_a):
            step1 = lambda st, i: rnd(st, plan, parts)  # noqa: E731
            conv = lambda st: B._batch_converged(st,   # noqa: E731
                                                 target)
            row = ((lambda s0, s1: sim._tel_series(
                s0, s1, plan, lambda x: x)) if tl else None)
            return certify_loop(step1, conv, state, clear,
                                batch.max_recovery_rounds, r_total,
                                tel_a[0] if tl else None, row,
                                tel_mask)

        args = [states, plans, parts_b, clears, targets]
    dn = (0,) + ((len(args),) if tl else ())
    if tl:
        args.append(stack_pytrees(
            [telemetry.init_state(telemetry_spec)
             for _ in range(s_count)]))
    args = _place(tuple(args), mesh)
    prog = _build_batch_program(
        "broadcast", one, args, mesh, dn,
        key=(n, nv, topology, sync_every, s_count, r_total, dup_on,
             delay_set, int(plans.starts.shape[1]),
             int(parts_b.starts.shape[1]), telemetry_spec))
    out = prog(*args)
    final, conv_round, msgs_clear = out[0], out[1], out[2]
    rec = np.asarray(final.received)                  # (S, N, W)
    anywhere = np.bitwise_or.reduce(rec, axis=1)      # (S, W)
    lost_lists = [
        [v for v in range(nv)
         if not (anywhere[i, v // 32] >> (v % 32)) & 1]
        for i in range(s_count)]
    res = _verdict_rows(batch, conv_round, msgs_clear,
                        np.asarray(final.msgs), lost_lists)
    res.update(n_nodes=n, n_values=nv, topology=topology,
               final=final)
    if tl:
        res["telemetry"] = [
            telemetry.series_arrays(
                jax.tree_util.tree_map(lambda x, i=i: x[i], out[3]),
                telemetry_spec)
            for i in range(s_count)]
    return res


def run_counter_batch(batch: ScenarioBatch, *, mesh=None,
                      telemetry_spec=None) -> dict:
    """S g-counter campaigns in ONE dispatch: per-node deltas acked at
    round 0 (the sequential runner's default ``arange(1, n+1)``),
    convergence = pending drained AND every cached read equals the KV,
    lost acked writes = the final ``acked_sum - kv - pending``
    shortfall (amnesia-killed deltas)."""
    kw = batch.runner_kw
    n = batch.n_nodes
    mode = kw.get("mode", "cas")
    poll_every = int(kw.get("poll_every", 2))
    scs = batch.scenarios
    s_count = len(scs)
    sim = CT.CounterSim(n, mode=mode, poll_every=poll_every)
    deltas = np.arange(1, n + 1, dtype=np.int32)
    acked_sum = int(deltas.sum())

    plans = faults.batch_plans([sc.spec for sc in scs])
    clears = jnp.asarray(
        np.array([sc.spec.clear_round for sc in scs], np.int32))
    r_total = (int(np.max(np.asarray(clears)))
               + batch.max_recovery_rounds)

    def one_state():
        st = sim.init_state()
        return st._replace(pending=st.pending
                           + jnp.asarray(deltas))

    states = stack_pytrees([one_state() for _ in range(s_count)])
    rnd = CT._build_batch_round(sim)
    tl = telemetry_spec is not None
    tel_mask = telemetry_spec.static_mask if tl else None
    from .engine import collectives
    coll = collectives(n)

    def one(state, plan, clear, *tel_a):
        step1 = lambda st, i: rnd(st, plan)            # noqa: E731
        row = ((lambda s0, s1: sim._tel_series(
            s0, s1, coll, sim.kv_sched, plan)) if tl else None)
        return certify_loop(step1, CT._batch_converged, state, clear,
                            batch.max_recovery_rounds, r_total,
                            tel_a[0] if tl else None, row, tel_mask)

    args = [states, plans, clears]
    dn = (0,) + ((len(args),) if tl else ())
    if tl:
        args.append(stack_pytrees(
            [telemetry.init_state(telemetry_spec)
             for _ in range(s_count)]))
    args = _place(tuple(args), mesh)
    prog = _build_batch_program(
        "counter", one, args, mesh, dn,
        key=(n, mode, poll_every, s_count, r_total,
             int(plans.starts.shape[1]), telemetry_spec))
    out = prog(*args)
    final, conv_round, msgs_clear = out[0], out[1], out[2]
    kv = np.asarray(final.kv)
    pend = np.asarray(final.pending).sum(axis=1)
    shortfall = acked_sum - kv - pend
    lost_lists = [([{"lost_sum": int(shortfall[i])}]
                   if shortfall[i] != 0 else [])
                  for i in range(s_count)]
    res = _verdict_rows(batch, conv_round, msgs_clear,
                        np.asarray(final.msgs), lost_lists,
                        extra=[{"acked_sum": acked_sum,
                                "kv": int(kv[i])}
                               for i in range(s_count)])
    res.update(n_nodes=n, mode=mode, final=final)
    if tl:
        res["telemetry"] = [
            telemetry.series_arrays(
                jax.tree_util.tree_map(lambda x, i=i: x[i], out[3]),
                telemetry_spec)
            for i in range(s_count)]
    return res


def run_kafka_batch(batch: ScenarioBatch, *, mesh=None,
                    telemetry_spec=None) -> dict:
    """S replicated-log campaigns in ONE dispatch: per-scenario seeded
    send traffic at live nodes (commit-free vectorized staging — the
    sequential runner's ``commits=False`` regime), the FAULTED
    origin-union replication path, convergence = every node's presence
    bitset identical, lost acked writes = allocated slots present at
    NO node (+ any committed-offset cache exceeding the shared
    cell)."""
    kw = batch.runner_kw
    n = batch.n_nodes
    n_keys = int(kw.get("n_keys", 4))
    capacity = int(kw.get("capacity", 64))
    max_sends = int(kw.get("max_sends", 2))
    resync_every = int(kw.get("resync_every", 4))
    send_prob = float(kw.get("send_prob", 0.7))
    scs = batch.scenarios
    s_count = len(scs)
    sim = KF.KafkaSim(n, n_keys, capacity=capacity,
                      max_sends=max_sends, resync_every=resync_every)

    plans = faults.batch_plans([sc.spec for sc in scs])
    clears_np = np.array(
        [max(sc.spec.clear_round, int(kw.get("rounds") or 0))
         for sc in scs], np.int32)
    clears = jnp.asarray(clears_np)
    max_clear = int(clears_np.max())
    r_total = max_clear + batch.max_recovery_rounds
    sks, svs = stage_kafka_batch(batch, r_total, n_keys=n_keys,
                                 max_sends=max_sends,
                                 send_prob=send_prob)

    states = stack_pytrees([sim.init_state()
                            for _ in range(s_count)])
    rnd = KF._build_batch_round(sim)
    tl = telemetry_spec is not None
    tel_mask = telemetry_spec.static_mask if tl else None
    full_scan = (tl and "present_bits_full" in telemetry_spec.series)
    from .engine import collectives
    coll = collectives(n)

    def one(state, plan, sk_r, sv_r, clear, *tel_a):
        def step1(st, i):
            sk = lax.dynamic_index_in_dim(sk_r, i, axis=0,
                                          keepdims=False)
            sv = lax.dynamic_index_in_dim(sv_r, i, axis=0,
                                          keepdims=False)
            return rnd(st, plan, sk, sv)

        row = ((lambda s0, s1: sim._tel_series(
            s0, s1, coll, plan, full_scan)) if tl else None)
        return certify_loop(step1, KF._batch_converged, state, clear,
                            batch.max_recovery_rounds, r_total,
                            tel_a[0] if tl else None, row, tel_mask)

    args = [states, plans, sks, svs, clears]
    dn = (0,) + ((len(args),) if tl else ())
    if tl:
        args.append(stack_pytrees(
            [telemetry.init_state(telemetry_spec)
             for _ in range(s_count)]))
    args = _place(tuple(args), mesh)
    prog = _build_batch_program(
        "kafka", one, args, mesh, dn,
        key=(n, n_keys, capacity, max_sends, resync_every, s_count,
             r_total, int(plans.starts.shape[1]), telemetry_spec))
    out = prog(*args)
    final, conv_round, msgs_clear = out[0], out[1], out[2]
    pres = np.asarray(final.present) > 0              # (S, N, K, Wc)
    log_vals = np.asarray(final.log_vals)             # (S, K, C)
    lost_lists = []
    for i in range(s_count):
        allocated = log_vals[i] >= 0
        anywhere = np.zeros_like(allocated)
        p = np.asarray(final.present)[i]              # (N, K, Wc)
        bits = np.unpackbits(
            p.view(np.uint8), axis=-1, bitorder="little")
        anywhere = bits.any(axis=0)[:, :allocated.shape[1]]
        lost = [(int(k), int(c) + 1)
                for k, c in zip(*np.nonzero(allocated
                                            & ~anywhere))]
        kvv = np.asarray(final.kv_val)[i]
        lc = np.asarray(final.local_committed)[i]
        over = lc > np.where(kvv > 0, kvv, 0)[None, :]
        lost += [{"committed_over_cell": (int(a), int(b))}
                 for a, b in zip(*np.nonzero(over))]
        lost_lists.append(lost)
    res = _verdict_rows(
        batch, conv_round, msgs_clear, np.asarray(final.msgs),
        lost_lists,
        extra=[{"n_allocated": int((log_vals[i] >= 0).sum())}
               for i in range(s_count)])
    res.update(n_nodes=n, n_keys=n_keys, final=final)
    if tl:
        res["telemetry"] = [
            telemetry.series_arrays(
                jax.tree_util.tree_map(lambda x, i=i: x[i], out[3]),
                telemetry_spec)
            for i in range(s_count)]
    return res


_RUNNERS = {"broadcast": run_broadcast_batch,
            "counter": run_counter_batch,
            "kafka": run_kafka_batch}


def run_scenario_batch(batch: ScenarioBatch, *, mesh=None,
                       telemetry_spec=None,
                       pad_to_mesh: bool = True) -> dict:
    """Dispatch one :class:`ScenarioBatch` (pad to the device count
    first when a mesh is given, dropping the filler rows from the
    result) — the fuzzer's unit of work."""
    n_real = len(batch.scenarios)
    if mesh is not None and pad_to_mesh:
        batch, n_real = pad_batch(batch, int(mesh.shape["nodes"]))
    res = _RUNNERS[batch.workload](batch, mesh=mesh,
                                   telemetry_spec=telemetry_spec)
    if n_real < res["n_scenarios"]:
        res["scenarios"] = res["scenarios"][:n_real]
        res["failing"] = [i for i in res["failing"] if i < n_real]
        if "telemetry" in res:
            res["telemetry"] = res["telemetry"][:n_real]
        res["n_scenarios"] = n_real
        res["ok"] = not res["failing"]
    return res


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def batch_state_bytes(workload: str, s_local: int, n: int, *,
                      nv: int = 0, n_keys: int = 0,
                      capacity: int = 0) -> int:
    """Per-shard donated state bytes of a scenario-batch program
    (``s_local`` scenarios per device) — the donation/memory claim of
    the contract rows."""
    if workload == "broadcast":
        per = 2 * n * ((nv + 31) // 32) * 4
    elif workload == "counter":
        per = 2 * n * 4
    else:
        wc = (capacity + 31) // 32
        per = (n * n_keys * wc * 4 + n_keys * capacity * 4
               + n_keys * 4 + n * n_keys * 4)
    return s_local * per


def audit_contracts():
    """The scenario-batch drivers' :class:`~.audit.ProgramContract`
    rows: scenario placement runs every scenario's node axis LOCALLY,
    so the compiled batch program must contain ZERO collective ops of
    any kind (the cap-0 census over the whole COLLECTIVE_OPS family),
    alias the whole stacked state carry in place (donation scaled by
    S/devices), and sit in the analytic memory band of S_local x the
    single-scenario state."""
    from .audit import AuditProgram, ProgramContract
    from .engine import analytic_peak_bytes
    from .engine import operand_bytes as engine_operand_bytes

    def _specs(n, s):
        out = []
        for i in range(s):
            out.append(Scenario(spec=faults.random_spec(
                n, seed=i + 1, horizon=8,
                n_crash_windows=1 + i % 2, loss_rate=0.1,
                dup_rate=0.05 if i % 2 else 0.0)))
        return tuple(out)

    def broadcast_batch(mesh):
        n, nv, s = 32, 64, 16
        batch = ScenarioBatch(
            workload="broadcast", scenarios=_specs(n, s),
            runner_kw={"n_values": nv, "topology": "tree",
                       "sync_every": 4}, max_recovery_rounds=16)
        prog, args = _audit_program("broadcast", batch, mesh)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = batch_state_bytes("broadcast", s_local, n,
                                        nv=nv)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                faults.batch_plans([sc.spec
                                    for sc in batch.scenarios])),
            slab_bytes=s_local * n * ((nv + 31) // 32) * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def counter_batch(mesh):
        n, s = 32, 16
        batch = ScenarioBatch(
            workload="counter", scenarios=_specs(n, s),
            runner_kw={"mode": "cas", "poll_every": 2},
            max_recovery_rounds=16)
        prog, args = _audit_program("counter", batch, mesh)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = batch_state_bytes("counter", s_local, n)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                faults.batch_plans([sc.spec
                                    for sc in batch.scenarios])),
            slab_bytes=s_local * n * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def kafka_batch(mesh):
        n, s = 16, 16
        batch = ScenarioBatch(
            workload="kafka", scenarios=_specs(n, s),
            runner_kw={"n_keys": 4, "capacity": 32, "max_sends": 1,
                       "resync_every": 2, "send_prob": 0.5},
            max_recovery_rounds=12)
        prog, args = _audit_program("kafka", batch, mesh)
        s_local = s // (1 if mesh is None else 8)
        state_bytes = batch_state_bytes("kafka", s_local, n,
                                        n_keys=4, capacity=32)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(
                faults.batch_plans([sc.spec
                                    for sc in batch.scenarios])),
            slab_bytes=s_local * n * n * 1 * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="broadcast/scenario-batch-run",
            build=broadcast_batch,
            collectives={},
            donation=True,
            mem_lo=0.05, mem_hi=8.0,
            notes="scenario-sharded batched broadcast campaigns: S "
                  "whole scenarios vmapped, node axis local per "
                  "scenario — ZERO collective ops of any kind in the "
                  "compiled batch program; stacked state carry "
                  "aliases in place"),
        ProgramContract(
            name="counter/scenario-batch-run",
            build=counter_batch,
            collectives={},
            donation=True,
            mem_lo=0.02, mem_hi=12.0,
            notes="scenario-sharded batched counter campaigns: cap-0 "
                  "census over the whole collective family (identity "
                  "collectives per scenario)"),
        ProgramContract(
            name="kafka/scenario-batch-run",
            build=kafka_batch,
            collectives={},
            donation=True,
            mem_lo=0.02, mem_hi=12.0,
            notes="scenario-sharded batched kafka campaigns on the "
                  "faulted origin-union path: the batched program "
                  "keeps the union elementwise per scenario — no "
                  "all-gather, no ppermute, no matmul mask"),
    ]


def _audit_program(workload: str, batch: ScenarioBatch, mesh):
    """(jitted, example_args) of a batch driver: run the runner once
    with :func:`engine.scenario_program` intercepted so the EXACT
    jitted object the batch executed (and its staged operand shapes)
    is what the contract auditor lowers — the ``audit_step_program``
    convention, applied to the batch drivers.  The runner DONATES its
    state args, so the captured operands are handed back as
    ``ShapeDtypeStruct`` leaves (lowering needs avals, not buffers)."""
    import contextlib

    captured = {}
    orig = scenario_program

    def capture(per_scenario, example_args, **kw):
        prog = orig(per_scenario, example_args, **kw)
        captured["prog"] = prog
        captured["args"] = tuple(
            jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), a)
            for a in example_args)
        return prog

    import gossip_glomers_tpu.tpu_sim.scenario as _self
    with contextlib.ExitStack() as stack:
        stack.callback(setattr, _self, "scenario_program", orig)
        setattr(_self, "scenario_program", capture)
        _PROGS.clear()
        _RUNNERS[workload](batch, mesh=mesh)
    return captured["prog"], captured["args"]
