"""Vectorized globally-unique ID generation (challenge 2) on TPU.

The reference derives uniqueness from UUIDv1 = (timestamp, node-id,
clock-seq) — time plus identity, no coordination (unique-ids/main.go:
25-52, seeding the UUID node field from the Maelstrom node ID).  The
vectorized form keeps exactly those ingredients: an ID is the packed
triple

    (round t, node index, per-round sequence number)

which is unique by construction across the whole cluster with zero
messages — the same property the UUID approach buys, minus the random
padding (our node indices are already distinct, so no collision channel
exists at all).

One round mints up to G ids per node in a single fused op; at 1M nodes
x 32 ids that is 32M ids/round with no inter-chip traffic (the
``availability: total`` stance of the challenge — generation never
blocks on the network).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class UniqueIdsState(NamedTuple):
    t: jnp.ndarray        # () int32 — round (the "timestamp")
    minted: jnp.ndarray   # (N,) int32 — ids issued per node (ever)


class UniqueIdsSim:
    """Batched ID mint.  ``step(state, counts)`` issues ``counts[n]``
    ids at node n and returns (new_state, ids) where ids is
    (N, G, 3) int32 [t, node, seq] with -1 padding beyond counts."""

    def __init__(self, n_nodes: int, *, max_per_round: int = 4,
                 mesh: Mesh | None = None) -> None:
        self.n_nodes = n_nodes
        self.max_per_round = max_per_round
        self.mesh = mesh
        self._step = self._build_step()

    def init_state(self) -> UniqueIdsState:
        minted = jnp.zeros((self.n_nodes,), jnp.int32)
        if self.mesh is not None:
            from .engine import node_axes

            minted = shard_put(
                minted,
                NamedSharding(self.mesh, P(node_axes(self.mesh))))
        return UniqueIdsState(t=jnp.int32(0), minted=minted)

    def _build_step(self):
        g = self.max_per_round

        def mint(state: UniqueIdsState, counts, row_ids):
            seq = jnp.arange(g, dtype=jnp.int32)[None, :]      # (1, G)
            issue = seq < counts[:, None]                      # (rows, G)
            ids = jnp.stack(
                [jnp.broadcast_to(state.t, issue.shape),
                 jnp.broadcast_to(row_ids[:, None], issue.shape),
                 seq + jnp.zeros_like(counts)[:, None]], axis=-1)
            ids = jnp.where(issue[..., None], ids, -1)
            new = UniqueIdsState(t=state.t + 1,
                                 minted=state.minted + counts)
            return new, ids

        if self.mesh is None:
            row_ids = jnp.arange(self.n_nodes, dtype=jnp.int32)
            return jax.jit(
                lambda state, counts: mint(state, counts, row_ids))

        from jax import lax

        from .engine import jit_program, node_axes

        na = node_axes(self.mesh)
        node = P(na)
        state_spec = UniqueIdsState(P(), node)

        def step(state, counts):
            block = counts.shape[0]
            row_ids = (lax.axis_index(na) * block
                       + jnp.arange(block, dtype=jnp.int32))
            return mint(state, counts, row_ids)

        return jit_program(
            step, mesh=self.mesh, in_specs=(state_spec, node),
            out_specs=(state_spec, P(na, None, None)))

    def step(self, state: UniqueIdsState, counts: np.ndarray
             ) -> tuple[UniqueIdsState, jnp.ndarray]:
        c = jnp.asarray(counts, jnp.int32)
        if self.mesh is not None:
            from .engine import node_axes

            c = shard_put(
                c, NamedSharding(self.mesh, P(node_axes(self.mesh))))
        return self._step(state, c)

    @staticmethod
    def format_ids(ids: jnp.ndarray) -> list[str]:
        """Flatten a round's (N, G, 3) id block to wire-format strings
        ("t-node-seq", the analogue of the uuid string in
        generate_ok.id, unique-ids/main.go:36-52)."""
        arr = np.asarray(ids).reshape(-1, 3)
        return [f"{t:08x}-{n:08x}-{s:04x}"
                for t, n, s in arr if t >= 0]
