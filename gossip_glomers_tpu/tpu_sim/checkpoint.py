"""Checkpoint / resume for tpu_sim states.

The reference keeps all state in memory and loses it on restart (survey
§5 "Checkpoint / resume: none").  The vectorized backend makes durable
state nearly free: every sim state is a NamedTuple of arrays, so a
checkpoint is one compressed ``.npz`` per state — enough to stop a
million-node run mid-flight and resume it bit-exactly (tests assert the
resumed run equals the uninterrupted one).

Works for every tpu_sim state class (BroadcastState, CounterState,
KafkaState, UniqueIdsState, EchoState) and any future NamedTuple of
arrays.  Sharded states are gathered to host on save; ``restore`` takes
an optional ``device_put`` function to re-place arrays with their
shardings (e.g. ``sim.init_state``-style placement).
"""

from __future__ import annotations

import json
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np


def save(path: str, state: Any, meta: dict | None = None, *,
         fault_spec=None) -> None:
    """Write a NamedTuple-of-arrays state as one compressed npz.

    ``fault_spec``: the active nemesis spec of a faulted run (a
    ``faults.NemesisSpec`` or its ``to_meta()`` dict) — stored in the
    checkpoint meta under ``"fault_spec"`` so a resume can rebuild the
    IDENTICAL seeded :class:`~.faults.FaultPlan` (crash windows and
    loss/dup coins are pure functions of (spec, round), so a run
    checkpointed mid-fault-window and resumed equals the uninterrupted
    faulted run bit-exactly — tested)."""
    fields = getattr(state, "_fields", None)
    if fields is None:
        raise TypeError("state must be a NamedTuple of arrays")
    meta = dict(meta or {})
    if fault_spec is not None:
        meta["fault_spec"] = (fault_spec if isinstance(fault_spec, dict)
                              else fault_spec.to_meta())
    present = [f for f in fields if getattr(state, f) is not None]
    payload = {f: np.asarray(getattr(state, f)) for f in present}
    payload["__meta__"] = np.frombuffer(
        json.dumps({"fields": present,
                    "none_fields": [f for f in fields
                                    if f not in present],
                    "class": type(state).__name__,
                    **meta}).encode(), dtype=np.uint8)
    np.savez_compressed(path, **payload)


def fault_spec_from_meta(meta: dict):
    """Rebuild the checkpointed ``NemesisSpec`` from :func:`restore`'s
    meta dict, or None when the run was fault-free."""
    raw = meta.get("fault_spec")
    if raw is None:
        return None
    from .faults import NemesisSpec
    return NemesisSpec.from_meta(raw)


def restore(path: str, state_cls: type, *,
            device_put: Callable[[str, np.ndarray], Any] | None = None,
            ) -> tuple[Any, dict]:
    """Load a state saved by :func:`save`.  Returns (state, meta).

    ``device_put(field_name, host_array)`` may re-place each array (with
    a sharding); by default arrays become ordinary device arrays.
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta["class"] != state_cls.__name__:
            raise ValueError(
                f"checkpoint holds {meta['class']}, not "
                f"{state_cls.__name__}")
        vals = {}
        for f in meta["fields"]:
            arr = z[f]
            vals[f] = (device_put(f, arr) if device_put is not None
                       else jnp.asarray(arr))
        for f in meta.get("none_fields", []):
            vals[f] = None
    extra = {k: v for k, v in meta.items()
             if k not in ("fields", "none_fields", "class")}
    return state_cls(**vals), extra
