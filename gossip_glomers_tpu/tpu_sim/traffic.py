"""Open-loop client traffic for the vectorized backend: seeded arrival
schedules over a client axis, per-op latency tracking, and loud
backpressure accounting — Maelstrom's Layer-0 *rate-based workload
generator* (PAPER.md §1), vectorized.

Everything the repo measured before PR 7 was closed-loop: seed the
state, iterate rounds to convergence, check.  This module is the other
half of the harness — concurrent client ops arriving WHILE the system
runs, so runs report steady-state serving behavior (p50/p99 op latency
in rounds, sustained ops/round, backpressure) instead of
rounds-to-convergence.  Three pieces, each following an existing
design:

- **`TrafficSpec`** (the `NemesisSpec` shape): a host-side seeded,
  JSON-able spec over a *client axis* — Poisson (Bernoulli-per-round,
  i.e. geometric inter-arrivals: the round-synchronous Poisson
  process), constant-rate (a per-client fixed-point phase accumulator),
  or burst (rate-multiplier windows over the Poisson stream) —
  compiled to a tiny :class:`TrafficPlan` that rides through the fused
  drivers as ONE replicated traced operand next to a
  :class:`~.faults.FaultPlan`.
- **arrival coins** (the `faults.coin_block` pattern): arrivals are
  STATELESS hashes of ``(seed, round, client)``, evaluated per round on
  device — an arbitrary horizon never materializes an (R, clients)
  tensor, every shard sees the same coins, and a (spec, seed) pair
  replays bit-exactly across stepwise/fused/donated drivers and any
  client-slab blocking.
- **`TrafficState`** (rides the DONATED state pytree, one entry per op
  slot): each client owns ``ops_per_client`` op slots, so op identity
  ``(client, k)`` is static and the tracker arrays shard with the node
  axis (clients map to nodes by a block/stride rule that keeps each
  client's home node on its own shard — injection is shard-local, like
  the nemesis masks).  ``issue_round`` is recorded at injection;
  ``done_round`` at the first round the op's effect is *globally
  visible* (the workload's convergence predicate applied per op:
  broadcast — the value bit at every node; counter — every cache ≥ the
  KV value the op's flush landed in; kafka — the allocated (key, slot)
  presence bit at every node).  Latency = done − issue, in rounds.

**Backpressure is loud, never silent**: every arrival is classified
exactly once — *issued* (acked and tracked) or *deferred* (client got
backpressure: home node down, per-node intake saturated, op-slot
capacity exhausted, or — kafka — the allocation itself failed).
PR 17 adds the *resizing* backpressure class (:func:`resizing_defer` +
the ``deferred_resizing`` sub-counter): arrivals that land while an
elastic-resharding checkpoint-restore is in flight are deferred with
the cause named, never dropped.
Conservation ``arrived == issued + deferred`` and ``issued ==
completed + in_flight`` holds at every round and is pinned by
tests/test_traffic.py; an op that can never complete (an acked write
that died in an amnesia row) stays in flight forever and surfaces as a
lost acked op in the serving certifier (harness/serving.py), exactly
like `checkers.check_recovery`'s lost-writes evidence.

The sims' injection hooks and fused ``run_traffic`` drivers live with
the sims (broadcast/counter/kafka); this module owns the spec, the
coins, and the tracker so the three share one accounting contract.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import faults
from .engine import _env_int, scan_blocks, windows_fold

# The module's host/device split, DECLARED (the PR-6 faults.py
# pattern): the determinism lint (tpu_sim/audit.py) treats exactly
# TRACED_EVALUATORS as traced scope.  tests/test_traffic.py pins the
# split TOTAL, so a new module-level function must be added to one of
# these tuples (or be a class) or the test fails — new traced traffic
# code can never silently dodge the lint.
TRACED_EVALUATORS = (
    "arrive", "_arrival_num", "_client_hash", "local_node_cols",
    "intake_rank", "issue", "record_aux", "done_scan",
    "resizing_defer", "tel_series")
HOST_SIDE = (
    "plan_specs", "state_specs", "init_state", "client_nodes",
    "host_arrivals", "traffic_block", "latency_summary",
    "per_round_series", "offered_per_round", "pad_tplan",
    "batch_tplans")

# distinct stream salts off the shared (seed, t, id) counter family
_SALT_ARRIVE = 0x1B873593
_SALT_PHASE = 0xCC9E2D51
# kafka per-op key assignment draws from this stream (key is a pure
# function of (seed, client, slot) — recomputable at completion time)
SALT_KEY = 0xA2C2A35D


class TrafficPlan(NamedTuple):
    """Compiled device form of a :class:`TrafficSpec` — tiny replicated
    arrays threaded through drivers as a traced operand (never donated,
    never a baked-in constant), exactly like a FaultPlan."""

    kind: jnp.ndarray      # () int32 — 0 poisson, 1 constant
    rate_num: jnp.ndarray  # () uint32 — arrive iff hash < rate_num
    until: jnp.ndarray     # () int32 — arrivals for rounds [0, until)
    b_starts: jnp.ndarray  # (B,) int32 — burst window start (incl)
    b_ends: jnp.ndarray    # (B,) int32 — burst window end (excl)
    b_num: jnp.ndarray     # (B,) uint32 — in-window rate threshold
    seed: jnp.ndarray      # () uint32 — the replay key


def plan_specs() -> TrafficPlan:
    """shard_map in_specs for a :class:`TrafficPlan` operand: every
    leaf replicated (coins are evaluated per shard on global ids)."""
    return TrafficPlan(P(), P(), P(), P(None), P(None), P(None), P())


_KINDS = ("poisson", "constant")


@dataclass(frozen=True)
class TrafficSpec:
    """Host-side seeded open-loop traffic spec — JSON-able
    (:meth:`to_meta`) and ``compile()``-able to the device
    :class:`TrafficPlan`.

    ``n_clients`` clients each issue at most ONE op per round (offered
    load per client is capped at 1 op/round — ``rate`` is the mean
    arrivals per client per round, so total offered load is
    ``rate * n_clients`` ops/round).  Clients map to home nodes
    statically: ``n_clients >= n_nodes`` packs ``n_clients/n_nodes``
    clients per node (contiguous blocks), otherwise clients spread
    every ``n_nodes/n_clients``-th node — either way a client block
    lands on its home node's shard, so injection is shard-local.

    ``ops_per_client`` bounds each client's op slots (the tracker
    capacity): an arrival past it is DEFERRED loudly, never silently
    dropped.  ``intake`` caps how many arrivals one NODE accepts per
    round (None = no cap beyond the sims' own limits — kafka always
    caps at its ``max_sends`` batch width).  ``burst`` windows
    multiply the Poisson rate inside ``[start, end)`` rounds.
    """

    n_nodes: int
    n_clients: int
    ops_per_client: int
    until: int
    rate: float = 0.25
    kind: str = "poisson"
    burst: tuple = field(default_factory=tuple)   # ((start, end, mult),)
    intake: int | None = None
    seed: int = 0

    def __post_init__(self) -> None:
        if self.n_nodes < 1 or self.n_clients < 1:
            raise ValueError("need n_nodes >= 1 and n_clients >= 1")
        if not (self.n_clients % self.n_nodes == 0
                or self.n_nodes % self.n_clients == 0):
            raise ValueError(
                f"n_clients={self.n_clients} must divide or be "
                f"divisible by n_nodes={self.n_nodes} (the static "
                "client -> home-node map keeps injection shard-local)")
        if self.ops_per_client < 1:
            raise ValueError("ops_per_client must be >= 1")
        if self.n_clients * self.ops_per_client >= 2 ** 31:
            raise ValueError(
                "n_clients * ops_per_client must fit int32 op ids")
        if self.until < 1:
            raise ValueError("until must be >= 1 round")
        if not 0.0 < self.rate <= 1.0:
            raise ValueError(
                f"rate={self.rate} must be in (0, 1] — each client "
                "issues at most one op per round")
        if self.kind not in _KINDS:
            raise ValueError(f"unknown kind {self.kind!r}; "
                             f"one of {_KINDS}")
        norm = []
        for start, end, mult in self.burst:
            if not 0 <= int(start) < int(end) <= self.until:
                raise ValueError(
                    f"bad burst window [{start}, {end}): windows "
                    f"must lie inside the arrival horizon "
                    f"[0, {self.until})")
            if not 0.0 < float(mult) * self.rate <= 1.0:
                raise ValueError(
                    f"burst mult {mult} pushes the in-window rate "
                    f"past 1 op/client/round (rate={self.rate})")
            norm.append((int(start), int(end), float(mult)))
        for (s1, e1, _m1), (s2, e2, _m2) in zip(
                sorted(norm), sorted(norm)[1:]):
            if s2 < e1:
                raise ValueError(
                    f"burst windows [{s1}, {e1}) and [{s2}, {e2}) "
                    "overlap — the offered-load accounting (and the "
                    "last-window-wins device fold) need disjoint "
                    "windows")
        object.__setattr__(self, "burst", tuple(norm))
        if self.intake is not None and self.intake < 0:
            raise ValueError("intake must be >= 0 (or None)")

    # -- host mirrors ----------------------------------------------------

    @property
    def clients_per_node(self) -> int:
        return max(1, self.n_clients // self.n_nodes)

    @property
    def node_stride(self) -> int:
        return max(1, self.n_nodes // self.n_clients)

    # -- compilation -----------------------------------------------------

    def compile(self) -> TrafficPlan:
        b = len(self.burst)
        starts = np.zeros((b,), np.int32)
        ends = np.zeros((b,), np.int32)
        nums = np.zeros((b,), np.uint32)
        for w, (start, end, mult) in enumerate(self.burst):
            starts[w], ends[w] = start, end
            nums[w] = faults._rate_to_num(min(1.0, self.rate * mult))
        return TrafficPlan(
            kind=jnp.int32(_KINDS.index(self.kind)),
            rate_num=jnp.uint32(faults._rate_to_num(self.rate)),
            until=jnp.int32(self.until),
            b_starts=jnp.asarray(starts), b_ends=jnp.asarray(ends),
            b_num=jnp.asarray(nums),
            seed=jnp.uint32(self.seed & 0xFFFFFFFF))

    # -- checkpoint / bench meta ----------------------------------------

    def to_meta(self) -> dict:
        return {"n_nodes": self.n_nodes, "n_clients": self.n_clients,
                "ops_per_client": self.ops_per_client,
                "until": self.until, "rate": self.rate,
                "kind": self.kind,
                "burst": [list(w) for w in self.burst],
                "intake": self.intake, "seed": self.seed}

    @staticmethod
    def from_meta(meta: dict) -> "TrafficSpec":
        return TrafficSpec(
            n_nodes=int(meta["n_nodes"]),
            n_clients=int(meta["n_clients"]),
            ops_per_client=int(meta["ops_per_client"]),
            until=int(meta["until"]), rate=float(meta["rate"]),
            kind=str(meta.get("kind", "poisson")),
            burst=tuple(tuple(w) for w in meta.get("burst", ())),
            intake=meta.get("intake"), seed=int(meta.get("seed", 0)))

    def with_rate(self, rate: float) -> "TrafficSpec":
        """The serving-curve sweep knob: same spec, new offered load."""
        return replace(self, rate=rate)

    @property
    def program_key(self) -> tuple:
        """The STATIC (trace-relevant) part of the spec.  A traffic
        driver compiled for one key runs ANY spec sharing it — rate,
        seed, kind, horizon, and the burst window values all ride the
        compiled :class:`TrafficPlan` as traced operands — so a
        serving-curve load sweep reuses one compiled program across
        its rates."""
        return (self.n_nodes, self.n_clients, self.ops_per_client,
                self.intake, len(self.burst))


# -- scenario-axis batching (PR 13, the faults.pad_plan/batch_plans
#    mirror) --------------------------------------------------------------
#
# Padding semantics: a pad burst window is ``[0, 0)`` with a zero
# in-window threshold — ``b_starts[w] <= t < b_ends[w]`` is
# unsatisfiable at every t, so the windows_fold in :func:`_arrival_num`
# treats it as never-active and a padded plan draws BIT-IDENTICAL
# arrival coins (pinned by tests/test_frontier.py).  All specs in a
# batch must share the STATIC program_key fields (n_nodes, n_clients,
# ops_per_client, intake — they shape the compiled program); rate,
# seed, kind, horizon and the burst values stack into (S,) / (S, B)
# traced operands, exactly like a batched FaultPlan.


def pad_tplan(plan: TrafficPlan, n_burst: int) -> TrafficPlan:
    """Pad a compiled traffic plan's burst-window axis to ``n_burst``
    with never-active ``[0, 0)`` windows (see above).  Evaluation is
    bit-identical — the pad windows fold as inactive at every round."""
    b = int(plan.b_starts.shape[0])
    if b > n_burst:
        raise ValueError(
            f"plan has {b} burst windows, cannot pad to {n_burst}")
    if b == n_burst:
        return plan
    pad = n_burst - b
    return plan._replace(
        b_starts=jnp.concatenate(
            [plan.b_starts, jnp.zeros((pad,), jnp.int32)]),
        b_ends=jnp.concatenate(
            [plan.b_ends, jnp.zeros((pad,), jnp.int32)]),
        b_num=jnp.concatenate(
            [plan.b_num, jnp.zeros((pad,), jnp.uint32)]))


def batch_tplans(specs, n_burst: int | None = None) -> TrafficPlan:
    """Compile + pad + stack a sequence of :class:`TrafficSpec`s into
    ONE batched :class:`TrafficPlan` with a leading scenario axis:
    scalars ``(S,)``, burst windows ``(S, B)``.  The serving batch
    drivers (tpu_sim/scenario.py) vmap over the leading axis, so each
    grid cell evaluates exactly its own (padded) arrival schedule.
    ``n_burst`` overrides the padded window count (the fuzzer's
    shape-bucket knob — a power-of-two bucket keeps one compiled
    program across campaigns)."""
    specs = list(specs)
    if not specs:
        raise ValueError("batch_tplans needs at least one spec")
    key = specs[0].program_key[:4]
    for sp in specs:
        if sp.program_key[:4] != key:
            raise ValueError(
                "traffic batch mixes static shapes "
                f"{key} and {sp.program_key[:4]} — n_nodes, "
                "n_clients, ops_per_client and intake must be "
                "uniform across a batch (rate/seed/kind/until/burst "
                "values ride the traced plan)")
    b_max = max(len(sp.burst) for sp in specs)
    if n_burst is not None:
        if n_burst < b_max:
            raise ValueError(
                f"n_burst={n_burst} < the batch's widest burst "
                f"count {b_max}")
        b_max = n_burst
    plans = [pad_tplan(sp.compile(), b_max) for sp in specs]
    return TrafficPlan(*(jnp.stack([p[i] for p in plans])
                         for i in range(len(TrafficPlan._fields))))


# -- device-side arrival evaluation --------------------------------------


def _client_hash(plan: TrafficPlan, t, ids, salt: int) -> jnp.ndarray:
    """uint32 counter-based stream h(seed, t, client, salt) — the
    faults._edge_hash family over the client axis: stateless, so every
    shard (and every replay, at any blocking) evaluates the same coin
    for the same (round, client)."""
    x = (jnp.asarray(ids).astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
         ^ jnp.asarray(t).astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         ^ plan.seed ^ jnp.uint32(salt))
    return faults._mix32(x)


def _arrival_num(plan: TrafficPlan, t) -> jnp.ndarray:
    """() uint32 — the arrival threshold at round t: the base rate,
    overridden inside any active burst window (windows-as-data, the
    one evaluation shape every compiled schedule here uses)."""
    return windows_fold(
        plan.b_starts, plan.b_ends, t,
        lambda w, active, num: jnp.where(active, plan.b_num[w], num),
        plan.rate_num)


def arrive(plan: TrafficPlan, t, ids: jnp.ndarray) -> jnp.ndarray:
    """bool, shaped like ``ids`` — which (GLOBAL) client ids issue an
    op at round ``t``.

    - ``poisson``: Bernoulli(rate) per (client, round) — geometric
      inter-arrivals, the round-synchronous Poisson process.
    - ``constant``: per-client fixed-point accumulator
      ``acc_t = phase_c + t * rate_num (mod 2^32)`` fires exactly when
      adding another ``rate_num`` would wrap — a deterministic
      1-in-(1/rate) cadence, de-phased across clients by the seeded
      ``phase_c`` so the fleet's constant streams do not stampede.

    Burst windows multiply the Poisson threshold via
    :func:`_arrival_num`.  ``rate == 1`` fires every round."""
    num = _arrival_num(plan, t)
    always = num == jnp.uint32(0xFFFFFFFF)
    poisson = _client_hash(plan, t, ids, _SALT_ARRIVE) < num
    phase = faults._mix32(
        jnp.asarray(ids).astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
        ^ plan.seed ^ jnp.uint32(_SALT_PHASE))
    acc = phase + jnp.asarray(t).astype(jnp.uint32) * num
    constant = acc > ~num
    fire = jnp.where(plan.kind == jnp.int32(1), constant,
                     poisson) | always
    t32 = jnp.asarray(t).astype(jnp.int32)
    return fire & (t32 >= 0) & (t32 < plan.until)


def local_node_cols(spec: TrafficSpec, n_loc: int) -> jnp.ndarray:
    """(n_loc,) int32 — LOCAL node column of each local client (an
    iota expression, so no host constant is baked in).  Valid because
    the client axis blocks align with the node axis blocks: with
    ``clients_per_node`` packing, local client lc sits at local node
    ``lc // cpn``; with striding, at ``lc * stride``."""
    c, n = spec.n_clients, spec.n_nodes
    lc = jnp.arange(n_loc, dtype=jnp.int32)
    if c >= n:
        return lc // jnp.int32(spec.clients_per_node)
    return lc * jnp.int32(spec.node_stride)


def intake_rank(arr: jnp.ndarray, cpn: int) -> jnp.ndarray:
    """(C_loc,) int32 — each arriving client's rank among this round's
    arrivals AT ITS HOME NODE (client-index order — the deterministic
    intake queue).  ``cpn`` static clients per node; rank 0 everywhere
    when each node has one client."""
    if cpn <= 1:
        return jnp.zeros(arr.shape, jnp.int32)
    a = arr.reshape(-1, cpn).astype(jnp.int32)
    return (jnp.cumsum(a, axis=1) - a).reshape(-1)


# -- the per-op tracker ---------------------------------------------------


class TrafficState(NamedTuple):
    """Per-op completion tracker + backpressure counters.  Rides the
    DONATED state pytree of the traffic drivers (it is mutable per
    round); client-axis leaves shard with the node axis.  Op identity
    is the static pair (client, k < ops_per_client)."""

    issued_k: jnp.ndarray     # (C,) int32 — next free op slot per client
    issue_round: jnp.ndarray  # (C, K) int32 — -1 until issued
    done_round: jnp.ndarray   # (C, K) int32 — -1 until globally visible
    # (C, K) int32 sim payload: kafka — the allocated slot; counter —
    # the KV value the op's flush landed in; -1 = unset
    op_aux: jnp.ndarray
    arrived: jnp.ndarray      # () uint32
    deferred: jnp.ndarray     # () uint32 — backpressured arrivals
    completed: jnp.ndarray    # () uint32
    # () uint32 — the resize-boundary sub-class of ``deferred``
    # (PR 17): arrivals backpressured because an elastic resharding
    # checkpoint-restore is in flight.  Always <= deferred — the
    # conservation identity ``arrived == issued + deferred`` is
    # UNCHANGED; this counter just names the cause loudly.
    deferred_resizing: jnp.ndarray


def state_specs(sharded: bool, axes="nodes") -> TrafficState:
    """shard_map in/out_specs for a :class:`TrafficState`: client-axis
    leaves positionally sharded with the node axis (``axes`` — the
    sim's ``engine.node_axes`` result, a tuple on a hierarchical
    mesh), counters replicated (they are reduce_sum-globalized every
    round)."""
    r1 = P(axes) if sharded else P(None)
    r2 = P(axes, None) if sharded else P(None, None)
    return TrafficState(r1, r2, r2, r2, P(), P(), P(), P())


def init_state(spec: TrafficSpec, mesh=None) -> TrafficState:
    from .engine import node_axes, node_shards

    c, k = spec.n_clients, spec.ops_per_client
    ts = TrafficState(
        issued_k=jnp.zeros((c,), jnp.int32),
        issue_round=jnp.full((c, k), -1, jnp.int32),
        done_round=jnp.full((c, k), -1, jnp.int32),
        op_aux=jnp.full((c, k), -1, jnp.int32),
        arrived=jnp.uint32(0), deferred=jnp.uint32(0),
        completed=jnp.uint32(0), deferred_resizing=jnp.uint32(0))
    if mesh is not None:
        n_sh = node_shards(mesh)
        if c % n_sh != 0:
            raise ValueError(
                f"n_clients={c} must shard evenly over the "
                f"{n_sh}-way node axis")
        na = node_axes(mesh)
        s1 = NamedSharding(mesh, P(na))
        s2 = NamedSharding(mesh, P(na, None))
        ts = ts._replace(
            issued_k=shard_put(ts.issued_k, s1),
            issue_round=shard_put(ts.issue_round, s2),
            done_round=shard_put(ts.done_round, s2),
            op_aux=shard_put(ts.op_aux, s2))
    return ts


def issue(ts: TrafficState, arr: jnp.ndarray, accept: jnp.ndarray, t,
          reduce_sum: Callable) -> tuple:
    """Classify this round's LOCAL arrivals and record the issued ops:
    an arrival is issued iff ``accept`` holds AND the client has a
    free op slot; everything else is DEFERRED (counted, never
    dropped).  Returns ``(ts', ok, kslot)`` — ``ok`` the issued mask,
    ``kslot`` the op slot each issued arrival took (the pre-bump
    per-client counter).  ``reduce_sum`` globalizes the counters on a
    mesh (psum), so the scalar leaves stay replicated."""
    k = ts.issued_k
    n_k = ts.issue_round.shape[1]
    ok = arr & accept & (k < n_k)
    defer = arr & ~ok
    rows = jnp.arange(k.shape[0], dtype=jnp.int32)
    kcol = jnp.where(ok, k, jnp.int32(n_k))
    issue_round = ts.issue_round.at[rows, kcol].set(
        jnp.asarray(t, jnp.int32), mode="drop")
    ts = ts._replace(
        issued_k=k + ok.astype(jnp.int32),
        issue_round=issue_round,
        arrived=ts.arrived + reduce_sum(
            jnp.sum(arr.astype(jnp.uint32), dtype=jnp.uint32)),
        deferred=ts.deferred + reduce_sum(
            jnp.sum(defer.astype(jnp.uint32), dtype=jnp.uint32)))
    return ts, ok, k


def record_aux(ts: TrafficState, ok: jnp.ndarray, kslot: jnp.ndarray,
               vals: jnp.ndarray) -> TrafficState:
    """Store the sim payload for the ops just issued (kafka's
    allocated slot / counter's flush-KV placeholder)."""
    n_k = ts.op_aux.shape[1]
    rows = jnp.arange(kslot.shape[0], dtype=jnp.int32)
    kcol = jnp.where(ok, kslot, jnp.int32(n_k))
    return ts._replace(
        op_aux=ts.op_aux.at[rows, kcol].set(vals, mode="drop"))


def done_scan(ts: TrafficState, bit_fn: Callable, t_done,
              reduce_sum: Callable, block: int | None = None
              ) -> TrafficState:
    """Mark the ops that became globally visible this round:
    ``bit_fn(lo, block) -> (block, K) bool`` evaluates the workload's
    visibility predicate for the local client slab ``[lo, lo+block)``.
    The predicate reads replicated round outputs and static op
    identity only, so slab order cannot perturb a bit — the
    ``GG_TRAFFIC_BLOCK`` slab size (see :func:`traffic_block`) bounds
    the per-round tracker temps without changing any result (the
    scan_blocks streaming contract, ISSUE-5/PR-5)."""
    rows = ts.issue_round.shape[0]
    block = rows if block is None else block

    def blk(carry, lo):
        dr, comp = carry
        isl = lax.dynamic_slice_in_dim(ts.issue_round, lo, block,
                                       axis=0)
        dsl = lax.dynamic_slice_in_dim(dr, lo, block, axis=0)
        dn = (isl >= 0) & (dsl < 0) & bit_fn(lo, block)
        comp = comp + jnp.sum(dn.astype(jnp.uint32), dtype=jnp.uint32)
        return (lax.dynamic_update_slice_in_dim(
            dr, jnp.where(dn, jnp.asarray(t_done, jnp.int32), dsl),
            lo, axis=0), comp)

    dr, comp = scan_blocks(blk, (ts.done_round, jnp.uint32(0)),
                           rows, block)
    return ts._replace(done_round=dr,
                       completed=ts.completed + reduce_sum(comp))


def resizing_defer(ts: TrafficState, arr: jnp.ndarray,
                   reduce_sum: Callable) -> tuple:
    """Backpressure an ENTIRE round of arrivals with the explicit
    ``resizing`` class — the elastic-resharding intake gate (PR 17):
    while a checkpoint-restore resize is in flight no op can be issued
    (the padded node axis itself is changing shape, so there is no
    stable home node to ack from), so every arrival this round is
    deferred loudly — counted in BOTH ``deferred`` (the conservation
    identity ``arrived == issued + deferred`` is unchanged) and the
    ``deferred_resizing`` sub-class — and NEVER dropped: the client
    simply re-offers after the boundary.  Returns ``(ts', ok)`` with
    ``ok`` the all-False issued mask (the drop-in shape of
    :func:`issue`'s ``ok``, so resize rounds slot into the same driver
    scaffolding)."""
    n = reduce_sum(jnp.sum(jnp.asarray(arr).astype(jnp.uint32),
                           dtype=jnp.uint32))
    ts = ts._replace(
        arrived=ts.arrived + n,
        deferred=ts.deferred + n,
        deferred_resizing=ts.deferred_resizing + n)
    return ts, jnp.zeros(jnp.asarray(arr).shape, bool)


def tel_series(ts: TrafficState, reduce_sum: Callable) -> tuple:
    """The tracker's telemetry columns (tpu_sim/telemetry.py
    ``TRAFFIC_SERIES`` order): running totals ``(arrived, issued,
    completed, deferred)`` after this round.  ``arrived`` /
    ``completed`` / ``deferred`` are already psum-globalized scalars;
    ``issued`` is the per-shard count of issued op slots globalized
    here — so the recorded ring itself witnesses the loud-backpressure
    identity ``arrived == issued + deferred`` at EVERY round."""
    issued = reduce_sum(jnp.sum(
        (ts.issue_round >= 0).astype(jnp.uint32), dtype=jnp.uint32))
    return (ts.arrived, issued, ts.completed, ts.deferred)


# -- env knob -------------------------------------------------------------


def traffic_block(rows: int) -> int:
    """Client-axis slab size for the per-round tracker scan
    (:func:`done_scan`), from ``GG_TRAFFIC_BLOCK``.  Loud contract
    (the PR-6 ``_env_int`` rule): a non-integer value, or an integer
    that does not divide the local client axis, raises a ValueError
    NAMING the variable; values <= 0 or >= rows clamp to the whole
    axis (the materialized evaluation order, bit-identical)."""
    raw = os.environ.get("GG_TRAFFIC_BLOCK")
    if raw is None:
        return rows
    b = _env_int("GG_TRAFFIC_BLOCK", raw)
    if b <= 0 or b >= rows:
        return rows
    if rows % b != 0:
        raise ValueError(
            f"GG_TRAFFIC_BLOCK={b} does not divide the {rows}-row "
            "local client axis (the tracker scan needs even slabs); "
            "use a divisor, or unset it for the whole axis")
    return b


# -- host mirrors ---------------------------------------------------------


def client_nodes(spec: TrafficSpec) -> np.ndarray:
    """(n_clients,) int32 — each client's GLOBAL home node (host twin
    of :func:`local_node_cols` + the shard offset)."""
    ids = np.arange(spec.n_clients, dtype=np.int64)
    if spec.n_clients >= spec.n_nodes:
        return (ids // spec.clients_per_node).astype(np.int32)
    return (ids * spec.node_stride).astype(np.int32)


def host_arrivals(spec: TrafficSpec, t: int) -> np.ndarray:
    """(n_clients,) bool — numpy twin of :func:`arrive`, bit-identical
    coins (op staging away from the device, and the conservation
    tests' independent arrival count)."""
    if not 0 <= t < spec.until:
        return np.zeros(spec.n_clients, bool)
    num = np.uint32(faults._rate_to_num(spec.rate))
    for start, end, mult in spec.burst:
        if start <= t < end:
            num = np.uint32(faults._rate_to_num(
                min(1.0, spec.rate * mult)))
    seed = np.uint32(spec.seed & 0xFFFFFFFF)
    ids = np.arange(spec.n_clients, dtype=np.int64).astype(np.uint32)
    t_term = np.uint32((int(t) * 0x9E3779B9) & 0xFFFFFFFF)
    if num == np.uint32(0xFFFFFFFF):
        return np.ones(spec.n_clients, bool)
    if spec.kind == "constant":
        phase = faults._mix32_np(
            ids * np.uint32(0x27D4EB2F) ^ seed ^ np.uint32(_SALT_PHASE))
        acc = phase + np.uint32((int(t) * int(num)) & 0xFFFFFFFF)
        return acc > ~num
    h = faults._mix32_np(ids * np.uint32(0xC2B2AE35) ^ t_term
                         ^ seed ^ np.uint32(_SALT_ARRIVE))
    return h < num


def offered_per_round(spec: TrafficSpec) -> float:
    """Mean offered load in ops/round (rate x clients; burst windows
    raise the within-window mean)."""
    base = spec.rate * spec.n_clients
    if not spec.burst:
        return base
    boosted = sum((end - start) * (min(1.0, spec.rate * mult)
                                   - spec.rate) * spec.n_clients
                  for start, end, mult in spec.burst)
    return base + boosted / spec.until


# -- summaries ------------------------------------------------------------


def latency_summary(ts: TrafficState) -> dict:
    """Host-side per-run report: op counts, the conservation verdict,
    and latency percentiles in ROUNDS (p50/p99/max over completed
    ops).  ``conserved`` is the loud-backpressure invariant —
    ``arrived == issued + deferred`` (and completed ≤ issued): every
    arrival was classified exactly once, nothing dropped silently."""
    issue_r = np.asarray(ts.issue_round)
    done_r = np.asarray(ts.done_round)
    issued = int((issue_r >= 0).sum())
    comp_mask = done_r >= 0
    completed = int(comp_mask.sum())
    lat = (done_r[comp_mask] - issue_r[comp_mask]).astype(np.int64)
    arrived, deferred = int(ts.arrived), int(ts.deferred)
    return {
        "arrived": arrived, "issued": issued, "deferred": deferred,
        "deferred_resizing": int(ts.deferred_resizing),
        "completed": completed, "in_flight": issued - completed,
        "conserved": (arrived == issued + deferred
                      and int(ts.deferred_resizing) <= deferred
                      and completed == int(ts.completed)),
        "lat_p50": (float(np.percentile(lat, 50)) if completed
                    else None),
        "lat_p99": (float(np.percentile(lat, 99)) if completed
                    else None),
        "lat_max": int(lat.max()) if completed else None,
    }


def per_round_series(ts: TrafficState, n_rounds: int) -> dict:
    """Per-round issue/completion counts (the throughput-cliff
    evidence: completions/round collapses inside a fault window and
    recovers after it clears)."""
    issue_r = np.asarray(ts.issue_round)
    done_r = np.asarray(ts.done_round)
    return {
        "issued_by_round": np.bincount(
            issue_r[issue_r >= 0], minlength=n_rounds).tolist(),
        "completed_by_round": np.bincount(
            done_r[done_r >= 0], minlength=n_rounds).tolist(),
    }
