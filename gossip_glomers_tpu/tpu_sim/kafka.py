"""Vectorized replicated append-only log (challenge 5, "Kafka") on TPU.

Semantics mirrored from the reference node (kafka/log.go, logmap.go):

- ``send``: allocate the next offset for the key from a linearizable KV
  via a CAS loop (getNextOffsetKV, logmap.go:255-285), append locally,
  fire-and-forget replicate to every peer (sendReplicateMsg,
  log.go:159-175 — "acks=0", loss is acceptable), reply the offset.
- ``poll``: serve from the LOCAL log only (log.go:79-110).
- ``commit_offsets``: monotonic max into the KV (logmap.go:134-198).
- ``list_committed_offsets``: local cache only, deliberately not synced
  (log.go:131-156).

Vectorized model: offsets are slots of padded per-key arrays.  The CAS
contention loop becomes a **rank-within-round allocation**: all sends in
one round are linearized in (node, slot) order, each getting
``next_slot[key] + rank`` — the sort/scan equivalent of the reference's
one-winner-per-CAS-retry loop, and the "offset gen as a collective"
called for by BASELINE.json config 5.  Replication is one masked
einsum per round: delivery[dest] = OR over origins of (link alive AND
origin's new appends) — the full-mesh fire-and-forget as a batched
matmul, with link loss as a (N, N) boolean mask.

State (node axis shardable over the mesh):

- ``log_vals (K, C) int32``  — content by (key, slot); offset = slot+1
  (defaultOffset=1, logmap.go:16).  Replicated: offsets are unique, so
  all replicas agree on content — only *presence* differs per node.
- ``present (N, K, C) bool`` — does node n hold (key, slot)?
- ``next_slot (K,) int32``   — the lin-kv allocation high-water mark.
- ``committed (K,) int32``   — lin-kv committed offsets.
- ``local_committed (N, K) int32`` — per-node committed cache.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class KafkaState(NamedTuple):
    log_vals: jnp.ndarray         # (K, C) int32
    present: jnp.ndarray          # (N, K, C) bool
    next_slot: jnp.ndarray        # (K,) int32
    committed: jnp.ndarray        # (K,) int32
    local_committed: jnp.ndarray  # (N, K) int32
    t: jnp.ndarray                # () int32
    msgs: jnp.ndarray             # () uint32


def _rank_within_key(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """(M,) int32 — for each element, how many valid earlier elements
    share its key.  Sort-based (O(M log M)): stable-argsort the keys,
    then rank = position - start_of_run within the sorted order.  This
    is the linearization that replaces the reference's CAS-retry loop."""
    m = keys.shape[0]
    sort_keys = jnp.where(valid, keys, jnp.int32(2 ** 30))
    order = jnp.argsort(sort_keys, stable=True)
    sorted_keys = sort_keys[order]
    pos = jnp.arange(m, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]])
    run_start = lax.associative_scan(
        jnp.maximum, jnp.where(is_start, pos, 0))
    rank_sorted = pos - run_start
    return jnp.zeros((m,), jnp.int32).at[order].set(rank_sorted)


class KafkaSim:
    """Round-synchronous replicated-log simulator.

    Per round, each node submits up to S ``send`` ops and at most one
    ``commit_offsets`` op (batched as arrays); replication loss is an
    (N, N) link mask.  ``poll`` / ``list_committed`` are host-side reads
    with the reference's local-only semantics.
    """

    def __init__(self, n_nodes: int, n_keys: int, capacity: int, *,
                 max_sends: int = 4, mesh: Mesh | None = None,
                 kv_retries: int = 10) -> None:
        self.n_nodes = n_nodes
        self.n_keys = n_keys
        self.capacity = capacity
        self.max_sends = max_sends
        self.mesh = mesh
        # allocation-attempt cap for the contention-aware ledger
        # (defaultKVRetries, logmap.go:19)
        self.kv_retries = kv_retries
        self._run_rounds = None
        self._step = self._build_step()

    def init_state(self) -> KafkaState:
        n, k, c = self.n_nodes, self.n_keys, self.capacity
        state = KafkaState(
            log_vals=jnp.full((k, c), -1, jnp.int32),
            present=jnp.zeros((n, k, c), bool),
            next_slot=jnp.zeros((k,), jnp.int32),
            committed=jnp.zeros((k,), jnp.int32),
            local_committed=jnp.zeros((n, k), jnp.int32),
            t=jnp.int32(0), msgs=jnp.uint32(0))
        if self.mesh is not None:
            state = state._replace(
                present=jax.device_put(
                    state.present,
                    NamedSharding(self.mesh, P("nodes", None, None))),
                local_committed=jax.device_put(
                    state.local_committed,
                    NamedSharding(self.mesh, P("nodes", None))))
        return state

    # -- round -------------------------------------------------------------

    def _round(self, state: KafkaState, send_key, send_val, commit_req,
               repl_ok, *, row_ids, widen, reduce_sum,
               reduce_max) -> KafkaState:
        """One round: allocate + append + replicate + commit.

        send_key/send_val: (rows, S) int32, key = -1 for no-op.
        commit_req: (rows, K) int32, -1 for no commit of that key.
        repl_ok: (N, N) bool — repl_ok[o, d]: o's replicate_msg reaches d.
        widen/reduce_sum: identity single-device; all_gather along
        'nodes' / psum under shard_map.
        """
        n, k_dim, cap = self.n_nodes, self.n_keys, self.capacity
        s_dim = send_key.shape[1]

        # -- offset allocation (global, linearized in (node, slot) order:
        #    the reference's lin-kv CAS loop, logmap.go:255-285) --------
        all_key = widen(send_key).reshape(-1)            # (N*S,)
        all_val = widen(send_val).reshape(-1)
        valid = all_key >= 0
        keys_c = jnp.clip(all_key, 0, k_dim - 1)
        rank = _rank_within_key(keys_c, valid)
        slot = state.next_slot[keys_c] + rank            # (N*S,)
        ok = valid & (slot < cap)

        # -- append: content is global (offsets unique ⇒ no conflicts).
        # Invalid entries scatter to an out-of-bounds row and are dropped
        # (in-bounds dummy slots would race real writes).
        scat_k = jnp.where(ok, keys_c, jnp.int32(k_dim))
        scat_c = jnp.where(ok, slot, 0)
        log_vals = state.log_vals.at[scat_k, scat_c].set(
            all_val, mode="drop")
        counts = jnp.zeros((k_dim,), jnp.int32).at[keys_c].add(
            ok.astype(jnp.int32))
        next_slot = state.next_slot + counts

        # new appends per origin node: (N, K, C) one-hot
        origin = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s_dim)
        new_mask = jnp.zeros((n, k_dim, cap), bool).at[
            origin, scat_k, scat_c].max(ok, mode="drop")

        # -- replication: masked OR over origins as one matmul
        #    (fire-and-forget full mesh, log.go:159-175) ----------------
        deliver = jnp.einsum(
            "od,okc->dkc", repl_ok.astype(jnp.int8),
            new_mask.astype(jnp.int8)) > 0                # (N, K, C)
        present = state.present | deliver[row_ids] | new_mask[row_ids]

        # -- commits: monotonic max (logmap.go:134-198); the local cache
        #    tracks only this node's own commits (log.go:131-156) -------
        committed = jnp.maximum(
            state.committed, reduce_max(jnp.max(commit_req, axis=0)))
        local_committed = jnp.maximum(state.local_committed, commit_req)

        # -- ledger: CAS-contention-aware KV accounting.  A send that is
        #    rank r among this round's senders of its key loses the CAS
        #    race to the r earlier ones, so the reference's allocation
        #    loop (logmap.go:255-285) serializes into r+1 attempts of
        #    read + read_ok + cas + cas-reply = 4 messages each, capped
        #    at defaultKVRetries (logmap.go:19).  `rank` is global and
        #    identical on every shard, so its sum is NOT psum-reduced.
        #    Commits stay 4 flat: the commit dance does not retry a lost
        #    CAS (only code 21/timeout — the quirk at logmap.go:46-52).
        #    Replication: N-1 fire-and-forget replicate_msg per send.
        attempts = jnp.minimum(rank + 1, self.kv_retries)
        kv_send_msgs = jnp.sum(
            jnp.where(valid, 4 * attempts, 0).astype(jnp.uint32),
            dtype=jnp.uint32)
        n_sends = reduce_sum(jnp.sum(
            (send_key >= 0).astype(jnp.uint32)))
        n_commits = reduce_sum(jnp.sum(
            (commit_req >= 0).astype(jnp.uint32)))
        msgs = (state.msgs + kv_send_msgs
                + n_sends * jnp.uint32(n - 1)
                + n_commits * jnp.uint32(4))
        return KafkaState(log_vals, present, next_slot, committed,
                          local_committed, state.t + 1, msgs)

    def _round_1dev(self, state, send_key, send_val, commit_req,
                    repl_ok):
        """Single-device round wiring (identity collectives) — shared by
        the stepwise and the scanned (run_rounds) drivers."""
        row_ids = jnp.arange(self.n_nodes, dtype=jnp.int32)
        return self._round(state, send_key, send_val, commit_req,
                           repl_ok, row_ids=row_ids,
                           widen=lambda x: x,
                           reduce_sum=lambda x: x,
                           reduce_max=lambda x: x)

    def _build_step(self):
        if self.mesh is None:
            return jax.jit(self._round_1dev)

        mesh = self.mesh
        node2 = P("nodes", None)
        state_spec = KafkaState(P(None, None), P("nodes", None, None),
                                P(), P(), node2, P(), P())

        # check_vma=False: log_vals/next_slot are computed identically on
        # every shard from all_gather-ed send batches — genuinely
        # replicated, but derived from gathered (varying-marked) values,
        # which the static replication checker cannot prove.
        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(state_spec, node2, node2, node2, P(None, None)),
            out_specs=state_spec, check_vma=False)
        def step(state, send_key, send_val, commit_req, repl_ok):
            block = send_key.shape[0]
            row_ids = (lax.axis_index("nodes") * block
                       + jnp.arange(block, dtype=jnp.int32))
            return self._round(
                state, send_key, send_val, commit_req, repl_ok,
                row_ids=row_ids,
                widen=lambda x: lax.all_gather(x, "nodes", axis=0,
                                               tiled=True),
                reduce_sum=lambda x: lax.psum(x, "nodes"),
                reduce_max=lambda x: lax.pmax(x, "nodes"))

        return step

    def run_rounds(self, state: KafkaState, send_key: np.ndarray,
                   send_val: np.ndarray,
                   commit_req: np.ndarray | None = None,
                   repl_ok: np.ndarray | None = None) -> KafkaState:
        """R pre-staged rounds as ONE device program (``lax.scan``):
        send_key/send_val are (R, N, S), commit_req (R, N, K).  One
        dispatch instead of R — per-round dispatch latency dominates the
        stepwise driver on small rounds.  Single-device only (the
        stepwise path covers meshes)."""
        if self.mesh is not None:
            raise NotImplementedError("run_rounds is single-device; "
                                      "use step() on meshes")
        r = send_key.shape[0]
        if commit_req is None:
            commit_req = np.full((r, self.n_nodes, self.n_keys), -1,
                                 np.int32)
        if repl_ok is None:
            repl_ok = np.ones((self.n_nodes, self.n_nodes), bool)
        if self._run_rounds is None:
            @jax.jit
            def run(state, sks, svs, crs, repl):
                def body(s, xs):
                    sk, sv, cr = xs
                    return self._round_1dev(s, sk, sv, cr, repl), None
                out, _ = lax.scan(body, state, (sks, svs, crs))
                return out
            self._run_rounds = run
        return self._run_rounds(
            state, jnp.asarray(send_key, jnp.int32),
            jnp.asarray(send_val, jnp.int32),
            jnp.asarray(commit_req, jnp.int32), jnp.asarray(repl_ok))

    def step(self, state: KafkaState,
             send_key: np.ndarray | None = None,
             send_val: np.ndarray | None = None,
             commit_req: np.ndarray | None = None,
             repl_ok: np.ndarray | None = None) -> KafkaState:
        n, s, k = self.n_nodes, self.max_sends, self.n_keys
        if send_key is None:
            send_key = np.full((n, s), -1, np.int32)
            send_val = np.zeros((n, s), np.int32)
        if commit_req is None:
            commit_req = np.full((n, k), -1, np.int32)
        if repl_ok is None:
            repl_ok = np.ones((n, n), bool)
        args = [jnp.asarray(send_key, jnp.int32),
                jnp.asarray(send_val, jnp.int32),
                jnp.asarray(commit_req, jnp.int32),
                jnp.asarray(repl_ok)]
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P("nodes", None))
            args[:3] = [jax.device_put(a, sh) for a in args[:3]]
        return self._step(state, *args)

    # -- host-side reads (reference read semantics) ------------------------

    def alloc_offsets(self, state_before: KafkaState,
                      send_key: np.ndarray) -> np.ndarray:
        """(N, S) int32 — the offsets the sends of this round were acked
        with (``send_ok`` replies), or -1.  Computed host-side with the
        same (node, slot)-order linearization as the device round."""
        ns = state_before  # allocation depends only on pre-round next_slot
        base = np.asarray(ns.next_slot)
        flat = np.asarray(send_key, np.int32).reshape(-1)
        seen: dict[int, int] = {}
        out = np.full(flat.shape, -1, np.int32)
        for i, k in enumerate(flat):
            if k < 0:
                continue
            r = seen.get(int(k), 0)
            seen[int(k)] = r + 1
            slot = int(base[k]) + r
            if slot < self.capacity:
                out[i] = slot + 1       # offset = slot + defaultOffset(1)
        return out.reshape(send_key.shape)

    def poll(self, state: KafkaState, node: int, key: int,
             from_offset: int) -> list[list[int]]:
        """[[offset, msg], ...] from this node's LOCAL log only
        (log.go:79-110) — present slots at offset >= from_offset."""
        present = np.asarray(state.present[node, key])
        vals = np.asarray(state.log_vals[key])
        out = []
        for c in np.flatnonzero(present):
            off = int(c) + 1
            if off >= from_offset:
                out.append([off, int(vals[c])])
        return out

    def list_committed(self, state: KafkaState, node: int) -> dict[int, int]:
        """Per-key committed offsets from the node's LOCAL cache only
        (log.go:131-156)."""
        lc = np.asarray(state.local_committed[node])
        return {k: int(lc[k]) for k in range(self.n_keys) if lc[k] > 0}

    def committed_kv(self, state: KafkaState) -> dict[int, int]:
        c = np.asarray(state.committed)
        return {k: int(c[k]) for k in range(self.n_keys) if c[k] > 0}
