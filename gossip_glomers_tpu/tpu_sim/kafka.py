"""Vectorized replicated append-only log (challenge 5, "Kafka") on TPU.

Semantics mirrored from the reference node (kafka/log.go, logmap.go):

- ``send``: allocate the next offset for the key from a linearizable KV
  via a CAS loop (getNextOffsetKV, logmap.go:255-285), append locally,
  fire-and-forget replicate to every peer (sendReplicateMsg,
  log.go:159-175 — "acks=0", loss is acceptable), reply the offset.
- ``poll``: serve from the LOCAL log only (log.go:79-110).
- ``commit_offsets``: the read/write/CAS dance (trySetKVOffset,
  logmap.go:134-165), skipping keys whose local HWM already covers the
  request (CommitOffset, logmap.go:247-251).
- ``list_committed_offsets``: local cache only, deliberately not synced
  (log.go:131-156).

**The allocator and the commit dance share one lin-kv key.**  The
reference addresses the SAME key ``k`` from both paths
(logmap.go:260,272 vs :138,142,159), so after any send the commit
dance's read sees the allocator's next-offset value — which is >= any
honestly-committed offset, so the dance usually ends at the read
(``readOffset >= offset`` → return readOffset, logmap.go:156-158): TWO
messages, no CAS, and the node "learns" a commit HWM one past the last
send (the overshoot quirk).  The CAS/write legs fire only for commits
beyond the allocator value or on never-touched keys.

Vectorized model: offsets are slots of padded per-key arrays.  The CAS
contention loop becomes a **rank-within-round allocation**: all sends in
one round are linearized in (node, slot) order, each getting
``current + rank`` where ``current`` is the shared cell's value — the
sort/scan equivalent of the reference's one-winner-per-CAS-retry loop,
and the "offset gen as a collective" called for by BASELINE.json
config 5.  Replication — delivery[dest] = OR over origins of (link
alive AND origin's new appends) — exploits that offsets are globally
unique per key, so every presence BIT has exactly one origin: across
origins the bit-packed new-append words are DISJOINT, OR equals SUM,
and the masked OR is literally a matmul.  Split the uint32 words into
bytes and it is a uint8 x uint8 -> int32 matmul the MXU executes
natively (byte sums of disjoint bits stay <= 255, so int32
accumulation is exact); the delivered high-water mark then falls out
of a count-leading-zeros over the delivered words instead of an
(N, N, K) max intermediate.  An EXPLICIT link mask stays an (N, N)
boolean — it is the matmul's lhs — but the nemesis fault model needs
no materialized lhs at all: its loss coins are stateless hashes of
(t, src, dst) and its liveness is a per-column window fold, so the
faulted full-mesh delivery folds both elementwise into the per-origin
bits (``repl_mode="union_nem"``) and the matmul survives only as the
``repl_fast=False`` bit-exactness oracle.  On a mesh the fault-free
union is a blocked psum-of-OR over ICI (engine ``reduce_or``) and the
offset linearization is a ppermute prefix scan (engine
``exclusive_sum``), so the sharded fault-free round compiles with no
``all-gather`` anywhere (pinned by
tests/test_engine.py::test_kafka_sharded_step_hlo_has_no_all_gather).

Within a round, sends complete before commits (the round-aligned
equivalent of a harness scenario that issues sends and commits in
separate instants); commits of one round all read the shared cell
before any of them writes it, so the first committer in node order wins
a contended CAS and the rest abort (code 22 is NOT retried — the
reference's retry predicate tests code 21, logmap.go:46-52,171-181).

State (node axis shardable over the mesh):

- ``log_vals (K, C) int32``  — content by (key, slot); offset = slot+1
  (defaultOffset=1, logmap.go:16).  Replicated: offsets are unique, so
  all replicas agree on content — only *presence* differs per node.
- ``present (N, K, ceil(C/32)) uint32`` — bit c%32 of word c//32 set
  iff node n holds (key, slot c).  Bit-packed (32x over the bool
  layout) so the node axis scales: 1k nodes x 10k keys x C=128 is
  160 MB instead of 1.3 GB, and replication delivery becomes an MXU
  matmul (below) instead of an (N,N)x(N,K,C) einsum.
- ``kv_val (K,) int32``      — THE shared lin-kv cell per key
  (0 = missing; live values are always >= 1).
- ``local_committed (N, K) int32`` — ``kd.commitOffset``: set
  unconditionally by own appends (logmap.go:298), max-bumped by
  replicate deliveries (logmap.go:309-311), updated with the dance's
  result by commits (logmap.go:186-197).  In the round-synchronous
  regime the unconditional own-append set equals a max, because
  allocated offsets grow monotonically.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import faults, kvstore, provenance, telemetry, traffic
from .counter import KVReach, _reach
from .engine import (analytic_peak_bytes, collectives,
                     donate_argnums_for, fori_rounds, jit_program,
                     node_axes, node_shards, operand_bytes,
                     resolve_block, resolve_dcn_mode, scan_blocks,
                     scan_rounds, unpack_bits)


class KafkaState(NamedTuple):
    log_vals: jnp.ndarray         # (K, C) int32
    present: jnp.ndarray          # (N, K, ceil(C/32)) uint32 bitset
    kv_val: jnp.ndarray           # (K,) int32 — shared lin-kv cell
    local_committed: jnp.ndarray  # (N, K) int32 — kd.commitOffset
    # (N, K, ceil(C/32)) uint32 under resync_mode="push" (the bits each
    # node ORIGINATED — the durable per-origin log the push resync
    # re-replicates from; NOT wiped by amnesia), (N, K, 0) otherwise
    origin_bits: jnp.ndarray
    t: jnp.ndarray                # () int32
    msgs: jnp.ndarray             # () uint32
    # kv_backend="device" (PR 14): the authoritative sharded lin-kv
    # rows (tpu_sim/kvstore.py) — ``kv_val`` above becomes the derived
    # one-psum view of them.  None on the host backend.
    rows: "kvstore.KVRows | None" = None


def _rank_within_key(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """(M,) int32 — for each element, how many valid earlier elements
    share its key.  Sort-based (O(M log M)): stable-argsort the keys,
    then rank = position - start_of_run within the sorted order.  This
    is the linearization that replaces the reference's CAS-retry loop."""
    m = keys.shape[0]
    sort_keys = jnp.where(valid, keys, jnp.int32(2 ** 30))
    order = jnp.argsort(sort_keys, stable=True)
    sorted_keys = sort_keys[order]
    pos = jnp.arange(m, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]])
    run_start = lax.associative_scan(
        jnp.maximum, jnp.where(is_start, pos, 0))
    rank_sorted = pos - run_start
    return jnp.zeros((m,), jnp.int32).at[order].set(rank_sorted)


def _alloc(kv_val, send_key, reach, up_rows, exclusive_sum, k_dim: int,
           cap: int):
    """The round's offset allocator (globally linearized in (node,
    slot) order — the reference's lin-kv CAS loop, logmap.go:255-285),
    extracted so the open-loop traffic tracker (PR 7) can mirror the
    EXACT allocation the round performs: both evaluate this same pure
    function of (kv_val, batch, gates), so the tracker's acked-op set
    can never drift from the round's.

    Returns ``(tried, valid, keys_c, rank, slot, ok)`` over the
    flattened (rows*S,) batch: ``tried`` = a real op at an up node,
    ``valid`` = tried and the KV was reachable, ``ok`` = valid and the
    allocated slot fits capacity (the acked sends)."""
    current = jnp.where(kv_val > 0, kv_val, 1)          # (K,)
    s_dim = send_key.shape[1]
    loc_key = send_key.reshape(-1)                      # (rows*S,)
    tried = loc_key >= 0
    if up_rows is not None:
        # a down node submits nothing: its batch rows are dead ops,
        # not charged-and-timed-out ones
        tried = tried & jnp.repeat(up_rows, s_dim)
    # a KV-blocked send never allocates: the read times out and the
    # node aborts after one attempt (models/kafka.py alloc_offset)
    valid = tried & jnp.repeat(reach, s_dim)
    keys_c = jnp.clip(loc_key, 0, k_dim - 1)
    cnt_valid = jnp.zeros((k_dim,), jnp.int32).at[keys_c].add(
        valid.astype(jnp.int32))
    rank = (_rank_within_key(keys_c, valid)
            + exclusive_sum(cnt_valid)[keys_c])
    offset = current[keys_c] + rank                     # (rows*S,)
    slot = offset - 1
    ok = valid & (slot < cap)
    return tried, valid, keys_c, rank, slot, ok


class KafkaSim:
    """Round-synchronous replicated-log simulator.

    Per round, each node submits up to S ``send`` ops and at most one
    ``commit_offsets`` op per key (batched as arrays); replication loss
    is an (N, N) link mask.  ``poll`` / ``list_committed`` are host-side
    reads with the reference's local-only semantics.
    """

    def __init__(self, n_nodes: int, n_keys: int, capacity: int, *,
                 max_sends: int = 4, mesh: Mesh | None = None,
                 kv_retries: int = 10,
                 kv_sched: KVReach | None = None,
                 repl_fast: bool | None = None,
                 fault_plan: "faults.FaultPlan | None" = None,
                 resync_every: int = 4,
                 resync_mode: str = "pull",
                 union_block: "int | str | None" = None,
                 kv_backend: str = "host",
                 kv_amnesia: bool = False,
                 dcn_mode: "str | None" = None) -> None:
        """``kv_sched``: lin-kv reachability windows (counter.KVReach —
        the same nemesis shape the counter's flush is gated by).  A
        node partitioned from lin-kv at round t:

        - **send**: the allocation read times out and the node replies
          an error after ONE attempt (models/kafka.py alloc_offset —
          only CAS-mismatch retries, a timeout aborts): no offset, no
          append, no replication; ledger charges the 1 dropped read
          request (sends count at send time, like Maelstrom's ledger).
        - **commit** (active dance only): set_kv_offset re-runs on
          timeout up to kv_retries attempts (logmap.go:177-181; each
          attempt = 1 dropped read request), then gives up — no learn,
          kv_retries msgs.  Locally-skipped commits never touch the KV
          and are unaffected.
        - **poll / list_committed**: local-only (log.go:79-110), never
          gated.

        ``repl_fast``: replication-path pick.  None (default) selects
        an origin-union fast path whenever ``repl_ok`` is omitted or
        all-True (see :meth:`_round`'s replication block) — under a
        crash/loss ``fault_plan`` that is the FAULTED origin-union
        path, which folds the plan's elementwise (t, src, dst) loss
        coins and liveness columns directly into the per-origin
        delivery bits (O(rows·N·S) coin evaluations + one scatter —
        no materialized N x N lhs, no O(N²·K·C/32) matmul).  An
        explicit non-full ``repl_ok`` matrix takes the link-mask
        matmul; False pins the matmul unconditionally — it is the
        bit-exactness ORACLE the parity tests (and BENCH_PR4's faulted
        rows) hold the fast paths against.

        ``fault_plan`` (tpu_sim/faults.py): the crash/loss nemesis.  A
        down node cannot allocate, commit, receive replicate_msgs, or
        serve anti-entropy; on restart its AMNESIA rows lose the
        ``present`` bitset and ``local_committed`` cache (the
        reference keeps both in process memory) — the shared lin-kv
        cells and the log content survive (the service is durable).
        The plan's loss stream drops individual replicate deliveries
        in flight (the reference's acks=0 stance) and per-round KV
        exchanges.  Duplicate delivery is inert here — replicate
        inserts are idempotent on (key, offset) (logmap.go:315-317),
        bit-OR in this model.

        ``resync_every``: with a plan, every ``resync_every``-th round
        the anti-entropy repair loop runs, so runs converge after
        faults clear.  Inert without a plan (the fault-free paths are
        untouched).  Two shapes, picked by ``resync_mode``:

        - ``"pull"`` (default): each LIVE node pulls the union of the
          live peers' presence (and max-bumps its committed cache from
          it) — 2 ledger msgs per live node per resync round.
        - ``"push"``: each LIVE node with any DURABLE own appends
          re-replicates its OWN appends from the durable log to every
          peer (the reference's restart recovery message shape:
          re-running sendReplicateMsg off the log) — ``N - 1``
          replicate msgs per pusher.  Tracks the per-origin bits in
          ``KafkaState.origin_bits`` (durable: survives amnesia, like
          the log content).  A bit whose origin is DOWN at a resync
          round is NOT re-replicated until the origin restarts —
          narrower per-round coverage than the pull union, same
          converged fixpoint once every origin has been live for a
          resync round.

        ``union_block`` (ISSUE 5 tentpole): the destination-slab size
        of the STREAMING faulted union — the ``union_nem`` coins are
        stateless hashes of (t, src, dst), so instead of the
        materialized (rows, N·S) coin tensor (the inherent-looking N²
        cost of per-link loss on a full mesh — the PR-4 faulted
        ceiling at 4,096 nodes) the round evaluates them on the fly
        over destination slabs inside one ``engine.scan_blocks``
        sweep: O(rows·B·S) mask temps, bit-identical results.  On a
        mesh each shard scans only its LOCAL destination rows and the
        per-send metadata visits shards by ring ppermute (one block
        rotation per shard step) instead of the materialized path's
        all_gather — the blocked sharded step HLO stays
        all-gather-free.  None defers to ``GG_UNION_BLOCK`` (default
        ``"auto"``: materialized while the whole coin tensor fits the
        slab budget — small shapes keep the measured PR-4 programs);
        an int pins the slab; ``"materialized"`` pins the unblocked
        path as the blocking bit-exactness oracle (the ``repl_fast=
        False`` pattern, one level up).

        ``kv_backend`` (PR 14): ``"host"`` keeps the lin-kv cells as
        the replicated ``kv_val`` vector; ``"device"`` hosts them in
        the sharded :class:`~.kvstore.KVRows` slab (stateless-hash
        key→owner routing) — ``kv_val`` each round is DERIVED from the
        rows in one psum view and the round's net cell updates (alloc
        bumps + commit CAS/create wins) land as ONE masked
        compare-update per key per round, the same round-counter
        linearization the host cells follow.  Bit-exact vs the host
        backend (tests/test_kvstore.py).  ``kv_amnesia=True`` lets a
        restarting owner's rows die with it (default False = the
        durable Maelstrom service, the KVService pin).  Dup streams
        are rejected loudly on the device backend (ROADMAP item 6)."""
        if kv_backend not in ("host", "device"):
            raise ValueError(f"unknown kv_backend {kv_backend!r}")
        if kv_amnesia and kv_backend != "device":
            raise ValueError("kv_amnesia needs kv_backend='device'")
        if kv_backend == "device":
            kvstore.reject_dup_stream(fault_plan, "KafkaSim")
        self.kv_backend = kv_backend
        self.kv_amnesia = bool(kv_amnesia)
        self._device_kv = kv_backend == "device"
        if self._device_kv:
            self._kv_layout = kvstore.make_layout(n_keys, n_nodes)
            self._key_at = jnp.asarray(self._kv_layout.key_at)
        self.n_nodes = n_nodes
        self.n_keys = n_keys
        self.capacity = capacity
        self.n_pwords = (capacity + 31) // 32   # presence words per key
        self.max_sends = max_sends
        self.mesh = mesh
        # -- DCN mode (PR 20): sync (default) or pipelined; kafka's
        # offset allocation (exclusive_sum over the hosts ring) and
        # the lin-kv send path have no certified staleness semantics
        # — a lagged offset base would double-allocate — so refuse.
        self._dcn = resolve_dcn_mode(dcn_mode)
        if self._dcn.stale_k:
            raise ValueError(
                f"dcn_mode={self._dcn.label()!r}: kafka has no "
                "certified staleness semantics — offset allocation is "
                "an exclusive prefix sum over the composed axes (a "
                "k-round-stale base double-allocates offsets) and the "
                "lin-kv commit dance needs the current cell; run sync "
                "or pipelined")
        # allocation-attempt cap for the contention-aware ledger
        # (defaultKVRetries, logmap.go:19)
        self.kv_retries = kv_retries
        self.kv_sched = (kv_sched if kv_sched is not None
                         else KVReach.none(n_nodes))
        self.repl_fast = repl_fast
        self.fault_plan = fault_plan
        self.resync_every = resync_every
        if resync_mode not in ("pull", "push"):
            raise ValueError(f"unknown resync_mode {resync_mode!r}")
        self.resync_mode = resync_mode
        self._push = resync_mode == "push"
        if fault_plan is not None \
                and fault_plan.down.shape[1] != n_nodes:
            raise ValueError(
                f"FaultPlan is for {fault_plan.down.shape[1]} nodes, "
                f"sim has {n_nodes}")
        # a crash/loss plan drives the replication masks (a dup-only
        # plan is inert here: idempotent replicate inserts)
        self._fp_active = fault_plan is not None and (
            int(fault_plan.starts.shape[0]) > 0
            or int(fault_plan.loss_num) > 0)
        # streaming-union destination slab (None = materialized): per
        # LOCAL destination row the union_nem coin slab costs N·S
        # uint32 hashes
        n_sh = node_shards(mesh)
        self._na = node_axes(mesh)
        if n_nodes % n_sh != 0:
            raise ValueError("node axis must shard evenly")
        self._rows_local = n_nodes // n_sh
        self._ub = resolve_block(
            self._rows_local, union_block,
            per_row_bytes=n_nodes * max_sends * 4)
        self._run_rounds = {}
        self._step_progs = {}
        self._traffic_progs = {}
        # telemetry-on observed drivers (PR 8)
        self._obs_progs = {}
        self._poll_batch_fn = None
        self._alloc_fn = None

    def init_state(self) -> KafkaState:
        n, k, c = self.n_nodes, self.n_keys, self.capacity
        wo = self.n_pwords if self._push else 0
        state = KafkaState(
            log_vals=jnp.full((k, c), -1, jnp.int32),
            present=jnp.zeros((n, k, self.n_pwords), jnp.uint32),
            kv_val=jnp.zeros((k,), jnp.int32),
            local_committed=jnp.zeros((n, k), jnp.int32),
            origin_bits=jnp.zeros((n, k, wo), jnp.uint32),
            t=jnp.int32(0), msgs=jnp.uint32(0),
            rows=(kvstore.init_rows(self._kv_layout, self.mesh)
                  if self._device_kv else None))
        if self.mesh is not None:
            node3 = NamedSharding(self.mesh, P(self._na, None, None))
            state = state._replace(
                present=shard_put(state.present, node3),
                origin_bits=shard_put(state.origin_bits, node3),
                local_committed=shard_put(
                    state.local_committed,
                    NamedSharding(self.mesh, P(self._na, None))))
        return state

    # -- round -------------------------------------------------------------

    def _round(self, state: KafkaState, send_key, send_val, commit_req,
               repl_ok, sched: KVReach, coll, *,
               repl_mode: str = "union", plan=None) -> KafkaState:
        """One round: allocate + append + replicate, then commit.

        send_key/send_val: (rows, S) int32 LOCAL batch rows, key = -1
        for no-op.  commit_req: (rows, K) int32, -1 for no commit of
        that key.
        repl_ok: (N, N) bool — repl_ok[o, d]: o's replicate_msg reaches
        d; None outside ``repl_mode="matmul"``.
        sched: lin-kv reachability windows (see __init__) — blocked
        nodes' sends fail allocation and their active commit dances
        time out.
        coll: the engine collective surface (identity single-device;
        psum / pmax / pmin / ppermute reduce_or / exclusive_sum over
        'nodes' under shard_map).
        repl_mode (static): the replication path —

        - ``"union"``: lossless full mesh.  Each shard scatters its
          LOCAL new-append bits into a (K, Wc) partial union and the
          shards combine with ``reduce_or`` (recursive-doubling
          ppermutes): O(K·Wc) per shard, zero all_gather anywhere in
          the round (allocation included — see the prefix-scan below).
        - ``"union_nem"``: full mesh under a crash/loss plan.  The
          plan's (t, src, dst) loss coins and liveness columns fold
          ELEMENTWISE into the per-origin delivery bits: each shard
          evaluates (rows, N·S) coins against the widened per-send
          metadata and scatters the surviving bits — no N x N lhs is
          ever materialized, no matmul.  Own appends ride via the
          origin == dest term (a node always keeps its own append).
        - ``"matmul"``: the link-mask byte-split MXU matmul — the
          general-``repl_ok`` path and the bit-exactness ORACLE for
          both unions (``repl_fast=False`` pins it).

        plan (traced FaultPlan operand): amnesia rows, liveness/loss
        gating, and the periodic presence resync — see __init__.
        """
        row_ids = coll.row_ids
        widen, reduce_sum = coll.widen, coll.reduce_sum
        reduce_max, reduce_min = coll.reduce_max, coll.reduce_min
        reduce_or, exclusive_sum = coll.reduce_or, coll.exclusive_sum
        local_cols = coll.local_cols
        n, k_dim, cap = self.n_nodes, self.n_keys, self.capacity
        rows, s_dim = send_key.shape
        big = jnp.int32(n + 1)
        # who can reach lin-kv this round — LOCAL rows only (every
        # cross-shard combine below is a collective, not a gather)
        reach = _reach(state.t, row_ids, sched)
        up_rows = None
        if plan is not None:
            wipe_rows = faults.amnesia(plan, state.t, row_ids)
            # amnesia: a crashing node's in-memory presence bitset and
            # committed-offset cache die with the process (survives:
            # log content, the lin-kv cells, and the per-origin
            # origin_bits — the durable side); it restarts empty when
            # the window ends
            state = state._replace(
                present=jnp.where(wipe_rows[:, None, None],
                                  jnp.uint32(0), state.present),
                local_committed=jnp.where(wipe_rows[:, None], 0,
                                          state.local_committed))
            up_rows = faults.node_up(plan, state.t, row_ids)
            # down nodes cannot reach the KV; loss eats one round's
            # exchange (retried next round, like a 1-round window)
            reach = reach & up_rows & ~faults.kv_drop(plan, state.t,
                                                      row_ids)
        if self._device_kv:
            # the authoritative lin-kv cells are READ from the sharded
            # rows (PR 14): one psum view replaces the carried
            # replicated vector for the whole round — identical unless
            # a kv_amnesia wipe just ate an owner's rows
            if plan is not None and self.kv_amnesia:
                state = state._replace(rows=kvstore.rows_wipe(
                    state.rows, plan, state.t, row_ids))
            ka_kv = self._key_at[row_ids]
            state = state._replace(kv_val=kvstore.rows_view(
                state.rows, ka_kv, k_dim, reduce_sum)[0])

        # -- offset allocation (globally linearized in (node, slot)
        #    order: the reference's lin-kv CAS loop, logmap.go:255-285).
        #    The shared cell holds the NEXT offset; missing key reads
        #    as defaultOffset = 1 (logmap.go:262-266).  Decomposed
        #    shard-locally: global rank = local rank within the shard
        #    + exclusive prefix (over lower shards) of per-key valid
        #    counts — a ppermute scan of a (K,) vector, so the send
        #    batch is never all_gather-ed.  (:func:`_alloc` — shared
        #    with the traffic tracker's mirror, PR 7.)
        current = jnp.where(state.kv_val > 0, state.kv_val, 1)  # (K,)
        loc_val = send_val.reshape(-1)
        tried, valid, keys_c, rank, slot, ok = _alloc(
            state.kv_val, send_key, reach, up_rows, exclusive_sum,
            k_dim, cap)

        # -- append: content is global (offsets unique ⇒ no conflicts
        #    across shards), so the replicated log_vals update is a
        #    psum of disjoint per-shard write scatters.  Invalid
        #    entries scatter to an out-of-bounds row and are dropped
        #    (in-bounds dummy slots would race real writes).
        scat_k = jnp.where(ok, keys_c, jnp.int32(k_dim))
        scat_c = jnp.where(ok, slot, 0)
        wrote = reduce_sum(jnp.zeros((k_dim, cap), jnp.int32).at[
            scat_k, scat_c].add(ok.astype(jnp.int32), mode="drop"))
        wvals = reduce_sum(jnp.zeros((k_dim, cap), jnp.int32).at[
            scat_k, scat_c].add(jnp.where(ok, loc_val, 0),
                                mode="drop"))
        log_vals = jnp.where(wrote > 0, wvals, state.log_vals)
        counts = reduce_sum(jnp.zeros((k_dim,), jnp.int32).at[
            keys_c].add(ok.astype(jnp.int32)))
        kv_sent = jnp.where(counts > 0, current + counts, state.kv_val)

        # -- replication.  Offsets are globally unique per key, so every
        #    (key, slot) bit has exactly ONE origin: scatter-ADD of the
        #    bits is scatter-OR and the words are DISJOINT across
        #    origins.
        wc = self.n_pwords
        slot_ok = jnp.where(ok, slot, 0)
        word_idx = slot_ok // 32
        bit = jnp.where(ok, jnp.uint32(1)
                        << (slot_ok % 32).astype(jnp.uint32),
                        jnp.uint32(0))
        # this shard's own new-append words (rows, K, Wc) — the matmul
        # path's local new_words block, the push resync's durable
        # origin record, and the source of every union partial
        i_loc = jnp.repeat(jnp.arange(rows, dtype=jnp.int32), s_dim)
        own_words = jnp.zeros((rows, k_dim, wc), jnp.uint32).at[
            i_loc, scat_k, word_idx].add(bit, mode="drop")
        if repl_mode == "union":
            # blocked psum-of-OR: per-shard partial union combined over
            # ICI by recursive-doubling ppermutes (engine.reduce_or) —
            # O(K·Wc) per shard, the union already contains every
            # node's OWN appends (the full mesh includes the self
            # link), bit-identical to the all-ones matmul delivery.
            deliver = reduce_or(jnp.zeros((k_dim, wc), jnp.uint32).at[
                scat_k, word_idx].add(bit, mode="drop"))[None]
            present = state.present | deliver
        elif repl_mode == "union_nem" and self._ub is None:
            # MATERIALIZED faulted origin-union (the blocking
            # bit-exactness oracle — ``union_block="materialized"``):
            # the coins need (origin, dest) pairs, so widen the tiny
            # per-send metadata ((N, S) ints — the ONE gather of this
            # path; presence never moves) and fold liveness + the loss
            # stream elementwise into the delivery bits as one
            # (rows, N·S) coin tensor.  bit == 0 already encodes "no
            # append" (ok ⇒ bit >= 1), and a capacity-dropped key
            # scatters out of bounds, so no separate ok mask is needed.
            g_bit = widen(bit.reshape(rows, s_dim)).reshape(-1)
            g_k = widen(scat_k.reshape(rows, s_dim)).reshape(-1)
            g_w = widen(word_idx.reshape(rows, s_dim)).reshape(-1)
            g_origin = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s_dim)
            # dest down ⇒ nothing lands; the origin's own append always
            # lands (ok ⇒ origin was up); otherwise the delivery coin
            # at the send round decides (fire-and-forget,
            # log.go:159-175 — nothing retries a dropped replicate)
            recv = ((up_rows[:, None]
                     & ~faults.edge_drop(plan, state.t,
                                         g_origin[None, :],
                                         row_ids[:, None]))
                    | (g_origin[None, :] == row_ids[:, None]))
            deliver = jnp.zeros((rows, k_dim, wc), jnp.uint32).at[
                :, g_k, g_w].add(
                jnp.where(recv, g_bit[None, :], jnp.uint32(0)),
                mode="drop")
            present = state.present | deliver
        elif repl_mode == "union_nem":
            # STREAMING faulted origin-union (ISSUE 5): same coins,
            # never materialized — a scan_blocks sweep over destination
            # slabs evaluates each slab's (B, rows·S) coin block on the
            # fly (faults.coin_block) and ORs the surviving bits into
            # the delivery carry in place.  Cross-shard, the per-send
            # metadata makes one ring circuit (a block ppermute per
            # shard step — each shard scans only its LOCAL destination
            # rows against every visiting origin block), so the
            # compiled sharded step has NO all-gather, matching the
            # fault-free union contract.  Disjoint-bit ORs commute, so
            # any (block, shard-step) order is bit-identical to the
            # materialized oracle.
            ub = self._ub
            n_sh = n // rows
            shard0 = row_ids[0]
            cur_bit, cur_k, cur_w = bit, scat_k, word_idx  # (rows*S,)

            def rot(x):
                return lax.ppermute(
                    x, coll.axis_name,
                    [(p, (p + 1) % n_sh) for p in range(n_sh)])

            i_row = jnp.repeat(jnp.arange(rows, dtype=jnp.int32), s_dim)
            deliver = jnp.zeros((rows, k_dim, wc), jnp.uint32)
            for step in range(n_sh):
                # after `step` rotations the local metadata block came
                # from shard (p - step) mod n_sh — global origin rows
                base = (shard0 - jnp.int32(step * rows)) % jnp.int32(n)
                g_origin = base + i_row
                o_bit, o_k, o_w = cur_bit, cur_k, cur_w

                def blk(carry, lo, g_origin=g_origin, o_bit=o_bit,
                        o_k=o_k, o_w=o_w):
                    dst_lo = shard0 + lo
                    up_b, drop_b, _ = faults.coin_block(
                        plan, state.t, g_origin, dst_lo, ub)
                    dst = dst_lo + jnp.arange(ub, dtype=jnp.int32)
                    recv = ((up_b[:, None] & ~drop_b)
                            | (g_origin[None, :] == dst[:, None]))
                    d_blk = jnp.zeros((ub, k_dim, wc), jnp.uint32).at[
                        :, o_k, o_w].add(
                        jnp.where(recv, o_bit[None, :], jnp.uint32(0)),
                        mode="drop")
                    old = lax.dynamic_slice_in_dim(carry, lo, ub,
                                                   axis=0)
                    return lax.dynamic_update_slice_in_dim(
                        carry, old | d_blk, lo, axis=0)

                deliver = scan_blocks(blk, deliver, rows, ub)
                if step + 1 < n_sh:
                    cur_bit, cur_k, cur_w = (rot(cur_bit), rot(cur_k),
                                             rot(cur_w))
            present = state.present | deliver
        else:
            if up_rows is not None:
                # explicit link mask composed with the plan: both
                # endpoints up, delivery coin survives the loss stream
                ids = jnp.arange(n, dtype=jnp.int32)
                up_all = faults.node_up(plan, state.t, ids)
                repl_ok = (repl_ok & up_all[:, None] & up_all[None, :]
                           & ~faults.edge_drop(plan, state.t,
                                               ids[:, None],
                                               ids[None, :]))
            # new appends per origin node, bit-packed: (N, K, Wc) —
            # the all_gather of the per-shard own blocks (the oracle
            # path keeps the full operand).
            new_words = widen(own_words)
            # the masked OR over origins IS a matmul (fire-and-forget
            # with link loss, log.go:159-175): disjoint bits make
            # OR == SUM, so split the words into bytes and ride the
            # MXU — uint8 x uint8 -> int32, exact (disjoint-bit byte
            # sums stay <= 255).
            nb = jnp.stack(
                [(new_words >> jnp.uint32(8 * j)).astype(jnp.uint8)
                 for j in range(4)], axis=-1)            # (N, K, Wc, 4)
            # contract only this shard's destination columns of repl_ok
            # (identity single-device): each shard does rows/N of the
            # matmul and lands its (rows, ...) delivery block directly
            repl_local = local_cols(repl_ok)             # (N, rows)
            deliver_b = lax.dot_general(
                repl_local.astype(jnp.uint8),
                nb.reshape(n, k_dim * wc * 4),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)        # (rows, K*Wc*4)
            db = deliver_b.astype(jnp.uint32).reshape(rows, k_dim, wc, 4)
            deliver = (db[..., 0] | (db[..., 1] << 8)
                       | (db[..., 2] << 16) | (db[..., 3] << 24))
            present = state.present | deliver | own_words

        # -- local HWM after sends: own append sets kd.commitOffset
        #    unconditionally (logmap.go:298; == max here, offsets grow),
        #    replicate delivery max-bumps it (logmap.go:309-311).
        # max delivered offset per (dest, key) = highest delivered bit
        # + 1, straight off the delivered words via count-leading-zeros
        # (no (N, N, K) max intermediate)
        word_base = (jnp.arange(wc, dtype=jnp.int32) * 32)[None, None, :]

        def top_off(words):
            return jnp.max(jnp.where(
                words > 0,
                word_base + 32 - lax.clz(words).astype(jnp.int32),
                0), axis=2)

        deliv_off = top_off(deliver)             # (rows, K) / (1, K)
        if repl_mode == "matmul":
            # the union deliveries contain every own append (full-mesh
            # self link / the origin == dest term), so their top bit
            # already covers the unconditional own-append bump; the
            # masked matmul may exclude it, so bump explicitly
            hwm = jnp.maximum(state.local_committed,
                              jnp.maximum(top_off(own_words),
                                          deliv_off))
        else:
            hwm = jnp.maximum(state.local_committed, deliv_off)

        # -- durable per-origin record (push resync only): every append
        #    a node ever made, bit-packed — survives amnesia like the
        #    log content (the reference's durable log per origin)
        origin_bits = state.origin_bits
        if self._push:
            origin_bits = origin_bits | own_words

        # -- presence resync (plan only): every resync_every-th round
        #    the anti-entropy repair loop re-replicates what crashed
        #    origins appended and what loss dropped.  Pull mode: each
        #    LIVE node takes the union of live peers' presence.  Push
        #    mode: each LIVE origin with durable appends re-replicates
        #    its OWN origin_bits to every live peer (the reference's
        #    restart recovery shape: re-running sendReplicateMsg off
        #    the durable log).  Either way the landed bits max-bump
        #    the committed cache exactly like replicate deliveries
        #    (logmap.go:309-311).
        n_resync = jnp.uint32(0)
        if plan is not None:
            # gate the cadence on TRACED plan activity, not plan
            # presence: a batched frontier program stacks one plan per
            # grid cell and must pass the operand statically, so a
            # fault-free cell rides an all-zero plan — without this
            # gate its resync sweep (and 2-msgs-per-live-node ledger)
            # would fire where the sequential plan=None run skips it.
            # An inert plan (no crashed nodes, no loss stream) is now
            # bit-exactly plan=None, ledger included.  A declared
            # crash window with an EMPTY node set counts as absent.
            fp_on = (jnp.any(plan.down)
                     | (plan.loss_num > jnp.uint32(0)))
            is_rs = (fp_on
                     & (state.t % jnp.int32(self.resync_every) == 0)
                     & (state.t > 0))
            if self._push:
                pushers = up_rows & jnp.any(origin_bits > 0,
                                            axis=(1, 2))
                union = reduce_or(lax.reduce(
                    jnp.where(pushers[:, None, None], origin_bits,
                              jnp.uint32(0)),
                    jnp.uint32(0), lax.bitwise_or, (0,)))  # (K, Wc)
                # ledger: one fire-and-forget replicate batch per
                # (pusher, peer) pair per resync round
                n_resync = (reduce_sum(jnp.sum(jnp.where(
                    is_rs, pushers, False).astype(jnp.uint32)))
                    * jnp.uint32(n - 1))
            else:
                union = reduce_or(lax.reduce(
                    jnp.where(up_rows[:, None, None], present,
                              jnp.uint32(0)),
                    jnp.uint32(0), lax.bitwise_or, (0,)))  # (K, Wc)
            take = is_rs & up_rows
            sync_new = jnp.where(take[:, None, None],
                                 union[None] & ~present, jnp.uint32(0))
            present = present | sync_new
            hwm = jnp.maximum(hwm, top_off(sync_new))
            if not self._push:
                # ledger: one pull request + one response per live
                # node per resync round
                n_resync = reduce_sum(jnp.sum(
                    take.astype(jnp.uint32))) * jnp.uint32(2)

        # -- commits (after this round's sends).  Local skip when the
        #    HWM covers the request (logmap.go:247-251); otherwise the
        #    dance reads the SHARED cell:
        #      read >= req  → done, learn the read value (2 msgs — the
        #                     common case once the key has sends;
        #                     logmap.go:156-158, the overshoot quirk)
        #      read <  req  → CAS read→req; first committer in node
        #                     order wins, losers get code 22 and ABORT
        #                     (the retry predicate tests code 21,
        #                     logmap.go:46-52,171-181) — 4 msgs each
        #      missing key  → blind create-write; every writer succeeds
        #                     and the LAST one's value lands (a lin-kv
        #                     write cannot fail, so the reference's
        #                     code-21 re-run at logmap.go:143-149 is
        #                     unreachable against the actual service
        #                     contract) — 4 msgs each.
        #    Timeout re-runs (logmap.go:177-181) belong to the fault
        #    regime the wall-clock harness ledger covers; they have no
        #    round-synchronous analogue here.
        req = commit_req                                  # (rows, K)
        rows_col = row_ids[:, None]
        # offsets are >= 1 everywhere (defaultOffset, logmap.go:16); a
        # commit of 0 would write the cell's "missing" sentinel, so it
        # is treated as a no-op rather than allowed to desync the cell
        want = req >= 1
        if up_rows is not None:
            # down nodes submit no commits (dead ops, not timed-out
            # dances)
            want = want & up_rows[:, None]
        skip = want & (hwm > 0) & (hwm >= req)
        dance = want & ~skip
        # KV-blocked active dances time out and re-run kv_retries times
        # (logmap.go:177-181), then give up: no contention, no learn
        active = dance & reach[:, None]
        blocked_commit = dance & ~reach[:, None]
        exists = (kv_sent > 0)[None, :]
        readv = kv_sent[None, :]
        read_only = active & exists & (req <= readv)
        need_cas = active & exists & (req > readv)
        writers = active & ~exists

        cas_win = reduce_min(jnp.min(
            jnp.where(need_cas, rows_col, big), axis=0))          # (K,)
        wrt_last = reduce_max(jnp.max(
            jnp.where(writers, rows_col, -1), axis=0))            # (K,)
        cas_req = reduce_sum(jnp.sum(
            jnp.where(need_cas & (rows_col == cas_win[None, :]), req, 0),
            axis=0))
        wrt_req = reduce_sum(jnp.sum(
            jnp.where(writers & (rows_col == wrt_last[None, :]), req, 0),
            axis=0))
        kv_val = jnp.where(cas_win < big, cas_req,
                           jnp.where(wrt_last >= 0, wrt_req, kv_sent))

        learn = jnp.where(
            need_cas & (rows_col == cas_win[None, :]), req,
            jnp.where(read_only, readv,
                      jnp.where(writers, req, 0)))
        local_committed = jnp.maximum(hwm, learn)

        # -- ledger: CAS-contention-aware KV accounting.  A send that is
        #    rank r among this round's senders of its key loses the CAS
        #    race to the r earlier ones, so the reference's allocation
        #    loop (logmap.go:255-285) serializes into r+1 attempts of
        #    read + read_ok + cas + cas-reply = 4 messages each, capped
        #    at defaultKVRetries (logmap.go:19).  Sums are per-shard
        #    partials over the LOCAL batch rows, psum-combined.
        #    Commits: 2 per active dance (read + reply) + 2 more when it
        #    writes (CAS or create-write leg, winners and losers alike);
        #    locally-skipped commits cost nothing.
        #    Replication: N-1 fire-and-forget replicate_msg per send.
        attempts = jnp.minimum(rank + 1, self.kv_retries)
        kv_send_msgs = reduce_sum(jnp.sum(
            jnp.where(valid, 4 * attempts, 0).astype(jnp.uint32),
            dtype=jnp.uint32))
        # KV-blocked sends: 1 dropped read request each (the model
        # aborts allocation after one timed-out attempt); blocked
        # active commits: kv_retries dropped read requests each.
        # Requests count at send time, like every other ledger here.
        blocked_send_msgs = reduce_sum(jnp.sum(
            (tried & ~valid).astype(jnp.uint32), dtype=jnp.uint32))
        # replication fires only for ALLOCATED sends (no offset -> no
        # append -> no replicate_msg, log.go:66-77) — `ok`, not
        # `valid`: a capacity-overflow send pays its KV attempts but
        # never appends.
        n_sends = reduce_sum(jnp.sum(ok.astype(jnp.uint32),
                                     dtype=jnp.uint32))
        n_active = reduce_sum(jnp.sum(active.astype(jnp.uint32)))
        n_blocked_c = reduce_sum(jnp.sum(
            blocked_commit.astype(jnp.uint32)))
        n_write_leg = reduce_sum(jnp.sum(
            (need_cas | writers).astype(jnp.uint32)))
        msgs = (state.msgs + kv_send_msgs + blocked_send_msgs
                + n_sends * jnp.uint32(n - 1)
                + n_active * jnp.uint32(2) + n_write_leg * jnp.uint32(2)
                + n_blocked_c * jnp.uint32(self.kv_retries)
                + n_resync)
        rows_kv = state.rows
        if self._device_kv:
            # commit the round's net cell updates into the sharded
            # rows as ONE masked CAS per key (frm IS the authoritative
            # pre-round view, so every changed cell hits): the same
            # one-linearization-step-per-round the host cells follow
            rows_kv = kvstore.cas_apply(rows_kv, ka_kv,
                                        kv_val != state.kv_val,
                                        state.kv_val, kv_val)
        return KafkaState(log_vals, present, kv_val,
                          local_committed, origin_bits,
                          state.t + 1, msgs, rows=rows_kv)

    def _state_spec(self):
        rows = (kvstore.rows_spec(self.mesh) if self._device_kv
                else None)
        na = self._na
        return KafkaState(P(None, None), P(na, None, None),
                          P(), P(na, None),
                          P(na, None, None), P(), P(),
                          rows=rows)

    def _repl_mode(self, repl_ok) -> str:
        """Host-side path pick (see :meth:`_round`): the origin-union
        fast paths apply when every link delivers (``repl_ok`` omitted
        or all-True) — ``"union_nem"`` with an active crash/loss plan,
        ``"union"`` without — unless the constructor pinned
        ``repl_fast=False``, which keeps the link-mask matmul as the
        bit-exactness oracle.  An explicit non-full ``repl_ok`` always
        takes the matmul (the mask is its lhs)."""
        if self.repl_fast is False:
            return "matmul"
        if not (repl_ok is None or bool(np.all(repl_ok))):
            return "matmul"
        return "union_nem" if self._fp_active else "union"

    def union_footprint(self, *, block: "int | None | str" = "resolved",
                        donated: bool = True) -> dict:
        """Audited analytic footprint of one faulted ``union_nem``
        round (engine.analytic_peak_bytes — the BENCH_PR5 OOM-boundary
        formula, pinned at a known shape by tests/test_engine.py):

        - state: the donated pytree held live across the round
          (presence + log content + cells + HWM cache + origin bits);
        - operands: the FaultPlan leaves (traced, never donated);
        - slab: the transient replication temps — the coin-mask slab
          (``block`` × N·S uint32 coins; the whole (rows, N·S) tensor
          on the materialized path) plus the (rows, K, Wc) delivery
          carry.

        ``block="resolved"`` uses this sim's resolved slab;
        ``block=None`` prices the MATERIALIZED path (what provably
        cannot fit once rows·N·S·4 alone exceeds a chip's HBM)."""
        rows = self._rows_local
        if block == "resolved":
            block = self._ub
        eff = rows if block is None else int(block)
        n, k, wc = self.n_nodes, self.n_keys, self.n_pwords
        state = (n * k * wc * 4                  # present
                 + k * self.capacity * 4        # log_vals
                 + k * 4                         # kv_val
                 + n * k * 4                     # local_committed
                 + (n * k * wc * 4 if self._push else 0))
        coin = eff * n * self.max_sends * 4
        deliver = rows * k * wc * 4
        plan_b = (operand_bytes(self.fault_plan)
                  if self.fault_plan is not None else 0)
        out = analytic_peak_bytes(state_bytes=state,
                                  operand_bytes=plan_b,
                                  slab_bytes=coin + deliver,
                                  donated=donated)
        out.update(block=eff if block is not None else None,
                   coin_slab_bytes=coin, deliver_carry_bytes=deliver,
                   materialized=block is None)
        return out

    def _step_prog(self, repl_mode: str):
        """The one-round program, keyed by the (static) replication
        path.  check_vma=False on a mesh: log_vals/kv_val are combined
        across shards by psums of disjoint partials — genuinely
        replicated, but the static replication checker cannot prove
        values derived from collectives over varying-marked inputs."""
        if repl_mode not in self._step_progs:
            mesh = self.mesh
            fp = self._fp_active
            matmul = repl_mode == "matmul"

            def step(state, send_key, send_val, commit_req, *rest):
                rest = list(rest)
                plan = rest.pop() if fp else None
                sched = rest.pop()
                repl = rest.pop() if matmul else None
                coll = collectives(send_key.shape[0], mesh,
                                   dcn=self._dcn)
                return self._round(state, send_key, send_val,
                                   commit_req, repl, sched, coll,
                                   repl_mode=repl_mode, plan=plan)

            if mesh is None:
                prog = jit_program(step)
            else:
                node2 = P(self._na, None)
                state_spec = self._state_spec()
                in_specs = ((state_spec, node2, node2, node2)
                            + ((P(None, None),) if matmul else ())
                            + (KVReach(P(), P(), P(None, None)),)
                            + ((faults.plan_specs(),) if fp else ()))
                prog = jit_program(step, mesh=mesh, in_specs=in_specs,
                                   out_specs=state_spec,
                                   check_vma=False)
            self._step_progs[repl_mode] = prog
        return self._step_progs[repl_mode]

    def _run_prog(self, has_commits: bool, repl_mode: str,
                  donate: bool):
        """Build (and cache) the R-round ``lax.scan`` driver program —
        extracted from :meth:`run_rounds` so the contract auditor
        (tpu_sim/audit.py) can lower the EXACT jitted object the
        drivers execute (donation/alias tables are per-program)."""
        key = (has_commits, repl_mode, donate)
        if key not in self._run_rounds:
            k_dim = self.n_keys
            mesh = self.mesh
            dn = donate_argnums_for(donate, 0)
            fp = self._fp_active
            matmul = repl_mode == "matmul"

            def run(state, sks, svs, *rest):
                rest = list(rest)
                plan = rest.pop() if fp else None
                sched = rest.pop()
                repl = rest.pop() if matmul else None
                coll = collectives(sks.shape[1], mesh,
                                   dcn=self._dcn)

                def body(s, xs):
                    sk, sv = xs[0], xs[1]
                    cr = (xs[2] if has_commits else jnp.full(
                        (sk.shape[0], k_dim), -1, jnp.int32))
                    return self._round(s, sk, sv, cr, repl, sched,
                                       coll, repl_mode=repl_mode,
                                       plan=plan)

                xs = ((sks, svs) + ((rest[0],) if has_commits
                                    else ()))
                return scan_rounds(body, state, xs)

            if mesh is None:
                prog = jit_program(run, donate_argnums=dn)
            else:
                node3 = P(None, self._na, None)
                state_spec = self._state_spec()
                in_specs = ((state_spec, node3, node3)
                            + ((node3,) if has_commits else ())
                            + ((P(None, None),) if matmul else ())
                            + (KVReach(P(), P(), P(None, None)),)
                            + ((faults.plan_specs(),) if fp else ()))
                prog = jit_program(run, mesh=mesh, in_specs=in_specs,
                                   out_specs=state_spec,
                                   check_vma=False, donate_argnums=dn)
            self._run_rounds[key] = prog
        return self._run_rounds[key]

    def run_rounds(self, state: KafkaState, send_key: np.ndarray,
                   send_val: np.ndarray,
                   commit_req: np.ndarray | None = None,
                   repl_ok: np.ndarray | None = None, *,
                   donate: bool = False) -> KafkaState:
        """R pre-staged rounds as ONE device program (``lax.scan``):
        send_key/send_val are (R, N, S), commit_req (R, N, K).  One
        dispatch instead of R — per-round dispatch latency dominates the
        stepwise driver on small rounds.  On a mesh the scan body is the
        same sharded round as step() (scan under shard_map), so
        benchmark config 5 runs multi-device with identical results.

        ``donate``: consume the input state's buffers (the
        :meth:`run_fused` driver) — the scan then updates the ~O(N*K)
        presence/HWM state in place instead of holding input + output
        copies live."""
        # commit-free runs (the benchmark's send-heavy regime) build
        # the all--1 commit_req INSIDE the traced program: an (R, N, K)
        # host array would be ~330 MB at the sweep's 1k-node shape,
        # re-transferred over the tunnel on every chained timing call
        # (measured: it dominated the round time ~100x); as a traced
        # broadcast constant, `want = req >= 1` folds to False and XLA
        # dead-codes the whole commit pipeline.
        has_commits = commit_req is not None
        repl_mode = self._repl_mode(repl_ok)
        matmul = repl_mode == "matmul"
        if matmul and repl_ok is None:
            repl_ok = np.ones((self.n_nodes, self.n_nodes), bool)
        args = [jnp.asarray(send_key, jnp.int32),
                jnp.asarray(send_val, jnp.int32)]
        if has_commits:
            args.append(jnp.asarray(commit_req, jnp.int32))
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(None, self._na, None))
            args = [shard_put(a, sh) for a in args]
        if matmul:
            args.append(jnp.asarray(repl_ok))
        args.append(self.kv_sched)
        if self._fp_active:
            args.append(self.fault_plan)
        prog = self._run_prog(has_commits, repl_mode, donate)
        return prog(state, *args)

    def run_fused(self, state: KafkaState, send_key: np.ndarray,
                  send_val: np.ndarray,
                  commit_req: np.ndarray | None = None,
                  repl_ok: np.ndarray | None = None) -> KafkaState:
        """Donation-first :meth:`run_rounds`: bit-identical results, the
        input state's buffers are consumed and reused in place.  The
        passed-in state must not be used again afterwards."""
        return self.run_rounds(state, send_key, send_val, commit_req,
                               repl_ok, donate=True)

    # -- flight-recorder telemetry (PR 8) ----------------------------------

    def _tel_series(self, s0: KafkaState, s1: KafkaState, coll,
                    plan, full_scan: bool = False) -> tuple:
        """One round's telemetry row (telemetry.SIM_SERIES['kafka']
        order), traced: per-shard LOCAL partials globalized in ONE
        packed ``reduce_sum`` — liveness counted over the local rows,
        and ``present_bits`` as the presence-bitset popcount at the
        WITNESS node (global row 0): it climbs to ``alloc_total``
        exactly when every allocated send has replicated to node 0,
        so the two series together plot replication lag per round.
        ``present_bits_full`` is the full-cluster presence popcount —
        it re-streams the whole O(N·K·C) bitset every round (measured
        ~18% of the 1,024/10k sweep round in PR 8), so it is OPT-IN
        (telemetry.OPT_IN_SERIES): unselected it is a dead column and
        XLA prunes the scan; the witness gauge is O(K·C) on one shard
        and stays the default.  The allocated-slot total reads the
        replicated log content — no collective at all."""
        row_ids = coll.row_ids
        live_loc = (jnp.ones(row_ids.shape, bool) if plan is None
                    else faults.node_up(plan, s0.t, row_ids))
        wit = jnp.where(
            row_ids[0] == 0,
            jnp.sum(lax.population_count(s1.present[0])
                    .astype(jnp.uint32), dtype=jnp.uint32),
            jnp.uint32(0))
        # the full scan is a STATIC opt-in: when the column is
        # unselected it must not even enter the packed psum (a stacked
        # operand's elements are not individually dead-codeable)
        parts = [jnp.sum(live_loc.astype(jnp.uint32),
                         dtype=jnp.uint32), wit]
        if full_scan:
            parts.append(jnp.sum(lax.population_count(s1.present)
                                 .astype(jnp.uint32),
                                 dtype=jnp.uint32))
        g = coll.reduce_sum(jnp.stack(parts))
        alloc = jnp.sum((s1.log_vals >= 0).astype(jnp.uint32),
                        dtype=jnp.uint32)       # replicated — no psum
        full = g[2] if full_scan else jnp.uint32(0)
        return (g[0], alloc, g[1], full, s1.msgs)

    def _prov_record(self, s0: KafkaState, s2: KafkaState, prov,
                     sk, coll, sched: KVReach, plan, witness: int):
        """One round's provenance stamps (PR 9), traced: a PURE
        reader.  The allocation side mirrors the round's own
        :func:`_alloc` evaluation (the PR-7 tracker trick — same pure
        function of (kv_val, batch, gates), so the recorded (key,
        slot) → (round, origin) map can never drift from the round);
        the witness side reads the bits that became newly present at
        the witness node this round.  Per-shard partials are DISJOINT
        (offsets are globally unique; the witness lives on one
        shard), so the ``reduce_sum`` psums produce identical
        replicated (K, C) stamps — no gather anywhere."""
        row_ids = coll.row_ids
        rows, s_dim = sk.shape
        k_dim, cap = self.n_keys, self.capacity
        reach = _reach(s0.t, row_ids, sched)
        up_rows = None
        if plan is not None:
            up_rows = faults.node_up(plan, s0.t, row_ids)
            reach = reach & up_rows & ~faults.kv_drop(plan, s0.t,
                                                     row_ids)
        _t, _v, keys_c, _r, slot, ok = _alloc(
            s0.kv_val, sk, reach, up_rows, coll.exclusive_sum, k_dim,
            cap)
        scat_k = jnp.where(ok, keys_c, jnp.int32(k_dim))
        scat_c = jnp.where(ok, slot, 0)
        origin_flat = jnp.repeat(row_ids, s_dim)
        t1 = s2.t                        # stamps are t+1 throughout
        # BOTH stamp scatters packed into ONE (2, K, C) psum operand
        # (disjoint per-shard partials — offsets are globally unique)
        parts = jnp.zeros((2, k_dim, cap), jnp.int32)
        parts = parts.at[0, scat_k, scat_c].add(
            jnp.where(ok, t1, 0), mode="drop")
        parts = parts.at[1, scat_k, scat_c].add(
            jnp.where(ok, origin_flat + 1, 0), mode="drop")
        g = coll.reduce_sum(parts)
        ar, og = g[0], g[1]
        new_alloc = (ar > 0) & (prov.alloc_round < 0)
        alloc_round = jnp.where(new_alloc, ar, prov.alloc_round)
        origin = jnp.where(new_alloc, og - 1, prov.origin)
        # witness first presence: the bits present at the witness row
        # AFTER the round — :func:`provenance.stamp` only writes
        # unstamped cells, so the first round a bit shows up is the
        # one recorded (re-presence after amnesia never re-stamps).
        # Deliberately reads ONLY s2: touching s0.present here would
        # keep the full pre-round O(N·K·C) bitset alive past the
        # round (the donated update could no longer happen in place —
        # measured ~15%/round at the 1,024/10k sweep point); the one
        # witness row is sliced, never the whole bitset
        loc = jnp.int32(witness) - row_ids[0]
        inb = (loc >= 0) & (loc < rows)
        lc = jnp.clip(loc, 0, rows - 1)
        wrow = lax.dynamic_index_in_dim(s2.present, lc, axis=0,
                                        keepdims=False)
        wit = coll.reduce_sum(jnp.where(inb, wrow, jnp.uint32(0)))
        first = provenance.stamp(
            prov.first_present, unpack_bits(wit, cap), t1)
        return provenance.KafkaProv(alloc_round=alloc_round,
                                    origin=origin,
                                    first_present=first)

    def _build_obs_prog(self, tspec: "telemetry.TelemetrySpec | None",
                        has_commits: bool, donate: bool, pspec=None):
        """Telemetry-/provenance-on :meth:`_run_prog`: same scan body,
        a ``(state, tel?, prov?)`` carry donated together."""
        tl = tspec is not None
        pv = pspec is not None
        if not (tl or pv):
            raise ValueError(
                "observed drivers need a TelemetrySpec and/or a "
                "ProvenanceSpec")
        if tl and (tspec.workload != "kafka" or tspec.traffic):
            raise ValueError(
                "run_observed needs a TelemetrySpec(workload='kafka', "
                "traffic=False); open-loop runs record through "
                "run_traffic(tel=...)")
        if pv and pspec.witness >= self.n_nodes:
            raise ValueError(
                f"provenance witness {pspec.witness} out of range "
                f"for {self.n_nodes} nodes")
        repl_mode = self._repl_mode(None)
        if repl_mode == "matmul":
            raise ValueError(
                "observed drivers ride the origin-union replication "
                "paths; repl_fast=False pins the matmul oracle")
        key = (tspec, pspec, has_commits, donate)
        if key in self._obs_progs:
            return self._obs_progs[key]
        k_dim = self.n_keys
        mesh = self.mesh
        n_carry = 1 + int(tl) + int(pv)
        dn = donate_argnums_for(donate, *range(n_carry))
        fp = self._fp_active
        tel_mask = tspec.static_mask if tl else None
        full_scan = tl and "present_bits_full" in tspec.series
        witness = pspec.witness if pv else 0
        ip = 1 + int(tl)

        def run(*a):
            a = list(a)
            state = a.pop(0)
            tel = a.pop(0) if tl else None
            prov0 = a.pop(0) if pv else None
            sks, svs = a.pop(0), a.pop(0)
            rest = a
            plan = rest.pop() if fp else None
            sched = rest.pop()
            coll = collectives(sks.shape[1], mesh,
                               dcn=self._dcn)

            def body(c, xs):
                s = c[0]
                sk, sv = xs[0], xs[1]
                cr = (xs[2] if has_commits else jnp.full(
                    (sk.shape[0], k_dim), -1, jnp.int32))
                s2 = self._round(s, sk, sv, cr, None, sched, coll,
                                 repl_mode=repl_mode, plan=plan)
                out = (s2,)
                if tl:
                    out += (telemetry.record(
                        c[1], s.t,
                        self._tel_series(s, s2, coll, plan,
                                         full_scan=full_scan),
                        tel_mask),)
                if pv:
                    out += (self._prov_record(s, s2, c[ip], sk, coll,
                                              sched, plan, witness),)
                return out

            xs = ((sks, svs) + ((rest[0],) if has_commits else ()))
            carry = ((state,) + ((tel,) if tl else ())
                     + ((prov0,) if pv else ()))
            out, _ = lax.scan(lambda c, x: (body(c, x), None),
                              carry, xs)
            return out

        if mesh is None:
            prog = jit_program(run, donate_argnums=dn)
        else:
            node3 = P(None, self._na, None)
            state_spec = self._state_spec()
            tel_in = ((telemetry.state_specs(),) if tl else ())
            prov_in = ((provenance.kafka_specs(),) if pv else ())
            in_specs = ((state_spec,) + tel_in + prov_in
                        + (node3, node3)
                        + ((node3,) if has_commits else ())
                        + (KVReach(P(), P(), P(None, None)),)
                        + ((faults.plan_specs(),) if fp else ()))
            prog = jit_program(
                run, mesh=mesh, in_specs=in_specs,
                out_specs=(state_spec,) + tel_in + prov_in,
                check_vma=False, donate_argnums=dn)
        self._obs_progs[key] = prog
        return prog

    def telemetry_state(self, tspec) -> "telemetry.TelemetryState":
        return telemetry.init_state(tspec)

    def provenance_state(self, pspec) -> "provenance.KafkaProv":
        # replicated like log_vals/kv_val — no sharding to apply
        return provenance.init_kafka(self.n_keys, self.capacity)

    def run_observed(self, state: KafkaState, tel, tspec,
                     send_key: np.ndarray, send_val: np.ndarray,
                     commit_req: np.ndarray | None = None, *,
                     donate: bool = False, prov=None, prov_spec=None):
        """Telemetry-/provenance-on :meth:`run_rounds`: the R staged
        rounds as one scan with the per-round metrics ring and/or the
        per-(key, slot) provenance stamps recorded next to the state —
        bit-exact to the plain driver (the recorders only read state).
        Returns the carry in order: ``(state, tel?, prov?)``."""
        if (tel is None) != (tspec is None):
            raise ValueError(
                "pass tel and tel_spec together (build the ring with "
                "telemetry.init_state(spec))")
        provenance.prov_key(prov, prov_spec, "kafka")
        has_commits = commit_req is not None
        args = [jnp.asarray(send_key, jnp.int32),
                jnp.asarray(send_val, jnp.int32)]
        if has_commits:
            args.append(jnp.asarray(commit_req, jnp.int32))
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(None, self._na, None))
            args = [shard_put(a, sh) for a in args]
        args.append(self.kv_sched)
        if self._fp_active:
            args.append(self.fault_plan)
        prog = self._build_obs_prog(tspec, has_commits, donate,
                                    prov_spec)
        pre = ((state,) + ((tel,) if tspec is not None else ())
               + ((prov,) if prov_spec is not None else ()))
        return prog(*pre, *args)

    def audit_observed_program(self, tspec, *, donate: bool = True,
                               rounds: int = 8, prov_spec=None):
        """(jitted, example_args) of the observed driver — the handle
        the contract auditor lowers."""
        n, s = self.n_nodes, self.max_sends
        sks = np.full((rounds, n, s), -1, np.int32)
        sks[:, 0, 0] = 0
        svs = np.zeros((rounds, n, s), np.int32)
        prog = self._build_obs_prog(tspec, False, donate, prov_spec)
        args = [jnp.asarray(sks), jnp.asarray(svs)]
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(None, self._na, None))
            args = [shard_put(a, sh) for a in args]
        args.append(self.kv_sched)
        if self._fp_active:
            args.append(self.fault_plan)
        pre = ((self.init_state(),)
               + ((telemetry.init_state(tspec),)
                  if tspec is not None else ())
               + ((self.provenance_state(prov_spec),)
                  if prov_spec is not None else ()))
        return prog, (*pre, *args)

    def step(self, state: KafkaState,
             send_key: np.ndarray | None = None,
             send_val: np.ndarray | None = None,
             commit_req: np.ndarray | None = None,
             repl_ok: np.ndarray | None = None) -> KafkaState:
        n, s, k = self.n_nodes, self.max_sends, self.n_keys
        if send_key is None:
            send_key = np.full((n, s), -1, np.int32)
            send_val = np.zeros((n, s), np.int32)
        if commit_req is None:
            commit_req = np.full((n, k), -1, np.int32)
        repl_mode = self._repl_mode(repl_ok)
        matmul = repl_mode == "matmul"
        if matmul and repl_ok is None:
            repl_ok = np.ones((n, n), bool)
        args = [jnp.asarray(send_key, jnp.int32),
                jnp.asarray(send_val, jnp.int32),
                jnp.asarray(commit_req, jnp.int32)]
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(self._na, None))
            args = [shard_put(a, sh) for a in args]
        if matmul:
            args.append(jnp.asarray(repl_ok))
        args.append(self.kv_sched)
        if self._fp_active:
            args.append(self.fault_plan)
        return self._step_prog(repl_mode)(state, *args)

    # -- open-loop traffic (PR 7) -----------------------------------------

    def _traffic_round(self, state: KafkaState, ts, tspec, tplan,
                       sched: KVReach, coll, plan, repl_mode: str,
                       ub: int, tel=None, tel_mask=None,
                       tel_full: bool = False):
        """One traffic-injected round (traced): stage this round's
        arrivals as a shard-local send batch (op (client, k) sends a
        seeded key with its op id as the value — globally unique, like
        the staged campaigns), mirror the round's allocator
        (:func:`_alloc` — the same pure function the round evaluates)
        to learn which sends ACK, run the ordinary round, then advance
        the tracker.  Deferral classes, all loud: home node down; node
        intake saturated (more arrivals than ``max_sends`` batch slots
        — or the spec's tighter ``intake``); op slots exhausted; and
        the allocation itself failing (KV unreachable this round, or
        key capacity overflow) — the client got an error reply, so the
        op was never acked.  An op completes when its (key, slot)
        presence bit is set at EVERY node (the per-op form of the
        kafka convergence predicate), so crash windows stall
        completions until the resync repairs presence: the serving
        cliff."""
        rows = coll.row_ids.shape[0]
        bc = rows * tspec.n_clients // self.n_nodes
        p = coll.row_ids[0] // jnp.int32(rows)
        ids = p * jnp.int32(bc) + jnp.arange(bc, dtype=jnp.int32)
        arr = traffic.arrive(tplan, state.t, ids)
        node_loc = traffic.local_node_cols(tspec, bc)
        up_cl = (faults.node_up(plan, state.t,
                                coll.row_ids[0] + node_loc)
                 if plan is not None else jnp.ones(arr.shape, bool))
        s_dim = self.max_sends
        cap_in = s_dim if tspec.intake is None \
            else min(tspec.intake, s_dim)
        rank = traffic.intake_rank(arr, tspec.clients_per_node)
        cand = (arr & up_cl & (rank < cap_in)
                & (ts.issued_k < tspec.ops_per_client))
        kslot_pre = ts.issued_k
        v = ids * jnp.int32(tspec.ops_per_client) + kslot_pre
        kx = faults._mix32(
            ids.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
            ^ kslot_pre.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
            ^ tplan.seed ^ jnp.uint32(traffic.SALT_KEY))
        key_ck = (kx % jnp.uint32(self.n_keys)).astype(jnp.int32)
        slot_idx = jnp.where(cand, rank, jnp.int32(s_dim))
        send_key = jnp.full((rows, s_dim), -1, jnp.int32).at[
            node_loc, slot_idx].set(key_ck, mode="drop")
        send_val = jnp.zeros((rows, s_dim), jnp.int32).at[
            node_loc, slot_idx].set(v, mode="drop")
        # allocator mirror — bit-identical to the round's own
        # evaluation (same pure function, same operands)
        reach = _reach(state.t, coll.row_ids, sched)
        up_rows = None
        if plan is not None:
            up_rows = faults.node_up(plan, state.t, coll.row_ids)
            reach = reach & up_rows & ~faults.kv_drop(plan, state.t,
                                                      coll.row_ids)
        _t, _vd, _kc, _rk, slot, ok_flat = _alloc(
            state.kv_val, send_key, reach, up_rows,
            coll.exclusive_sum, self.n_keys, self.capacity)
        fi = jnp.where(cand, node_loc * jnp.int32(s_dim) + rank, 0)
        alloc_ok = cand & ok_flat[fi]
        op_slot = slot[fi]
        ts, ok, kslot = traffic.issue(
            ts, arr, up_cl & (rank < cap_in) & alloc_ok, state.t,
            coll.reduce_sum)
        ts = traffic.record_aux(ts, ok, kslot, op_slot)
        # commits ride as a traced all--1 constant: `want = req >= 1`
        # folds to False and XLA dead-codes the commit pipeline (the
        # run_rounds commit-free pattern)
        commit_req = jnp.full((rows, self.n_keys), -1, jnp.int32)
        s2 = self._round(state, send_key, send_val, commit_req, None,
                         sched, coll, repl_mode=repl_mode, plan=plan)
        # visibility: the (key, slot) bit at EVERY node — AND over the
        # local presence rows, combined by the ppermute-only
        # reduce_and (no all-gather), read per op slot
        local_and = lax.reduce(s2.present, jnp.uint32(0xFFFFFFFF),
                               lax.bitwise_and, (0,))
        all_pres = coll.reduce_and(local_and)          # (K, Wc)
        aux = ts.op_aux
        n_k = tspec.ops_per_client

        def bit_fn(lo, block):
            idv = (p * jnp.int32(bc) + lo
                   + jnp.arange(block, dtype=jnp.int32))
            kk = jnp.arange(n_k, dtype=jnp.int32)
            kx2 = faults._mix32(
                idv[:, None].astype(jnp.uint32)
                * jnp.uint32(0xC2B2AE35)
                ^ kk[None, :].astype(jnp.uint32)
                * jnp.uint32(0x9E3779B9)
                ^ tplan.seed ^ jnp.uint32(traffic.SALT_KEY))
            keys2 = (kx2 % jnp.uint32(self.n_keys)).astype(jnp.int32)
            a = lax.dynamic_slice_in_dim(aux, lo, block, axis=0)
            sl = jnp.maximum(a, 0)
            bit = ((all_pres[keys2, sl // 32]
                    >> (sl % 32).astype(jnp.uint32)) & jnp.uint32(1))
            return (a >= 0) & (bit > 0)

        ts = traffic.done_scan(ts, bit_fn, s2.t, coll.reduce_sum, ub)
        if tel is None:
            return s2, ts
        vals = (self._tel_series(state, s2, coll, plan,
                                 full_scan=tel_full)
                + traffic.tel_series(ts, coll.reduce_sum))
        return s2, ts, telemetry.record(tel, state.t, vals, tel_mask)

    def _build_traffic(self, tspec, donate: bool, tel_spec=None):
        if tspec.n_nodes != self.n_nodes:
            raise ValueError(
                f"TrafficSpec is for {tspec.n_nodes} nodes, sim has "
                f"{self.n_nodes}")
        repl_mode = self._repl_mode(None)
        if repl_mode == "matmul":
            raise ValueError(
                "traffic drivers ride the origin-union replication "
                "paths; repl_fast=False pins the matmul oracle — "
                "compare blocked vs materialized via union_block "
                "instead")
        mesh = self.mesh
        n_sh = node_shards(mesh)
        if tspec.n_clients % n_sh != 0:
            raise ValueError(
                f"n_clients={tspec.n_clients} must shard evenly over "
                f"the {n_sh}-way node axis")
        ub = traffic.traffic_block(tspec.n_clients // n_sh)
        tl = tel_spec is not None
        mask = tel_spec.static_mask if tl else None
        tel_full = tl and "present_bits_full" in tel_spec.series
        dn = donate_argnums_for(donate, *((0, 1, 2) if tl else (0, 1)))
        fp = self._fp_active

        def run(state, *rest):
            rest = list(rest)
            tel = rest.pop(0) if tl else None
            ts, n, tplan, sched = rest[0], rest[1], rest[2], rest[3]
            plan = rest[4] if fp else None
            coll = collectives(
                state.present.shape[0],
                mesh, dcn=self._dcn)

            def body(c, op):
                if tl:
                    return self._traffic_round(
                        c[0], c[1], tspec, op, sched, coll, plan,
                        repl_mode, ub, tel=c[2], tel_mask=mask,
                        tel_full=tel_full)
                return self._traffic_round(
                    c[0], c[1], tspec, op, sched, coll, plan,
                    repl_mode, ub)

            carry = (state, ts, tel) if tl else (state, ts)
            return fori_rounds(body, carry, n, operand=tplan)

        if mesh is None:
            prog = jit_program(run, donate_argnums=dn)
        else:
            t_specs = traffic.state_specs(True, self._na)
            state_spec = self._state_spec()
            tel_in = (telemetry.state_specs(),) if tl else ()
            in_specs = ((state_spec,) + tel_in
                        + (t_specs, P(), traffic.plan_specs(),
                           KVReach(P(), P(), P(None, None)))
                        + ((faults.plan_specs(),) if fp else ()))
            prog = jit_program(run, mesh=mesh, in_specs=in_specs,
                               out_specs=(state_spec, t_specs)
                               + tel_in,
                               check_vma=False, donate_argnums=dn)

        fp_args = (self.fault_plan,) if fp else ()

        def args_fn(state, ts, n, tplan, tel=None):
            pre = (state, tel) if tl else (state,)
            return pre + (ts, n, tplan, self.kv_sched) + fp_args

        runner = lambda state, ts, n, tplan, tel=None: prog(
            *args_fn(state, ts, n, tplan, tel))
        return prog, args_fn, runner

    def traffic_state(self, tspec) -> traffic.TrafficState:
        return traffic.init_state(tspec, self.mesh)

    def run_traffic(self, state: KafkaState, ts, tspec,
                    n_rounds: int, *, donate: bool = False,
                    tel=None, tel_spec=None):
        """Open-loop serving driver: ``n_rounds`` rounds as ONE device
        program, each round staging the spec's seeded arrivals through
        the existing send path (allocation, append, fire-and-forget
        replication) and advancing the per-op latency tracker
        (tpu_sim/traffic.py).  Composes with a FaultPlan — the
        (tplan, plan) operands ride the same fused program, blocked
        streaming union included.  ``donate`` consumes both the sim
        state and the tracker.  Programs cache by
        ``TrafficSpec.program_key``, so a load sweep reuses one
        compiled program across rates.  ``tel``/``tel_spec`` (PR 8):
        record the per-round telemetry ring next to the tracker —
        returns ``(state, ts, tel)``."""
        key = (tspec.program_key, donate,
               telemetry.tel_key(tel, tel_spec, "kafka"))
        if key not in self._traffic_progs:
            self._traffic_progs[key] = self._build_traffic(
                tspec, donate, tel_spec)
        return self._traffic_progs[key][2](state, ts,
                                           jnp.int32(n_rounds),
                                           tspec.compile(), tel)

    def audit_traffic_program(self, tspec, *, donate: bool = True,
                              tel_spec=None):
        """(jitted, example_args) of the traffic driver — the handle
        the contract auditor lowers (census + donation of the EXACT
        program :meth:`run_traffic` executes)."""
        key = (tspec.program_key, donate, tel_spec)
        if key not in self._traffic_progs:
            self._traffic_progs[key] = self._build_traffic(
                tspec, donate, tel_spec)
        prog, args_fn, _ = self._traffic_progs[key]
        tel = (telemetry.init_state(tel_spec) if tel_spec is not None
               else None)
        return prog, args_fn(self.init_state(),
                             self.traffic_state(tspec), jnp.int32(4),
                             tspec.compile(), tel)

    # -- host-side reads (reference read semantics) ------------------------

    def alloc_offsets(self, state_before: KafkaState,
                      send_key: np.ndarray) -> np.ndarray:
        """(N, S) int32 — the offsets the sends of this round were acked
        with (``send_ok`` replies), or -1.  Runs the SAME device
        program (:func:`_rank_within_key` + base lookup) as the round's
        allocator — one dispatch per batch, no per-send host loop."""
        if self._alloc_fn is None:
            cap = self.capacity
            k_dim = self.n_keys

            @jax.jit
            def alloc(kv_val, send_key, reach):
                flat = send_key.reshape(-1)
                valid = (flat >= 0) & jnp.repeat(reach,
                                                 send_key.shape[1])
                keys_c = jnp.clip(flat, 0, k_dim - 1)
                rank = _rank_within_key(keys_c, valid)
                base = jnp.where(kv_val > 0, kv_val, 1)
                off = base[keys_c] + rank
                ok = valid & (off - 1 < cap)
                return jnp.where(ok, off, -1).reshape(send_key.shape)

            self._alloc_fn = alloc
        # KV-blocked nodes' sends ack as errors (-1): mirror the
        # round's reach gate at this state's round number
        sched = self.kv_sched
        t = int(state_before.t)
        reach = np.ones(self.n_nodes, bool)
        for w in range(int(np.asarray(sched.starts).shape[0])):
            if int(sched.starts[w]) <= t < int(sched.ends[w]):
                reach &= ~np.asarray(sched.blocked[w])
        if self.fault_plan is not None:
            reach &= faults.host_kv_ok(self.fault_plan, t)
        return np.asarray(self._alloc_fn(
            state_before.kv_val, jnp.asarray(send_key, jnp.int32),
            jnp.asarray(reach)))

    def poll_batch_program(self):
        """The jitted batched-poll device program: ``(present,
        log_vals, nodes, keys, from_offsets) -> (offsets, msgs)`` with
        (Q,) query arrays and (Q, capacity) padded outputs (offset -1
        = empty slot).  Public so benchmarks can drive the device
        program directly (chained, device-resident) without the host
        round-trip :meth:`poll_batch` adds."""
        if self._poll_batch_fn is None:
            cap = self.capacity

            @jax.jit
            def pb(present, log_vals, nodes, keys, from_off):
                words = present[nodes, keys]            # (Q, Wc)
                offs = jnp.arange(1, cap + 1, dtype=jnp.int32)
                slots = offs - 1
                pres = ((words[:, slots // 32]
                         >> (slots % 32).astype(jnp.uint32))
                        & jnp.uint32(1)) > 0            # (Q, C)
                sel = pres & (offs[None, :] >= from_off[:, None])
                vals = log_vals[keys]                   # (Q, C)
                return (jnp.where(sel, offs[None, :], -1),
                        jnp.where(sel, vals, 0))

            self._poll_batch_fn = pb
        return self._poll_batch_fn

    def poll_batch(self, state: KafkaState, nodes: np.ndarray,
                   keys: np.ndarray, from_offsets: np.ndarray,
                   ) -> tuple[np.ndarray, np.ndarray]:
        """Batched LOCAL-log poll (log.go:79-110) as ONE device
        program: for Q queries (node, key, from_offset), returns padded
        ``(offsets, msgs)`` arrays of shape (Q, capacity) — offset -1
        marks an empty slot (not present locally, or below the
        requested offset).  Slots are offset-ascending by layout, so
        each row is a ready [offset, msg] block.  This is the
        poll-heavy path the benchmark drives at 10k keys; the
        single-query :meth:`poll` wraps it."""
        offs, vals = self.poll_batch_program()(
            state.present, state.log_vals,
            jnp.asarray(nodes, jnp.int32), jnp.asarray(keys, jnp.int32),
            jnp.asarray(from_offsets, jnp.int32))
        return np.asarray(offs), np.asarray(vals)

    def poll(self, state: KafkaState, node: int, key: int,
             from_offset: int) -> list[list[int]]:
        """[[offset, msg], ...] from this node's LOCAL log only
        (log.go:79-110) — the single-query view of
        :meth:`poll_batch`."""
        offs, vals = self.poll_batch(
            state, np.array([node]), np.array([key]),
            np.array([from_offset]))
        sel = offs[0] >= 0
        return [[int(o), int(v)]
                for o, v in zip(offs[0][sel], vals[0][sel])]

    def present_bool(self, state: KafkaState) -> np.ndarray:
        """(N, K, C) bool — the presence bitset unpacked, host-side
        (tests/inspection at small scale; the device layout stays
        bit-packed)."""
        words = np.asarray(state.present)
        c = np.arange(self.capacity)
        return ((words[..., c // 32] >> (c % 32)) & 1).astype(bool)

    def list_committed(self, state: KafkaState, node: int) -> dict[int, int]:
        """Per-key committed offsets from the node's LOCAL cache only
        (log.go:131-156)."""
        lc = np.asarray(state.local_committed[node])
        (nz,) = np.nonzero(lc > 0)
        return {int(k): int(lc[k]) for k in nz}

    def lin_kv(self, state: KafkaState) -> dict[int, int]:
        """The shared lin-kv cells (key -> value).  After sends this is
        the allocator's next offset, NOT a committed offset — the two
        paths share the key (see module docstring)."""
        c = np.asarray(state.kv_val)
        return {k: int(c[k]) for k in range(self.n_keys) if c[k] > 0}


# -- scenario-axis batch hooks (PR 10, tpu_sim/scenario.py) --------------


def _build_batch_round(sim: "KafkaSim"):
    """Per-scenario round closure for the scenario-axis batch drivers:
    the sim's own :meth:`KafkaSim._round` on the FAULTED origin-union
    path with identity collectives (each scenario's node axis is fully
    local under scenario sharding), the scenario's OWN plan + staged
    send batch as traced operands, and the commit-free all--1
    commit_req built inside the trace (the run_rounds commit-free
    convention — XLA dead-codes the commit pipeline)."""
    coll = collectives(sim.n_nodes)
    k_dim = sim.n_keys

    def rnd(state, plan, send_key, send_val):
        cr = jnp.full((send_key.shape[0], k_dim), -1, jnp.int32)
        return sim._round(state, send_key, send_val, cr, None,
                          sim.kv_sched, coll, repl_mode="union_nem",
                          plan=plan)
    return rnd


def _batch_converged(state: KafkaState, member=None) -> jnp.ndarray:
    """() bool, traced — one scenario's convergence predicate: every
    node's presence bitset identical (the traced twin of
    run_kafka_nemesis's host check).  ``member`` ((N,) bool, PR 17)
    compares MEMBER rows against the first member's row instead of
    row 0 (row 0 may have left) and exempts non-members — a left
    row's wiped presence can never resync."""
    if member is None:
        return jnp.all(state.present == state.present[:1])
    ref = jnp.argmax(member).astype(jnp.int32)
    ok = state.present == state.present[ref][None]
    return jnp.all(ok | ~member[:, None, None])


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def _audit_spec(n):
    from . import faults as F
    return F.NemesisSpec(n_nodes=n, seed=5, crash=((2, 4, (1,)),),
                         loss_rate=0.2, loss_until=6)


def _step_args(sim):
    """The one-round program's example operands (mirrors
    :meth:`KafkaSim.step`'s arg assembly, matmul mask excluded)."""
    n, s, k = sim.n_nodes, sim.max_sends, sim.n_keys
    args = [jnp.full((n, s), -1, jnp.int32),
            jnp.zeros((n, s), jnp.int32),
            jnp.full((n, k), -1, jnp.int32)]
    if sim.mesh is not None:
        sh = NamedSharding(sim.mesh, P(sim._na, None))
        args = [shard_put(a, sh) for a in args]
    return args


def audit_contracts():
    """The kafka drivers' :class:`~.audit.ProgramContract` rows —
    sharded-presence census gates for all four replication paths (the
    PR 4/5 no-all-gather contracts and the bounded widens of the
    materialized/matmul oracles) plus the donated blocked-union fused
    driver's donation + memory contract (the BENCH_PR5 analytic
    formula, audited against XLA's buffer assignment)."""
    from .audit import AuditProgram, ProgramContract

    def union_step(mesh):
        sim = KafkaSim(8, 4, capacity=64, max_sends=2, mesh=mesh)
        prog = sim._step_prog("union")
        return AuditProgram(prog, tuple([sim.init_state()]
                                        + _step_args(sim)
                                        + [sim.kv_sched]))

    def nem_step(mesh, union_block):
        n = 16
        sim = KafkaSim(n, 4, capacity=64, max_sends=2, mesh=mesh,
                       fault_plan=_audit_spec(n).compile(),
                       union_block=union_block)
        prog = sim._step_prog("union_nem")
        return AuditProgram(prog, tuple([sim.init_state()]
                                        + _step_args(sim)
                                        + [sim.kv_sched,
                                           sim.fault_plan]))

    def matmul_step(mesh):
        n = 8
        sim = KafkaSim(n, 4, capacity=64, max_sends=2, mesh=mesh,
                       repl_fast=False)
        prog = sim._step_prog("matmul")
        repl = jnp.asarray(np.ones((n, n), bool))
        return AuditProgram(prog, tuple([sim.init_state()]
                                        + _step_args(sim)
                                        + [repl, sim.kv_sched]))

    def traffic_run(mesh):
        # big enough that state dominates the per-round temps (the
        # memory band then audits the donated-footprint claim)
        n, keys, cap, k = 256, 64, 64, 4
        tspec = traffic.TrafficSpec(
            n_nodes=n, n_clients=n, ops_per_client=k, until=8,
            rate=0.5, seed=11)
        sim = KafkaSim(n, keys, capacity=cap, max_sends=2, mesh=mesh,
                       fault_plan=_audit_spec(n).compile(),
                       union_block=4)
        prog, args = sim.audit_traffic_program(tspec)
        # per-shard parameter shapes in the compiled header
        n_sh = 1 if mesh is None else 8
        wc = sim.n_pwords
        state_bytes = (n * keys * wc * 4          # present
                       + n * keys * 4              # local_committed
                       + n * 4 + 3 * n * k * 4    # tracker leaves
                       ) // n_sh
        repl = keys * cap * 4 + keys * 4           # log_vals + kv_val
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes + repl,
            operand_bytes=operand_bytes(
                (tspec.compile(), sim.fault_plan)),
            # deliver carry + coin slab + tracker-scan temps
            slab_bytes=(n // n_sh) * keys * wc * 4 + n * k * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def fused_donated(mesh):
        del mesh                       # single-device memory contract
        n, k, cap, s, b, r = 256, 16, 32, 8, 32, 2
        sim = KafkaSim(n, k, capacity=cap, max_sends=s,
                       fault_plan=_audit_spec(n).compile(),
                       union_block=b)
        prog = sim._run_prog(False, "union_nem", True)
        sks = jnp.full((r, n, s), -1, jnp.int32)
        svs = jnp.zeros((r, n, s), jnp.int32)
        fp = sim.union_footprint(donated=True)
        staged = int(operand_bytes((sks, svs)))
        return AuditProgram(
            prog, (sim.init_state(), sks, svs, sim.kv_sched,
                   sim.fault_plan),
            donated_bytes=fp["state_bytes"],
            analytic_peak_bytes=fp["peak_live_bytes"] + staged)

    return [
        ProgramContract(
            name="kafka/sharded-step-union",
            build=union_step,
            collectives={"all-reduce": None, "collective-permute": None},
            notes="fault-free sharded round: blocked psum-of-OR + "
                  "ppermute prefix scan — NO all-gather (the PR 4 "
                  "gate)"),
        ProgramContract(
            name="kafka/sharded-step-union-nem-blocked",
            build=lambda mesh: nem_step(mesh, 1),
            collectives={"all-reduce": None, "collective-permute": None},
            notes="blocked streaming faulted union: per-send metadata "
                  "rides a ring ppermute — NO all-gather (the PR 5 "
                  "gate)"),
        ProgramContract(
            name="kafka/sharded-step-union-nem-materialized",
            build=lambda mesh: nem_step(mesh, "materialized"),
            collectives={"all-reduce": None, "collective-permute": None,
                         "all-gather": 3},
            notes="materialized faulted union (blocking oracle): "
                  "exactly the 3 per-send metadata widens (bit, key, "
                  "word), presence never moves"),
        ProgramContract(
            name="kafka/sharded-step-matmul-oracle",
            build=matmul_step,
            collectives={"all-reduce": None, "collective-permute": None,
                         "all-gather": 1},
            notes="link-mask matmul oracle: the one own_words widen "
                  "is the oracle's documented full operand"),
        ProgramContract(
            name="kafka/sharded-traffic-run-union-nem-blocked",
            build=traffic_run,
            collectives={"all-reduce": None, "collective-permute": None},
            donation=True,
            mem_lo=0.2, mem_hi=6.0,
            notes="open-loop traffic driver under crash+loss on the "
                  "BLOCKED streaming union (PR 7): shard-local send "
                  "staging, the _alloc mirror's ppermute prefix scan, "
                  "the metadata ring, and the reduce_and presence-"
                  "visibility fold add ZERO gathers; (state, tracker) "
                  "alias in place — the injected-traffic census + "
                  "donation contract"),
        ProgramContract(
            name="kafka/fused-donated-union-nem-blocked",
            build=fused_donated,
            collectives={},
            donation=True,
            mem_lo=0.2, mem_hi=3.0,
            needs_mesh=False,
            notes="donated blocked-union scan driver at the "
                  "union_footprint test shape: state aliases in "
                  "place, compiled peak within band of the BENCH_PR5 "
                  "analytic formula + staged send operands"),
    ]
