"""tpu_sim: the vectorized TPU backend.

Instead of one OS process per node talking JSON through a harness (the
reference's model — Maelstrom spawns N copies of a Go binary, survey §1
Layer 0), every simulated node is a **row of a device-sharded state
array**.  A simulation round is a pure jitted function

    (state, static topology, fault masks) -> state'

and the "network" is a sparse neighbor gather: message delivery between
nodes on different devices rides XLA collectives (``all_gather`` /
``psum`` over the mesh's ICI links), not a socket.  Fault injection is a
time-varying boolean edge mask (survey §5), and one simulation round
models one network hop (Maelstrom's injected 100 ms per-hop latency ==
one round).

Modules:

- :mod:`.broadcast` — challenge 3 (fault-tolerant broadcast): bitset
  flood + periodic anti-entropy; the flagship/benchmark model.
- :mod:`.counter` — challenge 4 (g-counter): CAS-contention and
  all-reduce flush modes, KV-reachability faults.
- :mod:`.kafka` — challenge 5 (replicated log): rank-within-round
  offset allocation, loss-masked einsum replication.
- :mod:`.unique_ids` — challenge 2: coordination-free (t, node, seq)
  id mint.
- :mod:`.echo` — challenge 1: batched identity, the smoke test.
- :mod:`.engine` — the shared donation-first execution engine every
  stateful sim runs on: the ``shard_map`` entry-point compat, buffer-
  donating ``jit_program``, mesh collectives, round-fused drivers, and
  the halo primitives (see ARCHITECTURE.md "The shared execution
  engine").
- :mod:`.faults` — the nemesis beyond partitions: seeded, replayable
  crash/restart (amnesia rows), probabilistic message loss, and
  duplicate delivery, compiled to a ``FaultPlan`` operand every
  stateful sim threads through its fused drivers (see ARCHITECTURE.md
  "Nemesis").
- :mod:`.audit` — the program-contract auditor (PR 6): static
  HLO/jaxpr analysis (collective census, donation alias table, host
  boundary, memory contract) over a declarative per-driver
  ``ProgramContract`` registry, plus the AST determinism lint (see
  ARCHITECTURE.md "Static contracts").
- :mod:`.traffic` — the open-loop client-traffic engine (PR 7):
  seeded Poisson/constant/burst arrival schedules over a client axis
  (stateless (round, client) hash coins, a ``TrafficPlan`` operand
  next to the FaultPlan), the per-op completion-round tracker behind
  the p50/p99 serving-latency reports, and the loud backpressure
  accounting (see ARCHITECTURE.md "Open-loop traffic").
- :mod:`.telemetry` — flight-recorder telemetry (PR 8): the
  device-resident per-round metrics ring (``TelemetrySpec`` →
  ``TelemetryState`` carry, psum-of-partials, donated with the
  state) behind the sims' ``run_observed`` / ``run_traffic(tel=)``
  drivers and harness/observe.py's manifests, Perfetto timelines,
  and flight-recorder repro bundles (see ARCHITECTURE.md
  "Observability").
"""

from .broadcast import (BroadcastSim, BroadcastState, Partitions,
                        make_inject)
from .counter import CounterSim, CounterState, KVReach
from .echo import EchoSim, EchoState
from .faults import FaultPlan, NemesisSpec, random_spec
from .kafka import KafkaSim, KafkaState
from .structured import (FaultedDelayed, StructuredDelays,
                         StructuredFaults, make_delayed,
                         make_delayed_faulted, make_faulted)
from .telemetry import TelemetrySpec, TelemetryState
from .traffic import TrafficPlan, TrafficSpec, TrafficState
from .unique_ids import UniqueIdsSim, UniqueIdsState

__all__ = ["BroadcastSim", "BroadcastState", "Partitions", "make_inject",
           "CounterSim", "CounterState", "KVReach",
           "KafkaSim", "KafkaState",
           "FaultPlan", "NemesisSpec", "random_spec",
           "StructuredFaults", "make_faulted",
           "StructuredDelays", "make_delayed",
           "FaultedDelayed", "make_delayed_faulted",
           "TrafficSpec", "TrafficPlan", "TrafficState",
           "TelemetrySpec", "TelemetryState",
           "UniqueIdsSim", "UniqueIdsState",
           "EchoSim", "EchoState"]
