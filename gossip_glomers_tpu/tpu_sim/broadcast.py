"""Vectorized fault-tolerant broadcast (challenge 3) on TPU.

Semantics mirrored from the reference node (broadcast/broadcast.go):

- **Eager gossip** (HandleBroadcast + rebroadcastAllExcept,
  broadcast.go:50-79): a node that learns a new value floods it to its
  neighbors; duplicates are absorbed.  Here: each node keeps a *received*
  bitset and a *frontier* bitset (values learned last round); one round
  delivers every node's frontier to its live neighbors and the dedup is a
  bitwise ``& ~received``.
- **Periodic push-pull anti-entropy** (SyncBroadcast, broadcast.go:81-122,
  fired every 2 s + jitter by main.go:42-51): the partition-repair path.
  Here: every ``sync_every`` rounds a node's payload is its FULL received
  set instead of just the frontier — the round delivers the pairwise set
  unions the reference's read/diff/merge dance converges to, and newly
  learned values re-enter the frontier so they keep flooding (the
  reference's ``rebroadcastAllExcept`` inside the sync callback,
  broadcast.go:97-102).
- **Fault injection**: Maelstrom's partition nemesis becomes a
  time-varying boolean edge mask (survey §5); latency (100 ms/hop in the
  reference runs, README.md:16) is the round itself — 1 round == 1 hop.

State layout (struct-of-arrays, node axis shardable over the mesh):

- ``received``: (N, W) uint32 — bit v%32 of word v//32 set iff value v
  is known.  W = ceil(n_values/32).
- ``frontier``: (N, W) uint32 — values newly learned last round.

The inter-node "network" is one sparse gather: ``inbox[i] = OR_d
payload[nbr[i, d]]`` over live edges.  Multi-device, the payload is
``all_gather``-ed along the ``nodes`` mesh axis (ICI), then gathered
locally — the gossip fan-out *is* the collective.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import faults, provenance, telemetry, traffic
from .engine import (collectives, dcn_psum, donate_argnums_for,
                     fori_rounds, host_view, jit_program, node_axes,
                     node_shards, resolve_block, resolve_dcn_mode,
                     scan_blocks, shard_map, stepwise_converge,
                     unpack_bits, while_converge, windows_fold)
from .structured import _take_delayed

WORD = 32


def num_words(n_values: int) -> int:
    return max(1, (n_values + WORD - 1) // WORD)


def make_inject(n_nodes: int, n_values: int,
                origins: np.ndarray | None = None) -> np.ndarray:
    """Initial injection bitset: value v starts at node origins[v]
    (default v % n_nodes — the round-robin the workload client uses).
    Returns (N, W) uint32."""
    w = num_words(n_values)
    out = np.zeros((n_nodes, w), dtype=np.uint32)
    if origins is None:
        origins = np.arange(n_values) % n_nodes
    for v in range(n_values):
        out[origins[v], v // WORD] |= np.uint32(1 << (v % WORD))
    return out


class Partitions(NamedTuple):
    """Seeded partition schedule as data (faults.py's PartitionSchedule,
    compiled to arrays).  Window w is active for rounds
    [starts[w], ends[w]); while active, edges crossing groups drop."""

    starts: jnp.ndarray   # (P,) int32, round number (inclusive)
    ends: jnp.ndarray     # (P,) int32, round number (exclusive)
    group: jnp.ndarray    # (P, N) int8 — component id per node per window

    @staticmethod
    def none(n_nodes: int) -> "Partitions":
        return Partitions(jnp.zeros((0,), jnp.int32),
                          jnp.zeros((0,), jnp.int32),
                          jnp.zeros((0, n_nodes), jnp.int8))

    def to_meta(self) -> dict:
        """JSON-able form — the flight-recorder bundle carries the
        schedule so a partition-campaign failure replays from the
        bundle alone (harness/observe.py)."""
        return {"starts": [int(v) for v in np.asarray(self.starts)],
                "ends": [int(v) for v in np.asarray(self.ends)],
                "group": np.asarray(self.group).tolist()}

    @staticmethod
    def from_meta(meta: dict) -> "Partitions":
        group = np.asarray(meta["group"], dtype=np.int8)
        if group.ndim != 2:
            raise ValueError(
                f"Partitions meta group must be (P, N), got shape "
                f"{group.shape}")
        return Partitions(
            jnp.asarray(np.asarray(meta["starts"], np.int32)),
            jnp.asarray(np.asarray(meta["ends"], np.int32)),
            jnp.asarray(group))


class BroadcastState(NamedTuple):
    received: jnp.ndarray    # (N, W) uint32
    frontier: jnp.ndarray    # (N, W) uint32
    t: jnp.ndarray           # () int32 — round counter
    msgs: jnp.ndarray        # () uint32 — value-messages sent (wraps @2^32)
    # latency modes only: ring of past payload blocks — (L, N, W)
    # node-major for per-edge `delays` (gather path), (L, W, N)
    # words-major for per-direction `delayed` (structured path); in
    # both, node-SHARDED under a mesh so a delay-d edge/direction
    # delivers the payload flooded d rounds ago (Maelstrom's latency
    # as data) at O(L*N/shards) memory.  None when all edges are 1 hop.
    history: jnp.ndarray | None = None
    # reference-accounted server-to-server message total — what
    # Maelstrom's ledger would read for the same run.  Floods: one
    # `broadcast` per (value, topology neighbor) minus the sender
    # exclusion (rebroadcastAllExcept, broadcast.go:50-57) plus one
    # `broadcast_ok` per delivery; sync rounds: `read` per topology
    # neighbor + `read_ok` per live neighbor + the targeted diff pushes
    # and their acks (SyncBroadcast, broadcast.go:81-122).  Live on the
    # gather path by default and on the words-major structured path
    # when its sync_diff closure is supplied (structured.make_sync_diff
    # / make_sharded_sync_diff); None when srv_ledger=False or the
    # structured run has no sync_diff — `msgs` is then the only ledger
    # (throughput / value-messages).
    srv_msgs: jnp.ndarray | None = None


def _popcount(x: jnp.ndarray) -> jnp.ndarray:
    return lax.population_count(x)


def _edge_live(t: jnp.ndarray, row_ids: jnp.ndarray, nbrs: jnp.ndarray,
               nbr_mask: jnp.ndarray, parts: Partitions) -> jnp.ndarray:
    """(rows, D) bool — which edges deliver this round (pad edges never,
    partitioned edges not while a window covering them is active).

    ``row_ids`` are the *global* node indices of the local rows (arange(N)
    single-device; the shard's block under shard_map) — partition groups
    are indexed globally.
    """

    def body(w, active, live):
        g = parts.group[w]                       # (N,) global
        same = g[row_ids][:, None] == g[jnp.clip(nbrs, 0, g.shape[0] - 1)]
        return live & jnp.where(active, same, True)

    return windows_fold(parts.starts, parts.ends, t, body, nbr_mask)


def _live_split(t: jnp.ndarray, row_ids: jnp.ndarray, nbrs: jnp.ndarray,
                nbr_mask: jnp.ndarray, parts: Partitions,
                plan: "faults.FaultPlan | None", dup_on: bool):
    """Per-edge (rows, D) masks at send round ``t`` under the full
    nemesis: ``live_send`` = topology & partition windows & both
    endpoints up (sends attempted — the ledger side; loss counts as
    sent, the message died in flight); ``live_del`` = live_send minus
    the plan's per-direction loss coins (actual deliveries); ``dup`` =
    live_del edges that ALSO re-deliver their source's full received
    set this round (None when the plan has no dup stream)."""
    live = _edge_live(t, row_ids, nbrs, nbr_mask, parts)
    if plan is None:
        return live, live, None
    src = jnp.clip(nbrs, 0, plan.down.shape[1] - 1)
    live_send = (live & faults.node_up(plan, t, row_ids)[:, None]
                 & faults.node_up(plan, t, src))
    live_del = live_send & ~faults.edge_drop(plan, t, src,
                                             row_ids[:, None])
    dup = (live_del & faults.edge_dup(plan, t, src, row_ids[:, None])
           if dup_on else None)
    return live_send, live_del, dup


def _gather_or(payload: jnp.ndarray, nbrs: jnp.ndarray,
               live: jnp.ndarray) -> jnp.ndarray:
    """inbox[i] = OR over live edges d of payload[nbrs[i, d]].

    ``payload`` may cover more rows than ``nbrs`` (the all_gather-ed full
    node axis under shard_map); output has nbrs.shape[0] rows.  The loop
    over the (small, static) degree axis keeps the working set at one
    (N, W) gather per step instead of an (N, D, W) intermediate.
    """

    def term(d):
        idx = lax.dynamic_index_in_dim(nbrs, d, axis=1, keepdims=False)
        ok = lax.dynamic_index_in_dim(live, d, axis=1, keepdims=True)
        rows = payload[jnp.clip(idx, 0, payload.shape[0] - 1)]
        return jnp.where(ok, rows, jnp.uint32(0))

    # Initializing the carry from the d=0 term (instead of zeros) keeps
    # its sharding/varying type identical to the body output under
    # shard_map (scan-vma rule).
    return lax.fori_loop(1, nbrs.shape[1], lambda d, acc: acc | term(d),
                         term(0))


def _gather_or_delayed(history: jnp.ndarray, t: jnp.ndarray,
                       delays: jnp.ndarray, nbrs: jnp.ndarray,
                       nbr_mask: jnp.ndarray, parts: Partitions,
                       row_ids: jnp.ndarray, delay_set: tuple,
                       widen,
                       plan: "faults.FaultPlan | None" = None,
                       ) -> jnp.ndarray:
    """Latency-queue delivery: edge (i, d) with delay δ = delays[i, d]
    delivers the payload flooded at round t - (δ-1), with liveness
    evaluated at that send round (drops happen at send time, like
    Maelstrom's).

    ``history`` is a ring of past LOCAL payload blocks (L, rows, W) —
    node-SHARDED under shard_map, so a 1M-node delayed run holds
    O(L·N/shards) per device instead of a replicated O(L·N) ring.  The
    distinct delay values are static, so delivery is one masked
    ``widen`` (all_gather along 'nodes') + gather per value: the full
    past payload an edge class needs is materialized transiently per
    round, never stored.

    With a ``plan`` (faults.FaultPlan), each class's liveness at its
    send round also requires both endpoints up and the delivery coin
    to survive the loss stream — crash/loss compose with per-edge
    delays exactly like the partition windows (drops at send time)."""
    ring = history.shape[0]
    out = None
    for d in delay_set:
        src_t = t - (d - 1)
        _send, live_del, _dup = _live_split(src_t, row_ids, nbrs,
                                            nbr_mask, parts, plan,
                                            False)
        live = live_del & (delays == d) & (src_t >= 0)
        payload = widen(lax.dynamic_index_in_dim(
            history, src_t % ring, axis=0, keepdims=False))
        term = _gather_or(payload, nbrs, live)
        out = term if out is None else out | term
    return out


def _sync_diff_pc(payload_full: jnp.ndarray, recv_local: jnp.ndarray,
                  nbrs: jnp.ndarray, live: jnp.ndarray) -> jnp.ndarray:
    """() uint32 — total targeted-push volume of one reference sync
    wave: sum over live ordered neighbor pairs (j, i) of
    |recv_j \\ recv_i| (the ``mine minus peer's`` sends of
    broadcast.go:104-108), computed at each destination i against the
    payload rows its live neighbors hold."""

    def term(d):
        idx = lax.dynamic_index_in_dim(nbrs, d, axis=1, keepdims=False)
        ok = lax.dynamic_index_in_dim(live, d, axis=1, keepdims=False)
        rows = payload_full[jnp.clip(idx, 0, payload_full.shape[0] - 1)]
        per_node = _popcount(rows & ~recv_local).sum(
            axis=1).astype(jnp.uint32)
        return jnp.sum(jnp.where(ok, per_node, 0), dtype=jnp.uint32)

    return lax.fori_loop(1, nbrs.shape[1], lambda d, acc: acc + term(d),
                         term(0))


def _degree_masks(np_deg: np.ndarray):
    """(distinct degrees, per-degree full-ones (1, N) uint32 mask
    arrays) — the static masks the closed-form flood ledger ANDs with
    per-node popcounts instead of a u32 vector multiply (which, like
    1-D intermediates, lowers poorly on TPU)."""
    degs = sorted(set(np_deg.tolist()))
    return degs, [jnp.asarray(
        ((np_deg == d).astype(np.uint32)
         * np.uint32(0xFFFFFFFF))[None, :]) for d in degs]


def _flood_loop(exchange, rounds: int):
    """Pure exchange+merge fori_loop body over (received, frontier) —
    the timed benchmark program (no bookkeeping: in-loop reduces and
    selects defeat XLA's loop fusion).  unroll=2: measured up to ~15%
    faster in one session at 1M nodes / W=1 and parity in another
    (within tunnel-session variance) — kept because it never measured
    slower; higher unrolls did."""
    def loop(rec, fr):
        def one(i, c):
            rec, fr = c
            new = exchange(fr) & ~rec
            return (rec | new, new)

        return lax.fori_loop(0, rounds, one, (rec, fr),
                             unroll=2 if rounds > 1 else 1)

    return loop


def _flood_ledger(state: BroadcastState, rec, fr, degs, masks,
                  rounds: int,
                  reduce_sum=lambda s: s) -> BroadcastState:
    """Recover the value-message ledger of a pure flood in closed form:
    every (node, value) bit in `received` was in the frontier of
    exactly one executed round — flooded to deg neighbors then —
    except the final frontier (arrived last round, never flooded), so
    msgs += sum_i deg_i * (pc_i(received) - pc_i(frontier))."""
    dpc = (_popcount(rec).sum(axis=0, keepdims=True)
           - _popcount(fr).sum(axis=0, keepdims=True)
           ).astype(jnp.uint32)
    sent = jnp.uint32(0)
    for d, m in zip(degs, masks):
        sent = sent + jnp.uint32(d) * jnp.sum(dpc & m,
                                              dtype=jnp.uint32)
    return state._replace(received=rec, frontier=fr,
                          t=state.t + jnp.int32(rounds),
                          msgs=state.msgs + reduce_sum(sent))


def _prov_attribute(prov, new: jnp.ndarray, nbrs: jnp.ndarray,
                    term_fn, t_next):
    """Causal provenance write for one gather round (PR 9): stamp
    ``arrival = t_next`` and ``parent = nbrs[:, d]`` at exactly the
    per-(node, value) cells where the round's ``new`` bits landed,
    ``d`` being the FIRST direction whose delivery term carries the
    bit (``term_fn(d)`` -> the (rows, W) delivered words of direction
    ``d`` — the same terms the round's inbox OR already summed, so the
    recorder re-reads state in scope and adds no collectives).  Writes
    are first-incarnation (:func:`provenance.stamp` semantics): a bit
    re-learned after an amnesia wipe keeps its original arrival and
    parent, which is what keeps ``arrival[parent] < arrival[child]``
    true across crash/restart.  Shard-local throughout: ``nbrs`` holds
    global ids, the (rows, V) stamps shard with the node axis."""
    nv = prov.arrival.shape[1]
    fresh = unpack_bits(new, nv) & (prov.arrival < 0)
    parent = prov.parent
    remaining = new
    for d in range(nbrs.shape[1]):
        hit = term_fn(d) & remaining
        remaining = remaining & ~hit
        src = lax.dynamic_index_in_dim(nbrs, d, axis=1,
                                       keepdims=True)    # (rows, 1)
        parent = jnp.where(unpack_bits(hit, nv) & fresh, src, parent)
    arrival = jnp.where(fresh, jnp.asarray(t_next, jnp.int32),
                        prov.arrival)
    return provenance.BroadcastProv(arrival=arrival, parent=parent)


def _round(state: BroadcastState, *, row_ids: jnp.ndarray,
           nbrs: jnp.ndarray, nbr_mask: jnp.ndarray, parts: Partitions,
           sync_every: int,
           widen: Callable[[jnp.ndarray], jnp.ndarray] = lambda p: p,
           reduce_sum: Callable[[jnp.ndarray], jnp.ndarray] = lambda s: s,
           delays: jnp.ndarray | None = None,
           delay_set: tuple = (),
           sync_base_once: Callable[[jnp.ndarray], jnp.ndarray]
           = lambda x: x,
           plan: "faults.FaultPlan | None" = None,
           dup_on: bool = False,
           union_block: int | None = None,
           prov: "provenance.BroadcastProv | None" = None,
           ) -> "BroadcastState | tuple":
    """One simulation round == one base network hop — the single source
    of the node-major (adjacency-gather) round semantics, shared by the
    single-device and sharded paths.  (Structured topologies use the
    words-major :func:`_round_wm` instead.)

    Normal rounds flood the frontier (eager gossip); every
    ``sync_every``-th round floods the full received set (anti-entropy).
    ``widen`` maps the local payload block to the full node axis (identity
    single-device; ``all_gather`` along 'nodes' under shard_map) and
    ``reduce_sum`` globalizes the message count (identity / ``psum``).
    With ``delays`` ((N, D) rounds >= 1, static per edge), delivery reads
    the payload-history ring instead of the current payload.

    With ``plan`` (a compiled faults.FaultPlan), the round first wipes
    the AMNESIA rows — received/frontier die with a crashing process;
    the node sits empty while down and re-learns only through the
    flood + anti-entropy after restart (a Maelstrom kill/restart) —
    then masks every edge by endpoint liveness and the loss coins
    (:func:`_live_split`).  ``dup_on`` edges additionally re-deliver
    their source's full received set (at-least-once duplicates, absorbed
    by the ``& ~received`` dedup, visible in the msgs ledger).

    ``union_block`` (ISSUE 5): stream the faulted round over
    destination-row slabs of that size (engine.scan_blocks) instead of
    materializing the full (rows, D) liveness/coin masks at once — the
    full-mesh/star faulted shapes, whose per-edge coin tensor is
    O(N²), hold one O(B·D) slab of mask temps at a time.  The coins
    are stateless (t, src, dst) hashes, so any blocking is
    bit-identical to the materialized round — including the uint32
    ``msgs`` ledger, whose per-slab partial sums are exact modular
    adds.  Applies to 1-hop faulted rounds with the srv ledger off
    (``delays`` rings and the srv pass keep the materialized shape).

    ``prov`` (PR 9): a :class:`provenance.BroadcastProv` record — the
    round additionally returns ``(state, prov)`` with per-(node,
    value) arrival-round + parent stamps written where the ``new``
    bits land (:func:`_prov_attribute`; 1-hop AND per-edge ``delays``
    paths).  Provenance runs the materialized round (the blocked
    streaming branch is bit-identical, so the observed drivers simply
    pass ``union_block=None``).
    """
    if plan is None:
        rec0, fr0 = state.received, state.frontier
    else:
        wipe = faults.amnesia(plan, state.t, row_ids)
        rec0 = jnp.where(wipe[:, None], jnp.uint32(0),
                         state.received)
        fr0 = jnp.where(wipe[:, None], jnp.uint32(0),
                        state.frontier)
    is_sync = (state.t % jnp.int32(sync_every) == 0) & (state.t > 0)
    # frontier ⊆ received, so the anti-entropy payload is just `received`.
    payload = jnp.where(is_sync, rec0, fr0)
    payload_full = widen(payload)
    if (union_block is not None and plan is not None
            and delays is None and state.srv_msgs is None
            and prov is None):
        # -- streaming faulted round (see docstring) ------------------
        rows = nbrs.shape[0]
        ub = union_block
        pc_pay = _popcount(payload).sum(axis=1).astype(jnp.uint32)
        if dup_on:
            received_full = widen(rec0)
            pc_src = _popcount(received_full).sum(
                axis=1).astype(jnp.uint32)

        def blk(carry, lo):
            inbox_c, sent_c = carry
            rid = lax.dynamic_slice_in_dim(row_ids, lo, ub)
            nb = lax.dynamic_slice_in_dim(nbrs, lo, ub, axis=0)
            nm = lax.dynamic_slice_in_dim(nbr_mask, lo, ub, axis=0)
            ln, ld, dp = _live_split(state.t, rid, nb, nm, parts,
                                     plan, dup_on)
            s = jnp.sum(lax.dynamic_slice_in_dim(pc_pay, lo, ub)
                        * ln.sum(axis=1).astype(jnp.uint32),
                        dtype=jnp.uint32)
            ib = _gather_or(payload_full, nb, ld)
            if dp is not None:
                ib = ib | _gather_or(received_full, nb, dp)
                src_c = jnp.clip(nb, 0, payload_full.shape[0] - 1)
                s = s + jnp.sum(jnp.where(dp, pc_src[src_c], 0),
                                dtype=jnp.uint32)
            return (lax.dynamic_update_slice_in_dim(inbox_c, ib, lo,
                                                    axis=0),
                    sent_c + s)

        # carry zeros derived from varying operands so the scan carry
        # keeps the body's sharding/varying type under shard_map (the
        # same scan-vma rule as _gather_or's d=0 init)
        inbox, sent_local = scan_blocks(
            blk,
            (payload & jnp.uint32(0),
             jnp.sum(pc_pay, dtype=jnp.uint32) * jnp.uint32(0)),
            rows, ub)
        new = inbox & ~rec0
        return BroadcastState(received=rec0 | new, frontier=new,
                              t=state.t + 1,
                              msgs=state.msgs + reduce_sum(sent_local),
                              history=state.history, srv_msgs=None)
    live_now, live_del, dup = _live_split(state.t, row_ids, nbrs,
                                          nbr_mask, parts, plan, dup_on)
    # throughput ledger: one value-message per (value, live edge) —
    # counted at send time regardless of delivery delay or in-flight
    # loss (the plan's dropped messages were still sent).
    sent_local = jnp.sum(
        _popcount(payload).sum(axis=1).astype(jnp.uint32)
        * live_now.sum(axis=1).astype(jnp.uint32), dtype=jnp.uint32)
    if dup is not None:
        # 1-hop: a dup edge re-delivers its source's full received set
        # (charged at its popcount).  Under `delays` the ring stores
        # payload blocks, not received sets, so a dup edge re-delivers
        # its IN-FLIGHT message instead — the send-round payload,
        # charged here at send time; the second delivery of an
        # identical block is absorbed by dedup with zero state change,
        # so the dup stream is purely ledger-visible in delay modes.
        if delays is None:
            received_full = widen(rec0)
            pc_src = _popcount(received_full).sum(
                axis=1).astype(jnp.uint32)
        else:
            pc_src = _popcount(payload_full).sum(
                axis=1).astype(jnp.uint32)
        src_c = jnp.clip(nbrs, 0, payload_full.shape[0] - 1)
        sent_local = sent_local + jnp.sum(
            jnp.where(dup, pc_src[src_c], 0), dtype=jnp.uint32)
    sent = reduce_sum(sent_local)
    # reference-accounted server-message ledger (Maelstrom parity):
    # floods charge `broadcast` sends to every TOPOLOGY neighbor minus
    # the sender exclusion (drops still count as sends) plus one
    # `broadcast_ok` per live delivery; t == 0 frontier rows are
    # origins (client-injected, no sender to exclude).  Sync rounds
    # charge read-per-topo-neighbor + read_ok-per-live-neighbor + the
    # targeted diff pushes and their acks.  Under `delays`, sends are
    # still charged at send time and the sync diff is computed against
    # current (not RTT-stale) peer state; the reference dance instead
    # diffs the peer's one-hop-old reply against own state a full RTT
    # later (broadcast.go:86-108).  The two disagree only for values
    # still in flight across a wave's RTT window — at most one
    # spurious/missed push + ack (2 msgs) per such (value, directed
    # pair), and exact whenever waves hit quiescent state.  Measured
    # against the per-edge-latency virtual harness in
    # test_delay_mode_sync_diff_gap_is_one_push / _exact_when_quiescent.
    if state.srv_msgs is None:
        srv = None
    else:
        deg_topo = nbr_mask.sum(axis=1).astype(jnp.int32)
        if plan is None:
            # partition-only regime: every live edge delivers, so the
            # ack/reply degree IS the live degree and diffs flow over
            # single live edges
            ack_edges = live_now
            diff_edges = live_now
            req_deg = deg_topo
        else:
            # LOSS/CRASH plan (dup rejects at construction): requests
            # are charged at send time like every message, but replies
            # exist only when the triggering request DELIVERED — the
            # outgoing (row -> neighbor) coin at this round over a
            # live edge (both endpoints up) — and a sync pair
            # exchanges its diff only when BOTH direction coins
            # survive (read delivered AND read_ok delivered; the diff
            # pushes then ride the already-delivered direction).
            # Crash charge-at-send: a DOWN row sends nothing (req_deg
            # zeroed — its reads don't fire — and its frontier is
            # empty from the amnesia wipe), while requests TO a down
            # neighbor stay charged at full topology degree and die
            # with the process (live_now excludes the edge, so no
            # ack); the post-recovery anti-entropy wave re-pushes and
            # RE-CHARGES the repair (calibrated against the virtual
            # harness with its down_fn process model).  The flood ack
            # term assumes the sender-edge coin delivered (the sim
            # does not track per-value senders); windows of
            # disagreement are one ack per (value, node) whose
            # sender-edge coin drops during its flood round — exact
            # otherwise, pinned in test_ledger_calibration.py.
            src_c = jnp.clip(nbrs, 0, plan.down.shape[1] - 1)
            out_ok = ~faults.edge_drop(plan, state.t,
                                       row_ids[:, None], src_c)
            ack_edges = live_now & out_ok
            diff_edges = live_del & out_ok
            req_deg = jnp.where(
                faults.node_up(plan, state.t, row_ids), deg_topo, 0)
        ack_deg = ack_edges.sum(axis=1).astype(jnp.int32)
        pcf = _popcount(fr0).sum(axis=1).astype(jnp.uint32)
        coef = jnp.where(state.t == 0, req_deg + ack_deg,
                         jnp.maximum(req_deg + ack_deg - 2, 0))
        flood = jnp.sum(pcf * coef.astype(jnp.uint32), dtype=jnp.uint32)
        base = sync_base_once(
            jnp.sum(req_deg + ack_deg, dtype=jnp.int32).astype(
                jnp.uint32))
        # computed every round and masked (a lax.cond would need equal
        # sharding types across branches under shard_map); on sync
        # rounds payload_full IS the widened received set
        diff = _sync_diff_pc(payload_full, rec0, nbrs,
                             diff_edges)
        srv_inc = flood + jnp.where(is_sync, base + 2 * diff,
                                    jnp.uint32(0))
        srv = state.srv_msgs + reduce_sum(srv_inc)
    if delays is None:
        inbox = _gather_or(payload_full, nbrs, live_del)
        if dup is not None:
            inbox = inbox | _gather_or(received_full, nbrs, dup)
        history = state.history
    else:
        # the ring stores the LOCAL payload block (node-sharded under
        # shard_map); _gather_or_delayed widens the needed slices
        ring = state.history.shape[0]
        history = lax.dynamic_update_index_in_dim(
            state.history, payload, state.t % ring, axis=0)
        inbox = _gather_or_delayed(history, state.t, delays, nbrs,
                                   nbr_mask, parts, row_ids, delay_set,
                                   widen, plan)
        if plan is not None:
            # a message in flight to a node that crashed before the
            # delivery round dies with the process: _gather_or_delayed
            # gates liveness at the SEND round, so mask the receiver
            # side at delivery time too (a down node receives nothing)
            inbox = jnp.where(
                faults.node_up(plan, state.t, row_ids)[:, None],
                inbox, jnp.uint32(0))
    new = inbox & ~rec0
    out = BroadcastState(received=rec0 | new,
                         frontier=new,
                         t=state.t + 1,
                         msgs=state.msgs + sent,
                         history=history,
                         srv_msgs=srv)
    if prov is None:
        return out
    # -- provenance attribution (PR 9): re-read the round's own
    #    per-direction delivery terms (payload_full / received_full /
    #    the ring slices are all in scope — XLA CSEs the shared
    #    subexpressions, so this adds ZERO collectives) and stamp the
    #    new bits' arrival + parent
    if delays is None:
        def term(d):
            idx = lax.dynamic_index_in_dim(nbrs, d, axis=1,
                                           keepdims=False)
            ok = lax.dynamic_index_in_dim(live_del, d, axis=1,
                                          keepdims=True)
            rows_d = payload_full[jnp.clip(idx, 0,
                                           payload_full.shape[0] - 1)]
            t_ = jnp.where(ok, rows_d, jnp.uint32(0))
            if dup is not None:
                okd = lax.dynamic_index_in_dim(dup, d, axis=1,
                                               keepdims=True)
                src_rows = received_full[
                    jnp.clip(idx, 0, received_full.shape[0] - 1)]
                t_ = t_ | jnp.where(okd, src_rows, jnp.uint32(0))
            return t_
    else:
        # per-delay-class coins + ring slices, shared across
        # directions (the _gather_or_delayed evaluation, re-read);
        # dup never contributes NEW bits under delays (it re-delivers
        # the identical in-flight block), so the terms skip it
        ring = history.shape[0]
        coins = {v: _live_split(state.t - (v - 1), row_ids, nbrs,
                                nbr_mask, parts, plan, False)[1]
                 for v in delay_set}
        slices = {v: widen(lax.dynamic_index_in_dim(
            history, (state.t - (v - 1)) % ring, axis=0,
            keepdims=False)) for v in delay_set}
        up_recv = (faults.node_up(plan, state.t, row_ids)[:, None]
                   if plan is not None else None)

        def term(d):
            idx = lax.dynamic_index_in_dim(nbrs, d, axis=1,
                                           keepdims=False)
            dly = lax.dynamic_index_in_dim(delays, d, axis=1,
                                           keepdims=False)
            t_ = None
            for v in delay_set:
                src_t = state.t - (v - 1)
                ok = (lax.dynamic_index_in_dim(coins[v], d, axis=1,
                                               keepdims=False)
                      & (dly == v) & (src_t >= 0))
                rows_d = slices[v][jnp.clip(idx, 0,
                                            slices[v].shape[0] - 1)]
                one = jnp.where(ok[:, None], rows_d, jnp.uint32(0))
                t_ = one if t_ is None else t_ | one
            if up_recv is not None:
                t_ = jnp.where(up_recv, t_, jnp.uint32(0))
            return t_
    return out, _prov_attribute(prov, new, nbrs, term, state.t + 1)


def flood_step(state: BroadcastState, *, nbrs: jnp.ndarray,
               nbr_mask: jnp.ndarray, parts: Partitions,
               sync_every: int,
               delays: jnp.ndarray | None = None,
               delay_set: tuple = (),
               plan: "faults.FaultPlan | None" = None,
               dup_on: bool = False,
               union_block: int | None = None,
               prov=None) -> "BroadcastState | tuple":
    """Single-device node-major round (the ``entry()`` compile-check
    target).  With ``prov`` returns ``(state, prov)`` (PR 9)."""
    row_ids = jnp.arange(nbrs.shape[0], dtype=jnp.int32)
    if delays is not None and not delay_set:
        # convenience for direct callers (entry(), tests): derive the
        # static value set from the concrete delays array
        delay_set = tuple(int(x) for x in np.unique(np.asarray(delays)))
    return _round(state, row_ids=row_ids, nbrs=nbrs, nbr_mask=nbr_mask,
                  parts=parts, sync_every=sync_every, delays=delays,
                  delay_set=delay_set, plan=plan, dup_on=dup_on,
                  union_block=union_block, prov=prov)


def _round_wm(state: BroadcastState, *, deg: jnp.ndarray, sync_every: int,
              exchange: Callable[..., jnp.ndarray],
              widen: Callable[[jnp.ndarray], jnp.ndarray] = lambda p: p,
              reduce_sum: Callable[[jnp.ndarray], jnp.ndarray] = lambda s: s,
              local_slice: Callable[[jnp.ndarray], jnp.ndarray] = lambda x: x,
              sync_diff: Callable[..., jnp.ndarray] | None = None,
              sync_base_once: Callable[[jnp.ndarray], jnp.ndarray]
              = lambda x: x,
              live_rows: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
              deg_slice: Callable[[jnp.ndarray], jnp.ndarray] = lambda x: x,
              delayed_exchange: Callable | None = None,
              ) -> BroadcastState:
    """Words-major round for structured topologies: state is (W, N) so
    the node axis packs TPU lanes densely (the node-major layout wastes
    127/128 of each tile at W=1 — see structured.py).  ``deg`` is the
    per-node TOPOLOGY degree.

    Partition faults: with ``live_rows`` (BroadcastSim._live_rows over
    a StructuredFaults bundle) the round computes the (D, N)
    per-direction liveness at round t; the exchange and sync_diff then
    take ``(payload, live)`` (the masked closures) and the ledgers use
    the live degree ``live.sum(axis=0)`` — matching the gather path's
    per-edge accounting bit for bit, still gather-free.  ``deg_slice``
    maps the full-axis live degree to the local block on the sharded
    all_gather fallback (identity elsewhere).

    With ``sync_diff`` (structured.make_sync_diff /
    make_sharded_sync_diff), the round also keeps the
    reference-accounted server ledger: same formulas as the gather
    path's accounting in :func:`_round`, with the anti-entropy
    pairwise diff from per-direction structured deliveries instead of
    per-edge gathers — bit-identical totals, no all_gather."""
    is_sync = (state.t % jnp.int32(sync_every) == 0) & (state.t > 0)
    payload = jnp.where(is_sync, state.received, state.frontier)
    payload_full = widen(payload)
    if live_rows is None:
        live = None
        live_deg = deg
    else:
        live = live_rows(state.t)
        live_deg = deg_slice(
            live.sum(axis=0, dtype=jnp.int32).astype(jnp.uint32))
    pc = _popcount(payload).sum(axis=0).astype(jnp.uint32)    # (n_local,)
    sent = reduce_sum(jnp.sum(pc * live_deg, dtype=jnp.uint32))
    if state.srv_msgs is None:
        srv = None
    else:
        d = deg.astype(jnp.int32)
        ld = live_deg.astype(jnp.int32)
        pcf = _popcount(state.frontier).sum(axis=0).astype(jnp.uint32)
        coef = jnp.where(state.t == 0, d + ld,
                         jnp.maximum(d + ld - 2, 0)).astype(jnp.uint32)
        flood = jnp.sum(pcf * coef, dtype=jnp.uint32)
        base = sync_base_once(
            jnp.sum(d + ld, dtype=jnp.int32).astype(jnp.uint32))
        diff = (sync_diff(state.received) if live is None
                else sync_diff(state.received, live))
        srv = state.srv_msgs + reduce_sum(
            flood + jnp.where(is_sync, base + 2 * diff, jnp.uint32(0)))
    if delayed_exchange is not None:
        # per-direction-class delays: push this round's payload into
        # the ring of past LOCAL payload blocks and deliver each
        # direction from its class's slice (structured.make_delayed)
        ring = state.history.shape[0]
        history = lax.dynamic_update_index_in_dim(
            state.history, payload, state.t % ring, axis=0)
        inbox = delayed_exchange(history, state.t)
        new = inbox & ~state.received
        return BroadcastState(received=state.received | new,
                              frontier=new, t=state.t + 1,
                              msgs=state.msgs + sent, history=history,
                              srv_msgs=srv)
    inbox = local_slice(exchange(payload_full) if live is None
                        else exchange(payload_full, live))
    new = inbox & ~state.received
    return BroadcastState(received=state.received | new, frontier=new,
                          t=state.t + 1, msgs=state.msgs + sent,
                          srv_msgs=srv)


def _round_wm_nem(state: BroadcastState, arrs, plan, pstarts, pends, *,
                  nem, sync_every: int, dup_on: bool,
                  exchange: Callable, src_pc: Callable,
                  widen: Callable[[jnp.ndarray], jnp.ndarray] = lambda p: p,
                  reduce_sum: Callable[[jnp.ndarray], jnp.ndarray]
                  = lambda s: s,
                  local_slice: Callable[[jnp.ndarray], jnp.ndarray]
                  = lambda x: x,
                  cols_slice: Callable[[jnp.ndarray], jnp.ndarray]
                  = lambda x: x,
                  sync_diff: Callable | None = None,
                  sync_base_once: Callable[[jnp.ndarray], jnp.ndarray]
                  = lambda x: x,
                  ) -> BroadcastState:
    """Words-major round under the FULL nemesis — a compiled FaultPlan
    (crash/restart amnesia, per-direction loss, duplicate delivery)
    composed with partition windows and, optionally, per-direction-
    class delays — gather-free, bit-exact with the gather path's
    :func:`_round` (same received sets and message counts).

    Mirrors the gather round's order of operations: amnesia columns
    are wiped at crash entry (volatile state dies with the process;
    the structured twin is a pure elementwise column select), the
    ``msgs`` ledger charges this round's payload against the live
    SEND degree (partitions + both endpoints up; loss excluded — a
    dropped message was still sent), delivery masks each direction's
    structured term by liveness AND the loss coin at that direction's
    SEND round, and dup edges re-deliver the source's full received
    set (1-hop; absorbed by dedup, ledger-visible) or re-deliver the
    in-flight ring block (under delays: zero state change, charged at
    send time against the current payload — see :func:`_round`).

    ``arrs`` (faults.WMNemesisArrays), the plan, and the partition
    window rounds ride as traced operands; ``exchange(take, lv)`` /
    ``src_pc(d, pc)`` are the bundle's static delivery and
    count-relocation closures (full-axis or halo — the caller picks);
    ``cols_slice`` maps full-axis per-column rows to the local block
    on the all_gather fallback (identity elsewhere).

    The srv ledger runs here for LOSS-ONLY plans (PR 5, matching the
    gather path's loss-only accounting): ``sync_diff`` is the bundle's
    masked per-edge diff closure, fed the both-coin rows of
    faults.wm_srv_rows; requests charge at send time, replies per
    delivered request's edge coin (the ack rows), sync diffs over
    pairs where both direction coins survive.  Crash/dup plans and
    ``dir_delays`` arrive with ``state.srv_msgs is None`` (the
    constructor forces the ledger off loudly there)."""
    t = state.t
    up_now = faults.wm_up_cols(plan, t, arrs.down_cols)
    wipe = cols_slice(~up_now & faults.wm_up_cols(plan, t - 1,
                                                  arrs.down_cols))
    z = jnp.uint32(0)
    rec0 = jnp.where(wipe[None, :], z, state.received)
    fr0 = jnp.where(wipe[None, :], z, state.frontier)
    is_sync = (t % jnp.int32(sync_every) == 0) & (t > 0)
    payload = jnp.where(is_sync, rec0, fr0)
    live_deg = cols_slice(
        faults.wm_live_rows(plan, t, arrs, pstarts, pends, deg=True)
        .sum(axis=0, dtype=jnp.int32).astype(jnp.uint32))
    pc = _popcount(payload).sum(axis=0).astype(jnp.uint32)
    sent = jnp.sum(pc * live_deg, dtype=jnp.uint32)
    if state.srv_msgs is None or sync_diff is None:
        srv = None
    else:
        # LOSS-ONLY reference accounting (see docstring) — the same
        # formulas as the gather path's srv block in _round, over the
        # bundle's deg-contract coin rows.  On the halo path every
        # array here is already node-sharded (cols local); the
        # all_gather fallback keeps the ledger off (constructor).
        deg_topo = arrs.deg_exists.sum(axis=0).astype(jnp.int32)
        _lv, ack_r, both_r = faults.wm_srv_rows(plan, t, arrs,
                                                pstarts, pends)
        ack_deg = ack_r.sum(axis=0, dtype=jnp.int32)
        pcf = _popcount(fr0).sum(axis=0).astype(jnp.uint32)
        coef = jnp.where(t == 0, deg_topo + ack_deg,
                         jnp.maximum(deg_topo + ack_deg - 2, 0)
                         ).astype(jnp.uint32)
        flood = jnp.sum(pcf * coef, dtype=jnp.uint32)
        base = sync_base_once(jnp.sum(deg_topo + ack_deg,
                                      dtype=jnp.int32)
                              .astype(jnp.uint32))
        diff = sync_diff(rec0, both_r)
        srv = state.srv_msgs + reduce_sum(
            flood + jnp.where(is_sync, base + 2 * diff, jnp.uint32(0)))
    n_dirs = int(arrs.exists.shape[0])

    def dup_charge(dup_rows, counts):
        # popcount-at-source per dup edge: `counts` is the (1, rows)
        # per-node count vector; each direction relocates it to its
        # contract positions (pure repeat/shift/roll — no gather)
        out = jnp.uint32(0)
        for d in range(n_dirs):
            at_rows = src_pc(d, counts)[0]
            out = out + jnp.sum(
                cols_slice(jnp.where(dup_rows[d], at_rows, 0)),
                dtype=jnp.uint32)
        return out

    if nem.dir_delays is None:
        live_del, dup = faults.wm_live_del(plan, t, arrs, pstarts,
                                           pends, dup_on)
        payload_full = widen(payload)
        inbox = local_slice(exchange(lambda d: payload_full, live_del))
        history = state.history
        if dup is not None:
            rec_full = widen(rec0)
            inbox = inbox | local_slice(
                exchange(lambda d: rec_full, dup))
            counts = _popcount(rec_full).sum(axis=0) \
                .astype(jnp.uint32)[None, :]
            sent = sent + dup_charge(dup, counts)
    else:
        dd = nem.dir_delays
        ring = state.history.shape[0]
        history = lax.dynamic_update_index_in_dim(
            state.history, payload, t % ring, axis=0)
        vs = sorted(set(dd))
        # one liveness+coin evaluation and one ring slice per DISTINCT
        # delay value, shared by all directions with that value
        coins = {v: faults.wm_live_del(plan, t - (v - 1), arrs,
                                       pstarts, pends, False)[0]
                 for v in vs}
        slices = {v: widen(_take_delayed(history, t, v, ring))
                  for v in vs}
        lv_rows = [coins[dd[d]][d] for d in range(n_dirs)]
        inbox = local_slice(exchange(lambda d: slices[dd[d]], lv_rows))
        # a message in flight to a node that crashed before delivery
        # dies with the process (receiver-side mask at delivery time)
        inbox = jnp.where(cols_slice(up_now)[None, :], inbox, z)
        if dup_on:
            _ld, dup_now = faults.wm_live_del(plan, t, arrs, pstarts,
                                              pends, True)
            counts = _popcount(widen(payload)).sum(axis=0) \
                .astype(jnp.uint32)[None, :]
            sent = sent + dup_charge(dup_now, counts)
    new = inbox & ~rec0
    return BroadcastState(received=rec0 | new, frontier=new,
                          t=t + 1,
                          msgs=state.msgs + reduce_sum(sent),
                          history=history, srv_msgs=srv)


class BroadcastSim:
    """Round-synchronous broadcast simulator over an (optional) device
    mesh.

    Two state layouts:

    - **node-major (N, W)** with the generic adjacency gather — supports
      arbitrary topologies and per-edge partition schedules.
    - **words-major (W, N)** with a structured ``exchange`` from
    structured.py — gather-free contiguous delivery for named
    topologies, ~60-190x faster per round at 1M nodes / W=1
    (lane-dense layout, no tile-granularity random reads).  Partition
    schedules run here too via a ``StructuredFaults`` bundle
    (structured.make_faulted): host-precomputed per-direction liveness
    masks, applied per round by the masked exchanges.

    Single-device: plain ``jax.jit``.  Multi-device: ``shard_map`` over
    ``Mesh(axis 'nodes' [, 'words'])`` — the node axis block-sharded
    over 'nodes', bitset words over 'words'.  Words-major rounds
    deliver via the **halo path** when a ``sharded_exchange`` is given
    (O(boundary) ppermutes over ICI, every named topology); otherwise,
    and always for the node-major gather path, each round all_gathers
    the payload along 'nodes' first.
    """

    def __init__(self, nbrs: np.ndarray, *, n_values: int,
                 sync_every: int = 8,
                 parts: Partitions | None = None,
                 mesh: Mesh | None = None,
                 exchange: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
                 sharded_exchange: Callable[[jnp.ndarray], jnp.ndarray]
                 | None = None,
                 sync_diff: Callable[[jnp.ndarray], jnp.ndarray]
                 | None = None,
                 sharded_sync_diff: Callable[[jnp.ndarray], jnp.ndarray]
                 | None = None,
                 delays: np.ndarray | None = None,
                 srv_ledger: bool = True,
                 faulted=None,
                 delayed=None,
                 edge_delayed=None,
                 fault_plan: "faults.FaultPlan | None" = None,
                 nemesis=None,
                 union_block: "int | str | None" = None,
                 dcn_mode: "str | None" = None,
                 ) -> None:
        """``srv_ledger``: keep the reference-accounted server-message
        ledger (default).  It costs a second adjacency pass per round
        (the sync pairwise diff), which roughly doubles gather-path
        round time — throughput benchmarks at scale pass False.

        On the words-major structured path the ledger needs the
        matching diff closure: ``sync_diff``
        (structured.make_sync_diff) single-device, plus
        ``sharded_sync_diff`` (structured.make_sharded_sync_diff) for
        the halo path on a mesh.

        ``faulted`` (structured.StructuredFaults, from
        structured.make_faulted): required to run a partition schedule
        on the words-major path — per-direction receiver-side liveness
        masks precomputed per window on the host, applied by the
        masked exchange/diff closures each round (Maelstrom's nemesis
        at any scale without falling back to the gather path).

        ``delayed``: per-direction-class delays on the words-major
        path — each direction delivers from a ring of past payload
        blocks at structured speed (Maelstrom's uniform per-hop
        latency at any scale; per-edge-random delays stay on the
        gather path via ``delays``).  Pass a
        structured.StructuredDelays (make_delayed) for the fault-free
        case, or a structured.FaultedDelayed (make_delayed_faulted) to
        COMPOSE delays with a partition schedule — the bundle carries
        its own masks, so do not also pass ``faulted``.  The srv
        ledger follows the gather path's documented current-state
        approximation under delays: supply ``sync_diff``/
        ``sharded_sync_diff`` for the plain delayed mode (the
        FaultedDelayed bundle carries its own masked diffs).

        ``edge_delayed`` (structured.EdgeDelays, make_edge_delayed):
        RANDOM per-edge delays over a small static value set on the
        words-major path — Maelstrom's default latency model
        (random per hop) at structured speed.  The delay rows ride as
        one traced (D, N) array (node-sharded on the halo path).
        Mutually exclusive with ``delays``/``delayed``/``faulted``;
        composing with a PARTITION schedule needs the masked bundle
        (structured.make_edge_delayed_faulted — a FaultedEdgeDelays,
        which carries its own window masks and masked diffs: the
        Maelstrom default nemesis, latency AND partitions, at
        structured speed); the plain bundle's srv ledger gates
        exactly like the plain delayed mode (caller-supplied
        sync_diff closures, current-state approximation).

        ``fault_plan`` (tpu_sim/faults.py, compiled NemesisSpec): the
        nemesis beyond partitions — crash/restart with amnesia rows,
        per-direction probabilistic loss, duplicate delivery.
        Composes with ``parts`` partition schedules and per-edge
        ``delays`` on the gather path; under ``delays`` a dup edge
        re-delivers its IN-FLIGHT message (the send-round payload
        block, absorbed by dedup, charged to the msgs ledger at send
        time) rather than the source's full received set.  On the
        words-major structured path a plan needs the mask bundle:
        pass ``nemesis=`` (below).  The server ledger: LOSS and
        CRASH plans keep it on the gather path — requests charged at
        send time, replies only when the triggering request's
        per-round edge coin delivered over a live edge, sync diffs
        over both-coin pairs; crash cells charge-at-send (a request
        to a down node is charged and dies with the process, a down
        row sends nothing, the post-recovery retry re-charges — the
        PR-14 KV decision, calibrated against the virtual harness in
        test_ledger_calibration.py).  A dup stream REJECTS loudly at
        construction when the ledger is requested (re-delivered sets
        vs reference msg-id dedup cannot be calibrated — the
        kvstore.reject_dup_stream stance); every delays composition
        and words-major crash still force ``srv_ledger`` off; the
        ``msgs`` ledger counts loss at send time and dup
        re-deliveries as real traffic either way.

        ``nemesis`` (structured.StructuredNemesis, make_nemesis): the
        words-major decomposition of the SAME plan — host-precomputed
        per-direction sender/receiver masks with elementwise loss/dup
        coins, so the full Maelstrom fault model (crash/loss/dup
        composed with partition windows and per-direction-class
        delays, via the bundle's ``dir_delays``) runs gather-free and
        bit-exact with the gather path.  Requires ``fault_plan`` (the
        traced operand the masks were compiled from) and a structured
        ``exchange``; mutually exclusive with ``delays``/``delayed``/
        ``edge_delayed``/``faulted`` (the bundle subsumes them).
        LOSS-ONLY plans keep the srv ledger HERE TOO (PR 5): the
        bundle's deg-contract coin rows (faults.wm_srv_rows) and
        masked per-edge diff closures reproduce the gather path's
        loss-only accounting gather-free — requests charged at send,
        replies per delivered request's edge coin, sync diffs over
        both-coin pairs — calibrated against the gather ledger (and
        transitively the virtual harness) in
        test_ledger_calibration.py; crash windows, dup streams, and
        ``dir_delays`` still force it off loudly.

        ``union_block`` (ISSUE 5): stream the GATHER path's faulted
        rounds over destination-row slabs (engine.scan_blocks) — the
        full-mesh/star faulted shapes' O(N²) per-edge coin masks are
        evaluated one O(B·D) slab at a time, bit-identical (the coins
        are stateless (t, src, dst) hashes).  None defers to
        ``GG_UNION_BLOCK`` (auto: materialized until the whole mask
        exceeds the slab budget); ``"materialized"`` pins the
        unblocked oracle.  Gather path only, 1-hop faulted rounds,
        srv ledger off (loud otherwise)."""
        n = nbrs.shape[0]
        self.n_nodes = n
        self.n_values = n_values
        self.n_words = num_words(n_values)
        self.sync_every = sync_every
        self.mesh = mesh
        # -- DCN mode (PR 20): sync (default) or pipelined; broadcast's
        # delivery plane is halo/widen exchange + srv-ledger
        # calibration, so bounded staleness is undecided here — refuse.
        self._dcn = resolve_dcn_mode(dcn_mode)
        if self._dcn.stale_k:
            raise ValueError(
                f"dcn_mode={self._dcn.label()!r}: broadcast has no "
                "certified staleness semantics — its delivery plane is "
                "the halo/widen exchange and the srv ledger calibrates "
                "against synchronous round accounting; run sync or "
                "pipelined")
        # mode-aware all-axes psum for the inline ledger/convergence
        # reduce sites (the rounds that take a bare psum closure
        # instead of a Collectives)
        self._dcn_psum = dcn_psum(mesh, self._dcn)
        self.parts = parts if parts is not None else Partitions.none(n)
        self.exchange = exchange
        # halo path: local-block -> local-block delivery via ppermute
        # (structured.make_sharded_exchange); requires `exchange` too for
        # the single-device fallback and n divisible by the node axis.
        self.sharded_exchange = sharded_exchange
        if sharded_exchange is not None and exchange is None:
            raise ValueError("sharded_exchange requires exchange")
        self.words_major = exchange is not None
        self.sync_diff = sync_diff
        self.sharded_sync_diff = sharded_sync_diff
        n_windows = int(self.parts.starts.shape[0])
        self._delayed = delayed
        self._edge = edge_delayed
        # composed mode: a FaultedEdgeDelays bundle carries its own
        # window masks (random per-edge delays AND partitions)
        self._ef = (edge_delayed is not None
                    and hasattr(edge_delayed, "del_same"))
        if edge_delayed is not None:
            if not self.words_major:
                raise ValueError("edge_delayed needs a structured "
                                 "exchange")
            if delays is not None or delayed is not None \
                    or faulted is not None:
                raise ValueError(
                    "edge_delayed is mutually exclusive with delays/"
                    "delayed/faulted")
            if self._ef:
                if n_windows == 0:
                    raise ValueError(
                        "FaultedEdgeDelays needs a partition schedule; "
                        "use make_edge_delayed for the window-free "
                        "case")
                if edge_delayed.del_same.shape[0] != n_windows \
                        or edge_delayed.del_same.shape[-1] != n:
                    raise ValueError(
                        "FaultedEdgeDelays masks do not match the "
                        "partition schedule")
            elif n_windows > 0:
                raise ValueError(
                    "composing random per-edge delays with partitions "
                    "on the structured path needs a FaultedEdgeDelays "
                    "bundle (structured.make_edge_delayed_faulted)")
            if mesh is not None and edge_delayed.sharded_exchange \
                    is None:
                raise ValueError(
                    "edge-delayed structured delivery on a mesh needs "
                    "the halo closure (no all_gather fallback)")
        # composed mode: a FaultedDelayed bundle carries its own masks
        # (delays AND partition windows on the structured path)
        self._df = delayed is not None and hasattr(delayed, "same")
        if delayed is not None:
            if not self.words_major:
                raise ValueError("delayed needs a structured exchange")
            if delays is not None:
                raise ValueError(
                    "per-edge `delays` and per-direction `delayed` are "
                    "mutually exclusive")
            if self._df:
                if faulted is not None:
                    raise ValueError(
                        "pass EITHER faulted= or a FaultedDelayed "
                        "bundle — the bundle carries its own masks")
                if n_windows == 0:
                    raise ValueError(
                        "FaultedDelayed needs a partition schedule; "
                        "use make_delayed for the fault-free case")
                if delayed.same.shape[0] != n_windows \
                        or delayed.same.shape[-1] != n:
                    raise ValueError(
                        "FaultedDelayed masks do not match the "
                        "partition schedule")
            elif n_windows > 0 or faulted is not None:
                raise ValueError(
                    "composing delays with partitions on the "
                    "structured path needs a FaultedDelayed bundle "
                    "(structured.make_delayed_faulted)")
            if mesh is not None and delayed.sharded_exchange is None:
                raise ValueError(
                    "delayed structured delivery on a mesh needs the "
                    "halo closure (no all_gather fallback)")
        self._faulted = faulted if (self.words_major
                                    and n_windows > 0
                                    and not self._df) else None
        if (self.words_major and n_windows > 0 and faulted is None
                and not self._df and not self._ef and nemesis is None):
            raise ValueError(
                "a words-major structured run under a partition "
                "schedule needs the masked closures: pass "
                "faulted=structured.make_faulted(topology, n, groups)")
        if self._faulted is not None:
            if self._faulted.same.shape[0] != n_windows \
                    or self._faulted.same.shape[-1] != n:
                raise ValueError(
                    "StructuredFaults masks do not match the partition "
                    f"schedule: same{tuple(self._faulted.same.shape)} "
                    f"vs {n_windows} windows x {n} nodes")
        # the words-major ledger needs a structured per-edge diff: the
        # single-device closure off-mesh, the halo closure on-mesh
        if self._df:
            fd = self._delayed
            self._srv_on = srv_ledger and (
                fd.sync_diff is not None if mesh is None
                else fd.sharded_exchange is not None
                and fd.sharded_sync_diff is not None)
        elif self._delayed is not None:
            # plain delayed: same gating as plain words-major — the
            # caller-supplied sync_diff closures drive the gather
            # path's documented current-state accounting approximation
            self._srv_on = srv_ledger and (
                sync_diff is not None if mesh is None
                else (self._delayed.sharded_exchange is not None
                      and sharded_sync_diff is not None))
        elif self._ef:
            # faulted edge-delayed: the bundle carries its own masked
            # diffs (same gating as the FaultedDelayed mode)
            e = self._edge
            self._srv_on = srv_ledger and (
                e.sync_diff is not None if mesh is None
                else (e.sharded_exchange is not None
                      and e.sharded_sync_diff is not None))
        elif self._edge is not None:
            # edge-delayed: gates exactly like plain delayed
            self._srv_on = srv_ledger and (
                sync_diff is not None if mesh is None
                else (self._edge.sharded_exchange is not None
                      and sharded_sync_diff is not None))
        elif self._faulted is not None:
            f = self._faulted
            self._srv_on = srv_ledger and (
                f.sync_diff is not None if mesh is None
                else f.sharded_exchange is not None
                and f.sharded_sync_diff is not None)
        elif nemesis is not None:
            # words-major nemesis: the bundle carries its own masked
            # diff closures (loss-only gating follows below — crash/
            # dup/dir_delays force the ledger back off)
            self._srv_on = srv_ledger and (
                nemesis.sync_diff is not None if mesh is None
                else (nemesis.sharded_exchange is not None
                      and nemesis.sharded_sync_diff is not None))
        elif self.words_major:
            self._srv_on = srv_ledger and (
                sync_diff is not None if mesh is None
                else (sharded_exchange is not None
                      and sharded_sync_diff is not None))
        else:
            self._srv_on = srv_ledger
        # -- nemesis FaultPlan (crash/loss/dup, tpu_sim/faults.py) ------
        self.fault_plan = fault_plan
        self._nem = nemesis
        self._fp_dup = (fault_plan is not None
                        and int(fault_plan.dup_num) > 0)
        if nemesis is not None:
            if not self.words_major:
                raise ValueError(
                    "nemesis= is the words-major structured FaultPlan "
                    "path — it needs a structured exchange (the gather "
                    "path takes the plan alone)")
            if fault_plan is None:
                raise ValueError(
                    "nemesis= carries the structured masks FOR a "
                    "FaultPlan — pass fault_plan=spec.compile() too")
            if delays is not None or delayed is not None \
                    or edge_delayed is not None or faulted is not None:
                raise ValueError(
                    "nemesis= subsumes delays/delayed/edge_delayed/"
                    "faulted: compose partition windows via parts= and "
                    "per-direction delays via make_nemesis(dir_delays=)")
            if nemesis.arrs.same.shape[0] != n_windows \
                    or nemesis.arrs.same.shape[-1] != n:
                raise ValueError(
                    "StructuredNemesis masks do not match the "
                    "partition schedule: "
                    f"same{tuple(nemesis.arrs.same.shape)} vs "
                    f"{n_windows} windows x {n} nodes")
            if (nemesis.arrs.down_pair.shape[0]
                    != int(fault_plan.starts.shape[0])):
                raise ValueError(
                    "StructuredNemesis crash masks do not match the "
                    "FaultPlan's crash windows — rebuild the bundle "
                    "from the same NemesisSpec")
        if fault_plan is not None:
            if self.words_major and nemesis is None:
                raise ValueError(
                    "a FaultPlan on the words-major structured path "
                    "needs the mask bundle: pass "
                    "nemesis=structured.make_nemesis(topology, n, "
                    "spec, ...) — or drop exchange=/sharded_exchange= "
                    "for the gather path")
            if fault_plan.down.shape[1] != n:
                raise ValueError(
                    f"FaultPlan is for {fault_plan.down.shape[1]} "
                    f"nodes, sim has {n}")
            # LOSS and CRASH plans keep a DEFINED reference
            # accounting: the per-(t, src, dst) coin makes a round's
            # directed edge all-or-nothing, so requests are charged at
            # send time (loss-invisible, like the harness ledger),
            # replies only when the triggering request's edge-coin
            # delivered, and sync diffs only where BOTH direction
            # coins survive (the read AND its read_ok).  Crash windows
            # extend the same stance charge-at-send (the PR-14 KV
            # decision, ROADMAP item 6): a request to a down node is
            # charged when sent and dies with the process (no reply —
            # live edges require both endpoints up), a down row sends
            # nothing (its reads don't fire, its frontier was wiped at
            # the amnesia entry), and the post-recovery anti-entropy
            # retry re-charges.  Gather path: the srv block in _round;
            # words-major nemesis runs (PR 5) keep the loss-only
            # subset (the bundle's deg-contract coin rows have no
            # crash liveness decomposition) — both calibrated in
            # test_ledger_calibration.py.  A dup stream re-delivers
            # whole received sets while the reference dedups by
            # message id, so the ledgers CANNOT be calibrated — same
            # stance as kvstore.reject_dup_stream: rejected loudly
            # below when the ledger was requested.  Every delays
            # composition (gather `delays` and the bundle's
            # dir_delays) still forces the ledger off (documented
            # current-state approximation only holds per wave).
            if int(fault_plan.dup_num) > 0 and self._srv_on:
                raise ValueError(
                    "srv ledger under a dup stream: a dup edge "
                    "re-delivers its source's whole received set "
                    "while the reference dedups by message id, so "
                    "the server ledgers cannot be calibrated (the "
                    "kvstore backend's reject_dup_stream stance) — "
                    "pass srv_ledger=False and read the `msgs` value "
                    "ledger instead")
            has_crash = int(fault_plan.starts.shape[0]) > 0
            if self.words_major:
                wm_srv_ok = (
                    not has_crash
                    and nemesis is not None
                    and nemesis.dir_delays is None
                    and (nemesis.sync_diff is not None if mesh is None
                         else (nemesis.sharded_exchange is not None
                               and nemesis.sharded_sync_diff
                               is not None)))
            else:
                wm_srv_ok = delays is None
            if not wm_srv_ok:
                self._srv_on = False
        if delays is not None:
            if exchange is not None:
                raise ValueError("per-edge delays need the gather path")
            if delays.shape != nbrs.shape:
                raise ValueError("delays must match nbrs shape")
            if delays.min() < 1:
                raise ValueError("edge delays are rounds >= 1")
        self.delays = (None if delays is None
                       else jnp.asarray(delays, jnp.int32))
        # -- streaming faulted gather rounds (ISSUE 5) ------------------
        if union_block is not None and (self.words_major
                                        or delays is not None):
            raise ValueError(
                "union_block streams the GATHER path's 1-hop faulted "
                "rounds; the words-major path is already gather-free "
                "and the delays ring keeps the materialized shape")
        na = self._na = node_axes(mesh)
        if self.words_major or delays is not None or fault_plan is None:
            self._ub = None
        else:
            n_sh_nodes = node_shards(mesh)
            # per destination row: D edges x (liveness + loss/dup
            # coins + gather temps) ~ 16 bytes per edge slot
            self._ub = resolve_block(n // n_sh_nodes, union_block,
                                     per_row_bytes=nbrs.shape[1] * 16)
            if self._ub is not None and self._srv_on:
                if union_block is not None:
                    raise ValueError(
                        "blocked faulted gather rounds keep no srv "
                        "ledger: pass srv_ledger=False (or "
                        "union_block='materialized' to keep the "
                        "loss-only ledger on the materialized path)")
                # env-auto pick: the loss-only srv ledger needs the
                # materialized masks — keep them rather than erroring
                # on a sim the caller never asked to block
                self._ub = None
        self._nem_delayed = (nemesis is not None
                             and nemesis.dir_delays is not None)
        if delayed is not None:
            self.ring = delayed.ring
        elif edge_delayed is not None:
            self.ring = edge_delayed.ring
        elif self._nem_delayed:
            self.ring = nemesis.ring
        else:
            self.ring = 1 if delays is None else int(delays.max())
        # distinct delay values, static: delivery runs one masked
        # gather per value, which is what lets the history ring stay
        # node-sharded (one all_gather per value per round instead of a
        # replicated (L, N, W) ring — see _gather_or_delayed)
        self._delay_set = (() if delays is None else tuple(
            int(x) for x in np.unique(np.asarray(delays))))
        # fused/fixed runner caches, keyed by (trip parameter, donate):
        # each value is the engine-built program (fused) or a
        # (runner, flood parts | None) pair (fixed) — see _build_fixed
        self._fused = {}
        self._fixed = {}
        # telemetry-on observed drivers (PR 8)
        self._obs_progs = {}
        # open-loop traffic drivers, keyed by (TrafficSpec, donate)
        self._traffic_progs = {}

        nbr_mask = nbrs >= 0
        deg = nbr_mask.sum(axis=1).astype(np.uint32)
        # host copy of the degrees: _build_fixed derives its static
        # per-degree masks from this — reading self.deg back from the
        # device would be a D2H transfer, which on the tunneled TPU
        # degrades every subsequent dispatch in the session ~5000x
        # until it idles out (measured; see timing.py module docstring)
        self._host_deg = deg
        has_words = mesh is not None and "words" in mesh.axis_names
        if self.words_major:
            self._state_spec = (P("words", na) if has_words
                                else P(None, na)) \
                if mesh is not None else None
        else:
            self._state_spec = (P(na, "words") if has_words
                                else P(na, None)) \
                if mesh is not None else None
        if self.words_major:
            # the structured path never reads the adjacency on device —
            # keep it host-side (at 1M nodes it is ~6x the bitset state)
            self.nbrs = None
            self.nbr_mask = None
            self.deg = (shard_put(jnp.asarray(deg),
                                       NamedSharding(mesh, P(na)))
                        if mesh is not None else jnp.asarray(deg))
            if self._edge is not None:
                # delay rows ride as one traced (D, N) array, sharded
                # with the node axis on the halo path (receiver-side
                # rows, local masking, zero extra ICI)
                rows = jnp.asarray(self._edge.delay_rows, jnp.int32)
                if mesh is not None:
                    self._ed_spec = P(None, na)
                    rows = shard_put(
                        rows, NamedSharding(mesh, self._ed_spec))
                self._ed_rows = rows
                if self._ef:
                    # the composed bundle's window masks (ledger rows +
                    # delivery rows) shard with the node axis too —
                    # the edge mode is halo-only on a mesh
                    e2 = jnp.asarray(self._edge.exists)
                    s2 = jnp.asarray(self._edge.same)
                    d2 = jnp.asarray(self._edge.del_same)
                    if mesh is not None:
                        e_spec = P(None, na)
                        s_spec = P(None, None, na)
                        e2 = shard_put(
                            e2, NamedSharding(mesh, e_spec))
                        s2 = shard_put(
                            s2, NamedSharding(mesh, s_spec))
                        d2 = shard_put(
                            d2, NamedSharding(mesh, s_spec))
                        self._ef_specs = (e_spec, s_spec, s_spec)
                    self._ef_arrs = (e2, s2, d2)
            if self._nem is not None:
                arrs = faults.WMNemesisArrays(
                    *(jnp.asarray(a) for a in self._nem.arrs))
                if mesh is not None:
                    # halo: positionally sharded with the node axis;
                    # all_gather fallback: replicated full-axis masks
                    self._nem_specs = faults.wm_specs(
                        self._nem.sharded_exchange is not None, na)
                    arrs = faults.WMNemesisArrays(
                        *(shard_put(a, NamedSharding(mesh, s))
                          for a, s in zip(arrs, self._nem_specs)))
                self._nem_arrs = arrs
            masked_src = (self._faulted if self._faulted is not None
                          else self._delayed if self._df else None)
            if masked_src is not None:
                ex = jnp.asarray(masked_src.exists)
                sm = jnp.asarray(masked_src.same)
                if mesh is not None:
                    # halo mode: receiver-side rows shard with the node
                    # axis; all_gather fallback: replicated (the full-
                    # axis masked exchange needs full-axis masks)
                    if masked_src.sharded_exchange is not None:
                        e_spec = P(None, na)
                        s_spec = P(None, None, na)
                    else:
                        e_spec = P(None, None)
                        s_spec = P(None, None, None)
                    ex = shard_put(ex, NamedSharding(mesh, e_spec))
                    sm = shard_put(sm, NamedSharding(mesh, s_spec))
                    self._f_specs = (e_spec, s_spec)
                self._f_exists, self._f_same = ex, sm
        elif mesh is not None:
            node_sh = NamedSharding(mesh, P(na, None))
            self.nbrs = shard_put(jnp.asarray(nbrs, jnp.int32), node_sh)
            self.nbr_mask = shard_put(jnp.asarray(nbr_mask), node_sh)
            self.deg = shard_put(jnp.asarray(deg),
                                      NamedSharding(mesh, P(na)))
            if self.delays is not None:
                self.delays = shard_put(self.delays, node_sh)
        else:
            self.nbrs = jnp.asarray(nbrs, jnp.int32)
            self.nbr_mask = jnp.asarray(nbr_mask)
            self.deg = jnp.asarray(deg)
        self._step = self._build_step()

    # -- construction ------------------------------------------------------

    def init_state(self, inject: np.ndarray) -> BroadcastState:
        arr = np.asarray(inject, np.uint32)
        if self.words_major:
            arr = np.ascontiguousarray(arr.T)
        received = jnp.asarray(arr)
        if self.mesh is not None:
            received = shard_put(
                received, NamedSharding(self.mesh, self._state_spec))
        # frontier starts equal to received but must be a DISTINCT
        # buffer: the donation-first drivers (engine.py) donate the
        # whole state pytree, and XLA rejects donating one buffer
        # twice.  Device-side copy (not a second host upload), after
        # placement so the copy lands with the right sharding.
        frontier = jnp.copy(received)
        history = None
        if self._delayed is not None or self._edge is not None \
                or self._nem_delayed:
            # words-major ring of past LOCAL payload blocks (L, W, N),
            # node-sharded like the state
            history = jnp.zeros(
                (self.ring, self.n_words, self.n_nodes), jnp.uint32)
            if self.mesh is not None:
                history = shard_put(
                    history,
                    NamedSharding(self.mesh,
                                  P(None, *self._state_spec)))
        elif self.delays is not None:
            # ring of past LOCAL payload blocks, node-SHARDED: each
            # shard stores only its own rows' history (O(L·N/shards)
            # per device); delivery widens the per-delay-value slices
            # transiently (_gather_or_delayed), so million-node delayed
            # runs fit memory
            history = jnp.zeros(
                (self.ring, self.n_nodes, self.n_words), jnp.uint32)
            if self.mesh is not None:
                history = shard_put(
                    history,
                    NamedSharding(self.mesh,
                                  P(None, *self._state_spec)))
        return BroadcastState(received=received, frontier=frontier,
                              t=jnp.int32(0), msgs=jnp.uint32(0),
                              history=history,
                              srv_msgs=(jnp.uint32(0) if self._srv_on
                                        else None))

    def target_bits(self, inject: np.ndarray) -> jnp.ndarray:
        """(W,) uint32 — union of all injected values: the convergence
        target every node must reach."""
        return jnp.asarray(np.bitwise_or.reduce(
            np.asarray(inject, np.uint32), axis=0))

    # -- round/step builders ----------------------------------------------

    def _sharded_round(self, state: BroadcastState, nbrs, nbr_mask,
                       parts: Partitions,
                       delays=None, plan=None,
                       prov=None) -> "BroadcastState | tuple":
        """The node-major round inside shard_map: global row ids from the
        shard index, payload all_gather-ed along 'nodes' (the gossip
        collective riding ICI), ledger psum-ed.  ``plan``: the traced
        FaultPlan operand (replicated; masks evaluated on global ids
        per shard).  With ``prov`` returns ``(state, prov)`` — the
        stamps shard with the node axis, the attribution is local."""
        mesh_axes = tuple(self.mesh.axis_names)
        block = nbrs.shape[0]
        start = lax.axis_index(self._na) * block
        row_ids = start + jnp.arange(block, dtype=jnp.int32)
        if "words" in mesh_axes:
            # per-word-shard quantities (popcounts) psum linearly; the
            # per-node sync base (reads/read_oks) must count once
            sync_base_once = lambda b: jnp.where(  # noqa: E731
                lax.axis_index("words") == 0, b, jnp.uint32(0))
        else:
            sync_base_once = lambda b: b  # noqa: E731
        return _round(
            state, row_ids=row_ids, nbrs=nbrs, nbr_mask=nbr_mask,
            parts=parts, sync_every=self.sync_every,
            widen=lambda p: lax.all_gather(p, self._na, axis=0, tiled=True),
            reduce_sum=self._dcn_psum,
            delays=delays, delay_set=self._delay_set,
            sync_base_once=sync_base_once, plan=plan,
            dup_on=self._fp_dup,
            union_block=None if prov is not None else self._ub,
            prov=prov)

    @staticmethod
    def _live_rows(exists, same, starts, ends):
        """Device closure t -> (D, n) combined per-direction liveness:
        exists AND same-group under every active partition window (the
        per-direction-class form of :func:`_edge_live`)."""

        def live_rows(t):
            return windows_fold(
                starts, ends, t,
                lambda w, active, lv: lv & (same[w] | ~active), exists)

        return live_rows

    def _sharded_round_wm(self, state: BroadcastState, deg,
                          masks=None) -> BroadcastState:
        """The words-major round inside shard_map.

        Preferred: the **halo path** (``sharded_exchange`` from
        structured.make_sharded_exchange) — local block -> local block
        delivery via O(boundary) slice ppermutes, available for every
        named topology (ring/circulant rotations, tree parent/child
        multicast, grid/line boundary shifts).  Fallback for shapes
        without a halo decomposition: all_gather the payload along the
        node axis, run the full-axis exchange per shard, slice the
        local block back out (n_shards-fold redundant compute and
        O(N) ICI traffic per round).

        ``masks`` = (exists, same, starts, ends) under a partition
        schedule (faulted mode): the masked closures from the
        StructuredFaults bundle replace the plain ones and the
        per-round live rows drive the ledgers (sharded with the node
        axis on the halo path, so the masking is local and costs no
        ICI)."""
        mesh_axes = tuple(self.mesh.axis_names)
        if "words" in mesh_axes:
            # per-word-shard popcounts psum linearly; the per-node sync
            # base (reads/read_oks) must count once across word shards
            sync_base_once = lambda b: jnp.where(  # noqa: E731
                lax.axis_index("words") == 0, b, jnp.uint32(0))
        else:
            sync_base_once = lambda b: b  # noqa: E731
        f = self._faulted
        if self._nem is not None:
            arrs, pstarts, pends, plan = masks
            psum = self._dcn_psum
            if self._nem.sharded_exchange is not None:
                # halo path: masks arrive node-sharded, every mask
                # application is local, delivery is O(block) ppermutes
                return _round_wm_nem(
                    state, arrs, plan, pstarts, pends, nem=self._nem,
                    sync_every=self.sync_every, dup_on=self._fp_dup,
                    exchange=self._nem.sharded_exchange,
                    src_pc=self._nem.sharded_src_pc, reduce_sum=psum,
                    sync_diff=self._nem.sharded_sync_diff,
                    sync_base_once=sync_base_once)
            # all_gather fallback: replicated full-axis masks, full-
            # axis delivery per shard, local block sliced back out
            block = state.received.shape[1]
            start = lax.axis_index(self._na) * block
            return _round_wm_nem(
                state, arrs, plan, pstarts, pends, nem=self._nem,
                sync_every=self.sync_every, dup_on=self._fp_dup,
                exchange=self._nem.exchange, src_pc=self._nem.src_pc,
                reduce_sum=psum,
                widen=lambda p: lax.all_gather(p, self._na, axis=1,
                                               tiled=True),
                local_slice=lambda x: lax.dynamic_slice_in_dim(
                    x, start, block, axis=1),
                cols_slice=lambda x: lax.dynamic_slice_in_dim(
                    x, start, block))
        if self._ef:
            # halo-only (constructor enforces sharded_exchange); all
            # masks arrive node-sharded, masking is local
            rows, e2, s2, d2, ps, pe = masks
            eex = self._edge.sharded_exchange
            lbd = self._edge.live_by_delay
            return _round_wm(
                state, deg=deg, sync_every=self.sync_every,
                exchange=self.exchange,
                reduce_sum=self._dcn_psum,
                live_rows=self._live_rows(e2, s2, ps, pe),
                sync_diff=self._edge.sharded_sync_diff,
                sync_base_once=sync_base_once,
                delayed_exchange=lambda h, t: eex(
                    h, t, rows, lbd(d2, ps, pe, t)))
        if self._edge is not None:
            # halo-only (constructor enforces sharded_exchange); the
            # delay rows arrive node-sharded, masking is local
            (rows,) = masks
            eex = self._edge.sharded_exchange
            return _round_wm(
                state, deg=deg, sync_every=self.sync_every,
                exchange=self.exchange,
                reduce_sum=self._dcn_psum,
                sync_diff=self.sharded_sync_diff,
                sync_base_once=sync_base_once,
                delayed_exchange=lambda h, t: eex(h, t, rows))
        if self._delayed is not None:
            # halo-only (constructor enforces sharded_exchange)
            if masks is not None:      # composed faulted-delayed mode
                lr = self._live_rows(*masks)
                dex = self._delayed.sharded_exchange
                return _round_wm(
                    state, deg=deg, sync_every=self.sync_every,
                    exchange=self.exchange,
                    reduce_sum=self._dcn_psum,
                    live_rows=lr,
                    sync_diff=self._delayed.sharded_sync_diff,
                    sync_base_once=sync_base_once,
                    delayed_exchange=lambda h, t: dex(h, t, lr))
            return _round_wm(
                state, deg=deg, sync_every=self.sync_every,
                exchange=self.exchange,
                reduce_sum=self._dcn_psum,
                sync_diff=self.sharded_sync_diff,
                sync_base_once=sync_base_once,
                delayed_exchange=self._delayed.sharded_exchange)
        if masks is not None:
            live_rows = self._live_rows(*masks)
        else:
            live_rows = None
        if (f.sharded_exchange if masks is not None
                else self.sharded_exchange) is not None:
            # halo path: the exchange maps local block -> local block
            # with O(block) ppermutes; no all_gather, no slice.
            return _round_wm(
                state, deg=deg, sync_every=self.sync_every,
                exchange=(f.sharded_exchange if masks is not None
                          else self.sharded_exchange),
                reduce_sum=self._dcn_psum,
                sync_diff=(f.sharded_sync_diff if masks is not None
                           else self.sharded_sync_diff),
                sync_base_once=sync_base_once, live_rows=live_rows)
        block = state.received.shape[1]
        start = lax.axis_index(self._na) * block
        return _round_wm(
            state, deg=deg, sync_every=self.sync_every,
            exchange=(f.exchange if masks is not None
                      else self.exchange),
            widen=lambda p: lax.all_gather(p, self._na, axis=1, tiled=True),
            reduce_sum=self._dcn_psum,
            local_slice=lambda x: lax.dynamic_slice_in_dim(
                x, start, block, axis=1),
            live_rows=live_rows,
            deg_slice=lambda x: lax.dynamic_slice_in_dim(
                x, start, block))

    def _specs(self):
        state_spec = self._state_spec
        hist_spec = (P(None, *state_spec)       # node-sharded ring
                     if (self.delays is not None
                         or self._delayed is not None
                         or self._edge is not None
                         or self._nem_delayed) else None)
        srv_spec = P() if self._srv_on else None
        return (BroadcastState(state_spec, state_spec, P(), P(),
                               hist_spec, srv_spec),
                P(self._na, None),
                Partitions(P(), P(), P(None, None)))

    def _wm_round_single(self, state: BroadcastState, deg,
                         masks=None) -> BroadcastState:
        """Single-device words-major round — plain, faulted, or
        delayed.  ``deg`` and the fault ``masks`` arrive as traced jit
        arguments (like the shard_map path's explicit args) so the big
        per-node arrays are not baked into every traced program as
        constants."""
        f = self._faulted
        if self._nem is not None:
            arrs, pstarts, pends, plan = masks
            return _round_wm_nem(
                state, arrs, plan, pstarts, pends, nem=self._nem,
                sync_every=self.sync_every, dup_on=self._fp_dup,
                exchange=self._nem.exchange, src_pc=self._nem.src_pc,
                sync_diff=self._nem.sync_diff)
        if self._ef:
            rows, e2, s2, d2, ps, pe = masks
            eex = self._edge.exchange
            lbd = self._edge.live_by_delay
            return _round_wm(
                state, deg=deg, sync_every=self.sync_every,
                exchange=self.exchange,
                live_rows=self._live_rows(e2, s2, ps, pe),
                sync_diff=self._edge.sync_diff,
                delayed_exchange=lambda h, t: eex(
                    h, t, rows, lbd(d2, ps, pe, t)))
        if self._edge is not None:
            (rows,) = masks
            eex = self._edge.exchange
            return _round_wm(
                state, deg=deg, sync_every=self.sync_every,
                exchange=self.exchange, sync_diff=self.sync_diff,
                delayed_exchange=lambda h, t: eex(h, t, rows))
        if self._delayed is not None:
            if masks is not None:      # composed faulted-delayed mode
                lr = self._live_rows(*masks)
                dex = self._delayed.exchange
                return _round_wm(
                    state, deg=deg, sync_every=self.sync_every,
                    exchange=self.exchange, live_rows=lr,
                    sync_diff=self._delayed.sync_diff,
                    delayed_exchange=lambda h, t: dex(h, t, lr))
            return _round_wm(state, deg=deg,
                             sync_every=self.sync_every,
                             exchange=self.exchange,
                             sync_diff=self.sync_diff,
                             delayed_exchange=self._delayed.exchange)
        if masks is None:
            return _round_wm(state, deg=deg,
                             sync_every=self.sync_every,
                             exchange=self.exchange,
                             sync_diff=self.sync_diff)
        return _round_wm(
            state, deg=deg, sync_every=self.sync_every,
            exchange=f.exchange, sync_diff=f.sync_diff,
            live_rows=self._live_rows(*masks))

    def _wm_extra_args(self):
        """The masked words-major modes' extra traced arguments: mask
        arrays + window rounds (faulted modes), the delay rows (+
        window masks when composed) in the edge-delayed modes, or the
        full nemesis operand (mask pytree + window rounds + plan);
        empty otherwise."""
        if self._nem is not None:
            return (self._nem_arrs, self.parts.starts,
                    self.parts.ends, self.fault_plan)
        if self._ef:
            return (self._ed_rows,) + self._ef_arrs \
                + (self.parts.starts, self.parts.ends)
        if self._edge is not None:
            return (self._ed_rows,)
        if self._faulted is None and not self._df:
            return ()
        return (self._f_exists, self._f_same, self.parts.starts,
                self.parts.ends)

    def _wm_mesh_extra(self):
        """Extra (in_specs, args) the sharded words-major programs
        thread through shard_map in masked modes: the mask arrays and
        the window rounds (explicit args, not closure captures)."""
        if self._nem is not None:
            return ((self._nem_specs, P(), P(), faults.plan_specs()),
                    self._wm_extra_args())
        if self._ef:
            e_spec, s_spec, d_spec = self._ef_specs
            return ((self._ed_spec, e_spec, s_spec, d_spec, P(), P()),
                    self._wm_extra_args())
        if self._edge is not None:
            return ((self._ed_spec,), (self._ed_rows,))
        if self._faulted is None and not self._df:
            return (), ()
        e_spec, s_spec = self._f_specs
        return ((e_spec, s_spec, P(), P()), self._wm_extra_args())

    def _fp_mesh_extra(self):
        """Extra (in_specs, args) the sharded GATHER-path programs
        thread through shard_map when a FaultPlan is active: the plan
        rides as one replicated traced operand (never donated — the
        state pytree alone is).  Words-major nemesis runs thread the
        plan inside :meth:`_wm_extra_args` instead."""
        if self.fault_plan is None or self.words_major:
            return (), ()
        return ((faults.plan_specs(),), (self.fault_plan,))

    def _build_step(self):
        """Build the one-round driver.  Each branch also stashes the
        raw jitted program + its full-operand builder in
        ``self._audit_step`` — the contract auditor
        (:meth:`audit_step_program`) lowers the EXACT object
        :meth:`step` executes, never a re-built twin that could drift."""
        parts, sync_every = self.parts, self.sync_every

        if self.mesh is None:
            if self.words_major:
                @jax.jit
                def step_wm(state: BroadcastState, deg,
                            *masks) -> BroadcastState:
                    return self._wm_round_single(state, deg,
                                                 masks or None)
                extra = self._wm_extra_args()
                self._audit_step = (
                    step_wm, lambda state: (state, self.deg) + extra)
                return lambda state, nbrs, nbr_mask: step_wm(
                    state, self.deg, *extra)

            fp_args = self._fp_mesh_extra()[1]

            @jax.jit
            def step(state: BroadcastState, nbrs, nbr_mask,
                     *fp) -> BroadcastState:
                return flood_step(state, nbrs=nbrs, nbr_mask=nbr_mask,
                                  parts=parts, sync_every=sync_every,
                                  delays=self.delays,
                                  delay_set=self._delay_set,
                                  plan=fp[0] if fp else None,
                                  dup_on=self._fp_dup,
                                  union_block=self._ub)
            self._audit_step = (
                step, lambda state: (state, self.nbrs,
                                     self.nbr_mask) + fp_args)
            return lambda state, nbrs, nbr_mask: step(
                state, nbrs, nbr_mask, *fp_args)

        state_spec, node_spec, part_spec = self._specs()

        if self.words_major:
            extra_specs, extra_args = self._wm_mesh_extra()

            @jax.jit
            @functools.partial(
                shard_map, mesh=self.mesh,
                in_specs=(state_spec, P(self._na)) + extra_specs,
                out_specs=state_spec,
                check_vma=False,
            )
            def step_wm(state: BroadcastState, deg,
                        *masks) -> BroadcastState:
                return self._sharded_round_wm(state, deg, masks or None)

            self._audit_step = (
                step_wm,
                lambda state: (state, self.deg) + extra_args)
            return lambda state, nbrs, nbr_mask: step_wm(
                state, self.deg, *extra_args)

        fp_specs, fp_args = self._fp_mesh_extra()

        if self.delays is not None:
            @jax.jit
            @functools.partial(
                shard_map, mesh=self.mesh,
                in_specs=(state_spec, node_spec, node_spec, part_spec,
                          node_spec) + fp_specs,
                out_specs=state_spec, check_vma=False,
            )
            def step_d(state: BroadcastState, nbrs, nbr_mask,
                       parts: Partitions, delays, *fp) -> BroadcastState:
                return self._sharded_round(state, nbrs, nbr_mask, parts,
                                           delays,
                                           fp[0] if fp else None)

            self._audit_step = (
                step_d,
                lambda state: (state, self.nbrs, self.nbr_mask,
                               self.parts, self.delays) + fp_args)
            return lambda state, nbrs, nbr_mask: step_d(
                state, nbrs, nbr_mask, self.parts, self.delays,
                *fp_args)

        @jax.jit
        @functools.partial(
            shard_map, mesh=self.mesh,
            in_specs=(state_spec, node_spec, node_spec, part_spec)
            + fp_specs,
            out_specs=state_spec,
        )
        def step(state: BroadcastState, nbrs, nbr_mask,
                 parts: Partitions, *fp) -> BroadcastState:
            return self._sharded_round(state, nbrs, nbr_mask, parts,
                                       None, fp[0] if fp else None)

        self._audit_step = (
            step, lambda state: (state, self.nbrs, self.nbr_mask,
                                 self.parts) + fp_args)
        return lambda state, nbrs, nbr_mask: step(state, nbrs, nbr_mask,
                                                  self.parts, *fp_args)

    def step(self, state: BroadcastState) -> BroadcastState:
        return self._step(state, self.nbrs, self.nbr_mask)

    def audit_step_program(self):
        """(jitted, args_fn) of this sim's one-round step program — the
        EXACT jitted object :meth:`step` executes (stashed by
        :meth:`_build_step`, never a re-built twin that could drift)
        plus an ``args_fn(state) -> operand tuple``, for the contract
        auditor (tpu_sim/audit.py): the driver lambdas hide the jitted
        handle, and HLO/alias analysis is per-program."""
        return self._audit_step

    def _build_fused(self, max_rounds: int, donate: bool):
        """Whole-convergence runner as ONE device program: the engine's
        ``while_converge`` — rounds under a ``lax.while_loop`` with the
        convergence check on device.  Avoids a host↔device round-trip
        per step — the per-call dispatch latency is what dominates small
        rounds, especially over a remote-TPU tunnel.

        ``donate``: donate the state pytree into the program (the
        :meth:`run_fused` path, which stages the state internally), so
        the loop holds ONE live state copy instead of input + output —
        the engine's donation-first contract (engine.py)."""
        parts, sync_every = self.parts, self.sync_every
        limit = jnp.int32(max_rounds)
        wm = self.words_major
        dn = donate_argnums_for(donate, 0)

        def eq_target(s: BroadcastState, target) -> jnp.ndarray:
            # target is (W,); received is (W, n) words-major, (n, W) else
            t = target[:, None] if wm else target[None, :]
            return jnp.all(s.received == t)

        if self.mesh is None:
            # wm masks and the gather path's FaultPlan are mutually
            # exclusive, so `rest` is one or the other
            extra = self._wm_extra_args() + self._fp_mesh_extra()[1]

            @functools.partial(jax.jit, donate_argnums=dn)
            def run(state: BroadcastState, nbrs, nbr_mask, target, deg,
                    *rest):
                def body(s):
                    if wm:
                        return self._wm_round_single(s, deg,
                                                     rest or None)
                    return flood_step(s, nbrs=nbrs, nbr_mask=nbr_mask,
                                      parts=parts, sync_every=sync_every,
                                      delays=self.delays,
                                      delay_set=self._delay_set,
                                      plan=rest[0] if rest else None,
                                      dup_on=self._fp_dup,
                                      union_block=self._ub)

                return while_converge(
                    body, lambda s: eq_target(s, target), state, limit)

            return lambda state, nbrs, nbr_mask, target: run(
                state, nbrs, nbr_mask, target, self.deg, *extra)

        mesh = self.mesh
        state_spec, node_spec, part_spec = self._specs()
        target_spec = (P("words") if "words" in mesh.axis_names else P())
        n_shards = int(np.prod(mesh.devices.shape))

        def converge(state, target, one_round):
            def all_converged(s: BroadcastState) -> jnp.ndarray:
                ok_local = eq_target(s, target)
                return (self._dcn_psum(ok_local.astype(jnp.int32))
                        == n_shards)

            return while_converge(one_round, all_converged, state,
                                  limit)

        if wm:
            extra_specs, extra_args = self._wm_mesh_extra()

            @functools.partial(jax.jit, donate_argnums=dn)
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(state_spec, P(self._na), target_spec)
                + extra_specs,
                out_specs=state_spec, check_vma=False,
            )
            def run_wm(state: BroadcastState, deg, target,
                       *masks) -> BroadcastState:
                return converge(
                    state, target,
                    lambda s: self._sharded_round_wm(s, deg,
                                                     masks or None))

            return lambda state, nbrs, nbr_mask, target: run_wm(
                state, self.deg, target, *extra_args)

        fp_specs, fp_args = self._fp_mesh_extra()

        if self.delays is not None:
            @functools.partial(jax.jit, donate_argnums=dn)
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(state_spec, node_spec, node_spec, target_spec,
                          part_spec, node_spec) + fp_specs,
                out_specs=state_spec, check_vma=False,
            )
            def run_d(state: BroadcastState, nbrs, nbr_mask, target,
                      parts: Partitions, delays, *fp) -> BroadcastState:
                return converge(
                    state, target,
                    lambda s: self._sharded_round(
                        s, nbrs, nbr_mask, parts, delays,
                        fp[0] if fp else None))

            return lambda state, nbrs, nbr_mask, target: run_d(
                state, nbrs, nbr_mask, target, self.parts, self.delays,
                *fp_args)

        @functools.partial(jax.jit, donate_argnums=dn)
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(state_spec, node_spec, node_spec, target_spec,
                      part_spec) + fp_specs,
            out_specs=state_spec,
        )
        def run(state: BroadcastState, nbrs, nbr_mask, target,
                parts: Partitions, *fp) -> BroadcastState:
            return converge(
                state, target,
                lambda s: self._sharded_round(s, nbrs, nbr_mask, parts,
                                              None,
                                              fp[0] if fp else None))

        return lambda state, nbrs, nbr_mask, target: run(
            state, nbrs, nbr_mask, target, self.parts, *fp_args)

    def _build_fixed(self, rounds: int, donate: bool):
        """Fixed-trip-count runner: ``lax.fori_loop`` of exactly
        ``rounds`` rounds, counter-only control flow.  Bit-identical to
        the while-loop runner stopped at its convergence round, but
        with NO data-dependent loop condition — on tunneled single-chip
        setups a data-dependent ``while_loop`` pays a large fixed
        host-sync penalty plus a per-iteration round-trip (measured
        ~100 ms + ~1 ms/round on the remote-TPU tunnel), which is
        transport artifact, not simulation compute.  The caller must
        know ``rounds`` (e.g. from a prior :meth:`run_fused`) and
        should re-verify convergence on the result.

        Returns ``(runner, flood_parts | None)``.  ``donate``: donate
        the state (flood specialization: the (received, frontier) loop
        carry) into the program — the caller must treat the passed
        state as consumed (benchmarks re-stage per chain)."""
        parts, sync_every = self.parts, self.sync_every
        wm = self.words_major
        dn = donate_argnums_for(donate, 0)
        dn2 = donate_argnums_for(donate, 0, 1)

        def iterate(state, one_round):
            return fori_rounds(one_round, state, rounds)

        # Pure-flood specialization: when no sync wave fires within the
        # trip count (rounds <= sync_every) and no ledgers/faults need
        # per-round bookkeeping, the loop body is JUST exchange+merge
        # (_flood_loop) — free of the in-loop scalar reduces and
        # selects that defeat XLA's loop fusion, so the whole multi-
        # round program stays VMEM-resident at W=1 — and the value-
        # message ledger is recovered exactly post-loop
        # (_flood_ledger).  Bit-exactness vs the
        # while runner is pinned by
        # test_run_staged_fixed_matches_while_runner and
        # test_fixed_flood_specialization_matches_while_runner.
        flood_ok = (wm and not self._srv_on and self.delays is None
                    and self._faulted is None and self._delayed is None
                    and self._edge is None and self.fault_plan is None
                    and rounds <= sync_every and rounds > 0)

        if self.mesh is None and flood_ok:
            # degrees come from the host copy: a device readback here
            # would flip the tunnel session (see timing.py)
            degs, mask_arrays = _degree_masks(self._host_deg)
            masks = [shard_put(m) for m in mask_arrays]
            loop_fn = jax.jit(_flood_loop(self.exchange, rounds),
                              donate_argnums=dn2)

            @jax.jit
            def ledger_fn(state: BroadcastState, rec, fr, *ms):
                return _flood_ledger(state, rec, fr, degs, ms, rounds)

            return self._wire_flood_parts(loop_fn, ledger_fn, masks)

        if self.mesh is None:
            # as in _build_fused: `rest` is the wm masks OR the plan
            extra = self._wm_extra_args() + self._fp_mesh_extra()[1]

            @functools.partial(jax.jit, donate_argnums=dn)
            def run(state: BroadcastState, nbrs, nbr_mask, deg, *rest):
                def one(s):
                    if wm:
                        return self._wm_round_single(s, deg,
                                                     rest or None)
                    return flood_step(s, nbrs=nbrs, nbr_mask=nbr_mask,
                                      parts=parts,
                                      sync_every=sync_every,
                                      delays=self.delays,
                                      delay_set=self._delay_set,
                                      plan=rest[0] if rest else None,
                                      dup_on=self._fp_dup,
                                      union_block=self._ub)

                return iterate(state, one)

            return (lambda state, nbrs, nbr_mask: run(
                state, nbrs, nbr_mask, self.deg, *extra)), None

        mesh = self.mesh
        state_spec, node_spec, part_spec = self._specs()

        if flood_ok and self.sharded_exchange is not None:
            # mesh twin of the pure-flood specialization: same loop and
            # closed-form ledger cores, wrapped in shard_map — per-shard
            # masked reduces psum-globalized (word shards partition the
            # popcounts; frontier ⊆ received bitwise, so per-shard
            # partial sums subtract safely in uint32)
            st_spec = self._state_spec
            degs, mask_arrays = _degree_masks(self._host_deg)
            mask_spec = P(None, self._na)
            masks = [shard_put(m, NamedSharding(mesh, mask_spec))
                     for m in mask_arrays]

            loop_fn = jax.jit(functools.partial(
                shard_map, mesh=mesh,
                in_specs=(st_spec, st_spec),
                out_specs=(st_spec, st_spec), check_vma=False,
            )(_flood_loop(self.sharded_exchange, rounds)),
                donate_argnums=dn2)

            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(state_spec, st_spec, st_spec)
                + tuple(mask_spec for _ in masks),
                out_specs=state_spec, check_vma=False,
            )
            def ledger_fn(state: BroadcastState, rec, fr, *ms):
                return _flood_ledger(state, rec, fr, degs, ms, rounds,
                                     self._dcn_psum)

            return self._wire_flood_parts(loop_fn, ledger_fn, masks)

        if wm:
            extra_specs, extra_args = self._wm_mesh_extra()

            @functools.partial(jax.jit, donate_argnums=dn)
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(state_spec, P(self._na)) + extra_specs,
                out_specs=state_spec, check_vma=False,
            )
            def run_wm(state: BroadcastState, deg,
                       *masks) -> BroadcastState:
                return iterate(
                    state, lambda s: self._sharded_round_wm(
                        s, deg, masks or None))

            return (lambda state, nbrs, nbr_mask: run_wm(
                state, self.deg, *extra_args)), None

        fp_specs, fp_args = self._fp_mesh_extra()

        if self.delays is not None:
            @functools.partial(jax.jit, donate_argnums=dn)
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(state_spec, node_spec, node_spec, part_spec,
                          node_spec) + fp_specs,
                out_specs=state_spec, check_vma=False,
            )
            def run_d(state: BroadcastState, nbrs, nbr_mask,
                      parts: Partitions, delays, *fp) -> BroadcastState:
                return iterate(
                    state, lambda s: self._sharded_round(
                        s, nbrs, nbr_mask, parts, delays,
                        fp[0] if fp else None))

            return (lambda state, nbrs, nbr_mask: run_d(
                state, nbrs, nbr_mask, self.parts, self.delays,
                *fp_args)), None

        @functools.partial(jax.jit, donate_argnums=dn)
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(state_spec, node_spec, node_spec, part_spec)
            + fp_specs,
            out_specs=state_spec,
        )
        def run_g(state: BroadcastState, nbrs, nbr_mask,
                  parts: Partitions, *fp) -> BroadcastState:
            return iterate(
                state,
                lambda s: self._sharded_round(s, nbrs, nbr_mask, parts,
                                              None,
                                              fp[0] if fp else None))

        return (lambda state, nbrs, nbr_mask: run_g(
            state, nbrs, nbr_mask, self.parts, *fp_args)), None

    # -- flight-recorder telemetry (PR 8) ----------------------------------

    def _tel_series(self, s0: BroadcastState, s1: BroadcastState,
                    plan, reduce_sum) -> tuple:
        """One round's telemetry row (telemetry.SIM_SERIES
        ['broadcast'] order), traced: liveness from the replicated
        plan, frontier/new/known popcounts as per-shard partials
        globalized in ONE packed ``reduce_sum`` (node AND word shards
        partition the bit counts, so the psum over all mesh axes is
        exact), and the value-message running total.
        Layout-agnostic: the popcount sums reduce the whole local
        block, node-major or words-major."""
        def pc(x):
            return jnp.sum(lax.population_count(x).astype(jnp.uint32),
                           dtype=jnp.uint32)

        g = reduce_sum(jnp.stack(
            [pc(s0.frontier), pc(s1.frontier), pc(s1.received)]))
        return (telemetry.live_count(plan, s0.t, self.n_nodes),
                g[0], g[1], g[2], s1.msgs)

    def _build_observed(self, tspec: "telemetry.TelemetrySpec | None",
                        pspec, donate: bool):
        """The telemetry-/provenance-on fused driver (PR 8 / PR 9):
        the generic fixed-loop round bodies unchanged, a
        ``(state, tel?, prov?)`` carry with a DYNAMIC trip count,
        every carry leaf donated together.  Telemetry rides the 1-hop
        gather, per-edge ``delays`` gather, and words-major 1-hop
        paths; provenance (``pspec``) rides the GATHER paths only —
        the structured exchanges fold their per-direction terms
        internally, so attribution there would re-run the exchange D
        times.  Words-major delay-ring modes stay unwired."""
        tl = tspec is not None
        pv = pspec is not None
        if not (tl or pv):
            raise ValueError(
                "observed drivers need a TelemetrySpec and/or a "
                "ProvenanceSpec")
        if tl and (tspec.workload != "broadcast" or tspec.traffic):
            raise ValueError(
                "run_observed needs a TelemetrySpec(workload="
                "'broadcast', traffic=False); open-loop runs record "
                "through run_traffic(tel=...)")
        if pv and self.words_major:
            raise ValueError(
                "broadcast provenance rides the gather path (the "
                "structured words-major exchanges fold their "
                "direction terms internally — see "
                "tpu_sim/provenance.py); drop exchange= for a "
                "provenance-on run")
        if pv and self.mesh is not None \
                and "words" in self.mesh.axis_names:
            raise ValueError(
                "broadcast provenance runs on 1-D node meshes (the "
                "(N, V) stamps shard with the node axis only)")
        if self._delayed is not None or self._edge is not None \
                or self._nem_delayed:
            raise ValueError(
                "observed drivers run the gather (1-hop and per-edge "
                "delays) and 1-hop words-major paths; words-major "
                "delay-ring modes are not wired")
        parts, sync_every = self.parts, self.sync_every
        wm = self.words_major
        mesh = self.mesh
        n_carry = 1 + int(tl) + int(pv)
        dn = donate_argnums_for(donate, *range(n_carry))
        tel_mask = tspec.static_mask if tl else None
        has_nem = self._nem is not None
        ip = 1 + int(tl)             # prov position in the carry

        def carry_of(state, tel, prov):
            return ((state,) + ((tel,) if tl else ())
                    + ((prov,) if pv else ()))

        def mk_one(round_fn, plan, rs):
            """The observed round body: run the round (provenance
            threaded INTO it when on — the recorder re-reads the
            delivery terms in scope), then append the telemetry row."""
            def one(c):
                s = c[0]
                r = round_fn(s, c[ip] if pv else None)
                s2, p2 = r if pv else (r, None)
                out = (s2,)
                if tl:
                    out += (telemetry.record(
                        c[1], s.t, self._tel_series(s, s2, plan, rs),
                        tel_mask),)
                if pv:
                    out += (p2,)
                return out

            return one

        if mesh is None:
            extra = self._wm_extra_args() + self._fp_mesh_extra()[1]

            @functools.partial(jax.jit, donate_argnums=dn)
            def run(*a):
                a = list(a)
                state = a.pop(0)
                tel = a.pop(0) if tl else None
                prov0 = a.pop(0) if pv else None
                n, nbrs, nbr_mask, deg = a[0], a[1], a[2], a[3]
                rest = tuple(a[4:])
                if wm:
                    plan = rest[3] if has_nem else None
                else:
                    plan = rest[0] if rest else None

                def round_fn(s, p):
                    if wm:
                        return self._wm_round_single(s, deg,
                                                     rest or None)
                    return flood_step(
                        s, nbrs=nbrs, nbr_mask=nbr_mask,
                        parts=parts, sync_every=sync_every,
                        delays=self.delays,
                        delay_set=self._delay_set, plan=plan,
                        dup_on=self._fp_dup,
                        union_block=None if pv else self._ub,
                        prov=p)

                one = mk_one(round_fn, plan, lambda x: x)
                return fori_rounds(one, carry_of(state, tel, prov0),
                                   n)

            def args_fn(state, tel, prov, n):
                return carry_of(state, tel, prov) + (
                    n, self.nbrs, self.nbr_mask, self.deg) + extra

            runner = lambda state, tel, prov, n: run(
                *args_fn(state, tel, prov, n))
            return run, args_fn, runner

        state_spec, node_spec, part_spec = self._specs()
        tel_in = (telemetry.state_specs(),) if tl else ()
        prov_in = ((provenance.broadcast_specs(self._na),)
                   if pv else ())

        if wm:
            extra_specs, extra_args = self._wm_mesh_extra()

            @functools.partial(jax.jit, donate_argnums=dn)
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(state_spec,) + tel_in
                + (P(), P(self._na)) + extra_specs,
                out_specs=(state_spec,) + tel_in, check_vma=False,
            )
            def run_wm(state: BroadcastState, tel, n, deg, *masks):
                plan = masks[3] if has_nem else None
                rs = self._dcn_psum
                one = mk_one(
                    lambda s, p: self._sharded_round_wm(
                        s, deg, masks or None), plan, rs)
                return fori_rounds(one, carry_of(state, tel, None),
                                   n)

            def args_fn(state, tel, prov, n):
                return (state, tel, n, self.deg) + extra_args

            runner = lambda state, tel, prov, n: run_wm(
                *args_fn(state, tel, prov, n))
            return run_wm, args_fn, runner

        dl_in = (node_spec,) if self.delays is not None else ()
        fp_specs, fp_args = self._fp_mesh_extra()

        @functools.partial(jax.jit, donate_argnums=dn)
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(state_spec,) + tel_in + prov_in
            + (P(), node_spec, node_spec, part_spec) + dl_in
            + fp_specs,
            out_specs=(state_spec,) + tel_in + prov_in,
            check_vma=False,
        )
        def run_g(*a):
            a = list(a)
            state = a.pop(0)
            tel = a.pop(0) if tl else None
            prov0 = a.pop(0) if pv else None
            n, nbrs, nbr_mask, parts_ = a[0], a[1], a[2], a[3]
            a = a[4:]
            delays_ = a.pop(0) if self.delays is not None else None
            plan = a[0] if a else None
            rs = self._dcn_psum
            one = mk_one(
                lambda s, p: self._sharded_round(
                    s, nbrs, nbr_mask, parts_, delays_, plan,
                    prov=p), plan, rs)
            return fori_rounds(one, carry_of(state, tel, prov0), n)

        def args_fn(state, tel, prov, n):
            return carry_of(state, tel, prov) + (
                n, self.nbrs, self.nbr_mask, self.parts) \
                + ((self.delays,) if self.delays is not None else ()) \
                + fp_args

        runner = lambda state, tel, prov, n: run_g(
            *args_fn(state, tel, prov, n))
        return run_g, args_fn, runner

    def telemetry_state(self, tspec) -> "telemetry.TelemetryState":
        return telemetry.init_state(tspec)

    def provenance_state(self, pspec, inject
                         ) -> "provenance.BroadcastProv":
        """Fresh (N, V) provenance record for this sim, origin cells
        stamped from the round-0 ``inject`` bitset (node-sharded on a
        mesh, like the state)."""
        prov = provenance.init_broadcast(
            self.n_nodes, self.n_values, np.asarray(inject, np.uint32))
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(self._na, None))
            prov = provenance.BroadcastProv(
                *(shard_put(a, sh) for a in prov))
        return prov

    def run_observed(self, state: BroadcastState, tel, tspec,
                     n_rounds: int, *, donate: bool = False,
                     prov=None, prov_spec=None):
        """Telemetry-/provenance-on fused driver: ``n_rounds`` rounds
        as one device program with the per-round metrics ring and/or
        the per-(node, value) provenance stamps recorded next to the
        state — bit-exact to the plain drivers (the recorders only
        read state).  Returns the carry in order: ``(state, tel?,
        prov?)``."""
        if (tel is None) != (tspec is None):
            raise ValueError(
                "pass tel and tel_spec together (build the ring with "
                "telemetry.init_state(spec))")
        provenance.prov_key(prov, prov_spec, "broadcast")
        key = (tspec, prov_spec, donate)
        if key not in self._obs_progs:
            self._obs_progs[key] = self._build_observed(
                tspec, prov_spec, donate)
        return self._obs_progs[key][2](state, tel, prov,
                                       jnp.int32(n_rounds))

    def audit_observed_program(self, tspec, *, donate: bool = True,
                               prov_spec=None):
        """(jitted, example_args) of the observed driver — the handle
        the contract auditor lowers."""
        key = (tspec, prov_spec, donate)
        if key not in self._obs_progs:
            self._obs_progs[key] = self._build_observed(
                tspec, prov_spec, donate)
        prog, args_fn, _ = self._obs_progs[key]
        inj = np.zeros((self.n_nodes, self.n_words), np.uint32)
        state = self.init_state(inj)
        tel = (telemetry.init_state(tspec) if tspec is not None
               else None)
        prov = (self.provenance_state(prov_spec, inj)
                if prov_spec is not None else None)
        return prog, args_fn(state, tel, prov, jnp.int32(4))

    # -- drivers -----------------------------------------------------------

    # -- open-loop traffic (PR 7) -----------------------------------------

    def _traffic_validate(self, tspec) -> None:
        if tspec.n_nodes != self.n_nodes:
            raise ValueError(
                f"TrafficSpec is for {tspec.n_nodes} nodes, sim has "
                f"{self.n_nodes}")
        if self._srv_on:
            raise ValueError(
                "traffic drivers keep no server ledger (open-loop "
                "ops have no reference srv accounting): build the "
                "sim with srv_ledger=False")
        # delay-ring modes (gather per-edge `delays`, words-major
        # `delayed`/`edge_delayed`/nemesis dir_delays) take traffic
        # since PR 9: injection lands in received+frontier BEFORE the
        # round pushes the payload into the history ring, so a
        # mid-run client value floods with the edge's latency like
        # any other bit — serving curves cover delayed topologies
        # (the ROADMAP item-1 leftover).
        need = tspec.n_clients * tspec.ops_per_client
        if need > self.n_values:
            raise ValueError(
                f"value universe too small: n_values={self.n_values} "
                f"< n_clients*ops_per_client={need} (every op is its "
                "own value bit)")
        if self.mesh is not None:
            if "words" in self.mesh.axis_names:
                raise ValueError(
                    "traffic drivers run on 1-D node meshes")
            if tspec.n_clients % node_shards(self.mesh) != 0:
                raise ValueError(
                    f"n_clients={tspec.n_clients} must shard evenly "
                    "over the node axis")

    def _traffic_inject(self, state: BroadcastState, ts, tspec, tplan,
                        plan, coll):
        """Fold this round's arrivals into the node rows: op (client,
        k) is value bit ``client * ops_per_client + k``, set at the
        client's home node in ``received`` AND ``frontier`` so the
        next exchange floods it (a mid-run client ``broadcast``).  All
        scatters are shard-local (the client blocks align with the
        node blocks); deferral classes — home node down, per-node
        ``intake`` cap, op slots exhausted — are counted by
        ``traffic.issue``, never dropped."""
        wm = self.words_major
        rows = (state.received.shape[1] if wm
                else state.received.shape[0])
        bc = rows * tspec.n_clients // self.n_nodes
        p = coll.row_ids[0] // jnp.int32(rows)
        ids = p * jnp.int32(bc) + jnp.arange(bc, dtype=jnp.int32)
        arr = traffic.arrive(tplan, state.t, ids)
        node_loc = traffic.local_node_cols(tspec, bc)
        accept = (faults.node_up(plan, state.t,
                                 coll.row_ids[0] + node_loc)
                  if plan is not None else jnp.ones(arr.shape, bool))
        if tspec.intake is not None:
            accept = accept & (
                traffic.intake_rank(arr, tspec.clients_per_node)
                < tspec.intake)
        ts, ok, kslot = traffic.issue(ts, arr, accept, state.t,
                                      coll.reduce_sum)
        v = ids * jnp.int32(tspec.ops_per_client) + kslot
        w = jnp.where(ok, v // 32, jnp.int32(self.n_words))
        bit = jnp.where(ok, jnp.uint32(1)
                        << (v % 32).astype(jnp.uint32), jnp.uint32(0))
        if wm:
            received = state.received.at[w, node_loc].add(
                bit, mode="drop")
            frontier = state.frontier.at[w, node_loc].add(
                bit, mode="drop")
        else:
            received = state.received.at[node_loc, w].add(
                bit, mode="drop")
            frontier = state.frontier.at[node_loc, w].add(
                bit, mode="drop")
        return state._replace(received=received,
                              frontier=frontier), ts

    def _traffic_done(self, s2: BroadcastState, ts, tspec, coll, ub):
        """Per-op visibility: the op's value bit present at EVERY
        node — an AND-fold over the local node axis combined by the
        engine's ppermute-only ``reduce_and`` (no all-gather), read
        back per op slot from the replicated (W,) all-nodes words."""
        wm = self.words_major
        rows = s2.received.shape[1] if wm else s2.received.shape[0]
        local_and = lax.reduce(s2.received, jnp.uint32(0xFFFFFFFF),
                               lax.bitwise_and, (1,) if wm else (0,))
        all_words = coll.reduce_and(local_and)
        bc = rows * tspec.n_clients // self.n_nodes
        c0 = (coll.row_ids[0] // jnp.int32(rows)) * jnp.int32(bc)
        n_k = tspec.ops_per_client

        def bit_fn(lo, block):
            idv = c0 + lo + jnp.arange(block, dtype=jnp.int32)
            v = (idv[:, None] * jnp.int32(n_k)
                 + jnp.arange(n_k, dtype=jnp.int32)[None, :])
            return ((all_words[v // 32]
                     >> (v % 32).astype(jnp.uint32))
                    & jnp.uint32(1)) > 0

        return traffic.done_scan(ts, bit_fn, s2.t, coll.reduce_sum,
                                 ub)

    def _traffic_tel(self, s_inj, s2, ts2, plan, coll, tel, tel_mask):
        """Record one traffic round's telemetry row (PR 8): s0 = the
        post-injection state (arrivals count in this round's frontier
        gauge), tracker totals appended."""
        vals = (self._tel_series(s_inj, s2, plan, coll.reduce_sum)
                + traffic.tel_series(ts2, coll.reduce_sum))
        return telemetry.record(tel, s_inj.t, vals, tel_mask)

    def _build_traffic(self, tspec, donate: bool, tel_spec=None):
        self._traffic_validate(tspec)
        mesh = self.mesh
        n_sh = node_shards(mesh)
        ub = traffic.traffic_block(tspec.n_clients // n_sh)
        tl = tel_spec is not None
        mask = tel_spec.static_mask if tl else None
        dn = donate_argnums_for(donate, *((0, 1, 2) if tl else (0, 1)))
        wm = self.words_major
        has_nem = self._nem is not None

        def mk_body(round_fn, plan, coll):
            """The per-round traffic body: inject, round, track —
            plus the telemetry row when the ring carry rides along."""
            def body(carry, op):
                s, t_ = self._traffic_inject(
                    carry[0], carry[1], tspec, op, plan, coll)
                s2 = round_fn(s)
                t2 = self._traffic_done(s2, t_, tspec, coll, ub)
                if not tl:
                    return (s2, t2)
                return (s2, t2, self._traffic_tel(
                    s, s2, t2, plan, coll, carry[2], mask))

            return body

        def carry_of(state, ts, tel):
            return (state, ts, tel) if tl else (state, ts)

        if mesh is None:
            if wm:
                extra = self._wm_extra_args()

                def run_wm(state, *rest):
                    rest = list(rest)
                    tel = rest.pop(0) if tl else None
                    ts, n, tplan, deg = (rest[0], rest[1], rest[2],
                                         rest[3])
                    masks = tuple(rest[4:])
                    coll = collectives(self.n_nodes)
                    plan = masks[3] if has_nem else None
                    body = mk_body(
                        lambda s: self._wm_round_single(
                            s, deg, masks or None), plan, coll)
                    return fori_rounds(body, carry_of(state, ts, tel),
                                       n, operand=tplan)

                prog = jit_program(run_wm, donate_argnums=dn)

                def args_fn(state, ts, n, tplan, tel=None):
                    pre = (state, tel) if tl else (state,)
                    return pre + (ts, n, tplan, self.deg) + extra
            else:
                fp_args = self._fp_mesh_extra()[1]

                def run_g(state, *rest):
                    rest = list(rest)
                    tel = rest.pop(0) if tl else None
                    ts, n, tplan, nbrs, nbr_mask = (
                        rest[0], rest[1], rest[2], rest[3], rest[4])
                    fp = tuple(rest[5:])
                    coll = collectives(self.n_nodes)
                    plan = fp[0] if fp else None
                    body = mk_body(
                        lambda s: flood_step(
                            s, nbrs=nbrs, nbr_mask=nbr_mask,
                            parts=self.parts,
                            sync_every=self.sync_every,
                            delays=self.delays,
                            delay_set=self._delay_set, plan=plan,
                            dup_on=self._fp_dup,
                            union_block=self._ub), plan, coll)
                    return fori_rounds(body, carry_of(state, ts, tel),
                                       n, operand=tplan)

                prog = jit_program(run_g, donate_argnums=dn)

                def args_fn(state, ts, n, tplan, tel=None):
                    pre = (state, tel) if tl else (state,)
                    return pre + (ts, n, tplan, self.nbrs,
                                  self.nbr_mask) + fp_args

            runner = lambda state, ts, n, tplan, tel=None: prog(
                *args_fn(state, ts, n, tplan, tel))
            return prog, args_fn, runner

        state_spec, node_spec, part_spec = self._specs()
        t_specs = traffic.state_specs(True, self._na)
        tel_in = (telemetry.state_specs(),) if tl else ()

        if wm:
            extra_specs, extra_args = self._wm_mesh_extra()

            def run_wm(state, *rest):
                rest = list(rest)
                tel = rest.pop(0) if tl else None
                ts, n, tplan, deg = (rest[0], rest[1], rest[2],
                                     rest[3])
                masks = tuple(rest[4:])
                coll = collectives(state.received.shape[1], mesh,
                                   dcn=self._dcn)
                plan = masks[3] if has_nem else None
                body = mk_body(
                    lambda s: self._sharded_round_wm(
                        s, deg, masks or None), plan, coll)
                return fori_rounds(body, carry_of(state, ts, tel), n,
                                   operand=tplan)

            prog = jit_program(
                run_wm, mesh=mesh,
                in_specs=(state_spec,) + tel_in
                + (t_specs, P(), traffic.plan_specs(), P(self._na))
                + extra_specs,
                out_specs=(state_spec, t_specs) + tel_in,
                check_vma=False, donate_argnums=dn)

            def args_fn(state, ts, n, tplan, tel=None):
                pre = (state, tel) if tl else (state,)
                return pre + (ts, n, tplan, self.deg) + extra_args
        else:
            fp_specs, fp_args = self._fp_mesh_extra()

            dl_in = ((node_spec,) if self.delays is not None else ())

            def run_g(state, *rest):
                rest = list(rest)
                tel = rest.pop(0) if tl else None
                ts, n, tplan, nbrs, nbr_mask, parts = (
                    rest[0], rest[1], rest[2], rest[3], rest[4],
                    rest[5])
                rest = rest[6:]
                delays_ = (rest.pop(0) if self.delays is not None
                           else None)
                fp = tuple(rest)
                coll = collectives(nbrs.shape[0], mesh,
                                   dcn=self._dcn)
                plan = fp[0] if fp else None
                body = mk_body(
                    lambda s: self._sharded_round(
                        s, nbrs, nbr_mask, parts, delays_, plan),
                    plan, coll)
                return fori_rounds(body, carry_of(state, ts, tel), n,
                                   operand=tplan)

            prog = jit_program(
                run_g, mesh=mesh,
                in_specs=(state_spec,) + tel_in
                + (t_specs, P(), traffic.plan_specs(), node_spec,
                   node_spec, part_spec) + dl_in + fp_specs,
                out_specs=(state_spec, t_specs) + tel_in,
                check_vma=False, donate_argnums=dn)

            def args_fn(state, ts, n, tplan, tel=None):
                pre = (state, tel) if tl else (state,)
                return pre + (ts, n, tplan, self.nbrs, self.nbr_mask,
                              self.parts) \
                    + ((self.delays,)
                       if self.delays is not None else ()) + fp_args

        runner = lambda state, ts, n, tplan, tel=None: prog(
            *args_fn(state, ts, n, tplan, tel))
        return prog, args_fn, runner

    def traffic_state(self, tspec) -> "traffic.TrafficState":
        return traffic.init_state(tspec, self.mesh)

    def run_traffic(self, state: BroadcastState, ts, tspec,
                    n_rounds: int, *, donate: bool = False,
                    tel=None, tel_spec=None):
        """Open-loop serving driver: ``n_rounds`` rounds as ONE device
        program, each round injecting the spec's seeded client
        arrivals (new values at their home nodes) before the flood/
        anti-entropy round and advancing the per-op latency tracker
        after it (tpu_sim/traffic.py).  The compiled TrafficPlan rides
        as a traced operand next to the FaultPlan — fault campaigns
        and serving load compose in one fused program, donation
        preserved (``donate`` consumes BOTH the sim state and the
        tracker).  ``tel``/``tel_spec`` (PR 8): record the per-round
        telemetry ring next to the tracker — returns ``(state, ts,
        tel)``.  Programs cache by ``TrafficSpec.program_key``, so a
        load sweep reuses one compiled program across rates."""
        key = (tspec.program_key, donate,
               telemetry.tel_key(tel, tel_spec, "broadcast"))
        if key not in self._traffic_progs:
            self._traffic_progs[key] = self._build_traffic(
                tspec, donate, tel_spec)
        return self._traffic_progs[key][2](state, ts,
                                           jnp.int32(n_rounds),
                                           tspec.compile(), tel)

    def audit_traffic_program(self, tspec, *, donate: bool = True,
                              tel_spec=None):
        """(jitted, example_args) of the traffic driver — the handle
        the contract auditor lowers (census + donation of the EXACT
        program :meth:`run_traffic` executes)."""
        key = (tspec.program_key, donate, tel_spec)
        if key not in self._traffic_progs:
            self._traffic_progs[key] = self._build_traffic(
                tspec, donate, tel_spec)
        prog, args_fn, _ = self._traffic_progs[key]
        state = self.init_state(
            np.zeros((self.n_nodes, self.n_words), np.uint32))
        tel = (telemetry.init_state(tel_spec) if tel_spec is not None
               else None)
        return prog, args_fn(state, self.traffic_state(tspec),
                             jnp.int32(4), tspec.compile(), tel)

    def converged(self, state: BroadcastState,
                  target: jnp.ndarray) -> bool:
        t = target[:, None] if self.words_major else target[None, :]
        return bool(jnp.all(state.received == t))

    def run(self, inject: np.ndarray, *, max_rounds: int = 1 << 16,
            check_every: int = 1) -> tuple[BroadcastState, int]:
        """Step until every node holds every injected value (or
        ``max_rounds``).  Returns (final state, rounds run).

        One host↔device sync per ``check_every`` rounds (the engine's
        host-driven convergence loop); use :meth:`run_fused` for a
        single-dispatch whole-run program.
        """
        target = self.target_bits(inject)
        return stepwise_converge(
            self.step, lambda s: self.converged(s, target),
            self.init_state(inject), max_rounds, check_every)

    def stage(self, inject: np.ndarray
              ) -> tuple[BroadcastState, jnp.ndarray]:
        """Upload a workload: (initial state, convergence target), both
        staged on device with their final shardings.  Lets a benchmark
        keep host->device transfer off the clock while still calling the
        public :meth:`run_staged`."""
        target = self.target_bits(inject)
        if self.mesh is not None and "words" in self.mesh.axis_names:
            target = shard_put(
                target, NamedSharding(self.mesh, P("words")))
        return self.init_state(inject), target

    def run_staged(self, state: BroadcastState, target: jnp.ndarray, *,
                   max_rounds: int = 1 << 16,
                   donate: bool = False) -> BroadcastState:
        """The whole-convergence device program on a pre-staged
        (state, target) pair from :meth:`stage` — one dispatch.  With
        ``donate`` the state's buffers are consumed (updated in place);
        the default keeps caller-owned staged states reusable."""
        key = (max_rounds, donate)
        if key not in self._fused:
            self._fused[key] = self._build_fused(max_rounds, donate)
        return self._fused[key](state, self.nbrs, self.nbr_mask, target)

    def run_fused(self, inject: np.ndarray, *, max_rounds: int = 1 << 16,
                  ) -> tuple[BroadcastState, int]:
        """Like :meth:`run` but the whole convergence loop executes as a
        single device program.  Returns (final state, rounds run).

        Donation-first: the state is staged internally and donated into
        the program, so the run holds ONE live state copy — this is the
        driver that brings the recorded ~3x live-buffer factor of the
        undonated fused programs (BENCH_ALL_r05.json OOM rows) toward
        1x."""
        state, target = self.stage(inject)
        final = self.run_staged(state, target, max_rounds=max_rounds,
                                donate=True)
        return final, int(final.t)

    def _wire_flood_parts(self, loop_fn, ledger_fn, masks):
        """Phase-split handles for benchmarks: the loop program is the
        only thing a timed sample should execute — the ledger program's
        reduces disturb the tunnel session (timing.py runs every sample
        before any finish).

        Donation note: with a donated ``loop_fn`` the input state's
        received/frontier buffers are consumed by the loop, so
        ``finish`` (and the composed runner) swap the loop OUTPUT back
        into the state pytree before the ledger program flattens it —
        passing the originals would read deleted buffers."""
        def finish(state0, loop_out):
            state0 = state0._replace(received=loop_out[0],
                                     frontier=loop_out[1])
            return ledger_fn(state0, *loop_out, *masks)

        def composed(state, nbrs, nbr_mask):
            return finish(state, loop_fn(state.received,
                                         state.frontier))

        return composed, (loop_fn, finish)

    def build_fixed(self, rounds: int, *, donate: bool = False):
        """Build (and cache) the fixed-trip runner for ``rounds``.
        Returns the phase-split handles ``(loop_fn, finish)`` when the
        pure-flood specialization applies (loop_fn: (received,
        frontier) -> (received, frontier); finish: (state0, loop_out)
        -> final state), else None (generic body, no split).  With
        ``donate`` the loop program consumes its inputs (engine.py) —
        chained callers must re-stage per chain."""
        key = (rounds, donate)
        if key not in self._fixed:
            self._fixed[key] = self._build_fixed(rounds, donate)
        return self._fixed[key][1]

    def run_staged_fixed(self, state: BroadcastState, rounds: int, *,
                         donate: bool = False) -> BroadcastState:
        """Exactly ``rounds`` rounds as one counter-only fori_loop
        program (see :meth:`_build_fixed`); the benchmark timed path.
        Bit-identical to :meth:`run_staged` when ``rounds`` is that
        run's convergence round count — callers re-verify with
        :meth:`converged`.  With ``donate`` the state is consumed."""
        self.build_fixed(rounds, donate=donate)
        return self._fixed[(rounds, donate)][0](state, self.nbrs,
                                                self.nbr_mask)

    def received_node_major(self, state: BroadcastState) -> np.ndarray:
        """(N, W) received bitset regardless of the internal layout
        (cross-process shards are replicated first — engine.host_view)."""
        rec = host_view(state.received)
        return rec.T if self.words_major else rec

    def server_msgs(self, state: BroadcastState) -> int:
        """Reference-accounted server-to-server message total (what the
        Maelstrom/harness ledger reads for the same run).  Available on
        the gather path and, given the matching ``sync_diff`` /
        ``sharded_sync_diff`` closures, on the words-major structured
        path too."""
        if state.srv_msgs is None:
            raise ValueError(
                "server-message ledger is off: srv_ledger=False, a "
                "words-major run without its sync_diff closure "
                "(structured.make_sync_diff / make_sharded_sync_diff), "
                "a delays composition, or a words-major FaultPlan "
                "beyond the loss-only regime (the bundle's coin rows "
                "have no crash liveness decomposition; loss AND crash "
                "plans keep the ledger on the gather path — crash "
                "cells charge-at-send — while loss-only plans keep it "
                "on words-major nemesis runs whose bundle carries the "
                "masked diff closures; dup streams reject at "
                "construction — see __init__)")
        return int(state.srv_msgs)

    def inject_mid(self, state: BroadcastState, node: int,
                   value: int) -> BroadcastState:
        """Mid-run client broadcast: set ``value`` at ``node`` so the
        next round floods it.  Charges the origin correction to the
        server ledger (an origin sends to ALL topology neighbors and is
        acked by every live one — one send + one ack more than the
        (deg-1)-charged learner the next flood round accounts it as).
        With ``srv_ledger=False`` there is no ledger to charge and the
        correction is skipped."""
        if self.words_major:
            raise ValueError("inject_mid targets the gather path")
        w, b = value // WORD, jnp.uint32(1 << (value % WORD))
        received = state.received.at[node, w].set(
            state.received[node, w] | b)
        frontier = state.frontier.at[node, w].set(
            state.frontier[node, w] | b)
        srv = (None if state.srv_msgs is None
               else state.srv_msgs + jnp.uint32(2))
        return state._replace(received=received, frontier=frontier,
                              srv_msgs=srv)

    def run_stats(self, inject: np.ndarray, *, max_rounds: int = 1 << 16,
                  ) -> tuple[BroadcastState, int, list[dict]]:
        """Like :meth:`run` but records per-round observability stats —
        the structured counterpart of Maelstrom's timeline plots (survey
        §5): known-bit totals (convergence progress) and the message
        ledger per round."""
        target = self.target_bits(inject)
        state = self.init_state(inject)
        stats: list[dict] = []
        prev_msgs = 0
        rounds = 0
        while rounds < max_rounds:
            state = self.step(state)
            rounds += 1
            known = int(jnp.sum(
                _popcount(state.received).astype(jnp.uint32)))
            msgs = int(state.msgs)
            stats.append({"round": rounds, "known_bits": known,
                          "msgs_round": msgs - prev_msgs,
                          "msgs_total": msgs})
            prev_msgs = msgs
            if self.converged(state, target):
                break
        return state, rounds, stats

    def read(self, state: BroadcastState) -> list[list[int]]:
        """Per-node sorted value lists (the ``read`` handler's reply,
        broadcast.go:124-132) — host-side, for checkers."""
        rec = self.received_node_major(state)
        out: list[list[int]] = []
        for i in range(rec.shape[0]):
            vals = []
            for w in range(rec.shape[1]):
                word = int(rec[i, w])
                while word:
                    b = word & -word
                    vals.append(w * WORD + b.bit_length() - 1)
                    word ^= b
            out.append(vals)
        return out


# -- scenario-axis batch hooks (PR 10, tpu_sim/scenario.py) --------------


def _build_batch_round(nbrs, nbr_mask, *, sync_every: int,
                       dup_on: bool, delay_set: tuple = ()):
    """Per-scenario round closure for the scenario-axis batch drivers:
    the gather-path :func:`_round` over SHARED adjacency with the
    scenario's OWN ``(plan, parts[, delays])`` traced operands —
    ``engine.scenario_program`` vmaps it over the leading scenario
    axis, so each scenario evaluates exactly its own padded fault
    data.  ``delay_set`` is the batch-wide static union of per-edge
    delay values (empty = 1-hop); ``dup_on`` is the batch-wide static
    dup switch (a scenario with ``dup_num == 0`` draws coins that
    never fire — bit-identical to a dup-off program)."""
    row_ids = jnp.arange(nbrs.shape[0], dtype=jnp.int32)

    if delay_set:
        def rnd_d(state, plan, parts, delays):
            return _round(state, row_ids=row_ids, nbrs=nbrs,
                          nbr_mask=nbr_mask, parts=parts,
                          sync_every=sync_every, delays=delays,
                          delay_set=delay_set, plan=plan,
                          dup_on=dup_on)
        return rnd_d

    def rnd(state, plan, parts):
        return _round(state, row_ids=row_ids, nbrs=nbrs,
                      nbr_mask=nbr_mask, parts=parts,
                      sync_every=sync_every, plan=plan, dup_on=dup_on)
    return rnd


def _batch_converged(state: BroadcastState, target,
                     member=None) -> jnp.ndarray:
    """() bool, traced — one scenario's convergence predicate (every
    node holds every target bit; node-major layout, the batch drivers
    run the gather path).  ``member`` ((N,) bool, PR 17) restricts the
    check to MEMBER rows — a left row holds nothing and a pre-join
    row held nothing, neither can (or must) converge."""
    ok = state.received == target[None, :]
    if member is None:
        return jnp.all(ok)
    return jnp.all(ok | ~member[:, None])


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def audit_contracts():
    """The broadcast drivers' :class:`~.audit.ProgramContract` rows:
    the gather path's bounded widen census (fault-free AND under a
    crash/loss plan), the words-major round's zero-collective and
    halo-sharded ppermute-only contracts, and the donated pure-flood
    loop's donation + memory contract."""
    from ..parallel.topology import to_padded_neighbors, tree
    from .audit import AuditProgram, ProgramContract
    from .engine import analytic_peak_bytes
    from .engine import operand_bytes as engine_operand_bytes
    from .structured import make_exchange, make_sharded_exchange

    n, nv = 64, 64

    def _nbrs():
        return to_padded_neighbors(tree(n, branching=4))

    def _built(sim):
        prog, args_fn = sim.audit_step_program()
        state, _ = sim.stage(make_inject(n, nv))
        return prog, args_fn(state)

    def gather_step(mesh):
        sim = BroadcastSim(_nbrs(), n_values=nv, srv_ledger=False,
                           mesh=mesh)
        return AuditProgram(*_built(sim))

    def gather_step_nem(mesh):
        spec = faults.NemesisSpec(n_nodes=n, seed=7,
                                  crash=((1, 3, (0, 5)),),
                                  loss_rate=0.1, loss_until=4,
                                  dup_rate=0.1, dup_until=4)
        sim = BroadcastSim(_nbrs(), n_values=nv, srv_ledger=False,
                           mesh=mesh, fault_plan=spec.compile())
        return AuditProgram(*_built(sim))

    def wm_sim(mesh):
        sharded = (make_sharded_exchange("tree", n, 8, branching=4)
                   if mesh is not None else None)
        return BroadcastSim(
            _nbrs(), n_values=nv, sync_every=1 << 20,
            srv_ledger=False, mesh=mesh,
            exchange=make_exchange("tree", n, branching=4),
            sharded_exchange=sharded)

    def wm_step(mesh):
        return AuditProgram(*_built(wm_sim(mesh)))

    def wm_nem_step(mesh):
        from .structured import make_nemesis
        spec = faults.NemesisSpec(n_nodes=n, seed=9,
                                  crash=((1, 3, (0, 5)),),
                                  loss_rate=0.15, loss_until=5,
                                  dup_rate=0.1, dup_until=5)
        nem = make_nemesis("tree", n, spec, n_shards=8, branching=4)
        sim = BroadcastSim(
            _nbrs(), n_values=nv, sync_every=4, srv_ledger=False,
            mesh=mesh, exchange=make_exchange("tree", n, branching=4),
            fault_plan=spec.compile(), nemesis=nem)
        return AuditProgram(*_built(sim))

    def traffic_wm_run(mesh):
        # a shape big enough that state dominates the per-round temps,
        # so the memory band audits the donated-footprint claim rather
        # than XLA's toy-shape buffer alignment
        nt, cl, k = 1024, 256, 8
        nv = cl * k
        tspec = traffic.TrafficSpec(
            n_nodes=nt, n_clients=cl, ops_per_client=k, until=8,
            rate=0.5, seed=11)
        sharded = (make_sharded_exchange("tree", nt, 8, branching=4)
                   if mesh is not None else None)
        sim = BroadcastSim(
            to_padded_neighbors(tree(nt, branching=4)), n_values=nv,
            sync_every=4, srv_ledger=False, mesh=mesh,
            exchange=make_exchange("tree", nt, branching=4),
            sharded_exchange=sharded)
        prog, args = sim.audit_traffic_program(tspec, donate=True)
        # the compiled header carries PER-SHARD parameter shapes, so
        # the declared donated bytes are the local blocks
        n_sh = 1 if mesh is None else 8
        w = nv // 32
        state_bytes = (2 * nt * w * 4            # received + frontier
                       + cl * 4 + 3 * cl * k * 4  # tracker leaves
                       ) // n_sh
        # claim: donated state + the traffic plan operand + one
        # transient payload copy per round (the exchange/visibility
        # temps); the band absorbs scheduling slack
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(tspec.compile()),
            slab_bytes=nt * w * 4 // n_sh)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def flood_donated(mesh):
        del mesh
        n2, nv2 = 1024, 4096                 # W = 128: state-dominated
        nbrs = to_padded_neighbors(tree(n2, branching=4))
        sim = BroadcastSim(nbrs, n_values=nv2, sync_every=1 << 20,
                           srv_ledger=False,
                           exchange=make_exchange("tree", n2,
                                                  branching=4))
        loop_fn, _finish = sim.build_fixed(4, donate=True)
        state, _ = sim.stage(make_inject(n2, nv2))
        state_bytes = 2 * n2 * (nv2 // 32) * 4   # received + frontier
        analytic = analytic_peak_bytes(state_bytes=state_bytes,
                                       donated=True)
        return AuditProgram(loop_fn, (state.received, state.frontier),
                            donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="broadcast/sharded-step-gather",
            build=gather_step,
            collectives={"all-gather": 1, "all-reduce": None},
            notes="gather path: ONE payload widen per round (received "
                  "never moves), ledger scalars psum"),
        ProgramContract(
            name="broadcast/sharded-step-gather-nem",
            build=gather_step_nem,
            collectives={"all-gather": 2, "all-reduce": None},
            notes="gather path under crash+loss+dup: the payload "
                  "widen plus the dup stream's source-set widen — the "
                  "plan must add no further gathers"),
        ProgramContract(
            name="broadcast/step-words-major",
            build=lambda mesh: wm_step(None),
            collectives={},
            needs_mesh=False,
            notes="single-device words-major round: ZERO collective "
                  "ops of any kind"),
        ProgramContract(
            name="broadcast/sharded-step-halo-wm",
            build=wm_step,
            collectives={"all-reduce": None,
                         "collective-permute": None},
            notes="halo-sharded words-major round: O(block) ppermute "
                  "halo exchanges only — NO all-gather (the "
                  "structured-path scale contract)"),
        ProgramContract(
            name="broadcast/sharded-step-halo-wm-nem",
            build=wm_nem_step,
            collectives={"all-reduce": None,
                         "collective-permute": None},
            notes="halo-sharded words-major round under the FULL "
                  "nemesis (crash+loss+dup, structured.make_nemesis): "
                  "the node-sharded mask decomposition adds ZERO "
                  "gathers — the PR 3 structured-path contract"),
        ProgramContract(
            name="broadcast/sharded-traffic-run-halo-wm",
            build=traffic_wm_run,
            collectives={"all-reduce": None,
                         "collective-permute": None},
            donation=True,
            mem_lo=0.2, mem_hi=6.0,
            notes="open-loop traffic driver on the halo words-major "
                  "path (PR 7): shard-local injection + the ppermute "
                  "reduce_and visibility fold add ZERO gathers, and "
                  "the (state, tracker) pytrees alias in place — the "
                  "injected-traffic census + donation contract"),
        ProgramContract(
            name="broadcast/fused-donated-flood",
            build=flood_donated,
            collectives={},
            donation=True,
            mem_lo=0.2, mem_hi=3.0,
            needs_mesh=False,
            notes="donated pure-flood fixed loop at W=128: the "
                  "(received, frontier) carry aliases in place; "
                  "compiled peak within band of 1x state + exchange "
                  "temps"),
    ]
