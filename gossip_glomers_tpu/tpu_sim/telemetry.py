"""Flight-recorder telemetry for the vectorized backend: a
device-resident per-round metrics ring that rides the donated fused
drivers the same way ``FaultPlan``/``TrafficPlan`` do.

Maelstrom's core deliverable beyond pass/fail is *observability* —
per-run timelines, msgs-per-op plots, latency series (PAPER.md survey
§5).  The repo reproduces that only for the slow host-side virtual
network (harness/tracing.py); the fused donated drivers — the whole
point of the TPU-native design — were black boxes between dispatch and
final state.  This module closes that gap without giving up a single
design invariant:

- **`TelemetrySpec`** (the `NemesisSpec`/`TrafficSpec` shape): a
  host-side JSON-able spec naming the workload, the ring capacity in
  rounds, and the recorded series (a subset of the workload's canonical
  series — unselected series are statically pruned, so XLA dead-codes
  their computation).  The spec is STATIC (it keys the compiled
  program); there is nothing to ``compile()`` — the carry is state.
- **`TelemetryState`**: a tiny ``(R, n_series)`` uint32 ring plus a
  written-rounds counter, carried through ``fori_rounds`` /
  ``scan_rounds`` next to the sim state and DONATED with it.  Each
  round, every shard computes its per-shard partials (popcounts,
  pending sums, tracker counts), globalizes them with the engine's
  existing ``reduce_sum`` psums — **zero all-gathers, zero host
  callbacks** — and writes one replicated row at ``t mod R``.  The
  recording step reads the round's input and output states and never
  feeds back into them, so telemetry-on programs are bit-exact to
  telemetry-off (pinned by tests/test_telemetry.py for all three sims,
  stepwise vs donated fused, single-device and 8-way mesh).
- **series conventions**: ``live_nodes`` and the ``*_bits``/``*_total``
  gauges are instantaneous values; ``msgs``, ``arrived``, ``issued``,
  ``completed``, ``deferred``, ``alloc_total``, ``kv_total`` are
  RUNNING TOTALS (the host differentiates for per-round rates), so one
  ring row cross-checks the final ledgers exactly — the conservation
  identities ``ring[msgs][-1] == state.msgs`` and ``arrived == issued
  + deferred`` hold at every recorded round
  (harness/checkers.py ``check_telemetry``).

The host side (harness/observe.py) turns a recorded run into run
manifests, Perfetto/Chrome-trace timelines, and — on checker failure —
a self-contained flight-recorder repro bundle.

Env knobs (loud parsing, the ``_env_int`` contract): ``GG_TELEMETRY``
(0/1 — default-off master switch the scenario runners consult) and
``GG_TELEMETRY_SERIES`` (comma-separated subset; unknown names raise a
ValueError NAMING the variable).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from typing import NamedTuple

from . import faults
from .engine import _env_int

# The module's host/device split, DECLARED (the PR-6 faults.py
# pattern): the determinism lint (tpu_sim/audit.py) treats exactly
# TRACED_EVALUATORS as traced scope; tests/test_telemetry.py pins the
# split TOTAL so new traced telemetry code can never dodge the lint.
TRACED_EVALUATORS = ("record", "live_count", "ring_stall_round",
                     "ring_progress_depth", "log2_bucket")
HOST_SIDE = (
    "series_names", "enabled", "env_series", "init_state",
    "state_specs", "ring_rows", "series_arrays", "default_spec",
    "tel_key", "signature_columns", "audit_contracts")

# canonical per-workload series, in ring-column order.  Totals vs
# gauges per the module docstring.  broadcast: frontier_bits = bits
# flooding OUT this round, new_bits = bits newly merged (the frontier
# entering the next round), known_bits = total received popcount.
# counter: flush attempts/acks per round and their difference (cas
# conflicts), pending backlog, the KV cell.  kafka: allocated sends
# (running total) and `present_bits` — the presence popcount at the
# WITNESS node (global row 0), which climbs to alloc_total exactly
# when replication to node 0 has caught up; `present_bits_full` is the
# full-cluster presence popcount (sum over ALL nodes), which
# re-streams the whole O(N·K·C) bitset every round — measured ~18%
# of the 1,024/10k sweep round in PR 8, so it is OPT-IN (see
# OPT_IN_SERIES): the default spec records the ~free witness gauge
# and the full scan runs only when named explicitly (a
# TelemetrySpec(series=...) subset or GG_TELEMETRY_SERIES).
SIM_SERIES = {
    "broadcast": ("live_nodes", "frontier_bits", "new_bits",
                  "known_bits", "msgs"),
    "counter": ("live_nodes", "pending_total", "flush_attempts",
                "flush_acks", "cas_conflicts", "kv_total", "msgs"),
    "kafka": ("live_nodes", "alloc_total", "present_bits",
              "present_bits_full", "msgs"),
}
# canonical series that a default spec (series=()) does NOT record:
# they stay in the ring layout (so explicit subsets can select them)
# but their per-round cost is opt-in — the PR-9 witness-default
# contract for kafka's full presence scan.
OPT_IN_SERIES = {
    "kafka": ("present_bits_full",),
}
# appended when the spec records an open-loop traffic run (PR 7):
# lifted straight from the TrafficState tracker's loud accounting
TRAFFIC_SERIES = ("arrived", "issued", "completed", "deferred")


def series_names(workload: str, traffic: bool = False) -> tuple:
    """The canonical ring-column names for one workload (+ the tracker
    columns when the run is open-loop)."""
    try:
        base = SIM_SERIES[workload]
    except KeyError:
        raise ValueError(
            f"unknown telemetry workload {workload!r}; one of "
            f"{sorted(SIM_SERIES)}") from None
    return base + (TRAFFIC_SERIES if traffic else ())


@dataclass(frozen=True)
class TelemetrySpec:
    """Host-side telemetry spec — JSON-able (:meth:`to_meta`), STATIC
    (it keys the compiled observed programs: ring capacity and the
    recorded-series mask are shapes/constants, not operands).

    ``rounds``: ring capacity R — rows write at ``t mod R``, so a run
    longer than R keeps the LAST R rounds (the flight-recorder
    semantics; ``TelemetryState.wrote`` counts total recorded rounds
    so the host can detect the wrap).  ``series``: subset of
    :func:`series_names` to record — unselected columns are statically
    zeroed, so XLA prunes their evaluation; an EMPTY subset selects
    every canonical series except the ``OPT_IN_SERIES`` (kafka's
    ``present_bits_full`` full-presence scan stays off unless named).
    ``traffic``: the run is open-loop (appends the tracker
    columns)."""

    workload: str
    rounds: int
    traffic: bool = False
    series: tuple = field(default_factory=tuple)

    def __post_init__(self) -> None:
        known = series_names(self.workload, self.traffic)
        if self.rounds < 1:
            raise ValueError("telemetry ring needs rounds >= 1")
        opt_in = OPT_IN_SERIES.get(self.workload, ())
        sel = tuple(self.series) or tuple(s for s in known
                                          if s not in opt_in)
        bad = [s for s in sel if s not in known]
        if bad:
            raise ValueError(
                f"unknown telemetry series {bad} for workload "
                f"{self.workload!r} (traffic={self.traffic}); known: "
                f"{list(known)}")
        # canonical order, duplicates dropped — the mask below indexes
        # ring columns positionally
        object.__setattr__(
            self, "series", tuple(s for s in known if s in sel))

    @property
    def names(self) -> tuple:
        """ALL ring-column names (the ring always carries the full
        canonical width so its layout never depends on the subset)."""
        return series_names(self.workload, self.traffic)

    @property
    def width(self) -> int:
        return len(self.names)

    @property
    def static_mask(self) -> tuple:
        """Per-column python bools (static): False columns record 0
        and their value expressions are dead-coded by XLA."""
        return tuple(n in self.series for n in self.names)

    def to_meta(self) -> dict:
        return {"workload": self.workload, "rounds": self.rounds,
                "traffic": self.traffic, "series": list(self.series)}

    @staticmethod
    def from_meta(meta: dict) -> "TelemetrySpec":
        return TelemetrySpec(
            workload=str(meta["workload"]), rounds=int(meta["rounds"]),
            traffic=bool(meta.get("traffic", False)),
            series=tuple(meta.get("series", ())))


class TelemetryState(NamedTuple):
    """The device carry: rides the DONATED state pytree of the
    observed drivers.  Replicated on a mesh (every shard computes the
    identical psum-globalized row)."""

    ring: jnp.ndarray    # (R, width) uint32 — row per recorded round
    wrote: jnp.ndarray   # () uint32 — total rounds recorded (wrap
    #                      detection: wrote > R means the ring holds
    #                      only the LAST R rounds)


def state_specs() -> TelemetryState:
    """shard_map in/out_specs: fully replicated."""
    return TelemetryState(P(None, None), P())


def init_state(spec: TelemetrySpec) -> TelemetryState:
    return TelemetryState(
        ring=jnp.zeros((spec.rounds, spec.width), jnp.uint32),
        wrote=jnp.uint32(0))


def record(tel: TelemetryState, t, vals, mask) -> TelemetryState:
    """Write one round's row at ``t mod R`` (traced).  ``vals`` must
    already be globalized (replicated psum results / replicated
    scalars) and match the spec's canonical column order; ``mask`` is
    the spec's STATIC per-column bool tuple — False columns are pruned
    at trace time."""
    row = jnp.stack(
        [jnp.asarray(v).astype(jnp.uint32) if keep else jnp.uint32(0)
         for v, keep in zip(vals, mask)])
    idx = lax.rem(jnp.asarray(t, jnp.int32),
                  jnp.int32(tel.ring.shape[0]))
    return TelemetryState(
        ring=lax.dynamic_update_slice_in_dim(tel.ring, row[None, :],
                                             idx, axis=0),
        wrote=tel.wrote + jnp.uint32(1))


def live_count(plan, t, n_nodes: int) -> jnp.ndarray:
    """() uint32 — nodes up at round ``t`` (traced).  Evaluated over
    the full global id range IDENTICALLY on every shard (the plan is
    replicated), so the result is replicated with no collective at
    all; a fault-free run records the constant N."""
    if plan is None:
        return jnp.uint32(n_nodes)
    ids = jnp.arange(n_nodes, dtype=jnp.int32)
    return jnp.sum(faults.node_up(plan, t, ids).astype(jnp.uint32),
                   dtype=jnp.uint32)


# -- ring-derived behavioral signature components (PR 13) -----------------
#
# The coverage observatory reduces a recorded run to a tiny integer
# signature WITHOUT new host callbacks: every component below reads the
# telemetry ring the run already carries.  The helpers assume the
# caller sized the ring to cover the whole run (rounds >= total driven
# rounds, the frontier runner contract) so row ``t`` IS round ``t`` —
# no wrap arithmetic in traced scope.


def ring_stall_round(ring, wrote, col: int, conv_round) -> jnp.ndarray:
    """() int32 — the FIRST recorded round ``t >= 1`` whose ``col``
    running total did not move (``ring[t, col] == ring[t-1, col]``)
    while the run was still unconverged (``conv_round < 0`` or
    ``t < conv_round``); -1 when the column climbs every pre-convergence
    round.  With ``col`` = the msgs ledger this is the first-divergence
    round of the signature: the round the protocol first went quiet
    before finishing (traced; replicated inputs -> replicated scalar,
    zero collectives)."""
    r = ring.shape[0]
    t = jnp.arange(r, dtype=jnp.int32)
    vals = ring[:, col]
    prev = jnp.concatenate([vals[:1], vals[:-1]])
    valid = (t >= 1) & (t < jnp.minimum(
        wrote.astype(jnp.int32), jnp.int32(r)))
    cr = jnp.asarray(conv_round, jnp.int32)
    unconv = (cr < 0) | (t < cr)
    stalled = valid & unconv & (vals == prev)
    first = jnp.min(jnp.where(stalled, t, jnp.int32(r)))
    return jnp.where(first >= r, jnp.int32(-1), first)


def ring_progress_depth(ring, wrote, col: int) -> jnp.ndarray:
    """() int32 — the LAST recorded round ``t >= 1`` whose ``col``
    value changed vs the previous row; -1 when the column is flat after
    round 0.  With ``col`` = the workload's progress gauge (broadcast
    ``known_bits``, counter ``kv_total``, kafka ``present_bits``) this
    is the critical-path depth of the dissemination: the final round
    at which NEW information still landed — for broadcast it equals the
    maximum provenance arrival round (pinned against
    ``provenance.depth_of`` by tests)."""
    r = ring.shape[0]
    t = jnp.arange(r, dtype=jnp.int32)
    vals = ring[:, col]
    prev = jnp.concatenate([vals[:1], vals[:-1]])
    valid = (t >= 1) & (t < jnp.minimum(
        wrote.astype(jnp.int32), jnp.int32(r)))
    changed = valid & (vals != prev)
    return jnp.max(jnp.where(changed, t, jnp.int32(-1)))


def log2_bucket(x, n_buckets: int = 14) -> jnp.ndarray:
    """() int32 — coarse log2 bucket for a signature component: -1 for
    negative sentinels, else the count of powers of two <= x (0 -> 0,
    1 -> 1, 2..3 -> 2, 4..7 -> 3, ... capped at ``n_buckets``).  A
    threshold sum, not a float log — traced, exact, branch-free."""
    xi = jnp.asarray(x, jnp.int32)
    b = jnp.int32(0)
    for k in range(n_buckets):
        b = b + jnp.where(xi >= jnp.int32(1 << k), 1, 0).astype(
            jnp.int32)
    return jnp.where(xi < 0, jnp.int32(-1), b)


def signature_columns(spec: TelemetrySpec) -> tuple[int, int]:
    """(msgs_col, progress_col) ring-column indices the signature
    evaluator reads for this spec's workload.  Loud contract: both
    columns must actually be RECORDED by the spec (a subset that
    dropped them would hand the evaluator statically-zeroed rows)."""
    progress = {"broadcast": "known_bits", "counter": "kv_total",
                "kafka": "present_bits"}[spec.workload]
    missing = [s for s in ("msgs", progress) if s not in spec.series]
    if missing:
        raise ValueError(
            f"behavioral signatures need telemetry series {missing} "
            f"recorded for workload {spec.workload!r}; got "
            f"series={list(spec.series)}")
    return spec.names.index("msgs"), spec.names.index(progress)


# -- env knobs ------------------------------------------------------------


def enabled(default: bool = False) -> bool:
    """The ``GG_TELEMETRY`` master switch (default OFF — telemetry
    costs a few extra state passes per round).  Loud contract: any
    value other than 0/1 raises a ValueError naming the variable."""
    raw = os.environ.get("GG_TELEMETRY")
    if raw is None:
        return default
    v = _env_int("GG_TELEMETRY", raw)
    if v not in (0, 1):
        raise ValueError(
            f"GG_TELEMETRY={v} must be 0 or 1 (telemetry off/on)")
    return bool(v)


def env_series(workload: str, traffic: bool = False) -> tuple | None:
    """The ``GG_TELEMETRY_SERIES`` subset filter (None = record all).
    Loud contract: a name that is not one of the workload's canonical
    series raises a ValueError naming the variable."""
    raw = os.environ.get("GG_TELEMETRY_SERIES")
    if raw is None:
        return None
    names = tuple(s.strip() for s in raw.split(",") if s.strip())
    known = series_names(workload, traffic)
    bad = [s for s in names if s not in known]
    if bad:
        raise ValueError(
            f"GG_TELEMETRY_SERIES names unknown series {bad} for "
            f"workload {workload!r} (traffic={traffic}); known: "
            f"{list(known)}")
    if not names:
        raise ValueError(
            "GG_TELEMETRY_SERIES is set but selects no series; unset "
            "it to record everything")
    return names


def default_spec(workload: str, rounds: int,
                 traffic: bool = False) -> TelemetrySpec:
    """The spec the scenario runners build when telemetry is switched
    on without an explicit spec: full canonical series, filtered by
    ``GG_TELEMETRY_SERIES`` if set."""
    sel = env_series(workload, traffic)
    return TelemetrySpec(workload=workload, rounds=max(1, rounds),
                         traffic=traffic, series=sel or ())


def tel_key(tel, tel_spec, workload: str):
    """Validate a traffic driver's ``(tel, tel_spec)`` pair (both or
    neither; the spec must name this workload with ``traffic=True``)
    and return the program-cache key component (the spec — it is the
    static shape)."""
    if (tel is None) != (tel_spec is None):
        raise ValueError(
            "pass tel and tel_spec together (build the ring with "
            "telemetry.init_state(spec))")
    if tel_spec is not None and (tel_spec.workload != workload
                                 or not tel_spec.traffic):
        raise ValueError(
            f"run_traffic telemetry needs TelemetrySpec(workload="
            f"{workload!r}, traffic=True), got {tel_spec.to_meta()}")
    return tel_spec


# -- host-side readout ----------------------------------------------------


def ring_rows(tel: TelemetryState,
              spec: TelemetrySpec) -> tuple[np.ndarray, int, bool]:
    """(rows, first_round, wrapped): the recorded rows in round order.
    ``rows[i]`` is round ``first_round + i``; with a wrap the ring
    holds only the last R rounds."""
    ring = np.asarray(tel.ring)
    wrote = int(tel.wrote)
    r = ring.shape[0]
    if wrote <= r:
        return ring[:wrote], 0, False
    head = wrote % r
    return np.concatenate([ring[head:], ring[:head]]), wrote - r, True


def series_arrays(tel: TelemetryState, spec: TelemetrySpec) -> dict:
    """{name: list[int]} for the RECORDED series, plus ``_round``
    (absolute round index per row) and ``_wrapped``.  The JSON-able
    payload the manifests / timelines / flight bundles carry."""
    rows, first, wrapped = ring_rows(tel, spec)
    out: dict = {
        "_round": list(range(first, first + rows.shape[0])),
        "_wrapped": wrapped,
    }
    for i, name in enumerate(spec.names):
        if name in spec.series:
            out[name] = [int(v) for v in rows[:, i]]
    return out


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def audit_contracts():
    """Telemetry-on driver rows: the observed fused drivers of all
    three sims under a crash+loss plan must stay all-gather-free
    (cap-0 census — telemetry rides psum-of-partials only), keep the
    donation alias table covering BOTH the sim state and the telemetry
    carry, and sit inside the analytic memory band extended by the
    ring bytes (``engine.analytic_peak_bytes``)."""
    from ..parallel.topology import to_padded_neighbors, tree
    from .audit import AuditProgram, ProgramContract
    from .broadcast import BroadcastSim
    from .counter import CounterSim
    from .engine import analytic_peak_bytes
    from .engine import operand_bytes as engine_operand_bytes
    from .kafka import KafkaSim
    from .structured import make_exchange, make_nemesis

    def _spec(n):
        return faults.NemesisSpec(
            n_nodes=n, seed=5, crash=((2, 4, (1, n // 2)),),
            loss_rate=0.1, loss_until=6)

    def counter_obs(mesh):
        n = 1024
        tspec = TelemetrySpec("counter", rounds=16)
        sim = CounterSim(n, mode="cas", poll_every=2, mesh=mesh,
                         fault_plan=_spec(n).compile())
        prog, args = sim.audit_observed_program(tspec)
        n_sh = 1 if mesh is None else 8
        state_bytes = 2 * n * 4 // n_sh
        tel_bytes = tspec.rounds * tspec.width * 4
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes + tel_bytes,
            operand_bytes=engine_operand_bytes(sim.fault_plan))
        return AuditProgram(prog, args,
                            donated_bytes=state_bytes + tel_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def broadcast_obs(mesh):
        n, nv = 256, 256
        spec = _spec(n)
        tspec = TelemetrySpec("broadcast", rounds=16)
        n_sh = None if mesh is None else 8
        sim = BroadcastSim(
            to_padded_neighbors(tree(n, branching=4)), n_values=nv,
            sync_every=4, srv_ledger=False, mesh=mesh,
            exchange=make_exchange("tree", n, branching=4),
            fault_plan=spec.compile(),
            nemesis=make_nemesis("tree", n, spec, n_shards=n_sh,
                                 branching=4))
        prog, args = sim.audit_observed_program(tspec)
        div = 1 if mesh is None else 8
        state_bytes = 2 * n * (nv // 32) * 4 // div
        tel_bytes = tspec.rounds * tspec.width * 4
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes + tel_bytes,
            operand_bytes=engine_operand_bytes(sim.fault_plan),
            slab_bytes=n * (nv // 32) * 4 // div)
        return AuditProgram(prog, args,
                            donated_bytes=state_bytes + tel_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def kafka_obs(mesh):
        n, k, cap = 64, 8, 64
        tspec = TelemetrySpec("kafka", rounds=16)
        # union_block pins the BLOCKED streaming union (the PR-5
        # gather-free path; "auto" would keep this small shape on the
        # materialized path, whose 3 metadata widens are the oracle's)
        sim = KafkaSim(n, k, capacity=cap, max_sends=2,
                       fault_plan=_spec(n).compile(),
                       resync_every=4, union_block=4, mesh=mesh)
        prog, args = sim.audit_observed_program(tspec)
        n_sh = 1 if mesh is None else 8
        wc = (cap + 31) // 32
        state_bytes = (n * k * wc * 4 + n * k * 4) // n_sh \
            + k * cap * 4 + k * 4
        tel_bytes = tspec.rounds * tspec.width * 4
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes + tel_bytes,
            operand_bytes=engine_operand_bytes(sim.fault_plan),
            slab_bytes=(n // n_sh) * n * 2 * 4 + (n // n_sh) * k * wc * 4)
        return AuditProgram(prog, args,
                            donated_bytes=state_bytes + tel_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="counter/observed-run",
            build=counter_obs,
            collectives={"all-reduce": None},
            donation=True,
            mem_lo=0.05, mem_hi=8.0,
            notes="telemetry-on donated counter driver under "
                  "crash+loss: the per-round series are psums of "
                  "per-shard partials — NO gather, no ppermute; the "
                  "(state, ring) pytrees alias in place"),
        ProgramContract(
            name="broadcast/observed-run-halo-wm-nem",
            build=broadcast_obs,
            collectives={"all-reduce": None,
                         "collective-permute": None},
            donation=True,
            mem_lo=0.05, mem_hi=8.0,
            notes="telemetry-on words-major nemesis driver: the ring "
                  "rides the halo path's psums — ZERO added gathers "
                  "(the PR-3/PR-8 composed contract)"),
        ProgramContract(
            name="kafka/observed-run-union-nem",
            build=kafka_obs,
            collectives={"all-reduce": None,
                         "collective-permute": None},
            donation=True,
            mem_lo=0.05, mem_hi=8.0,
            notes="telemetry-on faulted origin-union driver: presence "
                  "popcount partials psum next to the existing "
                  "reduce-or circuit — the sharded observed step "
                  "stays all-gather-free"),
    ]
