"""Shared benchmark timing for broadcast convergence runs.

One pattern, used by bench.py and benchmarks/run_all.py: run exactly
the convergence round count (host-computed, :func:`discover_rounds`) as
a counter-only ``fori_loop`` program with a pure exchange+merge body
(``BroadcastSim._build_fixed``'s flood specialization; ledgers are
recovered exactly post-loop in closed form), and measure it with
CHAINED AMORTIZED timing — host->device upload stays off the clock the
way Maelstrom timings exclude process startup (reference README.md:16
methodology).

Why chained (measured on the remote-TPU tunnel, see ARCHITECTURE.md
"Timing methodology"): every BLOCKING POINT — a D2H transfer such as
``np.asarray``/`int()` on a device value, or the per-iteration
condition fetch of a data-dependent ``while_loop`` — costs ~100 ms of
tunnel round-trip, swamping millisecond device programs; worse, in the
session's initial async mode ``block_until_ready`` can return BEFORE
the compute has run, so naive per-call timing lies fast (sub-artifact
"0.1 ms" readings for half-gigabyte workloads), while after any D2H
the session turns synchronous and per-call timing lies slow (~100 ms
floor; the state decays after minutes of idle).  Chaining K
data-dependent calls behind a single completion fence and differencing
two chain lengths cancels the per-blocking-point term and is correct
in both modes.  Data dependency between calls forces real execution;
after the first convergence the state is saturated, but the dense
bitwise round work is identical, so the amortized per-call time is the
steady-state convergence time.  Round counts are computed on the host
so no data-dependent while program ever needs to run, and
finish/validation readbacks happen only after all samples
(:class:`TimedRun` + :func:`bench_structured` enforce the schedule).
"""

from __future__ import annotations

import time

import numpy as np


def structured_sim(topology: str, n: int, n_values: int, *,
                   sync_every: int = 64, srv_ledger: bool = False,
                   parts=None, **kw):
    """A words-major structured BroadcastSim on the picked mesh (halo
    exchanges on >1 device), ledger off by default — the sync-diff
    accounting runs every round under jit, so timed runs keep it out
    (see structured.py's sync-diff cost note).

    ``parts`` (broadcast.Partitions, windows in rounds): run the
    schedule on the structured path via the masked-exchange bundle
    (structured.make_faulted) — Maelstrom's partition nemesis at any
    scale without falling back to the gather path."""
    from ..parallel.mesh import pick_mesh
    from .broadcast import BroadcastSim
    from .structured import (make_exchange, make_faulted,
                             make_sharded_exchange,
                             make_sharded_sync_diff, make_sync_diff)

    mesh = pick_mesh()
    sharded = sharded_diff = None
    if mesh is not None:
        sharded = make_sharded_exchange(topology, n, mesh.size, **kw)
        sharded_diff = make_sharded_sync_diff(topology, n, mesh.size,
                                              **kw)
    faulted = None
    if parts is not None and parts.starts.shape[0] > 0:
        faulted = make_faulted(
            topology, n, np.asarray(parts.group),
            n_shards=mesh.size if mesh is not None else None, **kw)
    return BroadcastSim(
        _nbrs_for(topology, n, **kw), n_values=n_values,
        sync_every=sync_every, mesh=mesh,
        parts=parts,
        exchange=make_exchange(topology, n, **kw),
        sharded_exchange=sharded,
        srv_ledger=srv_ledger,
        sync_diff=make_sync_diff(topology, n, **kw) if srv_ledger
        else None,
        sharded_sync_diff=sharded_diff if srv_ledger else None,
        faulted=faulted)


def discover_rounds(topology: str, n: int, n_values: int, **kw) -> int:
    """Host-only convergence round count for a structured flood — no
    device program runs, keeping the benchmark process session-clean.

    Rounds-to-convergence = max over injected values of the
    eccentricity of the value's origin (origins are round-robin
    ``v % n``):
    - tree: exact ecc(o) — for each ancestor a of o, the farthest node
      whose path to o turns at a is the deepest descendant of a
      outside the branch containing o (heap indexing makes subtree
      depth ranges closed-form; cross-checked against BFS in
      test_discover_rounds_tree_matches_bfs);
    - circulant / ring: vertex-transitive, so ecc is the same for
      every origin — one numpy BFS over the stride graph gives it;
    - line: ecc(o) = max(o, n-1-o);
    - grid (ragged, grid_cols columns): Manhattan ecc over the corner
      candidates of the staircase-convex cell region.
    Validated post-run: :meth:`TimedRun.finish` asserts the result
    actually converged and falls back to device discovery if not (that
    self-heals an under-estimate; the formulas here are exact, which
    the tests pin, so an over-estimate cannot occur)."""
    if topology == "tree":
        k = kw.get("branching", 4)

        def depth(i: int) -> int:
            d = 0
            while i > 0:
                i = (i - 1) // k
                d += 1
            return d

        def submax(a: int) -> int:
            # depth of the deepest descendant of node a
            lo = hi = a
            d = depth(a)
            while True:
                lo, hi = k * lo + 1, k * hi + k
                if lo > n - 1:
                    return d
                hi = min(hi, n - 1)
                d += 1

        def ecc(o: int) -> int:
            best = submax(o) - depth(o)          # down o's own subtree
            child, a = o, (o - 1) // k
            while o > 0:
                da = depth(a)
                m = max((submax(c)
                         for c in range(k * a + 1,
                                        min(k * a + k, n - 1) + 1)
                         if c != child), default=da)
                best = max(best, (depth(o) - da) + (m - da))
                if a == 0:
                    break
                child, a = a, (a - 1) // k
            return best

        return max(ecc(v % n) for v in range(min(n_values, n)))
    if topology in ("circulant", "ring"):
        strides = [1] if topology == "ring" else list(kw["strides"])
        reach = np.zeros(n, bool)
        reach[0] = True
        frontier = reach.copy()
        rounds = 0
        while not reach.all():
            new = np.zeros(n, bool)
            for s in strides:
                new |= np.roll(frontier, s) | np.roll(frontier, -s)
            frontier = new & ~reach
            if not frontier.any():
                raise ValueError("circulant strides do not connect")
            reach |= frontier
            rounds += 1
        return rounds
    if topology == "line":
        return max(max(v % n, n - 1 - v % n)
                   for v in range(min(n_values, n)))
    if topology == "grid":
        from ..parallel.topology import grid_cols

        cols = kw.get("cols") or grid_cols(n)
        rows = (n + cols - 1) // cols
        last = n - (rows - 1) * cols       # width of the ragged last row

        def ecc(o: int) -> int:
            r0, c0 = divmod(o, cols)
            best = 0
            for r in (0, rows - 1):
                w = cols if r < rows - 1 else last
                for c in (0, w - 1):
                    best = max(best, abs(r - r0) + abs(c - c0))
            # the ragged corner (cols-1 of the second-to-last row) can
            # exceed all four outer corners when the last row is short
            if last < cols and rows >= 2:
                best = max(best, abs(rows - 2 - r0) + abs(cols - 1 - c0))
            return best

        return max(ecc(v % n) for v in range(min(n_values, n)))
    raise ValueError(topology)


class TimedRun:
    """One convergence benchmark, phase-split: :meth:`prepare` stages
    inputs and compiles+warms the loop program, :meth:`sample` times it
    (loop program ONLY — no ledgers, no reductions), :meth:`finish`
    assembles the final state, verifies convergence, and computes the
    closed-form message ledger.  Callers run every sample before any
    finish (see module docstring)."""

    def __init__(self, sim, inject: np.ndarray, rounds: int) -> None:
        self.sim, self.inject, self.rounds = sim, inject, rounds
        self.samples: list[float] = []

    def prepare(self) -> None:
        import jax

        self.state0, self.target = self.sim.stage(self.inject)
        jax.block_until_ready(self.state0.received)
        self.parts = self.sim.build_fixed(self.rounds)
        if self.parts is None:           # generic body, no split
            out = self.sim.run_staged_fixed(self.state0, self.rounds)
            jax.block_until_ready(out.received)
        else:
            loop_fn, _ = self.parts
            out = loop_fn(self.state0.received, self.state0.frontier)
            jax.block_until_ready(out[0])

    def sample(self, repeats: int = 3) -> None:
        import jax

        # Chained amortized timing (see chained_time) needs a real
        # accelerator: on the CPU test backend the tunnel artifacts it
        # cancels don't exist, and long chains of shard_map collective
        # programs can abort XLA's CPU runtime — use plain per-call
        # timing there (and for the generic un-split body).
        chained = (self.parts is not None
                   and jax.devices()[0].platform != "cpu")
        if chained:
            loop_fn = self.parts[0]
            s0 = self.state0
            self.samples.extend(_chained_samples(
                lambda out: loop_fn(*out), (s0.received, s0.frontier),
                lambda out: np.asarray(out[0][:1, :1]), repeats))
        else:
            for _ in range(max(1, repeats)):
                s0, _ = self.sim.stage(self.inject)
                jax.block_until_ready(s0.received)
                t0 = time.perf_counter()
                if self.parts is None:
                    out = self.sim.run_staged_fixed(s0, self.rounds)
                    jax.block_until_ready(out.received)
                else:
                    out = self.parts[0](s0.received, s0.frontier)
                    jax.block_until_ready(out[0])
                self.samples.append(time.perf_counter() - t0)
            if self.parts is None:
                self._last, self._last_s0 = out, s0
                return
        # one fresh single call for finish()/validation (not timed)
        s1, _ = self.sim.stage(self.inject)
        jax.block_until_ready(s1.received)
        self._last = self.parts[0](s1.received, s1.frontier)
        self._last_s0 = s1

    def finish(self):
        """(median_s, rounds, final_state); re-discovers and re-times
        on device if the host-computed round count was wrong."""
        if self.parts is None:
            state = self._last
        else:
            state = self.parts[1](self._last_s0, self._last)
        if not self.sim.converged(state, self.target):
            _, true_rounds = self.sim.run(self.inject)
            assert true_rounds != self.rounds, \
                "fixed runner diverged from run()"
            retry = TimedRun(self.sim, self.inject, true_rounds)
            retry.prepare()
            retry.sample(max(1, len(self.samples)))
            return retry.finish()
        assert int(state.t) == self.rounds
        return (sorted(self.samples)[len(self.samples) // 2],
                self.rounds, state)


def bench_structured(n: int, entries, repeats: int = 3) -> dict:
    """Run several structured convergence benchmarks with the session-
    clean two-phase schedule.  ``entries``: (name, topology, n_values,
    kw, n_dirs) tuples.  Returns {name: {wall_s, rounds, ms_per_round,
    gbytes_per_s_lb}} — gbytes_per_s_lb is a logical-traffic lower
    bound on achieved HBM bandwidth in GIGABYTES/s: what a perfectly
    fused round must stream (read received+frontier, write
    received+frontier, plus one full-bitset payload read per exchange
    direction)."""
    from .broadcast import make_inject

    runs = []
    for name, topo, nv, kw, n_dirs in entries:
        sim = structured_sim(topo, n, nv, **kw)
        tr = TimedRun(sim, make_inject(n, nv),
                      discover_rounds(topo, n, nv, **kw))
        tr.prepare()
        tr.sample(repeats)
        runs.append((name, nv, n_dirs, tr, sim))
    out: dict = {}
    for name, nv, n_dirs, tr, sim in runs:  # finishes AFTER sampling
        dt, rounds, state = tr.finish()
        bitset_gb = n * (nv // 32) * 4 / 1e9
        entry = {
            "wall_s": round(dt, 4), "rounds": rounds,
            "ms_per_round": round(dt / rounds * 1e3, 3),
            "gbytes_per_s_lb": round(
                (4 + n_dirs) * bitset_gb * rounds / dt, 1),
            "_state": state}
        # `_state.msgs` is a device uint32 and WRAPS mod 2^32 in the
        # many-values regime (e.g. W=128 circulant ~7e10 true sends).
        # For pure-flood runs (the only runs this benchmark times) the
        # ledger has a closed form over the final state — recompute it
        # unwrapped: per-node popcount delta reduced ON DEVICE (the
        # full bitsets would be a ~1 GB D2H at W=128; the (N,) delta is
        # ~4 MB), final int64 dot on the host.  Max delta per node is
        # W*32 <= 4096, so int32 cannot overflow on device.
        if tr.parts is not None:
            dpc = np.asarray(
                _dpc_fn(sim.words_major)(state.received,
                                         state.frontier),
                dtype=np.int64)
            entry["msgs64"] = int(
                (sim._host_deg.astype(np.int64) * dpc).sum())
        out[name] = entry
    return out


def _dpc_fn(words_major: bool):
    """Jitted (received, frontier) -> per-node popcount delta (N,)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    axis = 0 if words_major else 1

    @jax.jit
    def dpc(rec, fr):
        return (lax.population_count(rec).astype(jnp.int32).sum(axis=axis)
                - lax.population_count(fr).astype(jnp.int32).sum(axis=axis))

    return dpc


def _chain_diff(chain, k1: int, k2: int, attempts: int = 3) -> float:
    """One amortized sample (t(k2) - t(k1)) / (k2 - k1), re-measured
    when a session hiccup makes the difference non-positive — a
    garbage sample must be discarded, not clamped into a fake ~0."""
    for _ in range(attempts):
        t1, t2 = chain(k1), chain(k2)
        if t2 > t1:
            return (t2 - t1) / (k2 - k1)
    raise RuntimeError(
        f"chained timing unstable: t({k2}) <= t({k1}) "
        f"{attempts} times in a row")


def _chained_samples(step, out0, fence, repeats: int = 3,
                     target_s: float = 0.6, reset=None) -> list:
    """``repeats`` amortized per-call samples of ``step`` (out -> out,
    data-dependent), with ``fence(out)`` forcing completion via a tiny
    D2H read.  Per-blocking-point overhead cancels in the chain-length
    difference (module docstring); one untimed warm call first so the
    k-calibration estimate never includes compile time.

    ``reset``: zero-arg factory returning a fresh staged chain start —
    REQUIRED when ``step`` is a donated program (engine.py): donation
    consumes each chain's input, so restarting a chain from a shared
    ``out0`` would read deleted buffers.  The factory runs off the
    clock (staging cost excluded, like ``out0``'s upload)."""
    src = (lambda: out0) if reset is None else reset
    fence(step(src()))                       # warm / compile, untimed

    def chain(k: int) -> float:
        out = src()
        t0 = time.perf_counter()
        for _ in range(k):
            out = step(out)
        fence(out)
        return time.perf_counter() - t0

    est = max(chain(2) / 2, 1e-5)
    k1 = min(max(2, int(round(target_s / est))), 16)
    k2 = 4 * k1
    return [_chain_diff(chain, k1, k2) for _ in range(max(1, repeats))]


def chained_time(step, out0, fence, repeats: int = 3,
                 target_s: float = 0.6, reset=None) -> float:
    """Median amortized per-call seconds of ``step`` — the chained
    methodology (module docstring) for non-broadcast sims (counter,
    kafka); :meth:`TimedRun.sample` uses the same sampler.  Pass
    ``reset`` (fresh-state factory) when ``step`` donates its input."""
    samples = _chained_samples(step, out0, fence, repeats, target_s,
                               reset)
    return sorted(samples)[len(samples) // 2]


def timed_convergence(sim, inject: np.ndarray, repeats: int = 3,
                      rounds: int | None = None):
    """(elapsed_s, rounds, final_state) for one convergence benchmark
    of ``sim`` on ``inject`` — single-run convenience over
    :class:`TimedRun`.  Pass ``rounds`` from :func:`discover_rounds`
    to keep the process session-clean; with ``rounds=None`` the count
    is discovered by a host-stepped device run first (fine off-tunnel,
    e.g. the CPU test mesh).  The MEDIAN of ``repeats`` samples is
    reported, so one anomalous sample (async-dispatch hiccup, tunnel
    jitter) cannot become the recorded number in either direction."""
    if rounds is None:
        _, rounds = sim.run(inject)
    tr = TimedRun(sim, inject, rounds)
    tr.prepare()
    tr.sample(repeats)
    return tr.finish()


def words_axis_entries(n: int, n_values: int, *, branching: int = 4,
                       strides_seed: int = 0) -> list:
    """The (name, topology, n_values, kw, n_dirs) entries of the
    many-values regime — THE single definition of its traffic model,
    consumed by :func:`words_axis_regime` (run_all config 6) and
    prepended to bench.py's entry list, so the two cannot drift."""
    from ..parallel.topology import expander_strides

    strides = expander_strides(n, degree=8, seed=strides_seed)
    return [("tree", "tree", n_values, {"branching": branching},
             branching + 1),
            ("circulant", "circulant", n_values, {"strides": strides},
             2 * len(strides))]


def format_words_regime(res: dict, n_values: int) -> dict:
    """Public w128-style dict from a :func:`bench_structured` result
    holding the :func:`words_axis_entries` names."""
    out = {"n_values": n_values}
    for name in ("tree", "circulant"):
        out[name] = {k: v for k, v in res[name].items()
                     if not k.startswith("_")}
    return out


def words_axis_regime(n: int = 1 << 20, n_values: int = 4096, *,
                      branching: int = 4, strides_seed: int = 0) -> dict:
    """The many-values regime (W = n_values/32 bitset words per node):
    timed convergence on tree and circulant structured exchanges."""
    res = bench_structured(
        n, words_axis_entries(n, n_values, branching=branching,
                              strides_seed=strides_seed))
    return format_words_regime(res, n_values)


def _nbrs_for(topology: str, n: int, **kw) -> np.ndarray:
    from ..parallel.topology import (circulant, grid, line, ring,
                                     to_padded_neighbors, tree)

    if topology == "tree":
        return to_padded_neighbors(
            tree(n, branching=kw.get("branching", 4)))
    if topology == "circulant":
        return circulant(n, list(kw["strides"]))
    if topology == "grid":
        # cols threads through so adjacency, exchange, and
        # discover_rounds can never disagree on the grid shape
        return to_padded_neighbors(grid(n, kw.get("cols")))
    if topology in ("ring", "line"):
        builder = {"ring": ring, "line": line}[topology]
        return to_padded_neighbors(builder(n))
    raise ValueError(topology)
