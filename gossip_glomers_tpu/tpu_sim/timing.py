"""Shared benchmark timing for broadcast convergence runs.

One pattern, used by bench.py and benchmarks/run_all.py: compile + warm
the fused whole-convergence device program, re-stage the workload on
device, then time exactly the staged program start-to-observed-end —
host->device upload stays off the clock the way Maelstrom timings
exclude process startup (reference README.md:16 methodology).
"""

from __future__ import annotations

import time

import numpy as np


def timed_convergence(sim, inject: np.ndarray, repeats: int = 3):
    """(elapsed_s, rounds, final_state) for a fused convergence run of
    ``sim`` (a BroadcastSim) on the ``inject`` workload.  The timed
    region runs ``repeats`` times and the MEDIAN is reported — one
    anomalous sample (async-dispatch hiccup, tunnel jitter) must not
    become the recorded number in either direction."""
    import jax

    state, _ = sim.run_fused(inject)            # compile + warm
    jax.block_until_ready(state.received)
    samples = []
    for _ in range(max(1, repeats)):
        state0, target = sim.stage(inject)
        jax.block_until_ready(state0.received)
        t0 = time.perf_counter()
        state = sim.run_staged(state0, target)
        jax.block_until_ready(state.received)
        samples.append(time.perf_counter() - t0)
    assert sim.converged(state, target), "benchmark run did not converge"
    return sorted(samples)[len(samples) // 2], int(state.t), state


def structured_sim(topology: str, n: int, n_values: int, *,
                   sync_every: int = 64, srv_ledger: bool = False,
                   **kw):
    """A words-major structured BroadcastSim on the picked mesh (halo
    exchanges on >1 device), ledger off by default — the sync-diff
    accounting runs every round under jit, so timed runs keep it out
    (see structured.py's sync-diff cost note)."""
    from ..parallel.mesh import pick_mesh
    from .broadcast import BroadcastSim
    from .structured import (make_exchange, make_sharded_exchange,
                             make_sharded_sync_diff, make_sync_diff)

    mesh = pick_mesh()
    sharded = sharded_diff = None
    if mesh is not None:
        sharded = make_sharded_exchange(topology, n, mesh.size, **kw)
        sharded_diff = make_sharded_sync_diff(topology, n, mesh.size,
                                              **kw)
    return BroadcastSim(
        _nbrs_for(topology, n, **kw), n_values=n_values,
        sync_every=sync_every, mesh=mesh,
        exchange=make_exchange(topology, n, **kw),
        sharded_exchange=sharded,
        srv_ledger=srv_ledger,
        sync_diff=make_sync_diff(topology, n, **kw) if srv_ledger
        else None,
        sharded_sync_diff=sharded_diff if srv_ledger else None)


def words_axis_regime(n: int = 1 << 20, n_values: int = 4096, *,
                      branching: int = 4, strides_seed: int = 0) -> dict:
    """The many-values regime (W = n_values/32 bitset words per node):
    timed convergence on tree and circulant structured exchanges.
    ``gbytes_per_s_lb`` is a logical-traffic lower bound on achieved
    HBM bandwidth in GIGABYTES/s: what a perfectly fused round must
    stream — read received+frontier, write received+frontier, plus one
    full-bitset payload read per exchange direction.  Shared by
    bench.py's ``w128`` key and benchmarks/run_all.py config 6 so the
    traffic model cannot drift between them."""
    from ..parallel.topology import expander_strides
    from .broadcast import make_inject

    inject = make_inject(n, n_values)
    bitset_gb = n * (n_values // 32) * 4 / 1e9     # one (W, N) array
    strides = expander_strides(n, degree=8, seed=strides_seed)
    out: dict = {"n_values": n_values}
    for topo, kw, n_dirs in (
            ("tree", {"branching": branching}, branching + 1),
            ("circulant", {"strides": strides}, 2 * len(strides))):
        sim = structured_sim(topo, n, n_values, **kw)
        dt, rounds, _ = timed_convergence(sim, inject)
        out[topo] = {
            "wall_s": round(dt, 4), "rounds": rounds,
            "ms_per_round": round(dt / rounds * 1e3, 3),
            "gbytes_per_s_lb": round(
                (4 + n_dirs) * bitset_gb * rounds / dt, 1)}
    return out


def _nbrs_for(topology: str, n: int, **kw) -> np.ndarray:
    from ..parallel.topology import circulant, to_padded_neighbors, tree

    if topology == "tree":
        return to_padded_neighbors(
            tree(n, branching=kw.get("branching", 4)))
    if topology == "circulant":
        return circulant(n, list(kw["strides"]))
    raise ValueError(topology)
