"""Elastic resharding: resize a run's padded node axis at a
checkpoint boundary (PR 17).

Membership events (join/leave) compile to two ``(N,)`` columns of the
:class:`~.faults.FaultPlan` and fold into every liveness gate
(tpu_sim/faults.py ``node_up``/``member_at``).  That makes a *resize*
expressible two ways, and this module owns the bridge between them:

- **In-place, at fixed capacity**: a grow is a block of padded rows
  JOINING at the resize round (they enter empty and catch up through
  the workload's own anti-entropy); a shrink is a block of rows
  LEAVING (they drain, then their liveness goes down and stays down).
  This form batches — a scenario-sharded campaign runs grow/shrink
  cells next to crash/loss cells in ONE compiled program, because the
  padded capacity never changes shape.
- **Across a checkpoint boundary, at a NEW capacity**:
  :func:`restore_resized` reloads a mid-run checkpoint
  (tpu_sim/checkpoint.py — the fault spec rides the meta) into a
  LARGER or SMALLER padded node axis: grown rows enter as empty
  padded rows that join at the boundary round; shrunk-away rows must
  already be non-members (validated loudly — :func:`resize_spec`
  names any still-member row).  The continuation spec it returns is
  the SAME spec the in-place form would run at the new capacity from
  round 0, which is why the two forms are bit-exact twins for
  capacity-independent dynamics (full-topology broadcast, the
  counter's shared-KV path) — harness/membership.py pins it.

Re-homing (the PR-14 stateless-hash KV routing under resize): key
ownership is a pure function of ``(key, n_nodes, seed)``, so a resize
moves exactly the keys whose hash changes home — :func:`rehomed_keys`
(host) and :func:`rehomed_mask` (device) compute that diff
independently and must agree bit-for-bit; :func:`apply_rehoming`
carries the KV registers across the boundary and the moved-key set it
implies is verified against both.

Host/device split, DECLARED (the PR-6 faults.py pattern).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import checkpoint, faults, kvstore

TRACED_EVALUATORS = ("member_census", "rehomed_mask")
HOST_SIDE = ("resize_spec", "resize_state", "restore_resized",
             "rehomed_keys", "apply_rehoming", "audit_contracts")

# Which leaves of each sim state carry the padded NODE axis (and on
# which dimension) — the only leaves a resize reshapes.  Everything
# else (round counters, message ledgers, the shared KV scalar, the
# (K, C) kafka log) is capacity-independent and carries over as-is.
_NODE_AXIS: dict[str, dict[str, int]] = {
    "BroadcastState": {"received": 0, "frontier": 0},
    "CounterState": {"pending": 0, "cached": 0},
    "KafkaState": {"present": 0, "local_committed": 0,
                   "origin_bits": 0},
}
# leaves a resize refuses to carry (loudly): the delay-ring history is
# a sliding window of PAST node-axis payloads — replaying it at a new
# capacity would fabricate deliveries that never happened; device KV
# rows move homes entirely (apply_rehoming), not by pad/truncate.
_REJECT_LEAVES: dict[str, dict[str, str]] = {
    "BroadcastState": {
        "history": "the per-edge delay ring holds PAST payload blocks "
                   "at the old capacity — resize campaigns run "
                   "1-hop (delays=None)"},
    "CounterState": {
        "rows": "device KV rows re-home by hash, not by pad/truncate "
                "— carry them with membership.apply_rehoming"},
    "KafkaState": {
        "rows": "device KV rows re-home by hash, not by pad/truncate "
                "— carry them with membership.apply_rehoming"},
}


# -- traced evaluators ---------------------------------------------------


def member_census(plan, t, row_ids: jnp.ndarray,
                  reduce_sum) -> jnp.ndarray:
    """() int32 — how many rows are members at round ``t``: each shard
    folds :func:`~.faults.member_at` over its LOCAL global ids, then
    ONE psum globalizes the count — all-reduce only, never a gather
    (the ``membership/sharded-census-run`` contract pins the HLO)."""
    m = faults.member_at(plan, t, row_ids)
    return reduce_sum(jnp.sum(m.astype(jnp.int32)))


def rehomed_mask(n_keys: int, n_from: int, n_to: int,
                 seed=0) -> jnp.ndarray:
    """(K,) bool ON DEVICE — which keys change owner across a
    ``n_from -> n_to`` resize, straight from the stateless routing
    hash (:func:`~.kvstore.owner_of`).  The device-observed moved-key
    set; tests pin it equal to the host twin :func:`rehomed_keys`."""
    keys = jnp.arange(n_keys, dtype=jnp.int32)
    return (kvstore.owner_of(keys, n_from, seed)
            != kvstore.owner_of(keys, n_to, seed))


# -- the resize boundary -------------------------------------------------


def resize_spec(spec: "faults.NemesisSpec", n_to: int,
                resize_round: int) -> "faults.NemesisSpec":
    """The continuation spec at the NEW padded capacity — and equally
    the straight-through twin's spec (run it from round 0 at ``n_to``
    and the resize boundary becomes an ordinary membership event).

    Grow: rows ``[n, n_to)`` JOIN at ``resize_round`` (they enter
    empty — :func:`~.faults.amnesia` fires at the join round, wiping
    the already-empty padded rows, so restore-then-continue and
    straight-through agree structurally).  Shrink: every dropped row
    must already be a non-member at the boundary — a still-member row
    is named loudly (schedule its leave before the resize, or the
    resize would destroy live state the certifier could never see
    again).  Crash windows and membership events on dropped rows are
    filtered out; loss/dup horizons are materialized explicitly so the
    filtered window list cannot silently change them."""
    from dataclasses import replace

    n = spec.n_nodes
    if resize_round < 1:
        raise ValueError(
            f"resize_round must be >= 1, got {resize_round} (round-0 "
            "members are the founding set; a boundary needs a past)")
    if n_to == n:
        raise ValueError(f"resize to the same capacity ({n})")
    if n_to > n:
        joined = tuple(range(n, n_to))
        return replace(
            spec, n_nodes=n_to,
            join=spec.join + ((resize_round, joined),),
            loss_until=spec._until(spec.loss_until, spec.loss_rate),
            dup_until=spec._until(spec.dup_until, spec.dup_rate))
    members = spec.host_members(resize_round)
    alive = np.nonzero(members[n_to:])[0] + n_to
    if alive.size:
        raise ValueError(
            f"cannot shrink {n} -> {n_to} at round {resize_round}: "
            f"rows {alive.tolist()} are still members — schedule "
            "their leave before the boundary (a leave drains; a "
            "truncation would destroy live acked state)")

    def keep(events):
        out = []
        for first, ns in events:
            ns = tuple(i for i in ns if i < n_to)
            if ns:
                out.append((first, ns))
        return tuple(out)

    crash = []
    for s, e, ns in spec.crash:
        ns = tuple(i for i in ns if i < n_to)
        if ns:
            crash.append((s, e, ns))
    return faults.NemesisSpec(
        n_nodes=n_to, seed=spec.seed, crash=tuple(crash),
        loss_rate=spec.loss_rate,
        loss_until=spec._until(spec.loss_until, spec.loss_rate),
        dup_rate=spec.dup_rate,
        dup_until=spec._until(spec.dup_until, spec.dup_rate),
        join=keep(spec.join), leave=keep(spec.leave))


def resize_state(state, n_to: int):
    """Map one sim state's padded node axis to ``n_to``: declared
    node-axis leaves (``_NODE_AXIS``) pad with EMPTY rows (grow) or
    truncate (shrink); every other leaf carries over untouched.
    Leaves that cannot be resized meaningfully are rejected loudly
    with the reason (``_REJECT_LEAVES``).  This is pure reshaping —
    the SAFETY of a shrink (no live member rows dropped) is
    :func:`resize_spec`'s validation, which :func:`restore_resized`
    always runs first."""
    cls = type(state).__name__
    axes = _NODE_AXIS.get(cls)
    if axes is None:
        raise ValueError(
            f"no node-axis resize map for {cls}: supported states "
            f"are {sorted(_NODE_AXIS)}")
    for fname, why in _REJECT_LEAVES.get(cls, {}).items():
        if getattr(state, fname, None) is not None:
            raise ValueError(
                f"{cls}.{fname} cannot cross a resize boundary: {why}")
    n_from = None
    repl = {}
    for fname, ax in axes.items():
        leaf = getattr(state, fname, None)
        if leaf is None:
            continue
        arr = np.asarray(leaf)
        if n_from is None:
            n_from = int(arr.shape[ax])
        elif int(arr.shape[ax]) != n_from:
            raise ValueError(
                f"{cls}.{fname} has node axis {arr.shape[ax]}, "
                f"expected {n_from} — state leaves disagree on the "
                "padded capacity")
        if n_to > n_from:
            pad_shape = list(arr.shape)
            pad_shape[ax] = n_to - n_from
            arr = np.concatenate(
                [arr, np.zeros(pad_shape, arr.dtype)], axis=ax)
        elif n_to < n_from:
            arr = np.take(arr, np.arange(n_to), axis=ax)
        repl[fname] = jnp.asarray(arr)
    if n_from is None:
        raise ValueError(f"{cls} has no node-axis leaves to resize")
    return state._replace(**repl)


def restore_resized(path: str, state_cls: type, n_to: int):
    """Reload a mid-run checkpoint into a resized padded node axis.

    Returns ``(state, spec, meta)``: the state with its node-axis
    leaves padded/truncated to ``n_to`` (:func:`resize_state`), and
    the continuation :class:`~.faults.NemesisSpec` at the new
    capacity (:func:`resize_spec` — the boundary round is the
    checkpointed ``state.t``, and shrink safety is validated there
    BEFORE any row is dropped).  The checkpoint must carry its fault
    spec in the meta (``checkpoint.save(..., fault_spec=spec)``) —
    that spec is what re-derives liveness and membership at the new
    capacity; without it the resize has no membership ground truth
    and is refused."""
    state, meta = checkpoint.restore(path, state_cls)
    spec = checkpoint.fault_spec_from_meta(meta)
    if spec is None:
        raise ValueError(
            "checkpoint carries no fault_spec in its meta: an elastic "
            "resize re-derives liveness and membership at the new "
            "capacity from the spec — pass fault_spec= to "
            "checkpoint.save at the boundary")
    boundary = int(np.asarray(state.t))
    spec2 = resize_spec(spec, n_to, boundary)
    return resize_state(state, n_to), spec2, meta


# -- KV re-homing --------------------------------------------------------


def rehomed_keys(n_keys: int, n_from: int, n_to: int, *,
                 seed: int = 0) -> np.ndarray:
    """(M,) int32 HOST twin of :func:`rehomed_mask`: the sorted key
    ids whose owner changes across the resize, from the same stateless
    routing hash (:func:`~.kvstore.host_owner_of`).  Deterministic in
    ``(n_keys, n_from, n_to, seed)`` — the emitted diff a resize
    campaign verifies the device-observed moved-key set against."""
    keys = np.arange(n_keys, dtype=np.int32)
    moved = (kvstore.host_owner_of(keys, n_from, seed)
             != kvstore.host_owner_of(keys, n_to, seed))
    return keys[moved]


def apply_rehoming(rows: "kvstore.KVRows", old: "kvstore.KVLayout",
                   new: "kvstore.KVLayout") -> "kvstore.KVRows":
    """Carry the device KV registers across a resize: read every
    key's (value, version) at its OLD home row, write it at its NEW
    home row.  A host-side boundary op — the resize itself is a host
    checkpoint boundary — whose moved-key set is exactly
    :func:`rehomed_keys`; unmoved keys land back in their old slot
    rank bit-for-bit."""
    if old.n_keys != new.n_keys:
        raise ValueError(
            f"layouts disagree on the key space: {old.n_keys} vs "
            f"{new.n_keys}")
    if old.seed != new.seed:
        raise ValueError(
            f"layouts disagree on the routing seed: {old.seed} vs "
            f"{new.seed} — re-homing is the CAPACITY diff only")
    vals = np.asarray(rows.vals)
    vers = np.asarray(rows.vers)
    kv = vals[old.owner, old.slot]
    kr = vers[old.owner, old.slot]
    nv = np.zeros((new.n_nodes, new.cap), np.int32)
    nr = np.zeros((new.n_nodes, new.cap), np.int32)
    nv[new.owner, new.slot] = kv
    nr[new.owner, new.slot] = kr
    return kvstore.KVRows(vals=jnp.asarray(nv), vers=jnp.asarray(nr))


# -- program contracts ---------------------------------------------------


def audit_contracts():
    """The membership layer's :class:`~.audit.ProgramContract` rows:
    the sharded member census (all-reduce only — no row gather ever
    learns who is a member) and the donated membership-run carry at a
    RESIZED capacity (grown rows ride as padded members-to-be;
    donation + analytic memory band over the resized state)."""
    from .audit import AuditProgram, ProgramContract
    from .engine import (analytic_peak_bytes, collectives, fori_rounds,
                        jit_program, node_axes)

    def sharded_census_run(mesh):
        n = 64
        spec = faults.NemesisSpec(
            n_nodes=n, seed=5, crash=((2, 6, (1, 2)),),
            join=((3, tuple(range(n - 8, n))),),
            leave=((5, (0, 4)),))
        plan = spec.compile()

        def run(plan, t, rows):
            coll = collectives(rows.shape[0], mesh)
            return member_census(plan, t, coll.row_ids,
                                 coll.reduce_sum)

        prog = jit_program(
            run, mesh=mesh,
            in_specs=(faults.plan_specs(), P(),
                      P(node_axes(mesh))),
            out_specs=P())
        args = (plan, jnp.int32(4), jnp.zeros((n,), jnp.int32))
        return AuditProgram(prog, args)

    def membership_run_donated(mesh):
        del mesh
        n, w, rounds = 4096, 64, 16
        # a grow-shaped membership run AT the resized capacity: the
        # top quarter of the padded axis joins mid-run (the resize
        # boundary as an in-place membership event), two founding
        # rows leave late
        spec = faults.NemesisSpec(
            n_nodes=n, seed=7, crash=((3, 6, (5, 6, 7)),),
            join=((4, tuple(range(3 * n // 4, n))),),
            leave=((10, (0, 1)),))
        plan = spec.compile()
        ids = jnp.arange(n, dtype=jnp.int32)

        def run(st, plan, n_rounds):
            def body(carry, plan):
                bits, t = carry
                member = faults.member_at(plan, t, ids)
                up = faults.node_up(plan, t, ids)
                wipe = faults.amnesia(plan, t, ids)
                bits = jnp.where(wipe[:, None], jnp.uint32(0), bits)
                anywhere = jnp.bitwise_or.reduce(
                    jnp.where(member[:, None], bits, jnp.uint32(0)),
                    axis=0)
                bits = jnp.where(up[:, None], bits | anywhere[None, :],
                                 bits)
                return bits, t + 1

            return fori_rounds(body, (st, jnp.int32(0)), n_rounds,
                               operand=plan)

        prog = jit_program(run, donate_argnums=(0,))
        state_bytes = n * w * 4
        analytic = analytic_peak_bytes(state_bytes=state_bytes,
                                       donated=True)
        st0 = jnp.ones((n, w), jnp.uint32)
        return AuditProgram(prog, (st0, plan, jnp.int32(rounds)),
                            donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="membership/sharded-census-run",
            build=sharded_census_run,
            collectives={"all-reduce": None},
            notes="per-shard member_at fold over local global ids + "
                  "ONE psum: the membership columns are replicated "
                  "plan leaves, so no collective ever gathers rows "
                  "to learn who is a member — all-reduce only, NO "
                  "all-gather"),
        ProgramContract(
            name="membership/membership-run-donated",
            build=membership_run_donated,
            collectives={},
            donation=True,
            mem_lo=0.2, mem_hi=4.0,
            needs_mesh=False,
            notes="donated fori membership run AT the resized padded "
                  "capacity: grown rows join mid-run (amnesia wipes "
                  "them empty at entry), leavers drop out of the "
                  "member fold; the (N', W) carry aliases in place — "
                  "compiled peak within band of 1x state + fold "
                  "temps"),
    ]
