"""Causal provenance tracing for the vectorized backend (PR 9):
device-resident dissemination trees riding the donated carry, the same
way the telemetry ring (telemetry.py) does.

PR 8's telemetry answers *how much* happened per round (aggregate
counters + conservation identities); this module answers *why*: which
edge first delivered a value, which hop chain is a run's critical
path, which origin a kafka slot replicated from.  Per-message
causality is exactly the observability a TPU-native design can afford
that a process-per-node harness cannot — the recorder is a handful of
masked elementwise writes next to state the round already computed.

- **`ProvenanceSpec`** (the `TelemetrySpec` shape): a tiny JSON-able
  host spec naming the workload (and kafka's witness node).  STATIC —
  it keys the compiled provenance-on programs; the carry is state.
- **per-workload `*Prov` state**, node-sharded where the data is:

  * broadcast (:class:`BroadcastProv`): per-(node, value) **arrival
    round** (-1 unseen; 0 = injected at the origin; t+1 = first
    present in the state after round t) and **parent node id** (-1 =
    origin) — written MASKED exactly where the round's ``new`` bits
    land, the parent chosen shard-locally as the first delivering
    direction (the per-direction terms the gather round already sums;
    the recorder re-reads them in scope, so provenance adds ZERO
    all-gathers and ZERO host callbacks).  Amnesia never wipes the
    record: stamps are first-incarnation (``arrival < 0`` gates every
    write), which keeps causality intact across crash/restart — a
    parent's first arrival always precedes any round it delivered in.
  * counter (:class:`CounterProv`): per-node flush → kv → visibility
    stamps — the round a node's acked deltas first drained into the
    KV, the KV value they landed in, and the round every cache had
    caught up to that value.
  * kafka (:class:`KafkaProv`): per-(key, slot) allocation round +
    origin node (from the same pure ``_alloc`` evaluation the round
    performs — the PR-7 mirror trick) and the slot's first-presence
    round at the WITNESS node (default global row 0, matching the
    ``present_bits`` telemetry gauge).

- **host-verifiable against the fault model itself**
  (harness/checkers.py ``check_provenance``): the loss/liveness coins
  are stateless ``(t, src, dst)`` hashes with exact numpy twins
  (faults.host_node_up / host_edge_drop), so the host re-evaluates
  whether each claimed parent edge was actually LIVE and UN-DROPPED at
  the claimed round — plus causality (``arrival[parent] <
  arrival[child]``), reachability (every held value has a recorded
  arrival), and tree/msgs-ledger consistency — all ANDed into the
  observed verdicts.  A forged parent on a dead or dropped edge fails
  loudly (tests/test_provenance.py).

The host side (harness/observe.py) rebuilds per-value spanning trees,
critical-path hop latency, and per-edge utilization
(``dissemination_tree`` / ``provenance_summary``), adds Perfetto FLOW
events (causal arrows) to the timelines, and folds the record into the
flight-recorder bundle so ``replay_bundle`` reports the
first-divergence round (the item-2 fuzzer's shrinker signal).

Paths: broadcast provenance rides the GATHER path (1-hop and per-edge
``delays`` ring modes, single-device and mesh) — the structured
words-major exchanges are opaque sums of direction terms, so
per-direction attribution there would re-run the exchange D times;
counter and kafka ride their ordinary fused drivers (kafka: the
origin-union replication paths).

Env knob: ``GG_PROVENANCE`` (0/1, default off, the loud ``_env_int``
contract — the scenario runners consult it like ``GG_TELEMETRY``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .engine import _env_int

# The module's host/device split, DECLARED (the PR-6 faults.py
# pattern): the determinism lint (tpu_sim/audit.py) treats exactly
# TRACED_EVALUATORS as traced scope; tests/test_provenance.py pins the
# split TOTAL.
TRACED_EVALUATORS = ("stamp", "critical_depth")
HOST_SIDE = (
    "init_broadcast", "init_counter", "init_kafka",
    "broadcast_specs", "counter_specs", "kafka_specs",
    "enabled", "default_spec", "prov_key", "arrays_of", "from_arrays",
    "depth_of", "audit_contracts")

WORKLOADS = ("broadcast", "counter", "kafka")


@dataclass(frozen=True)
class ProvenanceSpec:
    """Host-side provenance spec — JSON-able (:meth:`to_meta`), STATIC
    (it keys the compiled provenance-on programs).  ``witness``: the
    kafka first-presence observer node (global id; the telemetry
    ``present_bits`` witness by default)."""

    workload: str
    witness: int = 0

    def __post_init__(self) -> None:
        if self.workload not in WORKLOADS:
            raise ValueError(
                f"unknown provenance workload {self.workload!r}; one "
                f"of {list(WORKLOADS)}")
        if self.witness < 0:
            raise ValueError("witness must be a node id >= 0")

    def to_meta(self) -> dict:
        return {"workload": self.workload, "witness": self.witness}

    @staticmethod
    def from_meta(meta: dict) -> "ProvenanceSpec":
        return ProvenanceSpec(workload=str(meta["workload"]),
                              witness=int(meta.get("witness", 0)))


class BroadcastProv(NamedTuple):
    """Node-sharded (N, V) int32 stamps (module docstring)."""

    arrival: jnp.ndarray   # -1 unseen / 0 origin / t+1 first present
    parent: jnp.ndarray    # -1 origin / global node id that delivered


class CounterProv(NamedTuple):
    """Node-sharded (N,) int32 stamps."""

    flush_round: jnp.ndarray    # -1 / t+1 first full pending drain
    flush_kv: jnp.ndarray       # -1 / the KV value the flush landed in
    visible_round: jnp.ndarray  # -1 / t+1 every cache >= flush_kv


class KafkaProv(NamedTuple):
    """Replicated (K, C) int32 stamps (disjoint per-shard partials
    psum into identical replicas, like ``log_vals``)."""

    alloc_round: jnp.ndarray    # -1 / t+1 the slot was allocated
    origin: jnp.ndarray         # -1 / global node id of the sender
    first_present: jnp.ndarray  # -1 / t+1 first present at witness


def init_broadcast(n_nodes: int, n_values: int,
                   inject: np.ndarray | None = None) -> BroadcastProv:
    """Fresh broadcast record; ``inject`` ((N, W) uint32, the round-0
    injection bitset) stamps the origin cells arrival=0, parent=-1."""
    from .engine import host_unpack_bits

    arrival = np.full((n_nodes, n_values), -1, np.int32)
    if inject is not None:
        arrival[host_unpack_bits(inject, n_values)] = 0
    # jnp.array (copy), NOT jnp.asarray: the record is donated, and a
    # zero-copy numpy-backed view must never be the donated buffer
    # (see init_kafka)
    return BroadcastProv(
        arrival=jnp.array(arrival),
        parent=jnp.full((n_nodes, n_values), -1, jnp.int32))


def init_counter(n_nodes: int) -> CounterProv:
    # three DISTINCT buffers: the observed drivers donate the whole
    # pytree and XLA rejects donating one buffer twice
    return CounterProv(*(jnp.full((n_nodes,), -1, jnp.int32)
                         for _ in range(3)))


def init_kafka(n_keys: int, capacity: int) -> KafkaProv:
    # device-native buffers (jnp.full, not jnp.asarray over a host
    # array): the record is DONATED into the observed drivers, and on
    # CPU a numpy-backed jax array can be a zero-copy view — donating
    # the view while the output aliases it corrupts the stamps as
    # soon as any later dispatch reuses the freed pages
    return KafkaProv(*(jnp.full((n_keys, capacity), -1, jnp.int32)
                       for _ in range(3)))


def broadcast_specs(axes="nodes") -> BroadcastProv:
    """shard_map in/out_specs: node-sharded with the gather state
    (``axes`` is the sim's ``engine.node_axes`` result)."""
    return BroadcastProv(P(axes, None), P(axes, None))


def counter_specs(axes="nodes") -> CounterProv:
    return CounterProv(P(axes), P(axes), P(axes))


def kafka_specs() -> KafkaProv:
    return KafkaProv(P(None, None), P(None, None), P(None, None))


def stamp(cur: jnp.ndarray, mask: jnp.ndarray, val) -> jnp.ndarray:
    """Masked FIRST-occurrence write (traced): ``cur`` where already
    stamped (>= 0), ``val`` where ``mask`` and unstamped — the one
    write shape every provenance recorder uses, which is what makes
    the record first-incarnation under amnesia."""
    return jnp.where(mask & (cur < 0),
                     jnp.asarray(val, cur.dtype), cur)


def critical_depth(stamps: jnp.ndarray) -> jnp.ndarray:
    """() int32 — the critical-path depth of a stamp array (traced):
    the last ROUND at which a first-occurrence stamp landed.  Stamps
    follow the t+1 convention (-1 unseen, 0 = round-0 origin, t+1 =
    first present after round t), so the depth is ``max(stamps) - 1``,
    clamped to -1 when nothing past the origin was ever stamped.  This
    is the provenance-side twin of the ring-derived
    ``telemetry.ring_progress_depth`` for dissemination spanning >= 2
    rounds (pinned by tests; round-0-only deliveries are invisible to
    the ring's delta view, which baselines at row 0)."""
    return jnp.maximum(jnp.max(stamps).astype(jnp.int32) - 1,
                       jnp.int32(-1))


# -- env knob -------------------------------------------------------------


def enabled(default: bool = False) -> bool:
    """The ``GG_PROVENANCE`` master switch (default OFF).  Loud
    contract: any value other than 0/1 raises a ValueError naming the
    variable."""
    raw = os.environ.get("GG_PROVENANCE")
    if raw is None:
        return default
    v = _env_int("GG_PROVENANCE", raw)
    if v not in (0, 1):
        raise ValueError(
            f"GG_PROVENANCE={v} must be 0 or 1 (provenance off/on)")
    return bool(v)


def default_spec(workload: str) -> ProvenanceSpec:
    return ProvenanceSpec(workload=workload)


def prov_key(prov, prov_spec, workload: str):
    """Validate a driver's ``(prov, prov_spec)`` pair (both or
    neither; the spec must name this workload) and return the
    program-cache key component."""
    if (prov is None) != (prov_spec is None):
        raise ValueError(
            "pass prov and prov_spec together (build the record with "
            "the sim's provenance_state(spec, ...))")
    if prov_spec is not None and prov_spec.workload != workload:
        raise ValueError(
            f"run_observed provenance needs ProvenanceSpec(workload="
            f"{workload!r}), got {prov_spec.to_meta()}")
    return prov_spec


# -- host-side readout ----------------------------------------------------


_FIELDS = {"broadcast": ("arrival", "parent"),
           "counter": ("flush_round", "flush_kv", "visible_round"),
           "kafka": ("alloc_round", "origin", "first_present")}


def arrays_of(prov) -> dict:
    """{field: numpy int32 array} — the JSON-able-after-``tolist``
    payload the checkers, summaries, and flight bundles consume.
    Always a COPY (np.array), never a zero-copy view of the device
    buffer: the record rides donated carries, and a view would read
    freed pages once a later dispatch reuses them."""
    return {name: np.array(arr)
            for name, arr in zip(type(prov)._fields, prov)}


def from_arrays(workload: str, arrays: dict):
    """Rebuild the device record from a bundle's JSON arrays."""
    cls = {"broadcast": BroadcastProv, "counter": CounterProv,
           "kafka": KafkaProv}[workload]
    return cls(*(jnp.array(np.asarray(arrays[f], np.int32))
                 for f in _FIELDS[workload]))


def depth_of(workload: str, arrays: dict) -> int:
    """Host twin of :func:`critical_depth` over a bundle's JSON
    arrays: the last round a first-occurrence stamp landed, from the
    workload's dissemination field (broadcast ``arrival``, counter
    ``visible_round``, kafka ``first_present``).  The frontier replay
    cross-checks this against the ring-derived signature depth."""
    field = {"broadcast": "arrival", "counter": "visible_round",
             "kafka": "first_present"}[workload]
    a = np.asarray(arrays[field], np.int64)
    m = int(a.max()) if a.size else -1
    return max(m - 1, -1)


# -- program contracts (tpu_sim/audit.py registry) -----------------------


def audit_contracts():
    """Provenance-on driver rows: the recorders must add no gathers
    (counter/kafka stay all-gather-FREE — cap-0 census; the broadcast
    gather path keeps EXACTLY its plain 2-widen census, i.e. the
    per-direction attribution re-reads the widened payloads already in
    scope), keep the donation alias table covering BOTH the sim state
    and the provenance carry, and sit inside the analytic memory
    band."""
    from ..parallel.topology import to_padded_neighbors, tree
    from . import faults
    from .audit import AuditProgram, ProgramContract
    from .broadcast import BroadcastSim
    from .counter import CounterSim
    from .engine import analytic_peak_bytes
    from .engine import operand_bytes as engine_operand_bytes
    from .kafka import KafkaSim

    def _spec(n):
        return faults.NemesisSpec(
            n_nodes=n, seed=5, crash=((2, 4, (1, n // 2)),),
            loss_rate=0.1, loss_until=6, dup_rate=0.1, dup_until=6)

    def counter_prov(mesh):
        n = 1024
        pspec = ProvenanceSpec("counter")
        sim = CounterSim(n, mode="cas", poll_every=2, mesh=mesh,
                         fault_plan=_spec(n).compile())
        prog, args = sim.audit_observed_program(None, prov_spec=pspec)
        n_sh = 1 if mesh is None else 8
        state_bytes = (2 * n * 4 + 3 * n * 4) // n_sh
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(sim.fault_plan))
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def broadcast_prov(mesh):
        n, nv = 256, 256
        pspec = ProvenanceSpec("broadcast")
        sim = BroadcastSim(
            to_padded_neighbors(tree(n, branching=4)), n_values=nv,
            sync_every=4, srv_ledger=False, mesh=mesh,
            fault_plan=_spec(n).compile())
        prog, args = sim.audit_observed_program(None, prov_spec=pspec)
        n_sh = 1 if mesh is None else 8
        w = nv // 32
        state_bytes = (2 * n * w * 4 + 2 * n * nv * 4) // n_sh
        # slab: the two payload widens + the per-direction unpack
        # temps ((rows, V) bools and int32 selects)
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(sim.fault_plan),
            slab_bytes=2 * n * w * 4 + 6 * (n // n_sh) * nv)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    def kafka_prov(mesh):
        n, k, cap = 64, 8, 64
        pspec = ProvenanceSpec("kafka")
        sim = KafkaSim(n, k, capacity=cap, max_sends=2,
                       fault_plan=_spec(n).compile(),
                       resync_every=4, union_block=4, mesh=mesh)
        prog, args = sim.audit_observed_program(None, prov_spec=pspec)
        n_sh = 1 if mesh is None else 8
        wc = (cap + 31) // 32
        state_bytes = (n * k * wc * 4 + n * k * 4) // n_sh \
            + k * cap * 4 + k * 4 + 3 * k * cap * 4
        analytic = analytic_peak_bytes(
            state_bytes=state_bytes,
            operand_bytes=engine_operand_bytes(sim.fault_plan),
            slab_bytes=(n // n_sh) * n * 2 * 4
            + (n // n_sh) * k * wc * 4 + 3 * k * cap * 4)
        return AuditProgram(prog, args, donated_bytes=state_bytes,
                            analytic_peak_bytes=analytic[
                                "peak_live_bytes"])

    return [
        ProgramContract(
            name="counter/provenance-run",
            build=counter_prov,
            collectives={"all-reduce": None},
            donation=True,
            mem_lo=0.05, mem_hi=8.0,
            notes="provenance-on donated counter driver under "
                  "crash+loss+dup: the flush/visibility stamps are "
                  "masked elementwise writes next to the round's own "
                  "psums/pmins — NO gather (cap-0), (state, prov) "
                  "alias in place"),
        ProgramContract(
            name="broadcast/provenance-run-gather-nem",
            build=broadcast_prov,
            collectives={"all-gather": 2, "all-reduce": None},
            donation=True,
            mem_lo=0.02, mem_hi=8.0,
            notes="provenance-on gather driver under crash+loss+dup: "
                  "EXACTLY the plain round's two widens (payload + "
                  "dup source set) — per-direction parent attribution "
                  "re-reads them in scope and adds ZERO gathers"),
        ProgramContract(
            name="kafka/provenance-run-union-nem",
            build=kafka_prov,
            collectives={"all-reduce": None,
                         "collective-permute": None},
            donation=True,
            mem_lo=0.05, mem_hi=8.0,
            notes="provenance-on blocked faulted-union driver: the "
                  "_alloc mirror rides the existing ppermute prefix "
                  "scan, the (K, C) stamp partials psum — the sharded "
                  "observed step stays all-gather-free (cap-0)"),
    ]
