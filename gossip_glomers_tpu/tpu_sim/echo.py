"""Vectorized echo (challenge 1) on TPU — the smoke test.

The reference echo node replies to each request with the same body,
``type`` rewritten to ``echo_ok`` (echo/main.go:12-20).  Batched, that
is the identity kernel over a (N, B) payload block with a request/reply
message ledger — it exists to validate the op-injection → step → read
pipeline end-to-end with the simplest possible handler, exactly the
role echo plays for the reference stack.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import shard_put
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class EchoState(NamedTuple):
    t: jnp.ndarray      # () int32
    msgs: jnp.ndarray   # () uint32 — request + reply count


class EchoSim:
    def __init__(self, n_nodes: int, *, mesh: Mesh | None = None) -> None:
        self.n_nodes = n_nodes
        self.mesh = mesh

        def echo(state: EchoState, payload, valid):
            replies = jnp.where(valid, payload, jnp.int32(-1))
            n_ops = jnp.sum(valid.astype(jnp.uint32))
            if mesh is not None:
                n_ops = jax.lax.psum(n_ops, "nodes")
            new = EchoState(t=state.t + 1,
                            msgs=state.msgs + n_ops * jnp.uint32(2))
            return new, replies

        from .engine import jit_program

        if mesh is None:
            self._step = jit_program(echo)
        else:
            spec = P("nodes", None)
            self._step = jit_program(
                echo, mesh=mesh,
                in_specs=(EchoState(P(), P()), spec, spec),
                out_specs=(EchoState(P(), P()), spec))

    def init_state(self) -> EchoState:
        return EchoState(t=jnp.int32(0), msgs=jnp.uint32(0))

    def step(self, state: EchoState, payload: np.ndarray,
             valid: np.ndarray) -> tuple[EchoState, jnp.ndarray]:
        p = jnp.asarray(payload, jnp.int32)
        v = jnp.asarray(valid)
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P("nodes", None))
            p, v = shard_put(p, sh), shard_put(v, sh)
        return self._step(state, p, v)
