"""Nemesis faults for the vectorized backend: crash/restart, message
loss, duplicate delivery — compiled, seeded, replayable.

The harness side already models Maelstrom's *partition* nemesis as data
(harness/faults.py windows -> the gather path's :class:`~.broadcast.
Partitions` masks, the KV-reachability windows of counter/kafka).  This
module closes the rest of the Maelstrom fault model the same way
(survey §5 "fault injection = masked adjacency updates"):

- **crash/restart** (Maelstrom's kill/restart nemesis): windows of
  down nodes, exactly the shape of the partition schedule's
  ``starts/ends`` arrays.  A down node sends nothing, receives nothing,
  and cannot reach the KV services; on the round its window ends it
  restarts with its VOLATILE state re-initialized — an "amnesia row"
  (broadcast: received/frontier; counter: pending/cached; kafka:
  presence/local-committed rows) — and recovers only through the
  workload's own anti-entropy, like a Maelstrom-restarted process.
- **probabilistic message loss** (the lossy-link nemesis): each
  directed edge drops a given round's delivery with probability
  ``loss_rate``.  The coin is a stateless counter-based hash of
  ``(seed, round, src, dst)`` — zero state, zero memory, identical on
  every shard, and bit-replayable from the seed alone.
- **duplicate delivery**: with probability ``dup_rate`` an edge
  re-delivers every value its source ever flooded (the source's full
  ``received`` set) — the at-least-once duplicate stream that gossip
  dedup and CRDT merges must absorb.
- **membership events** (PR 17): node *join* (a padded row enters
  EMPTY at its join round — before it, the row is not a member at
  all: no sends, no receives, no KV reach, and unlike a
  restart-with-amnesia it was never up to begin with) and *permanent
  leave* (liveness goes down at the leave round and STAYS down —
  distinct from a crash window, which ends).  Membership compiles to
  two (N,) per-row round columns (``join_round``/``leave_round``
  with founding/never sentinels), folded into :func:`node_up` so
  every existing liveness gate in every sim inherits the events with
  zero call-site changes; :func:`amnesia` additionally fires at join
  entry, so the join row is structurally wiped empty by the same sim
  wipe calls that serve crash-restart.

Everything compiles to a :class:`FaultPlan` of tiny arrays/scalars that
rides through the fused drivers as ONE traced operand (never donated,
never baked in as a constant), so faulted programs stay donation-first
and a (spec, seed) pair replays bit-exactly — which is what lets the
recovery certifier (harness/checkers.py ``check_recovery``) assert hard
outcomes under the full fault model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .engine import windows_fold

# The module's host/device split, DECLARED (PR 6): the determinism
# lint (tpu_sim/audit.py) treats exactly TRACED_EVALUATORS as traced
# scope — device-side mask/coin evaluation where an rng/clock call or
# a host branch on traced data would fork seed replay.  Everything in
# HOST_SIDE runs before tracing (spec construction, compilation, op
# staging, the numpy mirrors) and may use numpy rngs freely —
# random_spec seeding a campaign is the point, not a bug.
# tests/test_audit.py pins the split TOTAL: a new module-level
# function must be added to one of these tuples (or be a class) or
# the test fails, so the lint can never silently skip new traced
# code here.
TRACED_EVALUATORS = (
    "node_up", "amnesia", "member_at", "plan_churn", "_mix32",
    "_edge_hash", "edge_drop", "edge_dup", "coin_block", "kv_drop",
    "wm_up_cols", "wm_live_rows", "wm_live_del", "wm_srv_rows")
HOST_SIDE = (
    "plan_specs", "wm_specs", "_rate_to_num", "random_spec",
    "crash_down_rows", "_mix32_np", "host_node_up", "host_member_at",
    "host_edge_drop", "host_kv_ok", "pad_plan", "batch_plans",
    "_plan_window_shapes")

# distinct stream salts: loss and dup draw independent coins from the
# same (seed, t, src, dst) counter
_SALT_LOSS = 0x9E3779B9
_SALT_DUP = 0x85EBCA6B
# the KV services are not a node row; their "edge" hashes use this as
# the dst so node<->service loss draws its own stream
KV_DST = 0x7FFFFFFF

# membership sentinels: a FOUNDING row "joined" at int32 min (member
# from before round 0), a row that never leaves "leaves" at int32 max.
# With these defaults the membership fold in node_up is an all-true
# mask — a membership-free plan evaluates bit-identically to PR 16.
JOIN_FOUNDING = -(2**31)
LEAVE_NEVER = 2**31 - 1


class FaultPlan(NamedTuple):
    """The compiled device form of a :class:`NemesisSpec` — the same
    data-as-faults shape as the partition schedules (windows evaluated
    at round t on device) plus the loss/dup thresholds and the hash
    seed.  All leaves are tiny and replicated; thread the plan through
    a driver as a traced argument (see :func:`plan_specs`), never
    donate it."""

    starts: jnp.ndarray    # (C,) int32 — crash window start round (incl)
    ends: jnp.ndarray      # (C,) int32 — crash window end round (excl)
    down: jnp.ndarray      # (C, N) bool — rows down while window active
    loss_num: jnp.ndarray  # () uint32 — drop iff hash < loss_num
    loss_until: jnp.ndarray  # () int32 — loss active for rounds < this
    dup_num: jnp.ndarray   # () uint32 — dup iff hash < dup_num
    dup_until: jnp.ndarray   # () int32
    seed: jnp.ndarray      # () uint32 — the replay key
    join_round: jnp.ndarray   # (N,) int32 — member from this round on
    leave_round: jnp.ndarray  # (N,) int32 — member strictly before this


def plan_specs() -> FaultPlan:
    """shard_map in_specs for a :class:`FaultPlan` operand: every leaf
    replicated (the masks are evaluated per shard on global ids)."""
    return FaultPlan(P(), P(), P(None, None), P(), P(), P(), P(), P(),
                     P(None), P(None))


def _rate_to_num(rate: float) -> np.uint32:
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"rate must be in [0, 1], got {rate}")
    return np.uint32(min(2**32 - 1, int(round(rate * 2**32))))


@dataclass(frozen=True)
class NemesisSpec:
    """Host-side seeded fault spec — JSON-able (checkpoint meta), and
    ``compile()``-able to the device :class:`FaultPlan`.

    ``crash``: list of ``(start_round, end_round, [node ids])`` windows.
    ``loss_rate``/``dup_rate`` apply to every directed delivery for
    rounds ``[0, loss_until)`` / ``[0, dup_until)``; ``until`` values
    default to the last crash-window end (so a pure-loss spec must set
    them explicitly).  ``clear_round`` is the first round with no fault
    active — the recovery certifier's t=0.

    ``join``/``leave`` (PR 17): membership events as
    ``((round, (node ids,)), ...)``.  A join row is NOT a member
    before its round (it holds no state, sends nothing, stages
    nothing) and enters EMPTY at it; a leave row is a member strictly
    before its round and then gone for good.  Rounds must be >= 1
    (round-0 members are the FOUNDING set), each node may join at
    most once and leave at most once, and a node that does both must
    leave after it joins.  A membership event is a fault event:
    ``clear_round`` covers it, so recovery certification starts after
    the last join/leave has landed.
    """

    n_nodes: int
    seed: int = 0
    crash: tuple = field(default_factory=tuple)   # ((start, end, (i,..)),)
    loss_rate: float = 0.0
    loss_until: int | None = None
    dup_rate: float = 0.0
    dup_until: int | None = None
    join: tuple = field(default_factory=tuple)    # ((round, (i,..)),)
    leave: tuple = field(default_factory=tuple)   # ((round, (i,..)),)

    def _until(self, explicit: int | None, rate: float) -> int:
        if explicit is not None:
            return int(explicit)
        if rate == 0.0:
            return 0
        ends = [int(e) for _s, e, _ns in self.crash]
        if not ends:
            raise ValueError(
                "a loss/dup rate with no crash windows needs an "
                "explicit loss_until/dup_until (rounds)")
        return max(ends)

    @property
    def clear_round(self) -> int:
        """First round at which every fault has cleared."""
        ends = [int(e) for _s, e, _ns in self.crash]
        mem = [int(r) for r, _ns in self.join + self.leave]
        return max([0] + ends + mem
                   + [self._until(self.loss_until, self.loss_rate),
                      self._until(self.dup_until, self.dup_rate)])

    @property
    def has_membership(self) -> bool:
        """True when the spec carries any join/leave event — the gate
        the reject-loudly satellites and the membership-aware batch
        dispatchers branch on."""
        return bool(self.join or self.leave)

    def __post_init__(self) -> None:
        norm = []
        for start, end, nodes in self.crash:
            nodes = tuple(sorted(int(i) for i in nodes))
            if not 0 <= int(start) < int(end):
                raise ValueError(
                    f"bad crash window [{start}, {end})")
            for i in nodes:
                if not 0 <= i < self.n_nodes:
                    raise ValueError(f"crash node {i} out of range")
            norm.append((int(start), int(end), nodes))
        object.__setattr__(self, "crash", tuple(norm))
        for name in ("join", "leave"):
            events, seen = [], set()
            for r, nodes in getattr(self, name):
                nodes = tuple(sorted(int(i) for i in nodes))
                if int(r) < 1:
                    raise ValueError(
                        f"{name} round {r} must be >= 1 (round-0 "
                        "members are the founding set)")
                for i in nodes:
                    if not 0 <= i < self.n_nodes:
                        raise ValueError(
                            f"{name} node {i} out of range")
                    if i in seen:
                        raise ValueError(
                            f"node {i} appears in more than one "
                            f"{name} event")
                    seen.add(i)
                events.append((int(r), nodes))
            object.__setattr__(self, name, tuple(events))
        jr, lr = self._membership_rows()
        bad = np.nonzero(lr <= jr)[0]
        if bad.size:
            raise ValueError(
                f"node {int(bad[0])} leaves at {int(lr[bad[0]])} but "
                f"only joins at {int(jr[bad[0]])}")
        _rate_to_num(self.loss_rate)
        _rate_to_num(self.dup_rate)
        # validate that every active rate has a derivable horizon
        self._until(self.loss_until, self.loss_rate)
        self._until(self.dup_until, self.dup_rate)

    def _membership_rows(self) -> tuple:
        """(join_round, leave_round) (N,) int32 columns with the
        founding/never sentinels — the compiled membership leaves."""
        jr = np.full(self.n_nodes, JOIN_FOUNDING, np.int32)
        lr = np.full(self.n_nodes, LEAVE_NEVER, np.int32)
        for r, nodes in self.join:
            jr[list(nodes)] = r
        for r, nodes in self.leave:
            lr[list(nodes)] = r
        return jr, lr

    # -- host mirrors ----------------------------------------------------

    def host_members(self, t: int) -> np.ndarray:
        """(N,) bool — which rows are MEMBERS at round ``t`` (joined
        at or before, not yet left).  Crash windows do not affect
        membership: a crashed member is still a member."""
        jr, lr = self._membership_rows()
        return (jr <= t) & (t < lr)

    def host_up(self, t: int) -> np.ndarray:
        """(N,) bool — which nodes are up at round ``t`` (the host twin
        of :func:`node_up`, for staging ops away from dead nodes).
        Membership folds in: a non-member row is never up."""
        up = self.host_members(t)
        for start, end, nodes in self.crash:
            if start <= t < end:
                up[list(nodes)] = False
        return up

    # -- compilation -----------------------------------------------------

    def compile(self) -> FaultPlan:
        c = len(self.crash)
        starts = np.zeros((c,), np.int32)
        ends = np.zeros((c,), np.int32)
        down = np.zeros((c, self.n_nodes), bool)
        for w, (start, end, nodes) in enumerate(self.crash):
            starts[w], ends[w] = start, end
            down[w, list(nodes)] = True
        jr, lr = self._membership_rows()
        return FaultPlan(
            starts=jnp.asarray(starts), ends=jnp.asarray(ends),
            down=jnp.asarray(down),
            loss_num=jnp.uint32(_rate_to_num(self.loss_rate)),
            loss_until=jnp.int32(self._until(self.loss_until,
                                             self.loss_rate)),
            dup_num=jnp.uint32(_rate_to_num(self.dup_rate)),
            dup_until=jnp.int32(self._until(self.dup_until,
                                            self.dup_rate)),
            seed=jnp.uint32(self.seed & 0xFFFFFFFF),
            join_round=jnp.asarray(jr), leave_round=jnp.asarray(lr))

    # -- checkpoint meta -------------------------------------------------

    def to_meta(self) -> dict:
        """JSON-able form for checkpoint meta (tpu_sim/checkpoint.py):
        a resumed faulted run rebuilds the identical plan from this."""
        return {"n_nodes": self.n_nodes, "seed": self.seed,
                "crash": [[s, e, list(ns)] for s, e, ns in self.crash],
                "loss_rate": self.loss_rate,
                "loss_until": self._until(self.loss_until,
                                          self.loss_rate),
                "dup_rate": self.dup_rate,
                "dup_until": self._until(self.dup_until, self.dup_rate),
                "join": [[r, list(ns)] for r, ns in self.join],
                "leave": [[r, list(ns)] for r, ns in self.leave]}

    @staticmethod
    def from_meta(meta: dict) -> "NemesisSpec":
        return NemesisSpec(
            n_nodes=int(meta["n_nodes"]), seed=int(meta["seed"]),
            crash=tuple((int(s), int(e), tuple(ns))
                        for s, e, ns in meta.get("crash", ())),
            loss_rate=float(meta.get("loss_rate", 0.0)),
            loss_until=meta.get("loss_until"),
            dup_rate=float(meta.get("dup_rate", 0.0)),
            dup_until=meta.get("dup_until"),
            join=tuple((int(r), tuple(ns))
                       for r, ns in meta.get("join", ())),
            leave=tuple((int(r), tuple(ns))
                        for r, ns in meta.get("leave", ())))


def random_spec(n_nodes: int, *, seed: int, horizon: int,
                n_crash_windows: int = 2, crash_frac: float = 0.25,
                crash_len: int | None = None,
                loss_rate: float = 0.0,
                dup_rate: float = 0.0) -> NemesisSpec:
    """Randomized nemesis campaign within ``[0, horizon)`` rounds —
    the shape of Maelstrom's combined kill+lossy nemesis, fully
    determined by ``seed``.  Each crash window takes a random subset of
    at most ``crash_frac`` of the nodes (never all of them: a majority
    always stays up to serve anti-entropy), at a random start, for
    ``crash_len`` rounds, clipped to end inside the horizon.  Windows
    are placed in DISJOINT time segments, so at any round at most one
    window is active and at least ``1 - crash_frac`` of the cluster
    stays up to serve anti-entropy.  Loss/dup run for the whole
    horizon."""
    if horizon < 2:
        raise ValueError("horizon must be >= 2 rounds")
    rng = np.random.default_rng(seed)
    n_down = max(1, min(n_nodes - 1, int(round(crash_frac * n_nodes))))
    seg = horizon / max(1, n_crash_windows)
    length = (crash_len if crash_len is not None
              else max(1, int(seg) // 2))
    windows = []
    for w in range(n_crash_windows):
        lo = max(1, int(w * seg))
        hi = max(lo + 1, int((w + 1) * seg))
        start = int(rng.integers(lo, hi))
        end = int(min(hi, start + max(1, length)))
        if end <= start:
            continue
        nodes = tuple(int(i) for i in rng.choice(
            n_nodes, size=n_down, replace=False))
        windows.append((start, end, nodes))
    return NemesisSpec(
        n_nodes=n_nodes, seed=seed, crash=tuple(windows),
        loss_rate=loss_rate, loss_until=horizon if loss_rate else None,
        dup_rate=dup_rate, dup_until=horizon if dup_rate else None)


# -- scenario-axis batching (PR 10) --------------------------------------
#
# The scenario-axis fuzzer (tpu_sim/scenario.py) runs S independent
# NemesisSpecs as ONE compiled program: the per-scenario FaultPlans are
# PADDED to a common crash-window count and STACKED leaf-by-leaf into a
# batched plan with a leading scenario axis, which `jax.vmap` then
# slices back into ordinary (C,)/(C, N)/() leaves per scenario.
#
# Padding semantics: a pad window is ``[0, 0)`` with an all-False down
# row — ``starts[w] <= t < ends[w]`` is unsatisfiable at every t, so
# windows_fold treats it as never-active and a padded plan is
# BIT-IDENTICAL to its unpadded original (pinned by
# tests/test_scenario.py).  All specs in a batch must share n_nodes
# (one compiled shape); rates/seeds stack into (S,) scalars.


def _plan_window_shapes(plan: FaultPlan, where: str = "plan") -> int:
    """Validate the crash-window axis is coherent across the three
    window leaves (starts/ends/down) and return its length.  Names
    ``where`` in the error so a batch failure points at the offending
    spec instead of surfacing as a raw JAX stacking error."""
    c = int(plan.starts.shape[0])
    if plan.starts.ndim != 1 or plan.ends.ndim != 1 \
            or plan.down.ndim != 2:
        raise ValueError(
            f"{where}: window leaves must be starts (C,), ends (C,), "
            f"down (C, N); got starts {tuple(plan.starts.shape)}, "
            f"ends {tuple(plan.ends.shape)}, "
            f"down {tuple(plan.down.shape)}")
    if int(plan.ends.shape[0]) != c or int(plan.down.shape[0]) != c:
        raise ValueError(
            f"{where}: window axes disagree — starts has {c} windows, "
            f"ends {int(plan.ends.shape[0])}, "
            f"down {int(plan.down.shape[0])}")
    return c


def pad_plan(plan: FaultPlan, n_windows: int, *,
             where: str = "plan") -> FaultPlan:
    """Pad a compiled plan's crash-window axis to ``n_windows`` with
    never-active ``[0, 0)`` windows (see above).  Evaluation is
    bit-identical — the pad windows fold as inactive at every round.
    ``where`` names the plan (e.g. its batch index) in shape
    errors."""
    c = _plan_window_shapes(plan, where)
    if c > n_windows:
        raise ValueError(
            f"{where} has {c} crash windows, cannot pad to "
            f"{n_windows}")
    if c == n_windows:
        return plan
    pad = n_windows - c
    n = int(plan.down.shape[1])
    return plan._replace(
        starts=jnp.concatenate(
            [plan.starts, jnp.zeros((pad,), jnp.int32)]),
        ends=jnp.concatenate(
            [plan.ends, jnp.zeros((pad,), jnp.int32)]),
        down=jnp.concatenate(
            [plan.down, jnp.zeros((pad, n), bool)], axis=0))


def batch_plans(specs, n_windows: int | None = None) -> FaultPlan:
    """Compile + pad + stack a sequence of :class:`NemesisSpec`s into
    ONE batched :class:`FaultPlan` with a leading scenario axis:
    ``starts/ends (S, C)``, ``down (S, C, N)``, scalars ``(S,)``.
    The scenario drivers vmap over the leading axis, so each scenario
    evaluates exactly its own (padded) plan.  ``n_windows`` overrides
    the padded crash-window count (the fuzzer's shape-bucket knob,
    PR 13: a power-of-two bucket keeps the batched plan shape — and so
    the compiled program — stable across campaigns)."""
    specs = list(specs)
    if not specs:
        raise ValueError("batch_plans needs at least one spec")
    n = specs[0].n_nodes
    for sp in specs:
        if sp.n_nodes != n:
            raise ValueError(
                f"scenario batch mixes n_nodes {n} and {sp.n_nodes} "
                "(one compiled shape per batch)")
    c_max = max(len(sp.crash) for sp in specs)
    if n_windows is not None:
        if n_windows < c_max:
            raise ValueError(
                f"n_windows={n_windows} < the batch's widest crash-"
                f"window count {c_max}")
        c_max = n_windows
    plans = [pad_plan(sp.compile(), c_max, where=f"spec {i}")
             for i, sp in enumerate(specs)]
    ref = plans[0]
    for i, p in enumerate(plans[1:], start=1):
        for name in FaultPlan._fields:
            got = tuple(getattr(p, name).shape)
            want = tuple(getattr(ref, name).shape)
            if got != want:
                raise ValueError(
                    f"batch_plans: spec {i} leaf {name!r} has shape "
                    f"{got}, but spec 0 has {want} — the batch does "
                    "not share one compiled shape")
    return FaultPlan(*(jnp.stack([p[i] for p in plans])
                       for i in range(len(FaultPlan._fields))))


# -- device-side mask evaluation ----------------------------------------


def member_at(plan: FaultPlan, t, ids: jnp.ndarray) -> jnp.ndarray:
    """bool, shaped like ``ids`` — which of the (GLOBAL) node ids are
    MEMBERS at round ``t``: joined at or before ``t`` and not yet
    left.  Crash windows do not affect membership — a crashed member
    is still a member (it will restart); a left row never is."""
    t32 = jnp.asarray(t).astype(jnp.int32)
    idx = jnp.asarray(ids).astype(jnp.int32)
    return ((t32 >= plan.join_round[idx])
            & (t32 < plan.leave_round[idx]))


def plan_churn(plan: FaultPlan) -> jnp.ndarray:
    """() int32 — how many membership events the plan carries (join
    rows + leave rows): the behavioral signature's churn input
    (scenario.signature_eval's fifth field), evaluated from the plan
    leaves the run already holds — zero extra operands."""
    joins = jnp.sum(plan.join_round != jnp.int32(JOIN_FOUNDING))
    leaves = jnp.sum(plan.leave_round != jnp.int32(LEAVE_NEVER))
    return (joins + leaves).astype(jnp.int32)


def node_up(plan: FaultPlan, t, ids: jnp.ndarray) -> jnp.ndarray:
    """bool, shaped like ``ids`` — which of the (GLOBAL) node ids are
    up at round ``t``.  Same windows-as-data evaluation as the
    partition masks (broadcast._edge_live, counter._reach); the
    membership fold rides on top — a non-member row (pre-join or
    post-leave) is never up, so every existing liveness gate in the
    sims inherits join/leave with no call-site change."""
    up = windows_fold(
        plan.starts, plan.ends, t,
        lambda w, active, up: up & ~(active & plan.down[w][ids]),
        jnp.ones(jnp.asarray(ids).shape, bool))
    return up & member_at(plan, t, ids)


def amnesia(plan: FaultPlan, t, ids: jnp.ndarray) -> jnp.ndarray:
    """bool, shaped like ``ids`` — nodes that CRASH at round ``t``
    (down now, up last round).  These are the amnesia rows: volatile
    state dies WITH the process, so the sims wipe it at crash entry;
    the rows stay empty while down (every edge to/from them is masked)
    and the node restarts empty when its window ends, recovering only
    via anti-entropy.

    A JOINING row also fires here (at exactly its join round): the
    same wipe call sites that serve crash-restart guarantee the row
    ENTERS EMPTY — structurally, not by convention.  The difference
    from restart-with-amnesia is in the liveness history, not the
    wipe: a joiner was never up before (``node_up`` is False for its
    whole pre-join past), a restarted node was."""
    crash = ~node_up(plan, t, ids) & node_up(plan, t - 1, ids)
    t32 = jnp.asarray(t).astype(jnp.int32)
    idx = jnp.asarray(ids).astype(jnp.int32)
    return crash | (t32 == plan.join_round[idx])


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    """32-bit finalizer (splitmix-style avalanche) — the same mixing
    family the counter's seeded CAS-winner hash uses."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _edge_hash(plan: FaultPlan, t, src, dst, salt: int) -> jnp.ndarray:
    """uint32 counter-based stream: h(seed, t, src, dst, salt) —
    stateless, so every shard (and every replay) evaluates the same
    coin for the same directed delivery."""
    x = (jnp.asarray(src).astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
         ^ jnp.asarray(dst).astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
         ^ jnp.asarray(t).astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         ^ plan.seed ^ jnp.uint32(salt))
    return _mix32(x)


def edge_drop(plan: FaultPlan, t, src, dst) -> jnp.ndarray:
    """bool (broadcast of src/dst shapes) — this round's delivery on
    the directed edge src -> dst is LOST in flight.  Loss is drawn per
    direction (the two directions of a link drop independently, like
    Maelstrom's lossy network)."""
    h = _edge_hash(plan, t, src, dst, _SALT_LOSS)
    return (t < plan.loss_until) & (h < plan.loss_num)


def edge_dup(plan: FaultPlan, t, src, dst) -> jnp.ndarray:
    """bool — this round the edge ALSO re-delivers everything its
    source ever sent (the source's full received set): the
    at-least-once duplicate stream.  Independent of the loss coin."""
    h = _edge_hash(plan, t, src, dst, _SALT_DUP)
    return (t < plan.dup_until) & (h < plan.dup_num)


def coin_block(plan: FaultPlan, t, src_ids: jnp.ndarray, dst_lo,
               block: int, *, dup: bool = False):
    """Streaming coin evaluation for ONE destination slab (the ISSUE-5
    tentpole primitive): ``(up, drop, dup | None)`` for the global
    destination rows ``dst_lo + [0, block)`` against the (flat) global
    ``src_ids`` — ``up`` is the (block,) destination liveness, ``drop``
    / ``dup`` are the (block, len(src_ids)) per-link coins.

    The coins are stateless hashes of (t, src, dst), so evaluating
    them slab by slab inside an ``engine.scan_blocks`` sweep is
    bit-identical to the materialized full-axis ``edge_drop`` /
    ``edge_dup`` masks — nothing forces the O(rows·N·S) widening; the
    peak mask temp drops to O(block·N·S).  ``dst_lo`` may be traced
    (a scan slab start, plus the shard's global row offset)."""
    dst = dst_lo + jnp.arange(block, dtype=jnp.int32)
    up = node_up(plan, t, dst)
    drop = edge_drop(plan, t, src_ids[None, :], dst[:, None])
    dups = (edge_dup(plan, t, src_ids[None, :], dst[:, None])
            if dup else None)
    return up, drop, dups


def kv_drop(plan: FaultPlan, t, ids) -> jnp.ndarray:
    """bool, shaped like ``ids`` — node i's KV exchange is lost this
    round (transient service unreachability: the node retries next
    round, exactly like a reachability window that lasts one round)."""
    return edge_drop(plan, t, ids, KV_DST)


# -- words-major (structured-path) mask compilation ----------------------
#
# The gather path evaluates crash liveness and the loss/dup coins per
# adjacency slot — a random gather per round, which the structured
# words-major exchanges exist to avoid.  The same decomposition that
# made partition windows gather-free (structured.fault_masks) applies
# to the whole plan: every structured delivery is a sum of per-
# DIRECTION terms with a host-known sender map, so
#
# - crash liveness becomes a host-precomputed (C, D, N) "either
#   endpoint down" mask per crash window (``down_pair``), AND-folded at
#   round t exactly like the partition ``same`` masks;
# - the loss/dup coins become ELEMENTWISE hashes over host-precomputed
#   (D, N) sender/receiver id arrays (the stateless counter-based
#   stream needs only (t, src, dst) — no adjacency read);
# - amnesia rows and receiver liveness become a (C, N) per-column
#   ``down`` array, evaluated with zero indexing (``wm_up_cols``).
#
# structured.make_nemesis assembles the :class:`WMNemesisArrays`
# operand from these pieces plus its direction-row contracts; the
# broadcast words-major round threads it as ONE traced pytree
# (positionally sharded with the node axis on the halo path), so the
# full Maelstrom fault model runs at structured speed.


class WMNemesisArrays(NamedTuple):
    """The traced words-major nemesis operand (see above).  Delivery-
    contract rows (``exists``/``same``/``down_pair``/``src``/``dst``)
    follow structured.nemesis_dir_pairs; degree-contract rows
    (``deg_*``) follow structured.fault_dir_senders and drive the
    message ledgers.  All leaves are host-precomputed and ride as
    traced arrays — never baked into the program."""

    exists: jnp.ndarray         # (D, N) bool — delivery edges
    same: jnp.ndarray           # (P, D, N) bool — partition same-group
    down_pair: jnp.ndarray      # (C, D, N) bool — src or dst down
    src: jnp.ndarray            # (D, N) uint32 — sender ids (coins)
    dst: jnp.ndarray            # (D, N) uint32 — receiver ids (coins)
    deg_exists: jnp.ndarray     # (Dg, N) bool — ledger edges
    deg_same: jnp.ndarray       # (P, Dg, N) bool
    deg_down_pair: jnp.ndarray  # (C, Dg, N) bool
    # (Dg, N) uint32 — sender/receiver ids of the DEGREE-contract rows:
    # the loss-only srv ledger's ack/diff coins (the gather path's
    # out_ok term) are elementwise hashes over these, one coin pair per
    # in-edge of each receiver column
    deg_src: jnp.ndarray
    deg_dst: jnp.ndarray
    down_cols: jnp.ndarray      # (C, N) bool — amnesia / receiver-up


def wm_specs(sharded: bool, axes="nodes") -> WMNemesisArrays:
    """shard_map in_specs for a :class:`WMNemesisArrays` operand: every
    row positionally sharded with the node axis (``axes`` — the sim's
    ``engine.node_axes`` result, a tuple on a hierarchical mesh) on
    the halo path (all masking is receiver-column-local, zero extra
    ICI), replicated on the all_gather fallback (the full-axis masked
    exchange needs full-axis masks)."""
    r2 = P(None, axes) if sharded else P(None, None)
    r3 = P(None, None, axes) if sharded else P(None, None, None)
    return WMNemesisArrays(r2, r3, r3, r2, r2, r2, r3, r3, r2, r2, r2)


def crash_down_rows(spec: "NemesisSpec", ids) -> np.ndarray:
    """(C, *ids.shape) bool — which of the (possibly -1-padded) global
    ``ids`` are down in each of the spec's crash windows.  Host
    compilation for the words-major masks: pad slots read False."""
    ids = np.asarray(ids)
    out = np.zeros((len(spec.crash),) + ids.shape, bool)
    for c, (_s, _e, nodes) in enumerate(spec.crash):
        d = np.zeros(spec.n_nodes, bool)
        d[list(nodes)] = True
        out[c] = d[np.clip(ids, 0, spec.n_nodes - 1)] & (ids >= 0)
    return out


def wm_up_cols(plan: FaultPlan, t, down_cols: jnp.ndarray) -> jnp.ndarray:
    """(n_cols,) bool — per-COLUMN liveness at round ``t`` from the
    positionally-(sharded-)precomputed ``down_cols`` rows: the
    words-major twin of :func:`node_up`, with no index/gather at all."""
    return windows_fold(
        plan.starts, plan.ends, t,
        lambda c, active, up: up & ~(active & down_cols[c]),
        jnp.ones(down_cols.shape[1:], bool))


def wm_live_rows(plan: FaultPlan, t, arrs: WMNemesisArrays,
                 pstarts, pends, *, deg: bool = False) -> jnp.ndarray:
    """(D, n_cols) bool — per-direction-row SEND liveness at round
    ``t``: exists AND same-group under every active partition window
    AND both endpoints up under every active crash window.  ``deg``
    selects the degree-contract rows (the ledger side; the delivery
    rows additionally lose the loss coins via :func:`wm_live_del`)."""
    exists = arrs.deg_exists if deg else arrs.exists
    same = arrs.deg_same if deg else arrs.same
    down_pair = arrs.deg_down_pair if deg else arrs.down_pair
    lv = windows_fold(pstarts, pends, t,
                      lambda w, active, lv: lv & (same[w] | ~active),
                      exists)
    return windows_fold(plan.starts, plan.ends, t,
                        lambda c, active, lv:
                        lv & ~(active & down_pair[c]),
                        lv)


def wm_live_del(plan: FaultPlan, t, arrs: WMNemesisArrays,
                pstarts, pends, dup_on: bool):
    """(live_del, dup | None) — the delivery-contract masks at send
    round ``t`` under the FULL nemesis: send liveness minus the
    per-direction loss coins, plus the duplicate-delivery coins.  The
    coins are elementwise over the precomputed (D, N) id arrays —
    bit-identical to the gather path's per-slot streams (same (t, src,
    dst) triples hash to the same coin)."""
    live = wm_live_rows(plan, t, arrs, pstarts, pends)
    live_del = live & ~edge_drop(plan, t, arrs.src, arrs.dst)
    dup = (live_del & edge_dup(plan, t, arrs.src, arrs.dst)
           if dup_on else None)
    return live_del, dup


def wm_srv_rows(plan: FaultPlan, t, arrs: WMNemesisArrays,
                pstarts, pends):
    """(live, ack, both) — the LOSS-ONLY srv-ledger mask rows over the
    DEGREE contract at round ``t``, (Dg, n_cols) each: ``live`` is the
    send-liveness (requests charged at send time), ``ack`` additionally
    requires the receiver column's OUTGOING coin (the reply exists only
    when the triggering request delivered — the gather path's
    ``out_ok`` term, here an elementwise hash over the precomputed
    deg_dst -> deg_src ids), and ``both`` requires BOTH direction
    coins (the sync-diff pairs).  Bit-identical to the gather path's
    per-slot streams: same (t, src, dst) triples, same coins."""
    live = wm_live_rows(plan, t, arrs, pstarts, pends, deg=True)
    out_ok = ~edge_drop(plan, t, arrs.deg_dst, arrs.deg_src)
    in_ok = ~edge_drop(plan, t, arrs.deg_src, arrs.deg_dst)
    return live, live & out_ok, live & in_ok & out_ok


# -- host mirrors (for op staging and ack accounting) --------------------


def _mix32_np(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint32)
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x7FEB352D)
    x ^= x >> np.uint32(15)
    x *= np.uint32(0x846CA68B)
    x ^= x >> np.uint32(16)
    return x


def host_member_at(plan: FaultPlan, t: int) -> np.ndarray:
    """(N,) bool — numpy twin of :func:`member_at` over a COMPILED
    plan (bit-identical membership fold for host-side staging and the
    checkers' member-masked evidence)."""
    jr = np.asarray(plan.join_round)
    lr = np.asarray(plan.leave_round)
    return (jr <= t) & (t < lr)


def host_node_up(plan: FaultPlan, t: int) -> np.ndarray:
    """(N,) bool — numpy twin of :func:`node_up` over a COMPILED plan
    (drivers that only hold the plan, e.g. ``KafkaSim.alloc_offsets``,
    mirror the round's gate without a device round-trip)."""
    up = host_member_at(plan, t)
    starts, ends = np.asarray(plan.starts), np.asarray(plan.ends)
    down = np.asarray(plan.down)
    for w in range(starts.shape[0]):
        if starts[w] <= t < ends[w]:
            up = up & ~down[w]
    return up


def host_edge_drop(plan: FaultPlan, t: int, src, dst) -> np.ndarray:
    """numpy twin of :func:`edge_drop` — bit-identical coins."""
    src = np.asarray(src, np.int64).astype(np.uint32)
    dst = np.asarray(dst, np.int64).astype(np.uint32)
    t_term = np.uint32((int(t) * 0x9E3779B9) & 0xFFFFFFFF)
    x = (src * np.uint32(0xC2B2AE35)
         ^ dst * np.uint32(0x27D4EB2F)
         ^ t_term ^ np.uint32(plan.seed) ^ np.uint32(_SALT_LOSS))
    return ((t < int(plan.loss_until))
            & (_mix32_np(x) < np.uint32(plan.loss_num)))


def host_kv_ok(plan: FaultPlan, t: int) -> np.ndarray:
    """(N,) bool — up AND this round's KV exchange not lost: the host
    twin of the sims' ``reach`` gate under a plan."""
    n = np.asarray(plan.down).shape[1]
    ids = np.arange(n)
    return host_node_up(plan, t) & ~host_edge_drop(
        plan, t, ids, np.full(n, KV_DST))


